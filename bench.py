"""Benchmark: fused rollout throughput at the north-star config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures env-steps/sec of the jitted rollout program (vmapped env + MAC
action selection + episode-batch emission fused into one XLA program) at the
BASELINE.json north-star scale point: 64 AGVs × 8 MECs × 1024 parallel envs,
d_model 256 agent network. ``vs_baseline`` is the ratio to the 50,000
env-steps/s/chip target (the reference publishes no numbers of its own —
BASELINE.md).

Flags:
  --smoke       tiny CPU config (CI validation of the bench harness itself)
  --envs N      override the env-batch size
  --steps N     override episode_limit for the timed program
  --iters N     timed repetitions (median reported)
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--envs", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--no-pallas", action="store_true",
                    help="XLA acting path (reproduces the BASELINE.md "
                         "XLA-path row)")
    args = ap.parse_args()

    if args.smoke:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp

    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    from t2omca_tpu.run import Experiment

    if args.smoke:
        n_envs = args.envs or 8
        steps = args.steps or 8
        cfg = sanity_check(TrainConfig(
            batch_size_run=n_envs,
            env_args=EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                               episode_limit=steps),
            model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                              mixer_heads=2, mixer_depth=1),
            replay=ReplayConfig(buffer_size=16),
        ))
    else:
        # north-star scale point (BASELINE.json configs[2]): 64 AGVs × 8 MEC,
        # 1024 envs, d_model 256. episode_limit is shortened for the timed
        # program (throughput is per-step; the full 150-slot episode batch at
        # entity obs 64×576 would exceed single-chip HBM — the training
        # config shards it over the data axis instead).
        n_envs = args.envs or 1024
        steps = args.steps or 32
        cfg = sanity_check(TrainConfig(
            batch_size_run=n_envs,
            env_args=EnvConfig(agv_num=64, mec_num=8, num_channels=8,
                               episode_limit=steps),
            model=ModelConfig(emb=256, heads=4, depth=2, mixer_emb=256,
                              mixer_heads=4, mixer_depth=2,
                              standard_heads=True, dtype="bfloat16",
                              use_pallas=not args.no_pallas),
            replay=ReplayConfig(buffer_size=4, store_dtype="bfloat16"),
        ))

    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    rollout = jax.jit(exp.runner.run, static_argnames="test_mode")
    params = ts.learner.params["agent"]

    import numpy as np

    def _sync(x):
        # device→host fetch: the only reliable barrier under the axon remote
        # tunnel, where block_until_ready on async futures returns early
        return float(np.asarray(x))

    # compile + warm-up (two runs: tunnel queues make the first timed run
    # unrepresentative)
    t0 = time.perf_counter()
    rs, batch, stats = rollout(params, ts.runner, test_mode=False)
    _sync(batch.reward[0, 0])
    compile_s = time.perf_counter() - t0
    rs, batch, stats = rollout(params, rs, test_mode=False)
    _sync(batch.reward[0, 0])
    print(f"# compile+first-run: {compile_s:.1f}s  "
          f"devices={jax.devices()}", file=sys.stderr)

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        rs, batch, stats = rollout(params, rs, test_mode=False)
        _sync(batch.reward[0, 0])
        times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[len(times) // 2]
    env_steps = cfg.batch_size_run * cfg.env_args.episode_limit
    rate = env_steps / dt
    print(f"# median rollout: {dt * 1e3:.1f}ms for {env_steps} env-steps "
          f"({n_envs} envs × {steps} slots, {cfg.env_args.agv_num} AGVs)",
          file=sys.stderr)

    print(json.dumps({
        "metric": "env_steps_per_sec",
        "value": round(rate, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": round(rate / 50_000.0, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
