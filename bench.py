"""Benchmark: fused rollout throughput at the north-star config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures env-steps/sec of the jitted rollout program (vmapped env + MAC
action selection + episode-batch emission fused into one XLA program) at the
BASELINE.json north-star scale point: 64 AGVs × 8 MECs × 1024 parallel envs,
d_model 256 agent network. ``vs_baseline`` is the ratio to the 50,000
env-steps/s/chip target (the reference publishes no numbers of its own —
BASELINE.md).

Flags:
  --smoke       tiny CPU config (CI validation of the bench harness itself)
  --envs N      override the env-batch size
  --steps N     override episode_limit for the timed program
  --iters N     timed repetitions (median reported)
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from t2omca_tpu.obs.spans import SpanRecorder

#: graftscope span recorder for the bench phases (stdlib-only import —
#: must not trigger jax before the smoke path pins JAX_PLATFORMS). The
#: emitted record embeds ``_REC.summary()`` so every BENCH_r*.json
#: carries the per-phase breakdown (probe / build / compile / warm /
#: measure), and on failure ``main_flight`` emits a partial record with
#: the open phase + flight tail — a wedged TPU bench is then
#: diagnosable instead of a bare "backend init" death (BENCH_r03–r05).
_REC = SpanRecorder(ring_size=128)

#: keys merged into every emitted success record (``_finalize``): the
#: probe's fallback-continue path tags records with the backend they
#: actually ran on, so a ``T2OMCA_BENCH_FALLBACK=1`` CPU number can
#: never masquerade as the pinned platform's
_RECORD_EXTRA: dict = {}

#: BENCH record schema version — every record now carries uniform
#: ``schema``/``platform``/``host`` meta (the r01–r07 series is
#: heterogeneous; ``obs timeline`` tolerates every historical shape)
BENCH_SCHEMA = 1
_HOST = socket.gethostname()


def _finalize(rec: dict) -> dict:
    """Attach the per-phase span summary, the uniform
    ``schema``/``platform``/``host`` meta, + any record-wide tags
    (platform fallback) to a bench record before emission."""
    rec.setdefault("spans", _REC.summary())
    rec.update(_RECORD_EXTRA)
    rec.setdefault("schema", BENCH_SCHEMA)
    rec.setdefault("host", _HOST)
    # platform: the live backend when main() recorded one
    # (_RECORD_EXTRA), else the env pin. NEVER jax.default_backend()
    # from here — on a probe-failure record that call would block on
    # the very wedged backend this record exists to report
    rec.setdefault("platform", os.environ.get("JAX_PLATFORMS") or None)
    return rec


class _ProbeTimeout(RuntimeError):
    """Probe attempt hit its slice of the budget (wedged-tunnel shape)."""


class _ProbeBackendError(RuntimeError):
    """Probe child ran and failed (real backend error, stderr attached)."""


def probe_backend(probe_s: float, _cmd=None, attempts: "int | None" = None,
                  _sleep=time.sleep) -> "dict | None":
    """Bounded backend-init probe in a SUBPROCESS, as a RETRYABLE phase
    on the resilience retry ladder (ROADMAP item 1 / ISSUE 11): attempts
    come from ``T2OMCA_BACKEND_PROBE_RETRIES`` (retries beyond the
    first, default 1 — the dispatch_retries convention), backoff between
    attempts from ``utils.watchdog.retry_call``'s exponential+jitter
    ladder (base ``T2OMCA_BACKEND_PROBE_BACKOFF``, default 0.5 s). A
    wedged axon tunnel blocks ``jax.devices()`` ~25 min inside backend
    init (BASELINE.md) — longer than most callers' own timeouts — and a
    blocked in-process thread can never be joined, so the probe runs
    ``jax.devices()`` in a child process the parent can kill at the
    bound. Returns ``None`` on success, else a structured
    ``{"error", "phase"}`` dict for the failure record — ``phase`` is
    ``"timeout"`` when the bound fired (the wedged-tunnel shape) and
    ``"backend_init"`` when the child itself failed (backend error with
    a real stderr).

    The budget is TOTAL: each attempt gets an equal split of whatever
    remains of ``probe_s`` (backoff sleeps spend budget too), so adding
    retries never pushes the error record past a caller's own timeout —
    recreating the no-record-on-stdout failure this probe exists to
    prevent.

    The child is spawned via ``Popen`` so the timeout path OWNS the
    cleanup: kill + ``wait`` in a ``finally``, guaranteeing the child is
    dead AND reaped (no zombie accumulating against the caller's pid
    limit — a soak loop hitting a wedged tunnel would otherwise leak one
    defunct process per probe). ``_cmd`` overrides the probed command and
    ``_sleep`` the backoff sleeper for tests.

    Deliberate cost: the child's backend init is thrown away, so a
    healthy run initializes twice (seconds on CPU/local TPU). That buys
    a killable probe — the previous in-process thread could never be
    joined once wedged and had to ``os._exit`` the whole bench."""
    from t2omca_tpu.utils import watchdog as _wd   # jit-free, stdlib-only

    if attempts is None:
        try:
            retries = int(os.environ.get("T2OMCA_BACKEND_PROBE_RETRIES",
                                         "1"))
        except ValueError:
            retries = 1
        attempts = 1 + max(retries, 0)
    try:
        backoff_s = float(os.environ.get("T2OMCA_BACKEND_PROBE_BACKOFF",
                                         "0.5"))
    except ValueError:
        backoff_s = 0.5
    cmd = _cmd or [sys.executable, "-c", "import jax; jax.devices()"]
    deadline = time.monotonic() + probe_s
    state = {"attempt": 0}

    def _attempt():
        state["attempt"] += 1
        a = state["attempt"]
        remaining = deadline - time.monotonic()
        per_attempt = remaining / max(attempts - a + 1, 1)
        if per_attempt <= 0:
            raise _ProbeTimeout(
                f"backend init exceeded the {probe_s:.0f}s probe "
                f"bound (attempt {a}/{attempts}; wedged tunnel?)")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            _, err = proc.communicate(timeout=per_attempt)
        except subprocess.TimeoutExpired:
            raise _ProbeTimeout(
                f"backend init exceeded {per_attempt:.0f}s probe "
                f"bound (attempt {a}/{attempts}; wedged tunnel?)"
            ) from None
        finally:
            # kill AND reap unconditionally: communicate() does not kill
            # on timeout, and a killed-but-unreaped child is a zombie
            if proc.poll() is None:
                proc.kill()
            proc.wait()
        if proc.returncode != 0:
            raise _ProbeBackendError(
                f"backend unavailable (attempt {a}/{attempts}): "
                f"{err.strip()[-400:]}")

    try:
        # every probe failure class retries (the pre-ladder behavior):
        # a timeout IS the transient wedge, and backend errors carry the
        # tunnel's text — fail-fast classification would misread a
        # garbled stderr as deterministic and skip the retry that
        # distinguishes a blip from a wedge
        _wd.retry_call(_attempt, attempts=attempts, backoff_s=backoff_s,
                       retriable=lambda e: True, label="bench.probe",
                       sleep=_sleep)
        return None
    except _ProbeTimeout as e:
        return {"error": str(e)[:500], "phase": "timeout"}
    except _ProbeBackendError as e:
        return {"error": str(e)[:500], "phase": "backend_init"}


def fallback_bound(probe_s: float) -> float:
    """The slice of the total probe budget RESERVED for the fallback
    probe. The caller runs the primary probe on ``probe_s -
    fallback_bound(probe_s)`` so primary + fallback together stay
    within ``probe_s`` — the no-record-past-the-caller's-timeout
    invariant holds for the whole probe PHASE, not just the primary.
    Proportional with no floor: a deliberately tiny budget (tests pin
    probe_s=0 = immediate-timeout) must not inflate into real waiting."""
    return min(probe_s / 6.0, 120.0)


def probe_fallback(bound: float, _cmd=None) -> dict:
    """``JAX_PLATFORMS=''`` auto-fallback probe (ROADMAP item 1): after
    the primary probe fails, ask a child with the platform pin CLEARED
    whether jax can initialize at all — separating "the pinned
    platform's tunnel is wedged" (fallback succeeds on another backend)
    from "jax itself is broken here" (fallback hangs too: auto-detection
    still tries the wedged plugin first, so the bound fires — that
    verdict is itself diagnostic). Returns the structured ``fallback``
    block embedded in the failure record: ``{"platforms": "", "ok":
    bool, "backend"|"error": str}``. With ``T2OMCA_BENCH_FALLBACK=1``
    the caller continues the bench on the fallback backend (record
    tagged with ``platform``) instead of exiting — a CPU smoke number
    from a wedged-TPU window, clearly labeled, beats no record at all.
    ``bound`` is the budget slice ``fallback_bound`` reserved."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = ""
    cmd = _cmd or [sys.executable, "-c",
                   "import jax; print(jax.default_backend())"]
    if bound <= 0:
        return {"platforms": "", "ok": False,
                "error": "no probe budget left for the fallback"}
    try:
        out = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             timeout=bound)
    except subprocess.TimeoutExpired:
        return {"platforms": "", "ok": False,
                "error": f"fallback probe exceeded {bound:.0f}s"}
    if out.returncode != 0:
        return {"platforms": "", "ok": False,
                "error": out.stderr.strip()[-200:]}
    lines = out.stdout.strip().splitlines()
    return {"platforms": "", "ok": True,
            "backend": lines[-1] if lines else "unknown"}


def _sync(x):
    """Device→host fetch: the only reliable barrier under the axon remote
    tunnel, where block_until_ready on async futures returns early."""
    return float(np.asarray(x))


def _chain_seconds(step, carry, k):
    """Seconds per iteration of k async-chained dispatches with ONE
    terminal sync. Each dispatch consumes the previous carry, so the
    device serializes them, but the host enqueues ahead — the per-call
    tunnel round-trip (~0.66 s, BASELINE.md) overlaps device compute.
    This is the steady-state rate the production driver loop sees (it
    never blocks on a host fetch per episode); a blocking median is the
    per-dispatch latency."""
    # one warm chained step first: the chained carry can have a different
    # layout/sharding than the caller's warm-path input (GSPMD output
    # placement), and that one-time recompile must not be timed
    carry, out = step(carry)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(k):
        carry, out = step(carry)
    _sync(out)
    return (time.perf_counter() - t0) / k


def breakdown(cfg, exp, ts, _time, args) -> int:
    """Attribute the rollout slot time (stderr table + one JSON line)."""
    import dataclasses
    import jax
    import jax.numpy as jnp

    env, mac = exp.env, exp.mac
    b, t_len = cfg.batch_size_run, cfg.env_args.episode_limit
    params = ts.learner.params["agent"]
    rs = ts.runner
    rows = {}

    def env_only(env_obj):
        def run(rs_states, key):
            def step_fn(carry, key_t):
                states, t = carry
                actions = jax.random.randint(
                    key_t, (b, env_obj.n_agents), 0, env_obj.n_actions)
                # empty-buffer lanes must take action 0 (legal projection)
                actions = actions * states.job_valid[:, :, 0]
                states, reward, *_ = jax.vmap(env_obj.step)(
                    states, actions, jax.random.split(key_t, b))
                return (states, t + 1), reward
            (states, _), rewards = jax.lax.scan(
                step_fn, (rs_states, 0), jax.random.split(key, t_len))
            return rewards.sum()
        return jax.jit(run)

    for label, fn in (("env_seq", False), ("env_fast", True)):
        e = dataclasses.replace(
            env, cfg=dataclasses.replace(env.cfg, fast_norm=fn))
        prog = env_only(e)
        rows[label] = _time(lambda p=prog: p(rs.env_states,
                                             jax.random.PRNGKey(0)))

    # acting-only: T sequential MAC forwards on a fixed obs batch
    obs = jnp.zeros((b, env.n_agents, env.obs_dim),
                    jnp.dtype(cfg.model.dtype))
    avail = jnp.ones((b, env.n_agents, env.n_actions), jnp.int32)

    def acting(params):
        # fold qslice weights outside the scan, as runner.run does
        params = mac.prepare_acting_params(params)

        def step_fn(carry, key_t):
            hidden, t_env = carry
            # entity-table acting recomputes the factored obs per step in
            # the real rollout scan — pay it here too for honest
            # attribution (XLA may still hoist this loop-invariant copy;
            # the 'full' row is the ground truth either way)
            compact = (jax.vmap(env.compact_obs)(rs.env_states)
                       if mac.use_entity_tables else None)
            actions, hidden, _ = mac.select_actions(
                params, obs, avail, hidden, key_t, t_env, test_mode=False,
                compact=compact)
            return (hidden, t_env + b), actions.sum()
        (_, _), outs = jax.lax.scan(
            step_fn, (mac.init_hidden(b), jnp.zeros((), jnp.int32)),
            jax.random.split(jax.random.PRNGKey(1), t_len))
        return outs.sum()

    rows["acting"] = _time(lambda: jax.jit(acting)(params))

    # one AOT compile serves both the timed calls and the cost model (a
    # second jit-cache compile of the full program would double bench
    # wall-clock at scale)
    rollout_c = (jax.jit(exp.runner.run, static_argnames="test_mode")
                 .lower(params, rs, test_mode=False).compile())
    def full():
        _, batch, _ = rollout_c(params, rs)
        return batch.reward[0, 0]
    rows["full"] = _time(full)

    # static XLA cost model of the full rollout program: attributes the
    # compute/bandwidth budget even when a profiler trace isn't available
    try:
        cost = rollout_c.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost:
            fl = cost.get("flops", 0.0)
            by = cost.get("bytes accessed", 0.0)
            print(f"# XLA cost model (full rollout): "
                  f"{fl / 1e12:.2f} TFLOP, {by / 1e9:.2f} GB accessed -> "
                  f"{fl / max(by, 1):.1f} FLOP/byte arithmetic intensity",
                  file=sys.stderr)
    except Exception as e:           # pragma: no cover - backend-dependent
        print(f"# cost_analysis unavailable: {e!r}", file=sys.stderr)

    env_steps = b * t_len
    acting_mode = ("entity" if mac.use_entity_tables
                   else "qslice" if mac.use_qslice else "dense")
    print(f"# breakdown at {b} envs x {t_len} slots "
          f"({cfg.env_args.agv_num} AGVs, d{cfg.model.emb}, "
          f"acting={acting_mode})", file=sys.stderr)
    for k, v in rows.items():
        print(f"#   {k:10s} {v * 1e3:8.1f} ms "
              f"({env_steps / v:,.0f} env-steps/s)", file=sys.stderr)
    print(json.dumps({k: round(env_steps / v, 1) for k, v in rows.items()}))
    return 0


def _train_numbers(cfg, _time, train_bs: int | None = None,
                   pipeline_k: int = 0) -> dict:
    """Learner-side throughput — the second half of the north-star metric
    (BASELINE.json: "env-steps/sec/chip + mixer train-steps/sec").

    Measures (a) ``train_iter``: PER sample → QMIX double-Q train step over
    the full episode scan → priority feedback, as one jitted program
    (reference hot loop /root/reference/per_run.py:224-238), and (b) one
    interleaved driver iteration (rollout + insert + train), reported as
    env-steps/s inclusive of training (config 4: PER + target-net sync)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from t2omca_tpu.run import Experiment

    bs = train_bs or 32
    cfg = cfg.replace(
        batch_size=bs,
        replay=dataclasses.replace(cfg.replay, prioritized=True,
                                   buffer_size=2 * cfg.batch_size_run))
    with _REC.span("bench.build", leg="train"):
        exp = Experiment.build(cfg)
        ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    b, t_len = cfg.batch_size_run, cfg.env_args.episode_limit

    # fill the buffer with one rollout so PER has real priorities
    with _REC.span("bench.compile", leg="train"):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=jnp.asarray(b, jnp.int32))
    key = jax.random.PRNGKey(7)

    def train_step(ts_):
        ts2, info = train_iter(ts_, key, jnp.asarray(1000))
        return ts2, info["loss"]

    def interleaved_step(ts_):
        rs2, batch2, _ = rollout(ts_.learner.params["agent"], ts_.runner,
                                 test_mode=False)
        ts2 = ts_.replace(runner=rs2, buffer=insert(ts_.buffer, batch2))
        return train_step(ts2)

    with _REC.span("bench.measure", leg="train"):
        dt_train = _time(lambda: train_step(ts)[1])
        dt_full = _time(lambda: interleaved_step(ts)[1])

    env_steps = b * t_len
    print(f"# train_iter ({bs} episodes x {t_len + 1} slots, PER on): "
          f"{dt_train * 1e3:.1f} ms -> {1.0 / dt_train:.2f} train-steps/s",
          file=sys.stderr)
    print(f"# interleaved rollout+insert+train: {dt_full * 1e3:.1f} ms -> "
          f"{env_steps / dt_full:,.0f} env-steps/s incl. training",
          file=sys.stderr)
    out = {
        "train_steps_per_sec": round(1.0 / dt_train, 2),
        "interleaved_env_steps_per_sec": round(env_steps / dt_full, 1),
        "train_batch_episodes": bs,
    }

    if pipeline_k:
        out["pipelined_train_steps_per_sec"] = round(
            1.0 / _chain_seconds(train_step, ts, pipeline_k), 2)
        out["pipelined_interleaved_env_steps_per_sec"] = round(
            env_steps / _chain_seconds(interleaved_step, ts, pipeline_k), 1)
    return out


def bench_dp(cfg, _time, args) -> int:
    """Config-5 measurement: the DP=8 training loop over a real device mesh
    (BASELINE.json configs[4]). Env lanes and replay episodes shard over the
    ``data`` axis; params replicate; GSPMD keeps the episode axis
    distributed and psums the grads. Measures BOTH metric halves: the
    rollout (env-steps/s) and the train iteration (PER sample → QMIX train
    over the episode scan → priority feedback; reference hot loop
    /root/reference/per_run.py:224-238). ``--train`` makes the train half
    the headline record. On a machine without 8 devices use
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (CPU
    validation) — per-chip numbers only mean something on a real slice."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from t2omca_tpu.parallel import DataParallel, make_mesh
    from t2omca_tpu.run import Experiment

    n_dev = 8
    # every episode axis must divide by the mesh: round env lanes down
    # (with a note) and the replay ring up. The ring holds one train
    # batch's worth of episodes (2×batch_size): train cost scales with the
    # sampled batch, not ring capacity (PER sampling is O(capacity)
    # vectorized — negligible), so the bench doesn't pay config-5's
    # production-sized ring HBM just to time the iteration.
    envs = (cfg.batch_size_run // n_dev) * n_dev
    if envs != cfg.batch_size_run:
        print(f"# rounding --envs {cfg.batch_size_run} down to {envs} "
              f"(multiple of DP={n_dev})", file=sys.stderr)
    if envs == 0:
        raise SystemExit(f"--envs must be >= {n_dev} for --config 5")
    bs = min(32, envs)
    ring = -(-max(cfg.replay.buffer_size, 2 * bs) // n_dev) * n_dev
    cfg = cfg.replace(
        batch_size_run=envs, batch_size=bs,
        replay=dataclasses.replace(cfg.replay, buffer_size=ring,
                                   prioritized=True))
    with _REC.span("bench.build", leg="dp"):
        exp = Experiment.build(cfg)
        mesh = make_mesh(n_dev)
        dp = DataParallel(exp, mesh)
        ts = dp.shard(exp.init_train_state(0))
    rollout, insert, train_iter = dp.jitted_programs()
    params = ts.learner.params["agent"]

    with _REC.span("bench.compile", leg="dp"):
        rs, batch, _ = rollout(params, ts.runner, test_mode=False)
    obs_leaf = jax.tree.leaves(batch.obs)[0]
    assert len(obs_leaf.sharding.device_set) == n_dev

    def one():
        _, b, _ = rollout(params, ts.runner, test_mode=False)
        return b.reward[0, 0]

    with _REC.span("bench.measure", leg="dp"):
        dt = _time(one)
    env_steps = cfg.batch_size_run * cfg.env_args.episode_limit
    rate = env_steps / dt
    print(f"# DP={n_dev} rollout: {dt * 1e3:.1f} ms for {env_steps} "
          f"env-steps ({cfg.batch_size_run} envs sharded over "
          f"{n_dev} devices)", file=sys.stderr)

    rate_pipe = None
    if args.pipeline:
        def roll_step(rs_):
            rs2, b, _ = rollout(params, rs_, test_mode=False)
            return rs2, b.reward[0, 0]
        rate_pipe = round(
            env_steps / _chain_seconds(roll_step, ts.runner, args.pipeline),
            1)

    # ---- train half: fill the ring with a slice of real episodes (the
    # rollout batch can exceed ring capacity at config-5 scale), keeping
    # the episode axis sharded, then time the full DP train iteration
    fill = jax.tree.map(lambda x: x[:ring], batch)
    fill = jax.device_put(fill, NamedSharding(mesh, P("data")))
    ts = ts.replace(runner=rs, buffer=insert(ts.buffer, fill),
                    # mesh-replicated, matching dp.shard — a single-device
                    # scalar here would give the chained train_iter a
                    # different input aval and force a second compile
                    episode=jax.device_put(jnp.asarray(ring, jnp.int32),
                                           NamedSharding(mesh, P())))
    key = jax.random.PRNGKey(7)

    def one_train():
        _, info = train_iter(ts, key, jnp.asarray(1000))
        return info["loss"]

    dt_train = _time(one_train)
    train_pipe = None
    if args.pipeline:
        def train_step(ts_):
            ts2_, info = train_iter(ts_, key, jnp.asarray(1000))
            return ts2_, info["loss"]
        train_pipe = round(
            1.0 / _chain_seconds(train_step, ts, args.pipeline), 2)
    ts2, _ = train_iter(ts, key, jnp.asarray(1000))
    leaf = jax.tree.leaves(ts2.learner.params)[0]
    assert leaf.sharding.is_fully_replicated, \
        "params must stay replicated through the DP train step"
    t_len = cfg.env_args.episode_limit
    print(f"# DP={n_dev} train_iter ({bs} episodes x {t_len + 1} slots, "
          f"PER on): {dt_train * 1e3:.1f} ms -> "
          f"{1.0 / dt_train:.2f} train-steps/s", file=sys.stderr)

    cfg_id = None if args.envs or args.steps else 5
    rollout_rec = {
        "metric": "env_steps_per_sec",
        "value": round(rate, 1),
        "unit": f"env-steps/s/{n_dev}-device-mesh",
        # vs_baseline keeps the per-chip semantics of every other record
        "vs_baseline": round(rate / n_dev / 50_000.0, 3),
        # only claim the BASELINE scale point when unmodified
        "config": cfg_id,
        "n_envs": cfg.batch_size_run, "dp": n_dev,
        "per_chip": round(rate / n_dev, 1),
        "train_steps_per_sec": round(1.0 / dt_train, 2),
        "train_batch_episodes": bs,
    }
    pipe_keys = {k: v for k, v in (
        ("pipelined_env_steps_per_sec", rate_pipe),
        ("pipelined_train_steps_per_sec", train_pipe)) if v is not None}
    if args.train:
        rec = {
            "metric": "train_steps_per_sec",
            "value": round(1.0 / dt_train, 2),
            "unit": f"train-steps/s/{n_dev}-device-mesh",
            "vs_baseline": None,
            "config": cfg_id,
            "dp": n_dev,
            "train_batch_episodes": bs,
            "env_steps_per_sec": round(rate, 1),
        }
    else:
        rec = rollout_rec
    rec.update(pipe_keys)
    print(json.dumps(_finalize(rec)))
    return 0


def bench_kernels(make_cfg_kernels, _time, args) -> int:
    """``--kernels``: the attention-kernel A/B leg. One rollout
    measurement per requested kernel mode (xla = einsum path, pallas =
    fused flash kernel; ``ab`` = both, xla first), each as its own JSON
    record with the mode in the record, so a kernel win is attributable
    in ``obs report``'s roofline table instead of a bare before/after
    number. Like ``--all``, each record embeds the CUMULATIVE span
    summary (a wedge in leg 2 still leaves leg 1's phase timings on
    record); the per-mode split lives in the span STREAM via the
    ``leg=kernels-<mode>`` meta on every span.

    The leg forces the DENSE acting path: MultiHeadAttention — the
    program the kernel switch selects — is what the dense rollout scan
    dispatches; the qslice/entity fast paths bypass it by construction,
    so an A/B over them would measure nothing.

    Each mode ALSO measures a TRAIN-STEP leg (PR 13): the jitted
    ``train_iter`` (sample → learner update → priority feedback) over a
    ring pre-filled from the rollout, one ``train_iters_per_sec`` record
    per mode — under ``pallas`` the learner's backward lowers through
    the flash backward kernels, which is the half of the A/B the
    rollout number can't see. Rides the ``--daemon`` matrix through the
    existing ``--kernels ab`` leg, so the next TPU window measures the
    backward kernel too."""
    import jax
    import jax.numpy as jnp

    from t2omca_tpu.run import Experiment

    modes = ("xla", "pallas") if args.kernels == "ab" else (args.kernels,)
    rc = 0
    for mode in modes:
        cfg = make_cfg_kernels(mode)
        label = f"kernels-{mode}"
        with _REC.span("bench.build", leg=label):
            exp = Experiment.build(cfg)
            ts = exp.init_train_state(0)
        rollout = jax.jit(exp.runner.run, static_argnames="test_mode")
        params = ts.learner.params["agent"]
        with _REC.span("bench.compile", leg=label):
            rs, batch, _ = rollout(params, ts.runner, test_mode=False)
            _sync(batch.reward[0, 0])

        def one(rollout=rollout, params=params, rs=rs):
            _, b, _ = rollout(params, rs, test_mode=False)
            return b.reward[0, 0]

        with _REC.span("bench.measure", leg=label):
            dt = _time(one)
        env_steps = cfg.batch_size_run * cfg.env_args.episode_limit
        rate = env_steps / dt
        print(f"# kernels={mode}: {dt * 1e3:.1f} ms for {env_steps} "
              f"env-steps (dense acting, "
              f"{cfg.env_args.agv_num} AGVs, d{cfg.model.emb})",
              file=sys.stderr)
        print(json.dumps(_finalize({
            "metric": "env_steps_per_sec",
            "value": round(rate, 1),
            "unit": "env-steps/s/chip",
            "vs_baseline": round(rate / 50_000.0, 3),
            "kernels": mode,
            "acting": "dense",
            "config": (None if args.smoke or args.envs or args.steps
                       else args.config),
            "n_envs": cfg.batch_size_run,
            "episode_steps": cfg.env_args.episode_limit,
        })), flush=True)

        # ---- train-step leg: fill the ring from the measured rollout,
        # then time the UNdonated train_iter on a fixed state (donation
        # would delete the inputs the next repetition re-times)
        tlabel = f"{label}-train"
        _, insert, train_iter = exp.jitted_programs()
        with _REC.span("bench.compile", leg=tlabel):
            buf_state = ts.buffer
            fills = -(-cfg.batch_size // cfg.batch_size_run)
            for _ in range(max(fills, 1)):
                buf_state = insert(buf_state, batch)
            ts_fill = ts.replace(buffer=buf_state)
            key = jax.random.PRNGKey(0)
            t_env = jnp.asarray(env_steps)
            _, info = train_iter(ts_fill, key, t_env)
            _sync(info["loss"])

        def one_train(train_iter=train_iter, ts_fill=ts_fill, key=key,
                      t_env=t_env):
            _, info = train_iter(ts_fill, key, t_env)
            return info["loss"]

        with _REC.span("bench.measure", leg=tlabel):
            dt_train = _time(one_train)
        print(f"# kernels={mode}: train_iter {dt_train * 1e3:.1f} ms "
              f"(batch {cfg.batch_size} episodes, dense learner unroll)",
              file=sys.stderr)
        print(json.dumps(_finalize({
            "metric": "train_iters_per_sec",
            "value": round(1.0 / dt_train, 2),
            "unit": "train-iters/s/chip",
            "vs_baseline": None,
            "kernels": mode,
            "leg": tlabel,
            "train_batch_episodes": cfg.batch_size,
            "config": (None if args.smoke or args.envs or args.steps
                       else args.config),
        })), flush=True)
    return rc


def bench_sebulba(cfg, _time, args) -> int:
    """``--sebulba``: the decoupled actor/learner A/B (ROADMAP item 2).

    Measures the same chained rollout→insert→train workload three ways
    and reports all of them in ONE record:

    * **classic** (context) — the classic three-program loop on a
      single device, async-chained with one terminal sync: today's
      default driver shape;
    * **serialized** — the SPLIT pipeline (1 actor + 1 learner device,
      ``parallel/sebulba.py``) run strictly phase-by-phase: each stage
      (rollout, queue hop, train, params publish) blocks to completion
      before the next starts. This is the serialized regime the
      decoupled architecture exists to remove — identical per-iteration
      work to the overlapped leg, so the A/B isolates exactly what
      overlap buys;
    * **overlapped** — the same split driven the way
      ``run.run_sebulba`` drives it: an actor thread rollouts and feeds
      the device-resident trajectory queue while the main thread
      consumes, trains and publishes params back, no per-stage syncs.
      Wall-clock covers the same k batches produced AND consumed.

    Headline = overlapped env-steps/s (training included);
    ``overlap_speedup`` = overlapped/serialized. On a real 2-chip split
    the two phases also overlap in COMPUTE; on a CPU smoke host the
    devices share cores, so the speedup there measures the removed
    serialization points only (stated by the record's backend field).
    Needs ≥ 2 devices (``--smoke`` forces 2 CPU host devices)."""
    import dataclasses
    import threading

    import jax
    import jax.numpy as jnp

    from t2omca_tpu.config import SebulbaConfig
    from t2omca_tpu.parallel.sebulba import make_sebulba
    from t2omca_tpu.run import Experiment

    k = max(2 * args.iters, 6)
    bs = 4 if args.smoke else 32
    b, t_len = cfg.batch_size_run, cfg.env_args.episode_limit
    env_steps = k * b * t_len
    cfg = cfg.replace(
        batch_size=bs,
        replay=dataclasses.replace(
            cfg.replay, prioritized=True,
            buffer_size=max(cfg.replay.buffer_size, 2 * b, bs)))

    # ---- classic context leg: one device, async-chained loop ----------
    with _REC.span("bench.build", leg="sebulba-classic"):
        exp = Experiment.build(cfg)
        ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    key = jax.random.PRNGKey(7)

    def classic_iter(ts, i):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + b)
        ts, info = train_iter(ts, jax.random.fold_in(key, i),
                              jnp.asarray(1000 + i))
        return ts, info

    with _REC.span("bench.compile", leg="sebulba-classic"):
        ts, info = classic_iter(ts, 0)      # compile + ring fill
        _sync(info["loss"])
    with _REC.span("bench.measure", leg="sebulba-classic"):
        t0 = time.perf_counter()
        for i in range(k):
            ts, info = classic_iter(ts, 1 + i)
        _sync(info["loss"])
        dt_classic = time.perf_counter() - t0
    rate_classic = env_steps / dt_classic
    print(f"# sebulba A/B classic (1 device, async chain): "
          f"{dt_classic * 1e3:.1f} ms for {env_steps} env-steps + {k} "
          f"train iters -> {rate_classic:,.0f} env-steps/s",
          file=sys.stderr)
    del ts, rollout, insert, train_iter, exp

    # ---- overlapped: 1 actor + 1 learner device ------------------------
    seb_cfg = cfg.replace(sebulba=SebulbaConfig(
        actor_devices=1, learner_devices=1, queue_slots=2, staleness=1))
    with _REC.span("bench.build", leg="sebulba-overlap"):
        exp2 = Experiment.build(seb_cfg)
        seb = make_sebulba(exp2)
        rs, ls = seb.init_states(0)
        q = seb.init_queue()
    actor_step, queue_put, queue_get, learner_step = seb.programs()
    sb = seb_cfg.sebulba

    with _REC.span("bench.compile", leg="sebulba-overlap"):
        # warm every program once (compiles + ring fill so the timed
        # iterations all take the train branch)
        params = seb.publish_params(ls.learner.params["agent"])
        rs, tm, _ = actor_step(params, rs, test_mode=False)
        q = queue_put(q, jnp.asarray(0, jnp.int32), seb.to_learner(tm))
        ls, q = queue_get(ls, q, jnp.asarray(0, jnp.int32))
        ls, info = learner_step(ls, jax.random.fold_in(key, 999),
                                jnp.asarray(1000))
        _sync(info["loss"])

    # ---- serialized split: IDENTICAL per-iteration work, every stage
    # blocked to completion before the next starts — the serialized
    # regime the decoupled loop removes
    with _REC.span("bench.measure", leg="sebulba-serial"):
        t0 = time.perf_counter()
        params = seb.publish_params(ls.learner.params["agent"])
        jax.block_until_ready(params)
        for i in range(k):
            rs, tm, stats = actor_step(params, rs, test_mode=False)
            jax.block_until_ready(stats.epsilon)
            tm_l = seb.to_learner(tm)
            jax.block_until_ready(tm_l.reward)
            q = queue_put(q, jnp.asarray(0, jnp.int32), tm_l)
            ls, q = queue_get(ls, q, jnp.asarray(0, jnp.int32))
            ls, info = learner_step(ls, jax.random.fold_in(key, 3000 + i),
                                    jnp.asarray(3000 + i))
            _sync(info["loss"])
            params = seb.publish_params(ls.learner.params["agent"])
            jax.block_until_ready(params)
        dt_serial = time.perf_counter() - t0
    rate_serial = env_steps / dt_serial
    print(f"# sebulba A/B serialized split (1+1 devices, stage-"
          f"synchronized): {dt_serial * 1e3:.1f} ms -> "
          f"{rate_serial:,.0f} env-steps/s", file=sys.stderr)

    cond = threading.Condition()
    shared = {"q": q, "params": seb.publish_params(
        ls.learner.params["agent"]), "put": 0, "consumed": 0,
        "error": None}

    def actor(rs=rs):
        try:
            for i in range(k):
                with cond:
                    while (i - shared["consumed"] > sb.staleness
                           or shared["put"] - shared["consumed"]
                           >= sb.queue_slots):
                        cond.wait()
                    params = shared["params"]
                rs, tm, stats = actor_step(params, rs, test_mode=False)
                jax.block_until_ready(stats.epsilon)
                tm_l = seb.to_learner(tm)
                with cond:
                    shared["q"] = queue_put(
                        shared["q"],
                        jnp.asarray(shared["put"] % sb.queue_slots,
                                    jnp.int32), tm_l)
                    shared["put"] += 1
                    cond.notify_all()
        except Exception as e:  # noqa: BLE001 — surfaced by the main leg
            with cond:
                shared["error"] = e
                cond.notify_all()

    with _REC.span("bench.measure", leg="sebulba-overlap"):
        t0 = time.perf_counter()
        th = threading.Thread(target=actor, daemon=True)
        th.start()
        for i in range(k):
            with cond:
                while shared["put"] <= i and shared["error"] is None:
                    cond.wait()
                if shared["error"] is not None:
                    raise shared["error"]
                ls, shared["q"] = queue_get(
                    ls, shared["q"],
                    jnp.asarray(i % sb.queue_slots, jnp.int32))
            ls, info = learner_step(ls, jax.random.fold_in(key, i),
                                    jnp.asarray(2000 + i))
            with cond:
                shared["params"] = seb.publish_params(
                    ls.learner.params["agent"])
                shared["consumed"] = i + 1
                cond.notify_all()
        _sync(info["loss"])
        dt_overlap = time.perf_counter() - t0
        th.join(timeout=30)
    rate_overlap = env_steps / dt_overlap
    speedup = rate_overlap / rate_serial
    print(f"# sebulba A/B overlapped (1+1 devices, queue_slots="
          f"{sb.queue_slots}, staleness={sb.staleness}): "
          f"{dt_overlap * 1e3:.1f} ms -> {rate_overlap:,.0f} env-steps/s "
          f"({speedup:.2f}x serialized)", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "env_steps_per_sec",
        "value": round(rate_overlap, 1),
        "unit": "env-steps/s/2-device-split",
        # per-chip semantics like the DP record: the split uses 2 chips
        "vs_baseline": round(rate_overlap / 2 / 50_000.0, 3),
        "sebulba": {"actor_devices": 1, "learner_devices": 1,
                    "queue_slots": sb.queue_slots,
                    "staleness": sb.staleness},
        # A/B pair: same split, same per-iteration work — serialized
        # blocks every stage, overlapped is the production coordination
        "serialized_env_steps_per_sec": round(rate_serial, 1),
        "overlap_speedup": round(speedup, 3),
        # context: the classic single-device async-chained loop (on a
        # shared-core CPU host this can exceed both split legs — the
        # split pays queue/copy overhead for compute overlap that only
        # disjoint real chips can deliver)
        "classic_env_steps_per_sec": round(rate_classic, 1),
        "config": (None if args.smoke or args.envs or args.steps
                   else args.config),
        "n_envs": b,
        "episode_steps": t_len,
        "train_batch_episodes": bs,
        "chained_iters": k,
        "backend": jax.default_backend(),
    })))
    return 0


def bench_superstep(cfg, _time, args) -> int:
    """``--superstep K``: the dispatch-amortized training rate. ONE fused
    XLA program scans K rollout → in-place ring insert → (gated)
    sample+train iterations per dispatch
    (``run.Experiment.superstep_program``) — the rate the production
    driver sees at ``superstep=K``, where the per-dispatch tunnel
    round-trip (~0.66 s, BASELINE.md) is paid once per K full train
    iterations instead of 3× per iteration. Headline: env-steps/s over
    the whole dispatch INCLUDING training."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from t2omca_tpu.run import Experiment

    k = args.superstep
    bs = 4 if args.smoke else 32
    b = cfg.batch_size_run
    cfg = cfg.replace(
        batch_size=bs,
        replay=dataclasses.replace(
            cfg.replay, prioritized=True,
            buffer_size=max(cfg.replay.buffer_size, 2 * b, bs)))
    with _REC.span("bench.build"):
        exp = Experiment.build(cfg)
        ts = exp.init_train_state(0)
        # un-donated: the timed dispatches re-run on the same warmed state
        superstep = exp.superstep_program(k)
    keys = jax.random.split(jax.random.PRNGKey(7), k)
    t_len = cfg.env_args.episode_limit
    # warm dispatch (compile + ring fill: k·b episodes) so the timed
    # dispatches exercise the train branch of the gate
    with _REC.span("bench.compile", k=k):
        ts, _, _ = superstep(ts, keys, jnp.zeros((), jnp.int32))
        gate_open = int(jax.device_get(ts.buffer.episodes_in_buffer)) >= bs

    with _REC.span("bench.measure", k=k):
        dt = _time(lambda: superstep(
            ts, keys, jnp.asarray(1000, jnp.int32))[1].epsilon[-1])
    env_steps = k * b * t_len
    rate = env_steps / dt
    print(f"# superstep K={k}: {dt * 1e3:.1f} ms/dispatch for {env_steps} "
          f"env-steps + {k if gate_open else 0} train iters "
          f"({b} envs x {t_len} slots, train batch {bs})", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "env_steps_per_sec",
        "value": round(rate, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": round(rate / 50_000.0, 3),
        "superstep": k,
        "config": (None if args.smoke or args.envs or args.steps
                   else args.config),
        "n_envs": b,
        "episode_steps": t_len,
        "train_batch_episodes": bs,
        "train_gate_open": gate_open,
        "dispatch_s": round(dt, 4),
    })))
    return 0


def bench_population(cfg, _time, args, dp=None) -> int:
    """``--population P``: the graftpop experiment-throughput leg
    (docs/POPULATION.md). ONE vmapped population superstep advances P
    seed variants per dispatch (``run.Experiment.
    population_superstep_program``); the A/B baseline is the SAME P
    experiments run SERIALIZED — P sequential solo superstep dispatches
    — which is exactly how the 16-AGV campaigns in git history burned
    wall-clock. Headline: ``experiments_per_sec`` = experiment·train-
    iters/s (P × per-dispatch iters / dispatch seconds); the record
    carries both rates and ``population_speedup``.

    Graftlattice compositions (docs/PERF.md §lattice):

    * ``--kernels pallas|xla`` selects the attention-kernel mode for
      BOTH sides of the A/B (vmap-over-pallas: the member axis vmaps
      over the fused flash kernels; dense acting forced like the
      ``--kernels`` leg);
    * ``dp=N`` (the ``--lattice`` matrix's population-over-dp sub-leg)
      shards the LEADING member axis over an N-device mesh
      (``parallel.population_shardings``) while the serialized baseline
      stays single-device."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from t2omca_tpu import population as graftpop
    from t2omca_tpu.config import PopulationConfig
    from t2omca_tpu.run import Experiment

    p = args.population
    mode = getattr(args, "kernels", None)
    if mode is not None:
        from t2omca_tpu.config import KernelsConfig
        # dense acting: the kernel switch selects the program the dense
        # rollout/learner unroll dispatches (bench_kernels docstring);
        # the population axis vmaps OVER the flash kernels
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, use_qslice=False),
            kernels=KernelsConfig(attention=mode))
    leg = ("population" if mode is None and not dp
           else f"population-{mode or f'dp{dp}'}")
    k = 1                      # iters per dispatch: the speedup under
    # measurement is the population axis, not the superstep scan
    bs = 4 if args.smoke else 32
    if args.smoke and not args.envs and not args.steps:
        # the population smoke point: a deliberately dispatch-overhead-
        # dominated workload (2 lanes × 2 slots) — the regime the axon
        # tunnel's ~0.66 s/dispatch puts EVERY TPU workload in, and the
        # one where the member-axis amortization is measurable on a
        # CPU host at all (at CPU compute-bound scales the 2-core box
        # caps the win near 1.5x; pass --envs/--steps to measure those)
        cfg = cfg.replace(
            batch_size_run=2,
            env_args=dataclasses.replace(cfg.env_args, episode_limit=2))
    b = cfg.batch_size_run
    base = cfg.replace(
        batch_size=bs,
        replay=dataclasses.replace(
            cfg.replay, prioritized=True,
            buffer_size=max(cfg.replay.buffer_size, 2 * b, bs)))
    pop_cfg = base.replace(population=PopulationConfig(size=p))

    with _REC.span("bench.build", leg=leg):
        exp = Experiment.build(pop_cfg)
        ts, spec = graftpop.init_population(exp, pop_cfg)
        # un-donated: the timed dispatches re-run on the same warm state
        prog = exp.population_superstep_program(k)
        solo_exp = Experiment.build(base)
        solo_ts = solo_exp.init_train_state(0)
        solo_prog = solo_exp.superstep_program(k)
    keys = jnp.stack([jax.random.split(jax.random.PRNGKey(7 + m), k)
                      for m in range(p)])
    t_env = jnp.zeros((), jnp.int32)
    if dp:
        # population-over-dp: the mesh shards the LEADING member axis —
        # whole members per device, no cross-member collectives; the
        # key stack shards with the state so the dispatched program
        # sees the same input shardings as the ratcheted
        # pop_dp_superstep audit twin
        from t2omca_tpu.parallel import make_mesh, population_shardings
        mesh = make_mesh(dp)
        ts = jax.device_put(ts, population_shardings(mesh, ts))
        spec = jax.device_put(spec, population_shardings(mesh, spec))
        keys = jax.device_put(keys, population_shardings(mesh, keys))
    # enough warm dispatches to FILL the ring past the train batch (each
    # inserts k·b episodes), so the timed dispatches exercise the train
    # branch of the gate in both modes — a fixed warm count would leave
    # the gate closed at small --envs and time two different workloads
    # (the vmapped select still executes-and-discards the train branch;
    # the solo scalar cond genuinely skips it)
    warm = max(2, -(-bs // (k * b)) + 1)
    with _REC.span("bench.compile", leg=leg, p=p, warm=warm):
        for _ in range(warm):
            ts, _, _ = prog(ts, keys, t_env, spec)
            solo_ts, _, _ = solo_prog(solo_ts, keys[0], t_env)
        gate_open = bool(jax.device_get(
            exp.buffer.can_sample(
                jax.tree.map(lambda x: x[0], ts.buffer), bs)))
    if not gate_open:
        print("# population: train gate CLOSED after warm-up — record "
              "measures rollout+insert only", file=sys.stderr)

    t1k = jnp.asarray(1000, jnp.int32)
    with _REC.span("bench.measure", leg=leg, mode="vmapped"):
        dt_pop = _time(
            lambda: prog(ts, keys, t1k, spec)[1].epsilon[-1, -1])

    def _serial():
        # the serialized A/B: the SAME P experiments as P SEPARATE
        # sequential campaigns — which is what "running seeds serially"
        # means: each run's driver loop syncs at its own cadences and
        # two different processes' dispatches never overlap, so each
        # solo dispatch is fetched before the next begins (state reuse
        # is fine — this times dispatches, not learning)
        out = None
        for m in range(p):
            out = solo_prog(solo_ts, keys[m], t1k)[1].epsilon[-1]
            _sync(out)
        return out
    with _REC.span("bench.measure", leg=leg, mode="serialized"):
        dt_serial = _time(_serial)

    pop_rate = p * k / dt_pop
    serial_rate = p * k / dt_serial
    speedup = dt_serial / dt_pop
    combo = ("" if mode is None and not dp else
             f" × {f'kernels={mode}' if mode else f'dp={dp}'}")
    print(f"# population P={p}{combo}: {dt_pop * 1e3:.1f} ms/dispatch "
          f"vmapped vs {dt_serial * 1e3:.1f} ms for {p} serialized solo "
          f"dispatches ({speedup:.2f}x; {b} envs, train batch {bs}, "
          f"gate {'open' if gate_open else 'CLOSED'})", file=sys.stderr)
    rec = {
        "metric": "experiments_per_sec",
        "value": round(pop_rate, 2),
        "unit": (f"experiment-train-iters/s/{dp}-device-mesh" if dp
                 else "experiment-train-iters/s/chip"),
        "vs_baseline": None,
        "population": p,
        "serialized_experiments_per_sec": round(serial_rate, 2),
        "population_speedup": round(speedup, 3),
        "config": (None if args.smoke or args.envs or args.steps
                   else args.config),
        "n_envs": b,
        "episode_steps": cfg.env_args.episode_limit,
        "train_batch_episodes": bs,
        "train_gate_open": gate_open,
        "dispatch_s": round(dt_pop, 4),
        "serialized_dispatch_s": round(dt_serial, 4),
    }
    # graftlattice composition identity (absent on the plain leg so its
    # record shape is unchanged)
    if mode is not None:
        rec["kernels"] = mode
    if dp:
        rec["dp"] = dp
    print(json.dumps(_finalize(rec)), flush=True)
    return 0


def bench_population_sebulba(cfg, _time, args) -> int:
    """``--population P --sebulba``: graftlattice's population × Sebulba
    lockstep leg (docs/POPULATION.md §composition). The vmapped
    population learner runs BEHIND the device-resident trajectory queue
    on a 1+1 device split in lockstep (``queue_slots=1, staleness=0`` —
    the only legal pop × sebulba regime, config.sanity_check), measured
    four ways in ONE record:

    * **population-classic** (context) — the fused vmapped population
      superstep on a single device, async-chained with one terminal
      sync: the shape ``--population`` alone measures. The fused
      program strictly serializes rollout → train inside each dispatch,
      so ``lockstep_vs_classic`` >= 1 exactly when the split's compute
      overlap beats its queue/copy/publish cost — which requires >= 2
      host cores (two CPU devices on a 1-core host time-slice one
      core; the record's ``host_cores`` field says which regime was
      measured);
    * **serial-solo** (context) — the same P experiments as P separate
      classic solo campaigns run serially, each dispatch fetched before
      the next: the pre-graftlattice baseline the compounded
      population x overlap win divides against
      (``lockstep_vs_serial_solo``);
    * **serialized** — the split pipeline run strictly phase-by-phase,
      every stage blocked: the A/B floor that isolates what overlap
      buys (``overlap_speedup``);
    * **lockstep** (headline) — the production coordination
      (``run.run_sebulba``): the actor thread's rollout ``i+1``
      dispatches as soon as train ``i`` is ENQUEUED, so rollout
      executes on the actor device while train executes on the learner
      device — lockstep ordering (bit-parity with classic) with the
      two stages' COMPUTE overlapped across the split.

    env-steps are counted identically for all four legs (``k·B·T·P``).
    Needs ≥ 2 devices (``--smoke`` forces 2 CPU host devices via the
    ``--sebulba`` pre-import path)."""
    import dataclasses
    import threading

    import jax
    import jax.numpy as jnp

    from t2omca_tpu import population as graftpop
    from t2omca_tpu.config import PopulationConfig, SebulbaConfig
    from t2omca_tpu.run import Experiment

    p = args.population
    k = max(2 * args.iters, 6)
    bs = 4 if args.smoke else 32
    b, t_len = cfg.batch_size_run, cfg.env_args.episode_limit
    env_steps = k * b * t_len * p
    base = cfg.replace(
        batch_size=bs,
        population=PopulationConfig(size=p),
        replay=dataclasses.replace(
            cfg.replay, prioritized=True,
            buffer_size=max(cfg.replay.buffer_size, 2 * b, bs)))

    def _keys(i):
        # per-member (P, 2) key column — the stacked shape the
        # population learner step takes
        return jnp.stack([jax.random.fold_in(jax.random.PRNGKey(7 + m), i)
                          for m in range(p)])

    # ---- population-classic context: one device, fused vmapped pop
    # superstep, async-chained ----------------------------------------
    with _REC.span("bench.build", leg="pop-sebulba-classic"):
        exp = Experiment.build(base)
        ts, spec = graftpop.init_population(exp, base)
        # un-donated: rebinding keeps the warm state reusable
        prog = exp.population_superstep_program(1)
    # fill the ring past the train batch so every timed iteration takes
    # the train branch in ALL legs (same warm discipline as
    # bench_population)
    warm = max(2, -(-bs // b) + 1)
    with _REC.span("bench.compile", leg="pop-sebulba-classic", warm=warm):
        for i in range(warm):
            ts, stats, _ = prog(ts, _keys(900 + i)[:, None, :],
                                jnp.asarray(0, jnp.int32), spec)
        _sync(stats.epsilon[-1, -1])
    ckeys = [_keys(1000 + i)[:, None, :] for i in range(k)]
    t1k = jnp.asarray(1000, jnp.int32)
    with _REC.span("bench.measure", leg="pop-sebulba-classic"):
        t0 = time.perf_counter()
        for i in range(k):
            ts, stats, _ = prog(ts, ckeys[i], t1k, spec)
        _sync(stats.epsilon[-1, -1])
        dt_classic = time.perf_counter() - t0
    rate_classic = env_steps / dt_classic
    print(f"# pop x sebulba classic (1 device, fused vmapped superstep, "
          f"P={p}): {dt_classic * 1e3:.1f} ms for {env_steps} env-steps "
          f"+ {k} train iters/member -> {rate_classic:,.0f} env-steps/s",
          file=sys.stderr)
    del ts, spec, prog, exp

    # ---- serial-solo context: the pre-graftlattice campaign reality —
    # the SAME P experiments as P separate classic solo runs, one after
    # the other (bench_population's serialized A/B; the denominator the
    # ISSUE's compounded-smoke story multiplies against)
    solo_cfg = cfg.replace(
        batch_size=bs,
        replay=dataclasses.replace(
            cfg.replay, prioritized=True,
            buffer_size=max(cfg.replay.buffer_size, 2 * b, bs)))
    with _REC.span("bench.build", leg="pop-sebulba-solo"):
        solo_exp = Experiment.build(solo_cfg)
        solo_ts = solo_exp.init_train_state(0)
        solo_prog = solo_exp.superstep_program(1)
    with _REC.span("bench.compile", leg="pop-sebulba-solo", warm=warm):
        for i in range(warm):
            solo_ts, sstats, _ = solo_prog(
                solo_ts, jax.random.split(jax.random.PRNGKey(900 + i), 1),
                jnp.asarray(0, jnp.int32))
        _sync(sstats.epsilon[-1])
    solo_keys = [jax.random.split(
        jax.random.fold_in(jax.random.PRNGKey(7 + m), 4000 + i), 1)
        for i in range(k) for m in range(p)]
    with _REC.span("bench.measure", leg="pop-sebulba-solo"):
        t0 = time.perf_counter()
        for sk in solo_keys:
            # separate campaigns never overlap: each solo dispatch is
            # fetched before the next begins (state reuse is fine —
            # this times dispatches, not learning)
            _sync(solo_prog(solo_ts, sk, t1k)[1].epsilon[-1])
        dt_solo = time.perf_counter() - t0
    rate_solo = env_steps / dt_solo
    print(f"# pop x sebulba serial-solo context ({p} separate classic "
          f"campaigns, 1 device): {dt_solo * 1e3:.1f} ms -> "
          f"{rate_solo:,.0f} env-steps/s", file=sys.stderr)
    del solo_ts, solo_prog, solo_exp

    # ---- the lockstep split: 1 actor + 1 learner device ---------------
    from t2omca_tpu.parallel.sebulba import make_sebulba
    seb_cfg = base.replace(sebulba=SebulbaConfig(
        actor_devices=1, learner_devices=1, queue_slots=1, staleness=0))
    with _REC.span("bench.build", leg="pop-sebulba-split"):
        exp2 = Experiment.build(seb_cfg)
        seb = make_sebulba(exp2)
        rs, ls = seb.init_states(0)
        q = seb.init_queue()
    actor_step, queue_put, queue_get, learner_step = seb.programs()
    sb = seb_cfg.sebulba
    slot0 = jnp.asarray(0, jnp.int32)

    with _REC.span("bench.compile", leg="pop-sebulba-split", warm=warm):
        # warm every program once AND fill the ring (put/get round-trips
        # insert k·B episodes per member each)
        params = seb.publish_params(ls.learner.params["agent"])
        for i in range(warm):
            rs, tm, _ = actor_step(params, rs, test_mode=False)
            q = queue_put(q, slot0, seb.to_learner(tm))
            ls, q = queue_get(ls, q, slot0)
        ls, info = learner_step(ls, _keys(999), jnp.asarray(1000))
        _sync(info["loss"][-1])

    skeys = [_keys(3000 + i) for i in range(k)]
    with _REC.span("bench.measure", leg="pop-sebulba-serial"):
        t0 = time.perf_counter()
        params = seb.publish_params(ls.learner.params["agent"])
        jax.block_until_ready(params)
        for i in range(k):
            rs, tm, stats = actor_step(params, rs, test_mode=False)
            jax.block_until_ready(stats.epsilon)
            tm_l = seb.to_learner(tm)
            jax.block_until_ready(tm_l.reward)
            q = queue_put(q, slot0, tm_l)
            ls, q = queue_get(ls, q, slot0)
            ls, info = learner_step(ls, skeys[i], jnp.asarray(3000 + i))
            _sync(info["loss"][-1])
            params = seb.publish_params(ls.learner.params["agent"])
            jax.block_until_ready(params)
        dt_serial = time.perf_counter() - t0
    rate_serial = env_steps / dt_serial
    print(f"# pop x sebulba serialized split (1+1 devices, stage-"
          f"synchronized): {dt_serial * 1e3:.1f} ms -> "
          f"{rate_serial:,.0f} env-steps/s", file=sys.stderr)

    okeys = [_keys(2000 + i) for i in range(k)]
    cond = threading.Condition()
    shared = {"q": q, "params": seb.publish_params(
        ls.learner.params["agent"]), "put": 0, "consumed": 0,
        "error": None}

    def actor(rs=rs):
        try:
            for i in range(k):
                with cond:
                    # lockstep: rollout i+1 may dispatch as soon as
                    # train i is ENQUEUED (consumed advanced) — its
                    # device execution overlaps train i's
                    while (i - shared["consumed"] > sb.staleness
                           or shared["put"] - shared["consumed"]
                           >= sb.queue_slots):
                        cond.wait()
                    params = shared["params"]
                rs, tm, stats = actor_step(params, rs, test_mode=False)
                jax.block_until_ready(stats.epsilon)
                tm_l = seb.to_learner(tm)
                with cond:
                    shared["q"] = queue_put(
                        shared["q"],
                        jnp.asarray(shared["put"] % sb.queue_slots,
                                    jnp.int32), tm_l)
                    shared["put"] += 1
                    cond.notify_all()
        except Exception as e:  # noqa: BLE001 — surfaced by the main leg
            with cond:
                shared["error"] = e
                cond.notify_all()

    with _REC.span("bench.measure", leg="pop-sebulba-lockstep"):
        t0 = time.perf_counter()
        th = threading.Thread(target=actor, daemon=True)
        th.start()
        for i in range(k):
            with cond:
                while shared["put"] <= i and shared["error"] is None:
                    cond.wait()
                if shared["error"] is not None:
                    raise shared["error"]
                ls, shared["q"] = queue_get(
                    ls, shared["q"],
                    jnp.asarray(i % sb.queue_slots, jnp.int32))
            ls, info = learner_step(ls, okeys[i], jnp.asarray(2000 + i))
            with cond:
                shared["params"] = seb.publish_params(
                    ls.learner.params["agent"])
                shared["consumed"] = i + 1
                cond.notify_all()
        _sync(info["loss"][-1])
        dt_lock = time.perf_counter() - t0
        th.join(timeout=30)
    rate_lock = env_steps / dt_lock
    overlap_speedup = rate_lock / rate_serial
    vs_classic = rate_lock / rate_classic
    vs_solo = rate_lock / rate_solo
    print(f"# pop x sebulba lockstep (1+1 devices, queue_slots=1, "
          f"staleness=0, P={p}): {dt_lock * 1e3:.1f} ms -> "
          f"{rate_lock:,.0f} env-steps/s ({overlap_speedup:.2f}x "
          f"serialized, {vs_classic:.2f}x fused population-classic, "
          f"{vs_solo:.2f}x serial solo campaigns)", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "env_steps_per_sec",
        "value": round(rate_lock, 1),
        "unit": "env-steps/s/2-device-split",
        # per-chip semantics like the sebulba record: 2 chips in play
        "vs_baseline": round(rate_lock / 2 / 50_000.0, 3),
        "population": p,
        "sebulba": {"actor_devices": 1, "learner_devices": 1,
                    "queue_slots": sb.queue_slots,
                    "staleness": sb.staleness},
        "serialized_env_steps_per_sec": round(rate_serial, 1),
        "overlap_speedup": round(overlap_speedup, 3),
        # two classic contexts. `lockstep_vs_classic` divides by the
        # fused single-device vmapped population superstep — the shape
        # `--population` alone drives; >= 1 needs the rollout/train
        # compute overlap to beat the split's queue+publish cost, which
        # takes >= 2 host cores (on a 1-core host the two device
        # streams time-slice one core and the copies are pure loss —
        # host_cores says which regime this record measured).
        # `lockstep_vs_serial_solo` divides by the pre-lattice
        # baseline: the same P experiments as P separate classic solo
        # campaigns run serially — the compounded population x overlap
        # win the lattice exists to deliver.
        "population_classic_env_steps_per_sec": round(rate_classic, 1),
        "lockstep_vs_classic": round(vs_classic, 3),
        "serial_solo_env_steps_per_sec": round(rate_solo, 1),
        "lockstep_vs_serial_solo": round(vs_solo, 3),
        "host_cores": os.cpu_count(),
        "config": (None if args.smoke or args.envs or args.steps
                   else args.config),
        "n_envs": b,
        "episode_steps": t_len,
        "train_batch_episodes": bs,
        "chained_iters": k,
        "backend": jax.default_backend(),
    })), flush=True)
    return 0


def bench_lattice(cfg, _time, args) -> int:
    """``--lattice``: the graftlattice composition matrix
    (docs/POPULATION.md §composition) — one schema-1 record per
    newly-legal combo of the population axis with the other graft axes,
    all in one process:

    * population × pallas — the member axis vmapped over the fused
      flash-attention kernels (vmapped vs serialized A/B);
    * population × dp — whole members sharded over a 2-device mesh
      (``parallel.population_shardings``);
    * population × sebulba — the vmapped learner in lockstep behind the
      device-resident queue, vs the fused classic pop superstep.

    ``--population P`` selects the member count (default 4; must be
    even for the 2-device dp sub-leg). Needs ≥ 2 devices (``--smoke``
    forces 2 CPU host devices pre-import)."""
    import argparse as _ap

    def sub(**over):
        ns = _ap.Namespace(**vars(args))
        for key, val in over.items():
            setattr(ns, key, val)
        return ns

    rc = bench_population(cfg, _time, sub(kernels="pallas"))
    rc |= bench_population(cfg, _time, sub(kernels=None), dp=2)
    rc |= bench_population_sebulba(cfg, _time, sub(kernels=None))
    return rc


def bench_train(cfg, _time, args) -> int:
    """``--train``: the learner measurement alone, as the headline line."""
    nums = _train_numbers(cfg, _time, train_bs=4 if args.smoke else 32,
                          pipeline_k=args.pipeline)
    rec = {
        "metric": "train_steps_per_sec",
        "value": nums.pop("train_steps_per_sec"),
        "unit": "train-steps/s/chip",
        "vs_baseline": None,
    }
    rec.update(nums)
    print(json.dumps(_finalize(rec)))
    return 0


def _episode_bytes_analytic(cfg, info, batch: int) -> int:
    """Bytes of ``batch`` stored episodes under the config's storage mode —
    the analytic model behind ``--hbm``, cross-checked against real
    allocated leaf bytes by ``--prod-hbm``."""
    from t2omca_tpu.ops.query_slice import entity_store_eligible

    a = info["n_agents"]
    obs_dim, state_dim = info["obs_shape"], info["state_shape"]
    n_act = info["n_actions"]
    t = cfg.env_args.episode_limit
    f = info["obs_entity_feats"]
    sd = 2 if cfg.replay.store_dtype == "bfloat16" else 4
    if entity_store_eligible(cfg):
        obs = batch * (t + 1) * a * ((f - 1) * 4 + 1 + 2 * f * 4)
    else:
        obs = batch * (t + 1) * a * obs_dim * sd
    state = batch * (t + 1) * state_dim * sd
    avail = batch * (t + 1) * a * n_act
    small = batch * t * (a * 4 + 4 + 1 + 1)
    return obs + state + avail + small


def bench_hbm(cfg, args) -> int:
    """``--hbm``: analytic device-memory budget for a config — sizes the
    dominant residents (replay ring, in-flight episode batch, learner scan
    residuals) from shapes alone, so OOM surprises are caught before a
    chip run. Estimates, not measurements: XLA adds workspace and
    fragmentation on top."""
    from t2omca_tpu.envs.registry import make_env
    from t2omca_tpu.ops.query_slice import entity_store_eligible

    env = make_env(cfg.env_args)
    info = env.get_env_info()
    a = info["n_agents"]
    t = cfg.env_args.episode_limit
    cd = 2 if cfg.model.dtype == "bfloat16" else 4
    compact = entity_store_eligible(cfg)

    def episode_bytes(batch):
        return _episode_bytes_analytic(cfg, info, batch)

    ring = episode_bytes(cfg.replay.buffer_size)
    rollout_batch = episode_bytes(cfg.batch_size_run)
    train_batch = episode_bytes(cfg.batch_size)

    # learner backward residuals: per timestep each unrolled forward keeps
    # O(tokens · emb) activations per block for the VJP unless remat is on
    emb = cfg.model.emb
    tokens_agent = 2 if compact else (a + 1)   # entity tables: folded rows
    act_per_step = (cfg.batch_size * a * tokens_agent * emb * cd
                    * cfg.model.depth * (2 + cfg.model.ff_hidden_mult))
    mixer_tokens = a + 3 + info["n_entities"]
    mix_per_step = (cfg.batch_size * mixer_tokens * cfg.model.mixer_emb * cd
                    * cfg.model.mixer_depth * (2 + cfg.model.ff_hidden_mult))
    residuals = (t + 1) * (act_per_step + mix_per_step)
    if cfg.model.remat:
        residuals = act_per_step + mix_per_step   # one step live at a time

    rows = {
        "replay_ring": ring,
        # ×3: the async driver loop bounds dispatch run-ahead at 2, so up
        # to 3 episode batches can be live at once (run.run_sequential) —
        # an upper bound; cadence barriers (B·T ≥ log intervals at the
        # large configs) usually keep fewer in flight
        "rollout_episode_batch": 3 * rollout_batch,
        "train_episode_batch": train_batch,
        "learner_scan_residuals": residuals,
    }
    total = sum(rows.values())
    gib = 1024 ** 3
    for k, v in rows.items():
        print(f"# {k:24s} {v / gib:8.3f} GiB", file=sys.stderr)
    print(f"# {'total (est.)':24s} {total / gib:8.3f} GiB "
          f"(storage={'compact' if compact else 'dense'}, "
          f"remat={'on' if cfg.model.remat else 'off'}; excludes XLA "
          f"workspace/fragmentation)", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "hbm_estimate_gib",
        "value": round(total / gib, 3),
        "unit": "GiB",
        "vs_baseline": None,
        "config": None if args.envs or args.steps else args.config,
        "breakdown_gib": {k: round(v / gib, 3) for k, v in rows.items()},
    })))
    return 0


def bench_prod_hbm(cfg) -> int:
    """``--prod-hbm``: config-5 at PRODUCTION storage scale, actually
    allocated (VERDICT r4 item 4). Unlike ``--config 5`` (which shrinks
    the ring to ~2x batch for timing) this builds the
    ``configs/config5_dp8.yaml`` replay ring — 16384 episodes x T=150,
    bf16 compact storage — as real arrays sharded over the DP=8 mesh,
    inserts a rollout's episodes, and runs one full-horizon train
    iteration (PER sample -> T=150 learner scan -> priorities) with the
    ring co-resident, under the production donation contract (in-place
    ring/state, no 2x transient). Reports the MEASURED resident bytes of
    the ring next to the ``--hbm`` analytic for the same shapes — the
    cross-check that keeps the analytic honest.

    Two honest reductions on a non-chip host (both recorded in the
    emitted JSON): the fill rollout runs ``--envs`` lanes (default 64,
    not 8192 — the in-flight 8192-lane batch stays analytic), and the
    learner compute dtype is f32 (CPU bf16 is emulated and ~50x slower;
    f32 residuals UPPER-bound the production bf16 ones). Storage stays
    production bf16 either way."""
    import jax
    import jax.numpy as jnp

    from t2omca_tpu.parallel import DataParallel, make_mesh
    from t2omca_tpu.run import Experiment

    n_dev = 8
    with _REC.span("bench.build", leg="prod_hbm"):
        exp = Experiment.build(cfg)
        mesh = make_mesh(n_dev)
        dp = DataParallel(exp, mesh)
        # born-sharded init: shard(init_train_state(0)) holds TWO copies
        # of the ring during the device_put (the measured OOM at
        # ring=16384 on a 125 GiB host — and the same 2x transient a
        # real slice would pay)
        ts = dp.init_sharded(0)
    # production contract: ring donated to insert, state to train_iter
    rollout, insert, train_iter = dp.jitted_programs(donate=True)

    def tree_bytes(tree):
        return sum(x.nbytes for x in jax.tree.leaves(tree)
                   if hasattr(x, "nbytes"))

    gib = 1024 ** 3
    ring_meas = tree_bytes(ts.buffer.storage)
    ring_total = tree_bytes(ts.buffer)          # + PER priorities etc.
    info = exp.env.get_env_info()
    ring_analytic = _episode_bytes_analytic(cfg, info,
                                            cfg.replay.buffer_size)
    print(f"# ring allocated: {ring_meas / gib:.3f} GiB storage "
          f"({ring_total / gib:.3f} with PER state) over {n_dev} devices "
          f"= {ring_total / n_dev / gib:.3f}/device; analytic "
          f"{ring_analytic / gib:.3f} GiB "
          f"({(ring_meas / ring_analytic - 1) * 100:+.1f}%)",
          file=sys.stderr)

    params = ts.learner.params["agent"]
    t0 = time.perf_counter()
    rs, batch, _ = rollout(params, ts.runner, test_mode=False)
    jax.block_until_ready(jax.tree.leaves(batch.reward)[0])
    t_roll = time.perf_counter() - t0
    batch_meas = tree_bytes(batch)
    pre_insert_ring = jax.tree.leaves(ts.buffer.storage)
    ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                    episode=ts.episode + cfg.batch_size_run)
    # donation proof, not shape arithmetic: the donated input buffers must
    # actually be consumed (no 2x-ring transient) — .nbytes comparisons
    # would pass either way
    assert all(x.is_deleted() for x in pre_insert_ring
               if isinstance(x, jax.Array)), \
        "insert must consume (donate) the ring"

    pre_train_ring = jax.tree.leaves(ts.buffer.storage)
    t0 = time.perf_counter()
    ts, tinfo = train_iter(ts, jax.random.PRNGKey(7), jnp.asarray(1000))
    loss = float(jax.device_get(tinfo["loss"]))
    t_train = time.perf_counter() - t0
    assert jnp.isfinite(loss), "train iteration on the production ring"
    assert all(x.is_deleted() for x in pre_train_ring
               if isinstance(x, jax.Array)), \
        "train_iter must consume (donate) the train state"
    ring_after = tree_bytes(ts.buffer.storage)
    assert ring_after == ring_meas, "ring layout changed across train"
    print(f"# fill rollout ({cfg.batch_size_run} lanes x "
          f"{cfg.env_args.episode_limit} steps): {t_roll:.1f}s; train "
          f"iteration (batch {cfg.batch_size}, T="
          f"{cfg.env_args.episode_limit}, remat="
          f"{'on' if cfg.model.remat else 'off'}): {t_train:.1f}s, "
          f"loss {loss:.4f}", file=sys.stderr)

    # the one resident NOT allocated here: the 8192-lane in-flight batch
    prod_envs = 8192
    batch_analytic = _episode_bytes_analytic(cfg, info, prod_envs)
    rec = {
        "metric": "prod_ring_resident_gib",
        "value": round(ring_total / gib, 3),
        "unit": "GiB-allocated",
        "vs_baseline": None,
        "config": 5,
        "ring_episodes": cfg.replay.buffer_size,
        "per_device_gib": round(ring_total / n_dev / gib, 4),
        "analytic_gib": round(ring_analytic / gib, 3),
        "analytic_delta_pct": round((ring_meas / ring_analytic - 1) * 100,
                                    1),
        "fill_batch_gib": round(batch_meas / gib, 4),
        "fill_envs": cfg.batch_size_run,
        "train_step_s": round(t_train, 1),
        "train_loss": round(loss, 5),
        "remat": bool(cfg.model.remat),
        "compute_dtype": cfg.model.dtype,
        "prng": jax.config.jax_default_prng_impl,
        # analytic-only leg, stated as such:
        "rollout_batch_8192_analytic_gib": round(batch_analytic / gib, 3),
    }
    print(json.dumps(_finalize(rec)))
    return 0


def bench_serve(args) -> int:
    """``--serve``: the serving-path measurement (ROADMAP item 5).

    Loads an exported artifact (``python -m t2omca_tpu.serve export``)
    through the production front-end and measures what traffic sees:

    * **p50/p99 decision latency** — per-request wall time of
      ``ServeFrontend.select`` over a deterministic ragged request
      schedule that crosses every bucket boundary (size 1, each bucket,
      each bucket's boundary+1 — the worst padding waste points);
    * **decisions/s/chip** — steady-state agent-decisions per second at
      the largest bucket with the hidden state carried between requests
      (the recurrent-policy serving loop).

    One BENCH-style JSON line; a failure anywhere still emits the
    partial record with the open phase + flight tail (``main_flight``),
    like every training leg. The record carries the live backend —
    a ``--smoke`` (CPU-pinned) serve measurement can never masquerade
    as a chip number."""
    import jax

    from t2omca_tpu.serve.frontend import ServeFrontend

    with _REC.span("bench.build", leg="serve"):
        fe = ServeFrontend.load(args.artifact, dtype=args.serve_dtype,
                                rec=_REC)
    a, d, na = fe.n_agents, fe.obs_dim, fe.n_actions
    rng = np.random.default_rng(0)

    def request(n):
        obs = rng.standard_normal((n, a, d)).astype(np.float32)
        avail = rng.random((n, a, na)) < 0.7
        avail[..., 0] = True            # every agent keeps a legal action
        return obs, avail

    with _REC.span("bench.compile", leg="serve"):
        fe.warmup()                     # one dispatch per bucket

    # ragged schedule crossing every bucket boundary (dedup, sorted)
    sizes = sorted({1, *fe.buckets,
                    *(b + 1 for b in fe.buckets[:-1])})
    reqs = {n: request(n) for n in sizes}
    # enough samples for an honest p99 tail
    reps = max(args.iters, -(-100 // len(sizes)))
    lat_ms = []
    with _REC.span("bench.measure", leg="serve"):
        for _ in range(reps):
            for n in sizes:
                obs, avail = reqs[n]
                t0 = time.perf_counter()
                fe.select(obs, avail)
                lat_ms.append((time.perf_counter() - t0) * 1e3)
        p50, p99 = np.percentile(lat_ms, [50, 99])

        # throughput leg: hidden-carried steady state at the max bucket
        bmax = fe.buckets[-1]
        obs, avail = reqs[bmax]
        _, hidden = fe.select(obs, avail)          # extra warm, fresh h
        k = max(3 * args.iters, 10)
        t0 = time.perf_counter()
        for _ in range(k):
            actions, hidden = fe.select(obs, avail, hidden)
        dt = time.perf_counter() - t0
    decisions = k * bmax * a / dt
    print(f"# serve latency over {len(lat_ms)} requests "
          f"(sizes {sizes}): p50 {p50:.2f} ms, p99 {p99:.2f} ms",
          file=sys.stderr)
    print(f"# serve throughput at bucket {bmax}: "
          f"{decisions:,.0f} decisions/s ({a} agents/request, "
          f"hidden carried)", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "serve_decisions_per_sec",
        "value": round(decisions, 1),
        "unit": "decisions/s/chip",
        "vs_baseline": None,
        "p50_ms": round(float(p50), 3),
        "p99_ms": round(float(p99), 3),
        "latency_samples": len(lat_ms),
        "request_sizes": sizes,
        "buckets": fe.buckets,
        "n_agents": a,
        "dtype": args.serve_dtype,
        "backend": jax.default_backend(),
        "artifact": args.artifact,
        "checkpoint_t_env": fe.meta.get("checkpoint", {}).get("t_env"),
    })))
    return 0


def bench_serve_chaos(args) -> int:
    """``--serve --chaos``: the fleet-under-fire measurement (ROADMAP
    item 4 — "p99-under-burst as a ratcheted number instead of a
    hope").

    Drives a :class:`~t2omca_tpu.serve.fleet.ServeFleet` of
    ``--fleet-engines`` share-nothing engines with **bursty
    heavy-tailed open-loop traffic** (Pareto-tailed request sizes;
    exponential arrivals whose rate steps up 5x inside burst windows;
    open-loop = requests are submitted on the clock whether or not
    earlier ones completed — the only honest way to measure shedding)
    while a **fault schedule** runs underneath:

    * engine 0 killed mid-burst (injected non-transient dispatch fault
      → quarantine, bounce, backoff restart, rejoin);
    * one injected dispatch hang on a peer engine (watchdog stall →
      hedge + quarantine);
    * one poisoned hot refresh (nonexistent checkpoint → must be
      REFUSED while serving continues).

    One BENCH-style JSON record: p50/p99 under burst (the ratchet
    value is the p99), shed fraction, engine recovery time, hedge and
    stall counters, the refresh outcome — and ``unresolved``, which a
    correct fleet keeps at exactly 0 (every admitted request completes
    or resolves with an explicit SHED/deadline/error status)."""
    import jax

    from t2omca_tpu.serve.fleet import FleetConfig, ServeFleet
    from t2omca_tpu.utils import resilience

    duration = float(args.chaos_seconds)
    n_eng = int(args.fleet_engines)
    fcfg = FleetConfig(
        queue_depth=32,
        deadline_s=max(2.0, duration / 2.5),
        dispatch_timeout_s=max(0.75, min(2.0, duration / 6.0)),
        restart_backoff_s=0.05, restart_backoff_max_s=0.5,
        ladder_cooldown_s=0.25,
    )
    with _REC.span("bench.build", leg="serve-chaos"):
        fleet = ServeFleet(args.artifact, n_engines=n_eng,
                           dtype=args.serve_dtype, cfg=fcfg,
                           rec=_REC).start()
    try:
        if fleet.serving_engines() == 0:
            st = fleet.stats()
            raise RuntimeError(
                f"no fleet engine reached serving: {st['engines']}")
        with _REC.span("bench.compile", leg="serve-chaos"):
            fleet.warmup()

        fe0 = fleet.engines[0].fe
        a, d, na = fe0.n_agents, fe0.obs_dim, fe0.n_actions
        bmax = fe0.buckets[-1]
        rng = np.random.default_rng(0)

        # request pool: heavy-tailed sizes (Pareto tail past the max
        # bucket exercises the chunking path), one pre-built request
        # per distinct size so the open-loop submitter costs ~nothing
        sizes = np.minimum(1 + rng.pareto(1.1, 4096).astype(np.int64),
                           2 * bmax)
        pool = {}
        for n in np.unique(sizes):
            n = int(n)
            obs = rng.standard_normal((n, a, d)).astype(np.float32)
            avail = rng.random((n, a, na)) < 0.7
            avail[..., 0] = True
            pool[n] = (obs, avail)

        # fault schedule (one-shot each, on the fleet's own chaos hooks)
        kill_at = 0.25 * duration
        refresh_at = 0.40 * duration
        hang_at = 0.55 * duration
        hang_s = fcfg.dispatch_timeout_s + min(1.5, 0.2 * duration)
        hang_engine = 1 % n_eng
        t0 = time.monotonic()
        killed, hung = [], []

        def _fault_schedule(engine, attempt, rid, **kw):
            now = time.monotonic() - t0
            if engine == 0 and not killed and now >= kill_at:
                killed.append(now)
                raise RuntimeError("chaos: engine killed (injected)")
            if engine == hang_engine and not hung and now >= hang_at:
                hung.append(now)
                time.sleep(hang_s)

        resilience.register_fault("fleet.dispatch", _fault_schedule)

        refresh_out = {}

        def _poisoned_refresh():
            refresh_out.update(fleet.refresh(
                os.path.join(args.artifact, "_no_such_checkpoint")))

        poison = threading.Timer(refresh_at, _poisoned_refresh)
        poison.daemon = True
        poison.start()

        # bursty open-loop arrivals: base rate sized to the measured
        # warm dispatch so CPU and TPU runs both saturate in bursts
        t_warm0 = time.perf_counter()
        fleet.select(*pool[min(pool)])
        warm_s = max(time.perf_counter() - t_warm0, 1e-4)
        base_rate = max(10.0, min(200.0, 1.5 * n_eng / warm_s))
        bursts = [(0.2 * duration, 0.3 * duration),
                  (0.5 * duration, 0.65 * duration),
                  (0.8 * duration, 0.9 * duration)]

        def rate_at(t):
            burst = any(lo <= t < hi for lo, hi in bursts)
            return base_rate * (5.0 if burst else 1.0)

        requests = []
        with _REC.span("bench.chaos", leg="serve-chaos"):
            t = 0.0
            i = 0
            while t < duration:
                now = time.monotonic() - t0
                if now < t:
                    time.sleep(min(t - now, 0.05))
                    continue
                n = int(sizes[i % len(sizes)])
                requests.append(fleet.submit(*pool[n]))
                i += 1
                t += rng.exponential(1.0 / rate_at(t))
            # drain: every admitted request must resolve (completion,
            # SHED, deadline or error) — the supervisor's deadline
            # sweep bounds this wait
            results = [r.wait(timeout=fcfg.deadline_s + 2.0)
                       for r in requests]
        poison.join(timeout=30.0)
    finally:
        resilience.clear_faults("fleet.dispatch")
        stats = fleet.stats()
        fleet.stop()

    by = {}
    for r in results:
        by[r.status] = by.get(r.status, 0) + 1
    ok_lat = sorted(r.latency_ms for r in results if r.ok)
    unresolved = sum(1 for r in results
                     if r.status == "error"
                     and "unresolved" in (r.error or ""))
    p50 = p99 = None
    if ok_lat:
        p50, p99 = np.percentile(ok_lat, [50, 99])
    recov = stats["recoveries_s"]
    shed_fraction = by.get("shed", 0) / max(len(results), 1)
    print(f"# chaos traffic: {len(results)} requests over "
          f"{duration:.1f}s ({base_rate:.0f}/s base, 5x bursts) — "
          f"{by.get('ok', 0)} ok, {by.get('shed', 0)} shed, "
          f"{by.get('deadline', 0)} deadline, {by.get('error', 0)} "
          f"error, {unresolved} unresolved", file=sys.stderr)
    print(f"# faults: kill@{killed[0] if killed else None}s "
          f"hang@{hung[0] if hung else None}s "
          f"refresh={refresh_out.get('status')} "
          f"recoveries={recov} "
          f"serving_end={stats['serving']}/{n_eng}", file=sys.stderr)
    print(json.dumps(_finalize({
        "metric": "serve_chaos_p99_ms",
        "value": round(float(p99), 3) if p99 is not None else None,
        "unit": "ms",
        "vs_baseline": None,
        "p50_ms": round(float(p50), 3) if p50 is not None else None,
        "p99_ms": round(float(p99), 3) if p99 is not None else None,
        "requests": len(results),
        "ok": by.get("ok", 0),
        "shed": by.get("shed", 0),
        "deadline": by.get("deadline", 0),
        "errors": by.get("error", 0),
        "unresolved": unresolved,
        "shed_fraction": round(shed_fraction, 4),
        "recovery_s": (round(max(recov), 3) if recov else None),
        "recoveries_s": recov,
        "hedges": stats.get("fleet_hedges_total", 0),
        "stalls": stats.get("fleet_stalls_total", 0),
        "engine_restarts": stats.get("fleet_restarts_total", 0),
        "ejected": stats.get("fleet_ejected_total", 0),
        "ladder_level_end": stats.get("ladder_level", 0),
        "refresh": refresh_out or None,
        "engines": n_eng,
        "engines_serving_end": stats["serving"],
        "duration_s": duration,
        "base_rate_rps": round(base_rate, 1),
        "dtype": args.serve_dtype,
        "backend": jax.default_backend(),
        "artifact": args.artifact,
    }), default=repr))
    return 0


def bench_all(make_cfg, _time, _pipe_rate, args) -> int:
    """``--all``: the full single-chip measurement set in ONE process —
    one backend init total, for tunnel-scarce conditions (BASELINE.md
    axon note). Emits one JSON line per measurement, most important
    first, so a mid-run death still leaves the headline on stdout."""
    import gc

    import jax

    from t2omca_tpu.run import Experiment

    def emit(rec):
        # cumulative per-phase summary (leg meta distinguishes the
        # sub-benches in the span stream; the summary aggregates)
        print(json.dumps(_finalize(rec)), flush=True)

    def rollout_rate(cfg, label, extra=None):
        # each leg carries its own spans (leg=<label> meta); the
        # records embed the CUMULATIVE summary, so a wedge in any leg
        # still leaves the earlier legs' phase timings on record
        with _REC.span("bench.build", leg=label):
            exp = Experiment.build(cfg)
            ts = exp.init_train_state(0)
        rollout = jax.jit(exp.runner.run, static_argnames="test_mode")
        params = ts.learner.params["agent"]
        with _REC.span("bench.compile", leg=label):
            rs, batch, _ = rollout(params, ts.runner, test_mode=False)

        def one():
            _, b, _ = rollout(params, rs, test_mode=False)
            return b.reward[0, 0]

        with _REC.span("bench.measure", leg=label):
            dt = _time(one)
        env_steps = cfg.batch_size_run * cfg.env_args.episode_limit
        rec = {
            "metric": "env_steps_per_sec",
            "value": round(env_steps / dt, 1),
            "unit": "env-steps/s/chip",
            "vs_baseline": round(env_steps / dt / 50_000.0, 3),
            "acting": label,
            "n_envs": cfg.batch_size_run,
            "episode_steps": cfg.env_args.episode_limit,
        }

        if args.pipeline:
            rec["pipelined_env_steps_per_sec"] = _pipe_rate(
                rollout, params, rs, env_steps, args.pipeline)
        if jax.config.jax_default_prng_impl != "threefry2x32":
            # read back the live impl, not the flag echo — a broken
            # switch must not be misattributed as an rbg measurement
            rec["prng"] = jax.config.jax_default_prng_impl
        if extra:
            rec.update(extra)
        return rec

    # only claim a BASELINE scale point when unmodified
    cid = lambda n: None if args.envs or args.steps else n

    # 1. headline: config 3, production acting path, both metric halves
    cfg3 = make_cfg("qslice", 3)
    rec = rollout_rate(cfg3, "entity/qslice", {"config": cid(3)})
    try:
        rec.update(_train_numbers(cfg3, _time,
                                  pipeline_k=args.pipeline))
    except Exception as e:                  # pragma: no cover - defensive
        print(f"# train half failed: {e!r}", file=sys.stderr)
    emit(rec)
    gc.collect()

    # 2. config 4 train scale (PER + 4096 envs interleave)
    try:
        cfg4 = make_cfg("qslice", 4)
        nums = _train_numbers(cfg4, _time, pipeline_k=args.pipeline)
        rec4 = {"metric": "train_steps_per_sec",
                "value": nums.pop("train_steps_per_sec"),
                "unit": "train-steps/s/chip", "vs_baseline": None,
                "config": cid(4)}
        rec4.update(nums)
        emit(rec4)
    except Exception as e:                  # pragma: no cover - defensive
        print(f"# config-4 train failed: {e!r}", file=sys.stderr)
    gc.collect()

    # 3. acting-path comparison at config 3 (dense = the XLA full
    #    forward; the Pallas kernel was deleted in round 5 — BASELINE.md)
    try:
        emit(rollout_rate(make_cfg("dense", 3), "dense",
                          {"config": cid(3)}))
    except Exception as e:                  # pragma: no cover - defensive
        print(f"# dense rollout failed: {e!r}", file=sys.stderr)
    gc.collect()

    # 3b. PRNG-impl comparison at config 3: leg 1 is the threefry
    #     baseline; rbg routes every draw through the TPU hardware bit
    #     generator (the record carries the live impl)
    try:
        emit(rollout_rate(make_cfg("qslice", 3, prng="rbg"),
                          "entity/qslice", {"config": cid(3)}))
    except Exception as e:                  # pragma: no cover - defensive
        print(f"# rbg rollout failed: {e!r}", file=sys.stderr)
    gc.collect()

    # 4. breakdown attribution at config 3 (its own JSON line)
    try:
        exp = Experiment.build(cfg3)
        breakdown(cfg3, exp, exp.init_train_state(0), _time, args)
    except Exception as e:                  # pragma: no cover - defensive
        print(f"# breakdown failed: {e!r}", file=sys.stderr)
    return 0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _daemon_legs(args) -> list:
    """The daemon's A/B matrix: one (name, child argv) per leg —
    exactly the legs ROADMAP open item 1 names (``--superstep``,
    ``--kernels ab``, ``--sebulba``, plus ``--serve`` when an artifact
    is given). Each leg runs in its own child so per-leg platform
    constraints (sebulba's pre-import XLA_FLAGS, the kernel switch)
    never collide in one process; ``--legs`` subsets the matrix."""
    sm = ["--smoke"] if args.smoke else []
    it = ["--iters", str(args.iters)]
    legs = [
        ("superstep", ["--superstep", "4", *sm, *it]),
        ("kernels", ["--kernels", "ab", *sm, *it]),
        ("sebulba", ["--sebulba", *sm, *it]),
        ("population", ["--population", "4", *sm, *it]),
        ("lattice", ["--lattice", *sm, *it]),
    ]
    if args.artifact:
        legs.append(("serve",
                     ["--serve", "--artifact", args.artifact, *it]))
    if args.legs:
        want = [s.strip() for s in args.legs.split(",") if s.strip()]
        if "serve" in want and not args.artifact:
            raise SystemExit("--legs serve needs --artifact DIR")
        unknown = set(want) - {n for n, _ in legs}
        if unknown:
            raise SystemExit(
                f"--legs: unknown leg(s) {sorted(unknown)}; valid: "
                f"superstep,kernels,sebulba,population,lattice"
                + (",serve" if args.artifact else
                   " (serve needs --artifact)"))
        legs = [(n, a) for n, a in legs if n in want]
    return legs


def _daemon_run_leg(bench_path: str, name: str, argv: list,
                    timeout_s: float, hub) -> tuple:
    """One matrix leg as a child process: a 1 s wait loop publishes
    ``daemon_leg_elapsed_seconds{leg=}`` while the child runs (legs
    print their record only at completion, so stdout is NOT a liveness
    signal — elapsed-vs-leg-timeout is the in-leg wedge signal, while
    the daemon's own ticker thread keeps the beat age honest); stdout
    is streamed for the records, stderr inherited (progress comments
    stay live on the console), kill + reap at the timeout.
    → (records, rc, note)."""
    proc = subprocess.Popen([sys.executable, bench_path, *argv],
                            stdout=subprocess.PIPE, text=True)
    lines: list = []

    def _reader():
        for line in proc.stdout:
            lines.append(line)

    th = threading.Thread(target=_reader, daemon=True,
                          name=f"bench-daemon-{name}")
    th.start()
    note = None
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    try:
        while True:
            try:
                proc.wait(timeout=1.0)
                break
            except subprocess.TimeoutExpired:
                if hub is not None:
                    hub.set("daemon_leg_elapsed_seconds",
                            round(time.monotonic() - t0, 1), leg=name)
                if time.monotonic() >= deadline:
                    note = f"leg killed at its {timeout_s:.0f}s timeout"
                    break
    finally:
        # kill AND reap unconditionally (the probe_backend discipline)
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    th.join(timeout=5.0)
    records = []
    for line in lines:
        line = line.strip()
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
                continue
            except ValueError:
                pass
        if line:
            print(f"# [{name}] {line}", file=sys.stderr)
    return records, proc.returncode, note


def bench_daemon(args) -> int:
    """``--daemon``: the surviving bench (ROADMAP open item 1). Every
    TPU bench since BENCH_r02 died at axon backend init — one probe,
    one death, no record. The daemon instead treats backend init as a
    RETRYABLE phase on the watchdog backoff ladder: probe in a killable
    child, back off (exp + jitter, ``T2OMCA_BENCH_DAEMON_BACKOFF``
    base), and retry until the tunnel opens or the total budget
    (``T2OMCA_BENCH_DAEMON_BUDGET``, default 4 h) runs out — then runs
    the full A/B matrix (``--superstep 4``, ``--kernels ab``,
    ``--sebulba``, ``--serve`` with ``--artifact``) in ONE session,
    each leg a child process, relaying one complete BENCH record per
    leg to stdout as it lands (a late wedge still leaves every earlier
    leg's record). ``--pulse-port`` serves live heartbeats throughout:
    ``/metrics`` carries probe attempts, budget remaining, the running
    leg and its live elapsed seconds (legs print only at completion,
    so elapsed-vs-timeout is the wedge signal, not stdout), so a
    wedged tunnel is WATCHED instead of silent. ``T2OMCA_BENCH_DAEMON_PROBE_CMD``
    overrides the probed command (tests inject wedges with it). The
    daemon parent never imports jax — a wedged backend can only ever
    cost a killable child."""
    from t2omca_tpu.obs.pulse import MetricsHub, PulseServer
    from t2omca_tpu.utils import watchdog as _wd

    hub = server = None
    if args.pulse_port is not None:
        hub = MetricsHub()
        try:
            # trace_supported=False: the daemon parent is jax-free and
            # has no TraceController — /trace must say so instead of
            # acking an arm nothing will ever consume
            server = PulseServer(hub, args.pulse_port, rec=_REC,
                                 trace_supported=False).start()
            print(f"# daemon: pulse heartbeats on :{server.port} "
                  f"(/metrics, /healthz)", file=sys.stderr, flush=True)
            hub.health("daemon", lambda: (True, "daemon running"))
        except OSError as e:
            print(f"# daemon: could not bind pulse port "
                  f"{args.pulse_port} ({e}); heartbeats disabled",
                  file=sys.stderr)
            hub = None

    budget = _env_float("T2OMCA_BENCH_DAEMON_BUDGET", 4 * 3600.0)
    backoff = _env_float("T2OMCA_BENCH_DAEMON_BACKOFF", 30.0)
    probe_each = _env_float("T2OMCA_BACKEND_PROBE_TIMEOUT", 900.0)
    cmd_env = os.environ.get("T2OMCA_BENCH_DAEMON_PROBE_CMD")
    probe_cmd = shlex.split(cmd_env) if cmd_env else None
    deadline = time.monotonic() + budget

    # the beat = "the daemon itself is alive": a dedicated 1 s ticker,
    # because the orchestration thread BLOCKS inside probe_backend for
    # up to probe_each (900 s) — beat age climbing through exactly the
    # wedged-tunnel window would read as a hung daemon and get a
    # healthy run killed by the very supervisor the endpoint serves
    beat_stop = threading.Event()
    if hub is not None:
        def _ticker():
            while not beat_stop.wait(1.0):
                hub.beat()
                hub.set("daemon_budget_remaining_seconds",
                        max(deadline - time.monotonic(), 0.0))
        threading.Thread(target=_ticker, daemon=True,
                         name="bench-daemon-beat").start()

    def _done(rc: int) -> int:
        beat_stop.set()
        if server is not None:
            server.close()
        return rc

    # ---- phase 1: wait out the wedged tunnel --------------------------
    attempt, failure = 0, None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            # `attempt` counts probes actually LAUNCHED — the budget
            # check precedes the increment so the record's diagnostic
            # attempt count is never inflated by a never-probed pass
            failure = failure or {"error": "daemon budget exhausted",
                                  "phase": "timeout"}
            break
        attempt += 1
        if hub is not None:
            hub.set("daemon_probe_attempts", attempt)
        with _REC.span("bench.daemon.probe", attempt=attempt):
            failure = probe_backend(min(probe_each, remaining),
                                    _cmd=probe_cmd, attempts=1)
        if failure is None:
            break
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        delay = min(_wd.backoff_delay(attempt, backoff, max_s=600.0),
                    max(remaining, 0.0))
        print(f"# daemon: probe attempt {attempt} failed "
              f"({failure['error'][:120]}); backoff ladder retries in "
              f"{delay:.1f}s ({remaining:.0f}s of budget left)",
              file=sys.stderr, flush=True)
        time.sleep(delay)
    if failure is not None:
        # the budget ran out with the tunnel still wedged: one partial
        # record saying so (the r03+ class, now with the attempt count)
        print(json.dumps(_finalize({
            "metric": "bench_daemon_legs", "value": None, "unit": "legs",
            "vs_baseline": None, "probe_attempts": attempt, **failure,
        }), default=repr), flush=True)
        return _done(1)
    print(f"# daemon: backend probe succeeded on attempt {attempt}; "
          f"running the A/B matrix", file=sys.stderr, flush=True)

    # ---- phase 2: the full A/B matrix, one child per leg --------------
    legs = _daemon_legs(args)
    leg_timeout = _env_float("T2OMCA_BENCH_DAEMON_LEG_TIMEOUT", 3600.0)
    bench_path = os.path.abspath(__file__)
    results: dict = {}
    measured = 0
    for i, (name, argv) in enumerate(legs):
        if hub is not None:
            hub.beat()
            hub.set("daemon_leg_running", 1, leg=name)
        with _REC.span("bench.daemon.leg", leg=name):
            records, rc, note = _daemon_run_leg(bench_path, name, argv,
                                                leg_timeout, hub)
        for r in records:
            r.setdefault("leg", name)
            print(json.dumps(_finalize(r), default=repr), flush=True)
        ok = any(isinstance(r.get("value"), (int, float))
                 for r in records)
        measured += bool(ok)
        results[name] = {"rc": rc, "records": len(records),
                         "measured": ok}
        if note:
            results[name]["note"] = note
        if hub is not None:
            hub.set("daemon_leg_running", 0, leg=name)
            hub.set("daemon_legs_completed", i + 1)
    print(json.dumps(_finalize({
        "metric": "bench_daemon_legs", "value": measured,
        "unit": "legs-measured", "vs_baseline": None,
        "matrix": [n for n, _ in legs], "legs": results,
        "probe_attempts": attempt,
    }), default=repr), flush=True)
    return _done(0 if measured == len(legs) else 1)


#: BASELINE.json measurement scale points (see BASELINE.md §configs):
#: (agv, mec, channels, envs, d_model, depth) — config 4 adds PER scale,
#: config 5 is the DP=8 point (needs ≥8 devices; compile-checked by the
#: multichip dryrun, measured per-chip here when a slice is available)
_CONFIGS = {
    1: dict(agv=4, mec=2, ch=2, envs=1, emb=64, depth=2),
    2: dict(agv=16, mec=4, ch=4, envs=256, emb=128, depth=2),
    3: dict(agv=64, mec=8, ch=8, envs=1024, emb=256, depth=2),
    4: dict(agv=64, mec=8, ch=8, envs=4096, emb=256, depth=2),
    5: dict(agv=256, mec=16, ch=16, envs=8192, emb=256, depth=2),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--config", type=int, choices=sorted(_CONFIGS),
                    default=3,
                    help="BASELINE.json measurement config (default 3, the "
                         "north-star scale point; 4 = PER/train scale, "
                         "5 = the DP=8 point — needs 8 devices)")
    ap.add_argument("--envs", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--profile", default="",
                    help="capture a jax.profiler trace of the timed "
                         "iterations into this directory")
    ap.add_argument("--acting", choices=("qslice", "dense"),
                    default="qslice",
                    help="agent forward for the rollout: qslice (exact "
                         "token-0-only reduction, ops/query_slice — the "
                         "default) or dense (XLA full forward; reproduces "
                         "the BASELINE.md XLA-path row)")
    ap.add_argument("--no-fast-norm", action="store_true",
                    help="sequential per-agent Welford (reference-exact "
                         "normalizer ordering) instead of the batched merge")
    ap.add_argument("--breakdown", action="store_true",
                    help="attribute the slot time: env-only rollout "
                         "(seq vs fast norm), acting-only scan, full rollout")
    ap.add_argument("--train", action="store_true",
                    help="benchmark the learner: train_iter (PER sample -> "
                         "train -> priority update) and the interleaved "
                         "rollout+train loop (BASELINE.json config 4)")
    ap.add_argument("--all", action="store_true",
                    help="comprehensive single-process sweep: default "
                         "rollout+train line, breakdown, qslice/dense "
                         "comparison, threefry/rbg comparison, config-4 "
                         "scale — one backend init, one JSON line per "
                         "measurement (tunnel-scarce mode)")
    ap.add_argument("--hbm", action="store_true",
                    help="print the analytic device-memory budget for the "
                         "selected config (no device work)")
    ap.add_argument("--prod-hbm", action="store_true",
                    help="allocate config-5's PRODUCTION replay ring "
                         "(--ring episodes, T=150, bf16 compact storage) "
                         "on the DP=8 mesh, insert + run one train "
                         "iteration with it co-resident, and cross-check "
                         "the --hbm analytic against real allocated "
                         "bytes (needs 8 devices: a slice, or "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8 JAX_PLATFORMS=cpu)")
    ap.add_argument("--ring", type=int, default=16384,
                    help="--prod-hbm ring capacity in episodes "
                         "(default: configs/config5_dp8.yaml's 16384)")
    ap.add_argument("--dtype", choices=("float32", "bfloat16"),
                    default=None,
                    help="--prod-hbm learner compute dtype (default f32: "
                         "CPU bf16 is emulated ~50x slower, and f32 "
                         "residuals upper-bound bf16; pass bfloat16 on "
                         "a real slice)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize learner scan forwards in the "
                         "backward pass (long-horizon HBM lever; exact)")
    ap.add_argument("--heads", type=int, default=4,
                    help="agent/mixer head count (d256 standard heads: 4 -> "
                         "head_dim 64, 2 -> head_dim 128 = full MXU lanes)")
    ap.add_argument("--prng", choices=("threefry", "rbg", "unsafe_rbg"),
                    default="threefry",
                    help="PRNG impl for all keys: rbg = the TPU hardware "
                         "bit generator (cheaper for the rollout's many "
                         "small draws; different stream than threefry)")
    ap.add_argument("--serve", action="store_true",
                    help="measure the serving path: load an exported "
                         "artifact (--artifact) through the batched "
                         "front-end and report p50/p99 decision latency "
                         "+ decisions/s/chip (docs/SERVING.md)")
    ap.add_argument("--artifact", default=None, metavar="DIR",
                    help="--serve: the exported serving artifact "
                         "(python -m t2omca_tpu.serve export)")
    ap.add_argument("--serve-dtype", choices=("float32", "bfloat16"),
                    default="float32",
                    help="--serve: which param variant to serve")
    ap.add_argument("--chaos", action="store_true",
                    help="--serve: drive the multi-engine FLEET "
                         "(serve/fleet.py) under bursty heavy-tailed "
                         "open-loop traffic plus a fault schedule "
                         "(engine kill mid-burst, injected dispatch "
                         "hang, poisoned refresh) — reports p99 under "
                         "burst, shed fraction and engine recovery "
                         "time (docs/SERVING.md §fleet)")
    ap.add_argument("--fleet-engines", type=int, default=2,
                    help="--serve --chaos: engines in the fleet")
    ap.add_argument("--chaos-seconds", type=float, default=8.0,
                    help="--serve --chaos: open-loop traffic duration")
    ap.add_argument("--kernels", choices=("xla", "pallas", "ab"),
                    default=None,
                    help="attention-kernel A/B leg: measure the DENSE "
                         "rollout under the selected kernels.attention "
                         "mode (xla = einsum path, pallas = fused flash "
                         "kernel; ab = both) — one JSON record per mode "
                         "with the mode in the record (spans summary is "
                         "cumulative across legs, like --all; per-mode "
                         "split via each span's leg= meta)")
    ap.add_argument("--sebulba", action="store_true",
                    help="measure the Sebulba decoupled actor/learner "
                         "split (parallel/sebulba.py): overlapped "
                         "rollout+train over a 1+1 device partition with "
                         "the device-resident trajectory queue, vs the "
                         "serialized single-device loop — one record "
                         "with both rates and the overlap speedup "
                         "(needs >= 2 devices; --smoke forces 2 CPU "
                         "host devices)")
    ap.add_argument("--superstep", type=int, default=None, metavar="K",
                    help="measure the fused training superstep: ONE "
                         "program scanning K rollout->insert->train "
                         "iterations per dispatch (config superstep=K; "
                         "K=1 still fuses the three stages into one "
                         "program). Reports the dispatch-amortized "
                         "env-steps/s including training")
    ap.add_argument("--population", type=int, default=None, metavar="P",
                    help="graftpop experiment-throughput leg: ONE "
                         "vmapped population superstep advancing P "
                         "seed variants per dispatch vs the SAME P "
                         "experiments serialized as P solo dispatches "
                         "(docs/POPULATION.md). Reports experiments_"
                         "per_sec + population_speedup. Composes with "
                         "--kernels pallas|xla (vmap-over-pallas) and "
                         "--sebulba (lockstep split, needs >= 2 "
                         "devices) — the graftlattice legs")
    ap.add_argument("--lattice", action="store_true",
                    help="graftlattice composition matrix (docs/"
                         "POPULATION.md §composition): the population "
                         "axis composed with each other graft axis — "
                         "kernels pallas, a dp=2 mesh, the sebulba "
                         "lockstep split — one record per combo "
                         "(--population picks P, default 4; needs >= 2 "
                         "devices, --smoke forces 2 CPU host devices)")
    ap.add_argument("--daemon", action="store_true",
                    help="the surviving bench (ROADMAP item 1): retry "
                         "backend init on the backoff ladder until the "
                         "wedged tunnel opens (T2OMCA_BENCH_DAEMON_"
                         "BUDGET total, default 4h), then run the full "
                         "A/B matrix (--superstep 4, --kernels ab, "
                         "--sebulba, --serve with --artifact) as child "
                         "processes in ONE session, one BENCH record "
                         "per leg; --pulse-port serves live heartbeats")
    ap.add_argument("--legs", default=None, metavar="a,b,...",
                    help="--daemon: subset of the matrix to run "
                         "(superstep,kernels,sebulba,serve)")
    ap.add_argument("--pulse-port", type=int, default=None, metavar="P",
                    help="--daemon: serve /metrics + /healthz "
                         "heartbeats on this port (0 = ephemeral, "
                         "printed to stderr)")
    ap.add_argument("--pipeline", type=int, default=None, metavar="K",
                    help="also report the steady-state rate over K "
                         "async-chained rollouts with one terminal sync "
                         "(amortizes the per-dispatch tunnel round-trip "
                         "the way the production driver loop does); "
                         "defaults to K=4 on full-scale runs, pass 0 "
                         "to disable")
    args = ap.parse_args()
    if args.daemon:
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.serve or args.superstep is not None
                or args.kernels is not None or args.sebulba
                or args.population is not None or args.lattice):
            ap.error("--daemon runs the full A/B matrix itself "
                     "(--superstep 4, --kernels ab, --sebulba, --serve "
                     "when --artifact is given); drop the per-leg flags")
        if args.pipeline:
            ap.error("--daemon legs own their pipelining; drop "
                     "--pipeline")
    else:
        if args.pulse_port is not None:
            ap.error("--pulse-port is the daemon's heartbeat endpoint; "
                     "add --daemon (training runs use the config key "
                     "obs.pulse_port instead)")
        if args.legs is not None:
            ap.error("--legs only applies to --daemon")
    if args.daemon:
        # the daemon parent must never import jax: a wedged backend may
        # only ever cost a killable child process
        return bench_daemon(args)
    if args.serve:
        if args.artifact is None:
            ap.error("--serve needs --artifact DIR (an exported serving "
                     "artifact; python -m t2omca_tpu.serve export)")
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.superstep is not None
                or args.config != 3):
            ap.error("--serve measures the exported artifact's serving "
                     "path; drop --all/--hbm/--prod-hbm/--breakdown/"
                     "--train/--superstep/--config")
        if args.pipeline:
            ap.error("--serve has its own hidden-carried throughput "
                     "leg; drop --pipeline")
        if args.fleet_engines < 1:
            ap.error("--fleet-engines must be >= 1")
        if args.chaos_seconds <= 0:
            ap.error("--chaos-seconds must be > 0")
    elif args.artifact is not None:
        ap.error("--artifact only applies to --serve")
    elif args.chaos:
        ap.error("--chaos only applies to --serve (the fleet chaos "
                 "traffic leg needs an exported artifact)")
    if args.kernels is not None:
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.serve or args.superstep is not None
                or args.config == 5):
            ap.error("--kernels measures the dense rollout under each "
                     "attention-kernel mode; drop --all/--hbm/--prod-hbm/"
                     "--breakdown/--train/--serve/--superstep/--config 5")
    if args.superstep is not None:
        if args.superstep < 1:
            ap.error("--superstep K must be >= 1")
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.config == 5):
            ap.error("--superstep measures the fused-dispatch loop on a "
                     "single chip; drop --all/--hbm/--prod-hbm/"
                     "--breakdown/--train/--config 5")
        if args.pipeline:
            ap.error("--superstep already amortizes dispatch inside one "
                     "program; drop --pipeline")
    if args.population is not None:
        if args.population < 2:
            ap.error("--population P must be >= 2 (P=1 is the classic "
                     "loop — measure it with --superstep)")
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.serve or args.superstep is not None
                or args.config == 5):
            ap.error("--population measures the vmapped population "
                     "superstep vs the serialized P-run; drop --all/"
                     "--hbm/--prod-hbm/--breakdown/--train/--serve/"
                     "--superstep/--config 5")
        if args.kernels == "ab":
            # graftlattice composes population with ONE kernel mode per
            # run: the record's A/B is vmapped-vs-serialized, not
            # xla-vs-pallas
            ap.error("--population composes with a single kernel mode; "
                     "pick --kernels pallas or --kernels xla (run both "
                     "modes as two invocations, or use --lattice)")
        if args.pipeline:
            ap.error("--population amortizes dispatch across the "
                     "member axis already; drop --pipeline")
    if args.sebulba:
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.serve or args.superstep is not None
                or args.kernels is not None or args.config == 5):
            ap.error("--sebulba measures the decoupled actor/learner "
                     "split; drop --all/--hbm/--prod-hbm/--breakdown/"
                     "--train/--serve/--superstep/--kernels/--config 5")
        if args.pipeline:
            ap.error("--sebulba overlaps dispatch across the device "
                     "split already; drop --pipeline")
        # the split needs 2 devices; force 2 CPU host devices while jax
        # is still unimported (no-op on hosts that already expose more —
        # the flag only widens the CPU host platform)
        if "jax" not in sys.modules:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + " --xla_force_host_platform_device_count=2").strip()
    if args.lattice:
        if (args.all or args.hbm or args.prod_hbm or args.breakdown
                or args.train or args.serve or args.superstep is not None
                or args.kernels is not None or args.sebulba
                or args.config == 5):
            ap.error("--lattice runs its own composition matrix "
                     "(population x pallas / x dp / x sebulba); drop "
                     "the per-leg flags")
        if args.pipeline:
            ap.error("--lattice legs amortize dispatch on their own "
                     "axes; drop --pipeline")
        if args.population is None:
            args.population = 4
        if args.population % 2:
            ap.error("--lattice shards the member axis over a 2-device "
                     "mesh (population-over-dp sub-leg); --population P "
                     "must be even")
        # the dp and sebulba sub-legs need 2 devices (same pre-import
        # widening as --sebulba)
        if "jax" not in sys.modules:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags
                    + " --xla_force_host_platform_device_count=2").strip()
    if args.pipeline is not None and args.pipeline < 0:
        ap.error("--pipeline K must be >= 0")
    if args.pipeline and (args.hbm or args.breakdown or args.prod_hbm):
        # these modes don't measure a chainable dispatch loop; silently
        # ignoring the flag would misattribute records
        ap.error("--pipeline applies to the rollout/train dispatch "
                 "chains (default line, --train, --config 5, --all); "
                 "drop it for --breakdown/--hbm/--prod-hbm")
    if args.pipeline is None:
        # default ON (K=4) wherever a dispatch chain is measured, so the
        # driver's plain `python bench.py` artifact carries the
        # steady-state rate; --pipeline 0 disables. Smoke stays off (the
        # CPU contract tests pin the minimal schema).
        measures_chain = not (args.smoke or args.hbm or args.breakdown
                              or args.prod_hbm or args.serve
                              or args.superstep is not None
                              or args.kernels is not None
                              or args.sebulba
                              or args.population is not None
                              or args.lattice)
        args.pipeline = 4 if measures_chain else 0

    if args.smoke or args.hbm:
        # --hbm is pure shape arithmetic: never touch a (possibly wedged)
        # TPU backend for it
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp

    if not args.smoke and not args.hbm:
        # probe the backend FIRST, bounded in a subprocess (probe_backend):
        # the parseable error record must land BEFORE any caller timeout.
        metric, unit = (("serve_decisions_per_sec", "decisions/s/chip")
                        if args.serve
                        else ("train_steps_per_sec", "train-steps/s/chip")
                        if args.train
                        else ("env_steps_per_sec", "env-steps/s/chip"))
        probe_s = float(os.environ.get("T2OMCA_BACKEND_PROBE_TIMEOUT",
                                       "900"))
        # the fallback's budget slice is RESERVED up front: primary +
        # fallback together stay within probe_s, so the failure record
        # still lands before a caller timeout tuned against probe_s
        fb_s = fallback_bound(probe_s)
        with _REC.span("bench.probe"):
            failure = probe_backend(probe_s - fb_s)
        if failure is not None:
            # JAX_PLATFORMS='' auto-fallback probe: the failure record
            # then says whether ONLY the pinned platform is wedged
            with _REC.span("bench.probe.fallback"):
                failure["fallback"] = probe_fallback(fb_s)
            use_fallback = (failure["fallback"].get("ok")
                            and os.environ.get("T2OMCA_BENCH_FALLBACK")
                            == "1")
            if not use_fallback:
                print(json.dumps(_finalize({
                    "metric": metric, "value": None,
                    "unit": unit, "vs_baseline": None, **failure,
                    # the flight tail rides along like main_flight's
                    # partial record: a wedged-tunnel probe failure then
                    # shows its phase history (BENCH_r03–r05 left only a
                    # bare error)
                    "spans_tail": _REC.tail()[-20:],
                }), default=repr), flush=True)
                return 1
            # explicit opt-in (T2OMCA_BENCH_FALLBACK=1): continue on the
            # auto-selected backend — jax is already imported but no
            # backend is initialized yet (the probe ran in children), so
            # clearing the pin here still governs platform selection.
            # The record is tagged `platform` so a fallback number can
            # never masquerade as the pinned platform's.
            print(f"# probe failed on the pinned platform "
                  f"({failure['error'][:120]}); continuing on fallback "
                  f"backend {failure['fallback']['backend']} "
                  f"(T2OMCA_BENCH_FALLBACK=1)", file=sys.stderr,
                  flush=True)
            jax.config.update("jax_platforms", None)
            _RECORD_EXTRA["platform"] = failure["fallback"]["backend"]
            _RECORD_EXTRA["probe_failure"] = failure["error"][:200]

    # backend committed (probe passed, fallback chosen, or smoke/hbm CPU
    # pin): record the LIVE platform for the uniform record meta — safe
    # to initialize here, the first bench leg would have anyway
    _RECORD_EXTRA.setdefault("platform", jax.default_backend())

    if args.serve:
        # the serving legs need no train config at all — everything
        # (model, buckets, params) comes from the artifact's meta
        if args.chaos:
            return bench_serve_chaos(args)
        return bench_serve(args)

    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    from t2omca_tpu.run import Experiment

    if args.smoke:
        n_envs = args.envs or 8
        steps = args.steps or 8
        cfg = sanity_check(TrainConfig(
            batch_size_run=n_envs,
            prng_impl=args.prng,
            env_args=EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                               episode_limit=steps),
            model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                              mixer_heads=2, mixer_depth=1,
                              use_qslice=args.acting != "dense"),
            replay=ReplayConfig(buffer_size=16),
        ))
    else:
        # BASELINE.json measurement scale points; default = config 3, the
        # north-star point (64 AGVs × 8 MEC, 1024 envs, d_model 256).
        # episode_limit is shortened for the timed program (throughput is
        # per-step; the full 150-slot episode batch at entity obs 64×576
        # would exceed single-chip HBM — the training config shards it over
        # the data axis instead).
        def make_cfg(acting: str, config_id: int, prng: str | None = None):
            c = _CONFIGS[config_id]
            return sanity_check(TrainConfig(
                batch_size_run=args.envs or c["envs"],
                prng_impl=prng or args.prng,
                env_args=EnvConfig(agv_num=c["agv"], mec_num=c["mec"],
                                   num_channels=c["ch"],
                                   episode_limit=args.steps or 32,
                                   fast_norm=not args.no_fast_norm),
                model=ModelConfig(emb=c["emb"], heads=args.heads,
                                  depth=c["depth"],
                                  mixer_emb=c["emb"],
                                  mixer_heads=args.heads,
                                  mixer_depth=c["depth"],
                                  standard_heads=True, dtype="bfloat16",
                                  use_qslice=acting != "dense",
                                  remat=args.remat),
                replay=ReplayConfig(buffer_size=4, store_dtype="bfloat16"),
            ))

        cfg = make_cfg(args.acting, args.config)
        n_envs = cfg.batch_size_run
        steps = cfg.env_args.episode_limit

    def _time(fn, iters=args.iters):
        """median seconds of fn() (fn must return an array to sync on)."""
        fn_times = []
        _sync(fn())   # warm-up beyond compile
        for _ in range(iters):
            t0 = time.perf_counter()
            _sync(fn())
            fn_times.append(time.perf_counter() - t0)
        fn_times.sort()
        return fn_times[len(fn_times) // 2]

    def _pipe_rate(rollout, params, rs, env_steps, k):
        """Steady-state env-steps/s over k async-chained rollouts
        (see _chain_seconds)."""
        def step(rs_):
            rs2, b, _ = rollout(params, rs_, test_mode=False)
            return rs2, b.reward[0, 0]
        return round(env_steps / _chain_seconds(step, rs, k), 1)

    import contextlib

    @contextlib.contextmanager
    def tracing():
        if not args.profile:
            yield
            return
        jax.profiler.start_trace(args.profile)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            print(f"# trace written to {args.profile}", file=sys.stderr,
                  flush=True)

    if args.lattice:
        if jax.device_count() < 2:
            raise SystemExit(
                "--lattice needs >= 2 devices (a slice, or XLA_FLAGS="
                "--xla_force_host_platform_device_count=2 "
                "JAX_PLATFORMS=cpu)")
        with tracing():
            return bench_lattice(cfg, _time, args)

    if args.kernels is not None and args.population is None:
        import dataclasses as _dc

        from t2omca_tpu.config import KernelsConfig

        def make_cfg_kernels(mode: str):
            # dense acting: the kernel switch selects the program the
            # dense rollout dispatches (bench_kernels docstring)
            base = (cfg.replace(model=_dc.replace(cfg.model,
                                                  use_qslice=False))
                    if args.smoke else make_cfg("dense", args.config))
            return base.replace(kernels=KernelsConfig(attention=mode))

        with tracing():
            return bench_kernels(make_cfg_kernels, _time, args)

    if args.sebulba and args.population is None:
        if jax.device_count() < 2:
            raise SystemExit(
                "--sebulba needs >= 2 devices (a slice, or XLA_FLAGS="
                "--xla_force_host_platform_device_count=2 "
                "JAX_PLATFORMS=cpu)")
        with tracing():
            return bench_sebulba(cfg, _time, args)

    if args.superstep is not None:
        with tracing():
            return bench_superstep(cfg, _time, args)

    if args.population is not None:
        if args.sebulba:
            # graftlattice: population x sebulba lockstep
            if jax.device_count() < 2:
                raise SystemExit(
                    "--population --sebulba needs >= 2 devices (a "
                    "slice, or XLA_FLAGS=--xla_force_host_platform_"
                    "device_count=2 JAX_PLATFORMS=cpu)")
            with tracing():
                return bench_population_sebulba(cfg, _time, args)
        with tracing():
            return bench_population(cfg, _time, args)

    if args.prod_hbm:
        if jax.device_count() < 8:
            raise SystemExit(
                "--prod-hbm needs 8 devices (a slice, or "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                "JAX_PLATFORMS=cpu)")
        c = _CONFIGS[5]
        n_dev = 8
        envs = max(((args.envs or 64) // n_dev) * n_dev, n_dev)
        ring = -(-args.ring // n_dev) * n_dev
        prod_cfg = sanity_check(TrainConfig(
            batch_size_run=envs, batch_size=32, prng_impl=args.prng,
            env_args=EnvConfig(agv_num=c["agv"], mec_num=c["mec"],
                               num_channels=c["ch"],
                               episode_limit=args.steps or 150),
            model=ModelConfig(emb=c["emb"], heads=args.heads,
                              depth=c["depth"], mixer_emb=c["emb"],
                              mixer_heads=args.heads, mixer_depth=c["depth"],
                              standard_heads=True,
                              dtype=args.dtype or "float32",
                              remat=args.remat),
            replay=ReplayConfig(buffer_size=ring, store_dtype="bfloat16"),
        ))
        return bench_prod_hbm(prod_cfg)

    if args.hbm:
        return bench_hbm(cfg, args)

    if args.all:
        if args.smoke:
            raise SystemExit("--all is a full-scale chip mode; drop --smoke")
        if (args.config != 3 or args.acting != "qslice" or args.train
                or args.breakdown or args.prng != "threefry"):
            # --all owns its measurement matrix; silently ignoring these
            # would misattribute records (and a non-default --prng would
            # turn the leg-1 headline into rbg with no threefry baseline)
            raise SystemExit(
                "--all runs its own fixed measurement set (config-3 "
                "headline + config-4 train + qslice/dense + "
                "threefry/rbg + breakdown); drop "
                "--config/--acting/--train/--breakdown/--prng")
        with tracing():
            return bench_all(make_cfg, _time, _pipe_rate, args)

    if args.config == 5 and not args.smoke:
        # the DP=8 scale point has its own program shape (sharded mesh);
        # bench_dp measures both metric halves (--train flips the headline);
        # --breakdown stays a single-chip mode
        if args.breakdown:
            raise SystemExit(
                "--config 5 measures the DP loop; use configs 1-4 for "
                "--breakdown")
        with tracing():
            return bench_dp(cfg, _time, args)

    if args.train or args.breakdown:
        # whole-mode trace (includes compiles; the default mode traces only
        # the timed iterations)
        with tracing():
            if args.train:   # builds its own Experiment (PER-enabled replay)
                return bench_train(cfg, _time, args)
            exp = Experiment.build(cfg)
            ts = exp.init_train_state(0)
            return breakdown(cfg, exp, ts, _time, args)

    with _REC.span("bench.build"):
        exp = Experiment.build(cfg)
        ts = exp.init_train_state(0)
    rollout = jax.jit(exp.runner.run, static_argnames="test_mode")
    params = ts.learner.params["agent"]

    # compile + warm-up (two runs: tunnel queues make the first timed run
    # unrepresentative)
    t0 = time.perf_counter()
    with _REC.span("bench.compile"):
        rs, batch, stats = rollout(params, ts.runner, test_mode=False)
        _sync(batch.reward[0, 0])
    compile_s = time.perf_counter() - t0
    with _REC.span("bench.warm"):
        rs, batch, stats = rollout(params, rs, test_mode=False)
        _sync(batch.reward[0, 0])
    print(f"# compile+first-run: {compile_s:.1f}s  "
          f"devices={jax.devices()}", file=sys.stderr)

    times = []
    with tracing():
        for _ in range(args.iters):
            t0 = time.perf_counter()
            with _REC.span("bench.measure"):
                rs, batch, stats = rollout(params, rs, test_mode=False)
                _sync(batch.reward[0, 0])
            times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[len(times) // 2]
    env_steps = cfg.batch_size_run * cfg.env_args.episode_limit
    rate = env_steps / dt
    print(f"# median rollout: {dt * 1e3:.1f}ms for {env_steps} env-steps "
          f"({n_envs} envs × {steps} slots, {cfg.env_args.agv_num} AGVs)",
          file=sys.stderr)

    line = {
        "metric": "env_steps_per_sec",
        "value": round(rate, 1),
        "unit": "env-steps/s/chip",
        "vs_baseline": round(rate / 50_000.0, 3),
        # a config id only when the run actually measured that scale point
        # (smoke and --envs/--steps overrides would misattribute the number)
        "config": (None if args.smoke or args.envs or args.steps
                   else args.config),
        "n_envs": n_envs,
        "episode_steps": steps,
        "acting": args.acting,
    }
    if jax.config.jax_default_prng_impl != "threefry2x32":
        # live impl, not the flag echo (see rollout_rate in bench_all)
        line["prng"] = jax.config.jax_default_prng_impl

    if args.pipeline:
        rate_pipe = _pipe_rate(rollout, params, rs, env_steps,
                               args.pipeline)
        line["pipelined_env_steps_per_sec"] = rate_pipe
        print(f"# pipelined (k={args.pipeline}): "
              f"{rate_pipe:.1f} env-steps/s steady-state",
              file=sys.stderr)

    # the north-star metric is BOTH halves ("env-steps/sec/chip + mixer
    # train-steps/sec", BASELINE.json): append the learner measurement to
    # the default line so every driver bench records it. The headline is
    # preserved on stderr first (and the first Experiment's device state
    # dropped) so even a process-fatal train failure cannot cost it.
    if not args.smoke:
        print(f"# headline: {json.dumps(line)}", file=sys.stderr, flush=True)
        del ts, rs, batch, stats, rollout, params, exp
        try:
            line.update(_train_numbers(cfg, _time,
                                       pipeline_k=args.pipeline))
        except Exception as e:      # pragma: no cover - defensive
            print(f"# train bench failed: {e!r}", file=sys.stderr)

    # per-phase span summary (probe / build / compile / warm / measure
    # + the train half's legs): first_ms isolates the compile,
    # steady_ms the warm rate — the record says where the time went.
    # Set LAST so the train-half spans above are included.
    _finalize(line)
    print(json.dumps(line))
    return 0


def main_flight() -> int:
    """``main()`` with a flight-recorder net: any unhandled failure
    still leaves ONE parseable JSON line on stdout — the partial record
    with the phase it died in (``bench.probe`` / ``bench.build`` /
    ``bench.compile`` / ...) and the span tail, so the next wedged TPU
    bench run produces a BENCH_r*.json that says WHERE it died instead
    of a bare traceback on stderr. Argparse/SystemExit (usage errors)
    pass through: those already print their own diagnostics and no
    measurement was in flight."""
    try:
        return main()
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 — the record IS the handler
        # the failing span has already closed (the exception unwound
        # through its __exit__), so fall back from the open-span phase
        # to the most recent span that recorded an error outcome
        phase = _REC.current_phase()
        if phase is None:
            for ev in reversed(_REC.tail()):
                if (ev.get("event") == "span"
                        and str(ev.get("outcome", "")).startswith("error")):
                    phase = ev["phase"]
                    break
        # match main()'s probe-failure record: a crashed --train or
        # --serve run must not file its partial record under the
        # rollout metric
        metric, unit = (("serve_chaos_p99_ms", "ms")
                        if "--serve" in sys.argv and "--chaos" in sys.argv
                        else ("serve_decisions_per_sec",
                              "decisions/s/chip")
                        if "--serve" in sys.argv
                        else ("train_steps_per_sec", "train-steps/s/chip")
                        if "--train" in sys.argv
                        else ("env_steps_per_sec", "env-steps/s/chip"))
        print(f"# bench failed in phase {phase or 'unknown'}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        print(json.dumps(_finalize({
            "metric": metric, "value": None,
            "unit": unit, "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}"[:500],
            "phase": phase,
            "spans_tail": _REC.tail()[-20:],
            # default=repr: a non-JSON span-meta value must degrade,
            # not crash the crash handler and lose the record
        }), default=repr), flush=True)
        return 1


if __name__ == "__main__":
    sys.exit(main_flight())
