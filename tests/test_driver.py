"""End-to-end driver tests: the real ``run()`` loop, checkpoint/resume (Q13),
and the host-RAM (``buffer_cpu_only``) branch — the stateful glue of
``/root/reference/per_run.py:106-309`` (VERDICT r2 Weak #6)."""

import glob
import json
import os

import jax
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment, run
from t2omca_tpu.utils.checkpoint import find_checkpoint, load_checkpoint
from t2omca_tpu.utils.logging import Logger


def tiny_cfg(tmp_path, **kw):
    replay_kw = kw.pop("replay_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=24,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=True, save_model_interval=24,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def logged_keys(results_root):
    keys = set()
    rows = []
    for p in glob.glob(os.path.join(results_root, "*", "metrics.jsonl")):
        with open(p) as f:
            for line in f:
                row = json.loads(line)
                keys.add(row["key"])
                rows.append(row)
    return keys, rows


def test_run_sequential_end_to_end(tmp_path):
    cfg = tiny_cfg(tmp_path)
    ts = run(cfg, Logger())
    # the loop ran past t_max, counting B env-steps per slot
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    # training actually happened
    assert int(jax.device_get(ts.learner.train_steps)) > 0
    keys, rows = logged_keys(tmp_path)
    # terminal-info metric contract keys (SURVEY.md §5.5) on both cadences
    for k in ("return_mean", "test_return_mean", "reward_mean",
              "task_completion_rate_mean", "episode_limit_mean", "epsilon",
              "loss", "grad_norm", "episode"):
        assert k in keys, (k, sorted(keys))
    # profiling timers flow into the same stream (SURVEY.md §5(1))
    assert "time_rollout_ms" in keys
    # checkpoints: numeric step dirs under models/<token>/
    dirs = glob.glob(os.path.join(tmp_path, "models", "*", "*"))
    assert dirs and all(os.path.basename(d).isdigit() for d in dirs)


@pytest.mark.slow   # two full run() loops (~50 s); resume-through-restore also hit by test_resilience nan-recovery
def test_checkpoint_resume_restores_cursor_q13(tmp_path):
    cfg = tiny_cfg(tmp_path)
    ts1 = run(cfg, Logger())
    t1 = int(jax.device_get(ts1.runner.t_env))
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    found = find_checkpoint(model_dir)
    assert found is not None
    _, step = found
    assert 0 < step <= t1

    # resume: t_env must restart from the checkpoint step (Q13), and the
    # loaded learner params must equal the saved ones (exact resume)
    cfg2 = tiny_cfg(tmp_path, checkpoint_path=model_dir, t_max=step + 24)
    ts2 = run(cfg2, Logger())
    t2 = int(jax.device_get(ts2.runner.t_env))
    assert t2 > step          # advanced from the restored cursor
    assert t2 <= step + 24 + 2 * cfg2.batch_size_run * \
        cfg2.env_args.episode_limit

    # round-trip fidelity: loading into a fresh template reproduces the
    # saved learner params bit-exactly
    exp = Experiment.build(cfg)
    template = exp.init_train_state(cfg.seed)
    dirname, _ = find_checkpoint(model_dir)
    restored = load_checkpoint(dirname, template)
    leaves_r = jax.tree.leaves(restored.learner.params)
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves_r)


@pytest.mark.slow   # full run() for checkpoints; nearest-match logic pinned cheaply in test_resilience
def test_load_step_nearest_match(tmp_path):
    cfg = tiny_cfg(tmp_path)
    run(cfg, Logger())
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    steps = sorted(int(os.path.basename(d))
                   for d in glob.glob(os.path.join(model_dir, "*")))
    assert steps
    # load_step=0 -> max; load_step=first -> nearest is the first
    assert find_checkpoint(model_dir, 0)[1] == steps[-1]
    assert find_checkpoint(model_dir, steps[0])[1] == steps[0]


def test_host_buffer_branch_end_to_end(tmp_path):
    """buffer_cpu_only: host-RAM replay + native sum-tree through the real
    driver loop (run.py jitted_programs host branch)."""
    cfg = tiny_cfg(tmp_path, replay_kw=dict(buffer_cpu_only=True))
    ts = run(cfg, Logger())
    assert int(jax.device_get(ts.learner.train_steps)) > 0
    keys, _ = logged_keys(tmp_path)
    assert "loss" in keys


@pytest.mark.slow   # two full DP run() loops (~70 s); DP program coverage stays in test_parallel
def test_dp_devices_drives_training_from_config_alone(tmp_path):
    """dp_devices=8 through the real ``run()`` loop on the virtual 8-mesh:
    the production driver trains data-parallel with no code beyond the
    config flag (SURVEY.md §7.2(6); replaces the reference's single-device
    select, per_run.py:26). Checks learning happened, params stayed
    replicated, and the restored checkpoint round-trips."""
    cfg = tiny_cfg(tmp_path, dp_devices=8, batch_size_run=8, batch_size=8)
    assert len(jax.devices()) >= 8, "conftest must fake 8 devices"
    ts = run(cfg, Logger())
    assert int(jax.device_get(ts.learner.train_steps)) > 0
    leaf = jax.tree.leaves(ts.learner.params)[0]
    assert leaf.sharding.is_fully_replicated
    # env lanes stayed sharded over the mesh through the whole loop
    env_leaf = jax.tree.leaves(ts.runner.env_states)[0]
    assert len(env_leaf.sharding.device_set) == 8
    keys, _ = logged_keys(tmp_path)
    assert "loss" in keys

    # resume through the same DP path: shard() re-places the restored state
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    found = find_checkpoint(model_dir)
    assert found is not None
    step = found[1]
    cfg2 = tiny_cfg(tmp_path, dp_devices=8, batch_size_run=8, batch_size=8,
                    checkpoint_path=model_dir, t_max=step + 48)
    ts2 = run(cfg2, Logger())
    assert int(jax.device_get(ts2.runner.t_env)) > step


def test_v2_checkpoint_migrates_to_v3_exactly(tmp_path):
    """Format v3 added RunnerState.rscale; a v2 full-state checkpoint (no
    such field, reward_scaling could not have been on) must still restore
    EXACTLY via the migration shim — replay, normalizer stats, and RNG
    state intact, reward-scale state fresh."""
    import json as _json
    from flax import serialization
    from t2omca_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    cfg = tiny_cfg(tmp_path)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    d = save_checkpoint(str(tmp_path / "ckpt"), 40, ts)

    # doctor the on-disk checkpoint into v2: strip runner.rscale and mark
    # the meta format
    with open(os.path.join(d, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    del raw["runner"]["rscale"]
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(raw))
    meta_p = os.path.join(d, "meta.json")
    meta = _json.load(open(meta_p))
    meta["format"] = 2
    # faithful v2: the sidecar predates the content checksum
    meta.pop("sha256", None)
    meta.pop("bytes", None)
    _json.dump(meta, open(meta_p, "w"))

    restored = load_checkpoint(d, exp.init_train_state(3))
    # everything except rscale restored exactly from the v2 file
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(restored))):
        if ".rscale" in jax.tree_util.keystr(kp):
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))
    # rscale came back fresh (all zeros)
    assert all(float(np.asarray(x).sum()) == 0.0
               for x in jax.tree_util.tree_leaves(restored.runner.rscale))


def test_metaless_checkpoint_missing_rscale_migrates(tmp_path):
    """A pre-v2 checkpoint has no meta.json sidecar at all; it also
    predates RunnerState.rscale. It must take the same migration path as
    a marked v2 file — fresh rscale injected, everything else exact —
    instead of surfacing the replay-layout ValueError (ADVICE r4)."""
    from flax import serialization
    from t2omca_tpu.utils.checkpoint import save_checkpoint

    cfg = tiny_cfg(tmp_path)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    d = save_checkpoint(str(tmp_path / "ckpt"), 40, ts)

    # doctor into pre-v2: strip runner.rscale AND remove the sidecar
    with open(os.path.join(d, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    del raw["runner"]["rscale"]
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(serialization.msgpack_serialize(raw))
    os.remove(os.path.join(d, "meta.json"))

    restored = load_checkpoint(d, exp.init_train_state(3))
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(restored))):
        if ".rscale" in jax.tree_util.keystr(kp):
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))
    assert all(float(np.asarray(x).sum()) == 0.0
               for x in jax.tree_util.tree_leaves(restored.runner.rscale))


def test_metaless_v3_checkpoint_restores_unmodified(tmp_path):
    """A v3 tree whose meta.json was deleted must restore exactly — the
    migration's rscale injection is conditional on the field being
    absent, not on the sidecar's presence."""
    from t2omca_tpu.utils.checkpoint import save_checkpoint

    cfg = tiny_cfg(tmp_path)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    d = save_checkpoint(str(tmp_path / "ckpt"), 40, ts)
    os.remove(os.path.join(d, "meta.json"))

    restored = load_checkpoint(d, exp.init_train_state(3))
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))


def test_prng_impl_switch_mid_process_warns(tmp_path):
    """Experiment.build pins the process-global PRNG impl; a later build
    that CHANGES it must warn (keys/programs from earlier builds would
    mis-resolve, ADVICE r4) and an identical re-build must not."""
    Experiment.build(tiny_cfg(tmp_path))            # pins threefry
    with pytest.warns(RuntimeWarning, match="mid-process"):
        Experiment.build(tiny_cfg(tmp_path, prng_impl="rbg"))
    # switch back quietly restores the default for the rest of the suite
    with pytest.warns(RuntimeWarning, match="mid-process"):
        Experiment.build(tiny_cfg(tmp_path))
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")             # same impl: no warning
        Experiment.build(tiny_cfg(tmp_path))


def test_chained_programs_compile_exactly_once(tmp_path):
    """The driver loop feeds every program output back in as an input; a
    weak_type or placement drift in ANY chained leaf (e.g. a
    Python-scalar jnp.where branch in the env step) silently compiles a
    second executable of the whole program on iteration 2 — at config-3
    chip scale that's ~30 s of extra compile per program per run. The
    jitted_programs boundary strips weak types; this pins it."""
    import jax.numpy as jnp
    cfg = tiny_cfg(tmp_path, replay_kw=dict(prioritized=True))
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    key = jax.random.PRNGKey(0)
    t_env = 0
    for i in range(3):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
        t_env += cfg.batch_size_run * cfg.env_args.episode_limit
        ts, _ = train_iter(ts, jax.random.fold_in(key, i),
                           jnp.asarray(t_env))
    assert rollout._cache_size() == 1
    assert insert._cache_size() == 1
    assert train_iter._cache_size() == 1


def test_sanity_rejects_unknown_prng_impl():
    with pytest.raises(ValueError, match="prng_impl"):
        sanity_check(TrainConfig(prng_impl="philox"))


def test_dp_devices_sanity_rejects_host_buffer():
    with pytest.raises(ValueError, match="buffer_cpu_only"):
        sanity_check(TrainConfig(
            dp_devices=8, batch_size_run=8, batch_size=8,
            replay=ReplayConfig(buffer_size=8, buffer_cpu_only=True)))


def test_dp_devices_sanity_rejects_indivisible():
    with pytest.raises(ValueError, match="divisible by dp_devices"):
        sanity_check(TrainConfig(dp_devices=8, batch_size_run=6,
                                 batch_size=8,
                                 replay=ReplayConfig(buffer_size=8)))


def test_evaluate_path_exports_replay_and_benchmark(tmp_path):
    """evaluate_sequential end-to-end: greedy episodes on the episode
    runner with replay (npz) + benchmark CSV export (reference
    evaluate_sequential, per_run.py:74-101)."""
    pytest.importorskip("pandas")   # benchmark_csv is gated on pandas
    cfg = tiny_cfg(tmp_path, evaluate=True, save_replay=True,
                   benchmark_mode=True, test_nepisode=2,
                   animation_interval_evaluation=2)
    ts = run(cfg, Logger())
    replays = glob.glob(os.path.join(tmp_path, "*", "replay_episode_*.npz"))
    # animation_interval_evaluation=2 -> episodes 0 (and 2, 4, ...) only
    assert len(replays) == 1, replays
    csvs = glob.glob(os.path.join(tmp_path, "*", "benchmark.csv"))
    assert csvs, "benchmark CSV missing"
    data = np.load(replays[0])
    assert "pos" in data and data["pos"].shape[0] == cfg.env_args.episode_limit


def test_checkpoint_layout_mismatch_names_the_flag(tmp_path):
    """A compact-storage checkpoint restored into a dense-storage config
    must fail with the exact flag to toggle (meta.json sidecar), not a
    deep msgpack structure error."""
    import dataclasses

    from t2omca_tpu.utils.checkpoint import save_checkpoint

    cfg = tiny_cfg(tmp_path)          # defaults: compact entity storage
    exp = Experiment.build(cfg)
    d = save_checkpoint(str(tmp_path / "ckpt"), 100, exp.init_train_state(0))
    assert os.path.exists(os.path.join(d, "meta.json"))

    cfg_dense = tiny_cfg(tmp_path, env_args=EnvConfig(
        agv_num=3, mec_num=2, num_channels=2, episode_limit=6,
        fast_norm=False))
    exp_dense = Experiment.build(cfg_dense)
    with pytest.raises(ValueError, match="compact_entity_store=true"):
        load_checkpoint(d, exp_dense.init_train_state(0))


@pytest.mark.slow   # DP run() + two restore paths (~50 s)
def test_dp_checkpoint_evaluates_under_other_configs(tmp_path):
    """A checkpoint from a DP=8 run must drive evaluation under a
    different config (fewer env lanes, no mesh): the full-state restore
    rejects the mismatched template, and the model-only fallback
    (reference semantics, per_run.py:185-187) restores the learner
    subtree — exercised end-to-end through the evaluate entry."""
    from t2omca_tpu.utils.checkpoint import load_learner_state

    cfg = tiny_cfg(tmp_path, dp_devices=8, batch_size_run=8, batch_size=8)
    run(cfg, Logger())
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    dirname, _ = find_checkpoint(model_dir)

    # direct: learner-only restore into a smaller single-device template
    cfg_single = tiny_cfg(tmp_path, batch_size_run=2, batch_size=4)
    exp = Experiment.build(cfg_single)
    with pytest.raises(ValueError):
        load_checkpoint(dirname, exp.init_train_state(0))
    restored = load_learner_state(dirname, exp.init_train_state(0))
    leaves = jax.tree.leaves(restored.learner.params)
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    rollout, _, _ = exp.jitted_programs()
    _, batch, _ = rollout(restored.learner.params["agent"],
                          exp.init_train_state(0).runner, test_mode=False)
    assert np.isfinite(np.asarray(jax.device_get(batch.reward))).all()

    # end-to-end: the evaluate entry takes the fallback automatically
    cfg_eval = tiny_cfg(tmp_path, batch_size_run=2, batch_size=4,
                        evaluate=True, test_nepisode=2,
                        checkpoint_path=model_dir)
    run(cfg_eval, Logger())


def test_model_only_restore_rejects_different_model(tmp_path):
    """load_learner_state must fail with the leaf named when the MODEL
    config mismatches (there is no further fallback — silent wrong-shape
    params would only explode later inside jit)."""
    from t2omca_tpu.utils.checkpoint import (load_learner_state,
                                             save_checkpoint)

    cfg = tiny_cfg(tmp_path)
    exp = Experiment.build(cfg)
    d = save_checkpoint(str(tmp_path / "ck"), 10, exp.init_train_state(0))

    cfg_big = tiny_cfg(tmp_path, model=ModelConfig(
        emb=16, heads=2, depth=1, mixer_emb=16, mixer_heads=2,
        mixer_depth=1))
    exp_big = Experiment.build(cfg_big)
    with pytest.raises(ValueError, match="different MODEL"):
        load_learner_state(d, exp_big.init_train_state(0))


@pytest.mark.slow   # full run() under the profiler (~60 s)
def test_profile_dir_produces_a_trace(tmp_path):
    """A1 evidence: profile_dir wires a jax.profiler trace window over the
    hot loop — the trace files must actually land on disk."""
    trace_dir = str(tmp_path / "trace")
    cfg = tiny_cfg(tmp_path, t_max=24, profile_dir=trace_dir,
                   profile_start=0, profile_iterations=2)
    run(cfg, Logger())
    produced = []
    for root, _, files in os.walk(trace_dir):
        produced.extend(files)
    assert produced, "no profiler trace files written"
