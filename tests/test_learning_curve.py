"""Learning-quality regression against the committed config-1 artifact.

The north-star quality target (BASELINE.json) asks for evidence that the
QMIX learner actually learns at the reference's config-1 scale point. A
full-length (t_max=205k) run's metric stream is committed under
``runs/config1_full/`` together with a measured random-policy baseline;
these tests pin the claim so a learner change that silently breaks learning
fails CI without re-running the 30-minute training.
"""

import glob
import json
import os

import numpy as np
import pytest

RUNS = os.path.join(os.path.dirname(__file__), "..", "runs")
ROOT = os.path.join(RUNS, "config1_full")


def _series(key, root=None, run_glob="qmix*"):
    paths = glob.glob(os.path.join(root or ROOT, run_glob, "metrics.jsonl"))
    if not paths:
        pytest.skip("learning-curve artifact not present")
    rows = [json.loads(l) for l in open(paths[0])]
    return [(r["t"], r["value"]) for r in rows if r["key"] == key]


@pytest.mark.parametrize("root,run_glob", [
    (ROOT, "qmix*"),                                     # dense-path run
    (os.path.join(RUNS, "config1_qslice"), "qmix*seed4*"),
    (os.path.join(RUNS, "config1_faststack"), "qmix*seed4*"),
    # the round-4 stability sweep (new default hypers): worst-case AND
    # best committed seeds — the gate covers more than one seed
    (os.path.join(RUNS, "config1_stable"), "qmix*seed0*"),
    (os.path.join(RUNS, "config1_stable"), "qmix*seed3*"),
    # round-5 loss-scale recipe (reward_unit + huber + mixer_zero_init):
    # learning preserved under the conditioning fix
    (os.path.join(RUNS, "config1_recipe"), "qmix*seed0*"),
    # recipe + NoisyNet (the 16-AGV campaign's arm-B selector)
    (os.path.join(RUNS, "config1_noisy"), "qmix*seed0*"),
], ids=["dense", "qslice", "faststack", "stable-s0", "stable-s3",
        "recipe-s0", "noisy-s0"])
def test_final_test_return_beats_random_baseline(root, run_glob):
    """One gate, three committed artifacts: the last-3-eval mean must beat
    the measured random baseline by > 2σ of its spread."""
    returns = _series("test_return_mean", root=root, run_glob=run_glob)
    with open(os.path.join(ROOT, "random_baseline.json")) as f:
        base = json.load(f)
    assert len(returns) >= 10
    final = np.mean([v for _, v in returns[-3:]])
    assert final > base["random_return_mean"] + 2 * base["random_return_std"], (
        final, base)


@pytest.mark.parametrize("seed", [1, 3])
def test_refpoint_noisy_seeds_beat_random_bar(seed):
    """Round-5 16-AGV campaign at the reference operating point (16/2/4ch,
    d128): the two CLEARING seeds of the recipe+NoisyNet arm stay above
    the measured +2σ random bar (runs/config2_scaling/SUMMARY.md — the
    campaign as a whole is a documented negative at 2/5; this pins
    exactly what is claimed, no more)."""
    path = os.path.join(
        RUNS, "config2_scaling",
        f"metrics_r5recipe_refpoint_noisy_seed{seed}.jsonl")
    if not os.path.exists(path):
        pytest.skip("campaign artifact not present")
    rows = [json.loads(l) for l in open(path)]
    returns = [r["value"] for r in rows if r["key"] == "test_return_mean"]
    with open(os.path.join(RUNS, "config2_scaling",
                           "random_baseline_refpoint.json")) as f:
        base = json.load(f)
    bar = base["random_return_mean"] + 2 * base["random_return_std"]
    assert len(returns) >= 10
    assert np.mean(returns[-3:]) > bar


def test_loss_decreased_by_an_order_of_magnitude():
    losses = _series("loss")
    assert len(losses) >= 10
    first = np.mean([v for _, v in losses[:2]])
    last = np.mean([v for _, v in losses[-2:]])
    assert last < first / 10.0, (first, last)


def test_conflicts_driven_down():
    crs = _series("test_conflict_ratio_mean")
    last = np.mean([v for _, v in crs[-3:]])
    assert last < 0.1, crs[-3:]


# ---------------------------------------------------------------- qslice run
# Same config-1 scale point trained end-to-end through the query-slice
# learner path (runs/config1_qslice, seed 4 of the 5-seed sweep) — pins that
# the default fast path learns, not just that it matches the dense forward.

QS_ROOT = os.path.join(RUNS, "config1_qslice")


def test_qslice_run_loss_decreased():
    losses = _series("loss", root=QS_ROOT, run_glob="qmix*seed4*")
    first = np.mean([v for _, v in losses[:2]])
    last = np.mean([v for _, v in losses[-2:]])
    # seed 4's artifact: 6028 → 2041 (2.95×); the return-vs-baseline test
    # above is the primary quality gate, this one pins the optimizer works
    assert last < first / 2.5, (first, last)
