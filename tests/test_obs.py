"""graftscope observability layer (``t2omca_tpu/obs``,
docs/OBSERVABILITY.md): span recorder schema/nesting/overhead, flight-
recorder tail ordering + atomic persistence, the profiler-trace →
program attribution parser, the report CLI against a seeded run dir,
the Logger history cap, and — slow-marked — driver integration: an
injected stall/crash/SIGTERM must each leave the flight trail the layer
exists to provide (the stall's ``stall_diagnosis.json`` carrying
``recent_spans`` with the hanging span last is the PR acceptance
criterion)."""

import ast
import glob
import gzip
import json
import os
import subprocess
import sys
import time

import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ObsConfig,
                               ReplayConfig, ResilienceConfig, TrainConfig,
                               sanity_check)
from t2omca_tpu.obs.spans import (KNOWN_PHASES, NULL_RECORDER,
                                  SpanRecorder, make_recorder, stacked)
from t2omca_tpu.utils.logging import Logger

REPO = os.path.join(os.path.dirname(__file__), "..")


# ---------------------------------------------------------------------------
# span recorder (jit-free units)
# ---------------------------------------------------------------------------

def test_span_schema_and_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    rec = SpanRecorder(ring_size=16, jsonl_path=path, flush_every=1)
    rec.mark("run", backend="cpu", superstep=4)
    with rec.span("dispatch.superstep", t_env=48, attempt=1, k=4):
        pass
    with rec.span("dispatch.superstep", t_env=96, attempt=1, k=4):
        pass
    rec.close()
    events = [json.loads(l) for l in open(path)]
    assert [e["event"] for e in events] == ["mark", "span", "span"]
    mark, first, second = events
    assert mark["kind"] == "run" and mark["superstep"] == 4
    for e in (first, second):
        assert e["phase"] == "dispatch.superstep"
        assert e["outcome"] == "ok"
        assert e["attempt"] == 1 and e["k"] == 4
        assert isinstance(e["wall_ms"], float) and e["wall_ms"] >= 0
        assert e["depth"] == 0
    # the first clean completion of a phase is the compile-inclusive
    # one (the watchdog's compile exemption, made measurable)
    assert first.get("first") is True
    assert "first" not in second
    assert first["seq"] < second["seq"]
    assert first["t_env"] == 48 and second["t_env"] == 96


def test_span_nesting_error_outcome_and_summary():
    rec = SpanRecorder()
    with pytest.raises(ValueError):
        with rec.span("checkpoint.save", t_env=1):
            with rec.span("collective.gather", t_env=1):
                raise ValueError("torn write")
    with rec.span("checkpoint.save", t_env=2):
        pass
    tail = rec.tail()
    inner = next(e for e in tail if e["phase"] == "collective.gather")
    outer_err = next(e for e in tail if e["phase"] == "checkpoint.save"
                     and e["outcome"] != "ok")
    assert inner["depth"] == 1 and inner["outcome"] == "error:ValueError"
    assert outer_err["depth"] == 0
    # an exception is NOT a completion: first_ms belongs to the first
    # CLEAN occurrence (matching Watchdog.clear(completed=...))
    s = rec.summary()["checkpoint.save"]
    assert s["n"] == 2
    assert s["first_ms"] >= 0
    ok = next(e for e in tail if e["phase"] == "checkpoint.save"
              and e["outcome"] == "ok")
    assert ok.get("first") is True


def test_flight_tail_open_span_last_and_persist_atomic(tmp_path):
    rec = SpanRecorder(ring_size=4)
    for i in range(6):                       # overflow the ring
        with rec.span("fetch.train_stats", t_env=i):
            pass
    hang = rec.span("dispatch.superstep", t_env=99)
    hang.__enter__()                         # stalled: never exits
    time.sleep(0.01)
    tail = rec.tail()
    assert len(tail) == 5                    # 4 ring + 1 open
    assert tail[-1]["phase"] == "dispatch.superstep"
    assert tail[-1]["open"] is True
    assert tail[-1]["wall_ms"] >= 10.0       # elapsed-so-far, not zero
    assert all("open" not in e for e in tail[:-1])
    # atomic persist replaces whatever was there (no torn JSON)
    target = str(tmp_path / "flight_recorder.json")
    with open(target, "w") as f:
        f.write("{'torn")
    assert rec.persist(target) == target
    data = json.load(open(target))
    assert data["events"][-1]["phase"] == "dispatch.superstep"
    assert not os.path.exists(target + ".tmp")
    hang.__exit__(None, None, None)


def test_null_recorder_and_make_recorder(tmp_path):
    assert NULL_RECORDER.enabled is False
    with NULL_RECORDER.span("dispatch.rollout", t_env=3):
        pass
    NULL_RECORDER.mark("run")
    assert NULL_RECORDER.tail() == []
    assert NULL_RECORDER.persist(str(tmp_path / "x.json")) is None
    assert not (tmp_path / "x.json").exists()
    # config plumbing: disabled -> the shared null recorder, no files
    assert make_recorder(ObsConfig(), str(tmp_path)) is NULL_RECORDER
    rec = make_recorder(ObsConfig(enabled=True, ring_size=7),
                        str(tmp_path))
    assert rec.enabled and rec.ring_size == 7
    assert rec.jsonl_path == str(tmp_path / "spans.jsonl")


def test_stacked_context_order_and_error_propagation():
    order = []

    class Ctx:
        def __init__(self, name):
            self.name = name

        def __enter__(self):
            order.append(("enter", self.name))

        def __exit__(self, exc_type, *exc):
            order.append(("exit", self.name,
                          exc_type.__name__ if exc_type else None))

    with pytest.raises(RuntimeError):
        with stacked(Ctx("watchdog"), Ctx("span")):
            raise RuntimeError("x")
    # watchdog stamp is the OUTER context: entered first, exited last,
    # and both see the exception
    assert order == [("enter", "watchdog"), ("enter", "span"),
                     ("exit", "span", "RuntimeError"),
                     ("exit", "watchdog", "RuntimeError")]


def test_span_overhead_under_budget(tmp_path):
    """Acceptance: span recording must cost < 1% of a steady-state
    iteration. The CPU smoke config's warm superstep dispatch is tens
    of ms and carries ~3 spans — so the per-span budget is generous;
    assert a hard per-span ceiling loose enough for a loaded CI box
    (measured ~5 µs enabled, ~0.2 µs disabled; docs/OBSERVABILITY.md)."""
    n = 2000
    rec = SpanRecorder(ring_size=64,
                       jsonl_path=str(tmp_path / "spans.jsonl"),
                       flush_every=32)
    t0 = time.perf_counter()
    for i in range(n):
        with rec.span("dispatch.superstep", t_env=i, attempt=1):
            pass
    per_span = (time.perf_counter() - t0) / n
    rec.close()
    assert per_span < 500e-6, f"enabled span cost {per_span * 1e6:.1f}µs"
    t0 = time.perf_counter()
    for i in range(n):
        with NULL_RECORDER.span("dispatch.superstep", t_env=i):
            pass
    per_null = (time.perf_counter() - t0) / n
    assert per_null < 50e-6, f"disabled span cost {per_null * 1e6:.1f}µs"


# ---------------------------------------------------------------------------
# hook coverage: every driver/bench span phase is registered
# ---------------------------------------------------------------------------

def _literal_phases(path, fn_names=(), span_attrs=("span",)):
    """Literal first-arg phases of wrapper calls (``_watched(...)``) and
    recorder ``.span(...)`` attribute calls in one source file."""
    tree = ast.parse(open(path).read())
    phases = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name_hit = (isinstance(node.func, ast.Name)
                    and node.func.id in fn_names)
        attr_hit = (isinstance(node.func, ast.Attribute)
                    and node.func.attr in span_attrs)
        if not (name_hit or attr_hit):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            phases.add(node.args[0].value)
    return phases


def test_every_driver_phase_is_registered():
    """The GL110 contract, asserted directly (the lint prelude enforces
    it too — this is the in-suite meta-test the satellite asks for):
    every watchdog-stamped phase in run.py and every bench span phase
    is in obs/spans.KNOWN_PHASES, so each has flight coverage."""
    driver = _literal_phases(
        os.path.join(REPO, "t2omca_tpu", "run.py"),
        fn_names=("_watched", "_sync_point", "_dispatch"))
    assert driver, "driver phase scan found nothing — scan broken?"
    assert driver <= KNOWN_PHASES, driver - KNOWN_PHASES
    bench = _literal_phases(os.path.join(REPO, "bench.py"))
    assert {"bench.probe", "bench.build", "bench.compile",
            "bench.measure"} <= bench
    assert bench <= KNOWN_PHASES, bench - KNOWN_PHASES
    # the resilience hook table and the span registry stay aligned for
    # the dispatch/fetch boundaries both name
    from t2omca_tpu.utils import resilience  # noqa: F401 — doc anchor
    for phase in ("dispatch.superstep", "dispatch.rollout",
                  "dispatch.train", "dispatch.test", "dispatch.wait",
                  "fetch.train_infos", "fetch.train_stats",
                  "fetch.test_stats", "collective.gather",
                  "backend.init",
                  # sebulba decoupled-loop boundaries (run.run_sebulba)
                  "actor.dispatch", "queue.put", "queue.get",
                  "learner.dispatch", "params.sync"):
        assert phase in KNOWN_PHASES, phase


# ---------------------------------------------------------------------------
# device-time attribution parser (synthetic trace — no profiler needed)
# ---------------------------------------------------------------------------

def test_parse_trace_device_times_synthetic(tmp_path):
    from t2omca_tpu.obs.device_time import parse_trace_device_times
    d = tmp_path / "plugins" / "profile" / "2026_08_03"
    d.mkdir(parents=True)
    trace = {"traceEvents": [
        # host executor track (pid 1): PjitFunction form, with a
        # nested same-call duplicate (observed on real CPU traces) —
        # the dedupe must count ONE call, and symbol rank must prefer
        # the device-module form below over this host track
        {"ph": "X", "pid": 1, "tid": 7, "ts": 0,
         "name": "PjitFunction(_superstep)", "dur": 9000},
        {"ph": "X", "pid": 1, "tid": 7, "ts": 1,
         "name": "PjitFunction(_superstep)", "dur": 8998},
        # device track (pid 2): the real execution time — attribution
        # must pick this (rank-0 symbol), not sum host+device
        {"ph": "X", "pid": 2, "ts": 0, "name": "XlaModule jit__superstep",
         "dur": 4000},
        {"ph": "X", "pid": 2, "ts": 5000,
         "name": "XlaModule jit__superstep", "dur": 6000},
        {"ph": "X", "pid": 2, "ts": 12000,
         "name": "XlaModule jit__rollout", "dur": 1500},
        # incomplete / unrelated events are ignored
        {"ph": "B", "pid": 2, "name": "jit__rollout"},
        {"ph": "X", "pid": 2, "ts": 0, "name": "something_else",
         "dur": 9999},
    ]}
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(trace, f)
    out = parse_trace_device_times(str(tmp_path))
    assert out["superstep"] == {"device_ms": 10.0, "events": 2,
                                "median_ms": 6.0}
    assert out["rollout"] == {"device_ms": 1.5, "events": 1,
                              "median_ms": 1.5}
    assert "train_iter" not in out          # no events, no entry
    # empty dir: no events, no crash
    assert parse_trace_device_times(str(tmp_path / "nope")) == {}


# ---------------------------------------------------------------------------
# report CLI against a seeded run dir (jax-free)
# ---------------------------------------------------------------------------

def _seed_run_dir(tmp_path, with_device_times=False):
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    events = [{"event": "mark", "kind": "run", "seq": 1, "t0": 0.0,
               "backend": "cpu", "batch_size_run": 2, "episode_limit": 6,
               "batch_size": 4, "superstep": 4}]
    seq = 2
    for i in range(4):
        events.append({"event": "span", "seq": seq, "t0": 0.0,
                       "phase": "dispatch.superstep", "t_env": 48 * i,
                       "depth": 0, "wall_ms": 5000.0 if i == 0 else 100.0,
                       "outcome": "ok", **({"first": True} if i == 0
                                           else {})})
        seq += 1
    events.append({"event": "span", "seq": seq, "t0": 0.0,
                   "phase": "fetch.train_stats", "t_env": 192, "depth": 0,
                   "wall_ms": 2.0, "outcome": "ok", "first": True})
    with open(run_dir / "spans.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    if with_device_times:
        with open(run_dir / "device_times.json", "w") as f:
            json.dump({"version": 1, "t_env": 192, "programs": {
                "superstep": {"device_ms": 240.0, "events": 3}}}, f)
    return run_dir


def test_report_cli_joins_spans_and_budgets(tmp_path, capsys):
    from t2omca_tpu.obs.__main__ import main
    rc = main(["report", str(_seed_run_dir(tmp_path))])
    out = capsys.readouterr().out
    assert rc == 0
    # the per-program join: measured wall next to programs.json budgets
    assert "superstep" in out and "dispatch.superstep" in out
    assert "wall" in out                      # time source column
    assert "5,000.0" in out                   # first (compile) ms
    assert "100.0" in out                     # steady ms/dispatch
    assert "FLOP/B" in out                    # budget-side columns joined
    assert "fetch.train_stats" in out         # non-program phase table
    assert "superstep=4" in out               # run header echoed


def test_report_cli_device_times_and_roofline(tmp_path, capsys):
    from t2omca_tpu.obs.__main__ import main
    run_dir = _seed_run_dir(tmp_path, with_device_times=True)
    rc = main(["report", str(run_dir), "--peak-gflops", "100",
               "--peak-gbps", "50"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "device" in out                    # device attribution used
    assert "roofline bound" in out and "%" in out


def test_report_cli_sebulba_utilization_section(tmp_path, capsys):
    """A decoupled run's report gains the actor/learner utilization
    table (busy = dispatch spans, idle = queue-wait spans) and the last
    queue-depth mark; classic runs (no sebulba phases) keep their
    report unchanged."""
    from t2omca_tpu.obs.__main__ import main
    from t2omca_tpu.obs.report import sebulba_utilization
    run_dir = tmp_path / "seb_run"
    run_dir.mkdir()
    events = [{"event": "mark", "kind": "run", "seq": 1, "t0": 0.0,
               "backend": "cpu", "batch_size_run": 2, "episode_limit": 6,
               "batch_size": 4, "superstep": 1, "queue_slots": 2,
               "staleness": 1}]
    seq = 2
    for i in range(4):
        for phase, ms in (("actor.dispatch", 60.0), ("queue.put", 20.0),
                          ("queue.get", 30.0), ("learner.dispatch", 50.0),
                          ("params.sync", 1.0)):
            events.append({"event": "span", "seq": seq, "phase": phase,
                           "t_env": 12 * i, "t0": float(i), "depth": 0,
                           "wall_ms": ms, "outcome": "ok"})
            seq += 1
    events.append({"event": "mark", "kind": "sebulba", "seq": seq,
                   "t0": 5.0, "t_env": 48, "queue_depth": 1,
                   "actor_idle_s": 0.08, "learner_idle_s": 0.12})
    with open(run_dir / "spans.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    rc = main(["report", str(run_dir)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "sebulba utilization" in out
    assert "actor" in out and "learner" in out
    assert "queue depth" in out
    # the numbers behind the table: busy/(busy+idle) per side
    u = sebulba_utilization(events, {
        "actor.dispatch": {"total_ms": 240.0},
        "queue.put": {"total_ms": 80.0},
        "queue.get": {"total_ms": 120.0},
        "learner.dispatch": {"total_ms": 200.0}})
    assert u["actor"]["util_pct"] == 75.0      # 240/(240+80)
    assert u["learner"]["util_pct"] == 62.5    # 200/(200+120)
    assert u["queue_depth"] == 1 and u["queue_slots"] == 2
    # classic runs: no section
    assert sebulba_utilization(
        [], {"dispatch.superstep": {"total_ms": 10.0}}) is None


def test_report_cli_usage_errors(tmp_path, capsys):
    from t2omca_tpu.obs.__main__ import main
    assert main(["report", str(tmp_path / "missing")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["report", str(empty)]) == 2   # no spans.jsonl


@pytest.mark.slow   # subprocess import check (~2 s interpreter startup)
def test_report_cli_is_jax_free():
    """The report must run on a host that cannot initialize a backend —
    the post-mortem case it exists for — so importing it must not pull
    in jax."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import t2omca_tpu.obs.report, t2omca_tpu.obs.__main__, sys; "
         "assert 'jax' not in sys.modules, 'report imports jax'"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]


# ---------------------------------------------------------------------------
# Logger history cap (satellite: unbounded self.stats growth)
# ---------------------------------------------------------------------------

def test_logger_history_is_capped():
    logger = Logger(max_history=64)
    for i in range(1000):
        logger.log_stat("loss", float(i), i)
    hist = logger.stats["loss"]
    assert len(hist) <= 64
    assert hist[-1] == (999, 999.0)           # newest entries survive
    # print_recent_stats (the only reader) still works on the tail
    logger.print_recent_stats()
    # 0 = unbounded (the pre-cap behavior, explicitly opt-in)
    unbounded = Logger(max_history=0)
    for i in range(3000):
        unbounded.log_stat("loss", float(i), i)
    assert len(unbounded.stats["loss"]) == 3000
    assert Logger().max_history == Logger.DEFAULT_MAX_HISTORY


def test_obs_config_sanity():
    base = TrainConfig()
    assert base.obs.enabled is False          # telemetry is opt-in
    for bad in (dict(ring_size=0), dict(flush_every=0),
                dict(stats_history=-1), dict(program_trace=True)):
        with pytest.raises(ValueError):
            sanity_check(TrainConfig(obs=ObsConfig(**bad)))
    # program_trace without the master switch contradicts the
    # enabled=False no-telemetry contract (dead-knob policy)
    with pytest.raises(ValueError):
        sanity_check(TrainConfig(profile_dir="/tmp/x",
                                 obs=ObsConfig(program_trace=True)))
    # valid with BOTH the profiler window and the master switch
    sanity_check(TrainConfig(profile_dir="/tmp/x",
                             obs=ObsConfig(enabled=True,
                                           program_trace=True)))


# ---------------------------------------------------------------------------
# driver integration (tiny CPU configs; slow — full run() legs)
# ---------------------------------------------------------------------------

def tiny_cfg(tmp_path, **kw):
    res_kw = kw.pop("res_kw", {})
    obs_kw = kw.pop("obs_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=True, save_model_interval=12,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        resilience=ResilienceConfig(stall_grace_s=0.0, **res_kw),
        obs=ObsConfig(enabled=True, flush_every=1, **obs_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _run_dir(tmp_path):
    dirs = [d for d in glob.glob(os.path.join(str(tmp_path), "*"))
            if os.path.isdir(d) and os.path.basename(d) != "models"]
    assert len(dirs) == 1, dirs
    return dirs[0]


def _span_events(run_dir):
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        return [json.loads(l) for l in f if l.strip()]


@pytest.fixture()
def _no_fault_leaks():
    from t2omca_tpu.utils import resilience
    resilience.clear_faults()
    yield
    resilience.clear_faults()


@pytest.mark.slow
@pytest.mark.faultinject
def test_stall_diagnosis_carries_flight_tail(tmp_path, _no_fault_leaks):
    """Acceptance: an injected hang in ``dispatch.superstep`` leaves a
    ``stall_diagnosis.json`` containing the flight-recorder tail with
    the hanging span LAST (open, wall-so-far >= the watchdog timeout).
    The diagnosis is written by the watchdog thread WHILE the main
    thread is still wedged — the post-mortem trail a wedged BENCH run
    never used to leave."""
    import jax  # noqa: F401 — ensures backend up before timing
    from t2omca_tpu.run import run
    from t2omca_tpu.utils import resilience

    cfg = tiny_cfg(tmp_path, superstep=2,
                   res_kw=dict(dispatch_timeout=0.75))
    hung = []

    def _hang(t_env, **kw):
        if t_env >= 24 and not hung:
            hung.append(t_env)
            time.sleep(2.5)

    resilience.register_fault("dispatch.superstep", _hang)
    run(cfg, Logger())
    assert hung == [24]
    model_dir = glob.glob(os.path.join(str(tmp_path), "models", "*"))[0]
    with open(os.path.join(model_dir, "stall_diagnosis.json")) as f:
        diag = json.load(f)
    assert diag["phase"] == "dispatch.superstep"
    spans = diag["recent_spans"]
    assert spans, "flight tail missing from the diagnosis"
    last = spans[-1]
    assert last["phase"] == "dispatch.superstep"
    assert last["open"] is True
    assert last["t_env"] == 24
    assert last["wall_ms"] >= cfg.resilience.dispatch_timeout * 1000.0
    # everything before the hang is a completed span/mark
    assert all(not e.get("open") for e in spans[:-1])
    # the run's own span stream also recorded warm dispatches first
    events = _span_events(_run_dir(tmp_path))
    phases = {e.get("phase") for e in events if e["event"] == "span"}
    assert "dispatch.superstep" in phases


@pytest.mark.slow
@pytest.mark.faultinject
def test_crash_persists_flight_recorder(tmp_path, _no_fault_leaks):
    from t2omca_tpu.run import run
    from t2omca_tpu.utils import resilience

    cfg = tiny_cfg(tmp_path)

    def _boom(t_env, **kw):
        if t_env >= 24:
            raise RuntimeError("deterministic bug, nothing to retry")

    resilience.register_fault("driver.iteration", _boom)
    with pytest.raises(RuntimeError, match="nothing to retry"):
        run(cfg, Logger())
    run_dir = _run_dir(tmp_path)
    flight = json.load(open(os.path.join(run_dir,
                                         "flight_recorder.json")))
    assert flight["events"], "crash left an empty flight recorder"
    crash = [e for e in flight["events"]
             if e["event"] == "mark" and e["kind"] == "crash"]
    assert crash and "nothing to retry" in crash[0]["error"]
    # the dispatches leading up to the crash are in the tail
    assert any(e.get("phase") == "dispatch.rollout"
               for e in flight["events"])


@pytest.mark.slow
@pytest.mark.faultinject
def test_sigterm_persists_flight_and_span_coverage(tmp_path,
                                                   _no_fault_leaks):
    """SIGTERM flight persistence, plus the runtime half of the
    hook-coverage meta-test: every phase the classic loop dispatches
    shows up as a completed span in spans.jsonl."""
    from t2omca_tpu.run import run
    from t2omca_tpu.utils import resilience

    cfg = tiny_cfg(tmp_path)

    def _preempt(t_env, guard=None, **kw):
        if t_env >= 36 and guard is not None:
            guard.request("test-sigterm")

    resilience.register_fault("driver.iteration", _preempt)
    run(cfg, Logger())
    run_dir = _run_dir(tmp_path)
    flight = json.load(open(os.path.join(run_dir,
                                         "flight_recorder.json")))
    kinds = [e["kind"] for e in flight["events"]
             if e["event"] == "mark"]
    assert "shutdown" in kinds
    events = _span_events(run_dir)
    phases = {e.get("phase") for e in events if e["event"] == "span"}
    # classic-loop coverage: rollout + train dispatches, the stat
    # fetches, the checkpoint save, and the startup backend init
    for expect in ("backend.init", "dispatch.rollout", "dispatch.train",
                   "fetch.train_stats", "checkpoint.save"):
        assert expect in phases, (expect, sorted(phases))
    assert phases <= KNOWN_PHASES, phases - KNOWN_PHASES
    # outcome bookkeeping: clean run, no error spans
    assert all(e["outcome"] == "ok" for e in events
               if e["event"] == "span")


@pytest.mark.slow
def test_report_cli_on_real_smoke_run(tmp_path):
    """Acceptance: ``python -m t2omca_tpu.obs report`` on a CPU smoke
    run (tiny config, superstep=4) prints the per-program table joining
    measured wall time with the graftprog budgets."""
    from t2omca_tpu.obs.__main__ import main
    from t2omca_tpu.run import run

    cfg = tiny_cfg(tmp_path, superstep=4, save_model=False,
                   save_model_interval=1_000_000, t_max=96)
    run(cfg, Logger())
    run_dir = _run_dir(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.obs", "report", run_dir],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    out = proc.stdout
    assert "superstep" in out and "dispatch.superstep" in out
    assert "FLOP/B" in out
    assert "superstep=4" in out
    # in-process too (covers the argparse path without a subprocess)
    assert main(["report", run_dir]) == 0
