"""The multi-host (DCN) leg, actually multi-process (SURVEY.md §2.2
'Communication backend'; A8): two OS processes x 4 virtual CPU devices
each form one 8-device global mesh via ``maybe_initialize_distributed``
(the production entry, driven by the standard topology env vars), and the
full rollout -> insert -> train step runs sharded ACROSS the process
boundary — the gradient psum rides the cross-process collective backend.

This is the strongest distributed evidence available without a pod: the
same code path on a TPU pod only swaps gloo for ICI/DCN."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from t2omca_tpu.parallel import maybe_initialize_distributed
from t2omca_tpu.utils import resilience

# two fresh interpreters + gloo rendezvous + full program compiles per
# 2-process test (~200 s on the 2-core CI box) — far outside the tier-1
# 870 s budget; those carry @pytest.mark.slow individually. The init
# retry/backoff tests below are in-gate (host-only, milliseconds).
REPO = os.path.join(os.path.dirname(__file__), "..")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(extra_env=None):
    """Start the 2-process worker pair; return the live Popen handles."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.abspath(REPO)]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join("tests", "mp_worker.py")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    return procs


def _launch_workers(extra_env=None):
    """Start the 2-process worker pair; return their stdouts."""
    outs = []
    for p in _spawn_workers(extra_env):
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)
    return outs


def _parse(outs, tag):
    vals = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith(tag + " ")]
        assert len(lines) == 1, out
        vals.append(float(lines[0].split()[1]))
    return vals


@pytest.mark.slow
def test_two_process_train_step_agrees():
    losses = _parse(_launch_workers(), "LOSS")
    # identical loss on both processes: the psum crossed the boundary
    np.testing.assert_allclose(losses[0], losses[1], rtol=0, atol=0)


@pytest.mark.slow
def test_two_process_checkpoint_restores_single_process(tmp_path):
    """VERDICT r4 item 6: a checkpoint SAVED FROM the 2-process mesh
    (gather-to-process-0 collective in save_checkpoint) restores in a
    plain single-process build via the model-only fallback
    (load_learner_state) and evaluates to the identical greedy metric."""
    from mp_worker import eval_fingerprint, worker_config
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import find_checkpoint, load_learner_state

    ckpt_root = str(tmp_path / "mh_ckpt")
    outs = _launch_workers({"MP_CKPT_DIR": ckpt_root})
    evals = _parse(outs, "EVAL")
    # both processes evaluate the identically-trained replicated model
    np.testing.assert_allclose(evals[0], evals[1], rtol=0, atol=0)

    found = find_checkpoint(ckpt_root)
    assert found is not None, "process 0 must have written the checkpoint"
    dirname, step = found
    assert step == 32
    assert os.path.exists(os.path.join(dirname, "meta.json"))

    # single-process restore, model-only fallback (reference semantics:
    # runner-side state starts fresh — exactly what eval_fingerprint uses)
    exp = Experiment.build(worker_config())
    ts = load_learner_state(dirname, exp.init_train_state(0))
    metric = eval_fingerprint(exp, ts.learner.params["agent"])
    np.testing.assert_allclose(metric, evals[0], rtol=0, atol=0)


@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_one_host_survivor_exits_resumable(tmp_path):
    """graftmorph chaos acceptance (ISSUE/docs/RESILIENCE.md §6): SIGKILL
    one of the two gloo hosts after the complete collective save. The
    survivor's preemption barrier must fail BOUNDED (not hang on the
    corpse), degrade to the per-host shard save, skip the resulting
    incomplete partial via the all-shards-or-skip gate, and exit 0
    pointing at the newest COMPLETE save — which a fresh SINGLE-process
    build (2 hosts x 4 devices -> 1 host) then restores elastically to
    the identical eval fingerprint."""
    from mp_worker import eval_fingerprint, worker_config
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import (find_checkpoint,
                                             restore_elastic,
                                             verify_checkpoint)

    ckpt_root = str(tmp_path / "chaos_ckpt")
    procs = _spawn_workers({"MP_CKPT_DIR": ckpt_root, "MP_CHAOS": "1"})
    out0, err0 = procs[0].communicate(timeout=900)
    # the victim died by SIGKILL (rc is -9 by design — never asserted);
    # reap it so no zombie outlives the test
    procs[1].communicate(timeout=900)
    assert procs[0].returncode == 0, f"survivor failed:\n{err0[-3000:]}"

    # the survivor resolved the COMPLETE collective save at 32, not its
    # own incomplete 1-of-2 partial at 48
    ckpt_lines = [l for l in out0.splitlines() if l.startswith("CKPT ")]
    assert ckpt_lines == ["CKPT 32"], out0

    # the degraded shard landed on disk but fails the completeness gate
    part = os.path.join(ckpt_root, "48")
    assert os.path.exists(os.path.join(part, "shard.0-of-2.msgpack"))
    assert not verify_checkpoint(part)
    found = find_checkpoint(ckpt_root)
    assert found is not None and found[1] == 32
    assert verify_checkpoint(found[0])

    # single-process elastic restore of the survivor-selected save: the
    # replicated model evaluates bit-identically to the 2-process run
    exp = Experiment.build(worker_config())
    ts = restore_elastic(found[0], exp.init_train_state(0))
    metric = eval_fingerprint(exp, ts.learner.params["agent"])
    np.testing.assert_allclose(metric, _parse([out0], "EVAL")[0],
                               rtol=0, atol=0)


# ---------------------------------------------------------------------------
# init retry/backoff (in-gate: host-only, the real initialize is stubbed).
# The 2-process rendezvous used to die ~50% of the time on this box to a
# transient gloo EnforceNotMet (CHANGES.md); maybe_initialize_distributed
# now retries transient-classified failures with backoff
# (utils.watchdog.retry_call) and the `backend.init` injection point makes
# the flake reproducible on demand.
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def test_init_retries_transient_rendezvous_failure(monkeypatch):
    """Attempt 1 hits the gloo flake (injected at backend.init), attempt
    2 succeeds — the job starts instead of dying at step zero. The real
    initialize must run exactly once (on the surviving attempt)."""
    calls = []
    monkeypatch.setattr("jax.distributed.initialize",
                        lambda **kw: calls.append(kw))
    attempts = []

    def _flaky(attempt):
        attempts.append(attempt)
        if attempt == 1:
            raise RuntimeError(
                "Gloo connectFullMesh failed: EnforceNotMet preamble "
                "size mismatch")

    resilience.register_fault("backend.init", _flaky)
    assert maybe_initialize_distributed(
        coordinator_address="localhost:1", num_processes=2, process_id=0,
        retries=3)
    assert attempts == [1, 2]
    assert len(calls) == 1
    assert calls[0]["num_processes"] == 2


def test_init_does_not_retry_deterministic_error(monkeypatch):
    """A non-transient init error (bad topology) must fail on the FIRST
    attempt — retrying a deterministic mistake only delays the real
    diagnosis."""
    calls = []

    def _bad(**kw):
        calls.append(kw)
        raise RuntimeError("invalid process id -7")

    monkeypatch.setattr("jax.distributed.initialize", _bad)
    with pytest.raises(RuntimeError, match="invalid process id"):
        maybe_initialize_distributed(coordinator_address="localhost:1",
                                     num_processes=2, process_id=0,
                                     retries=3)
    assert len(calls) == 1


def test_init_exhausted_retries_reraises(monkeypatch):
    """A persistent transient failure exhausts the attempts and surfaces
    the LAST error unmodified (callers keep their except clauses).
    ``retries`` counts attempts BEYOND the first (the resilience.
    dispatch_retries convention): retries=1 -> 2 total attempts."""
    calls = []

    def _always_flaky(**kw):
        calls.append(kw)
        raise RuntimeError("connection reset by peer")

    monkeypatch.setattr("jax.distributed.initialize", _always_flaky)
    with pytest.raises(RuntimeError, match="connection reset"):
        maybe_initialize_distributed(coordinator_address="localhost:1",
                                     num_processes=2, process_id=0,
                                     retries=1)
    assert len(calls) == 2


def test_init_retries_zero_means_single_attempt(monkeypatch):
    """retries=0 disables the retry entirely — one attempt, matching
    resilience.dispatch_retries=0 in the driver."""
    calls = []

    def _always_flaky(**kw):
        calls.append(kw)
        raise RuntimeError("connection reset by peer")

    monkeypatch.setattr("jax.distributed.initialize", _always_flaky)
    with pytest.raises(RuntimeError, match="connection reset"):
        maybe_initialize_distributed(coordinator_address="localhost:1",
                                     num_processes=2, process_id=0,
                                     retries=0)
    assert len(calls) == 1


def test_init_nonnumeric_env_retries_falls_back(monkeypatch):
    """A non-numeric T2OMCA_INIT_RETRIES must not crash the job at
    startup — it is ignored with a warning and the default (2 retries,
    3 attempts) applies."""
    monkeypatch.setenv("T2OMCA_INIT_RETRIES", "lots")
    calls = []

    def _always_flaky(**kw):
        calls.append(kw)
        raise RuntimeError("connection reset by peer")

    monkeypatch.setattr("jax.distributed.initialize", _always_flaky)
    with pytest.raises(RuntimeError, match="connection reset"):
        maybe_initialize_distributed(coordinator_address="localhost:1",
                                     num_processes=2, process_id=0)
    assert len(calls) == 3


def test_init_already_initialized_stays_idempotent(monkeypatch):
    """The runtime's own double-init error still reads as success — and
    is never retried."""
    calls = []

    def _dup(**kw):
        calls.append(kw)
        raise RuntimeError("jax.distributed is already initialized")

    monkeypatch.setattr("jax.distributed.initialize", _dup)
    assert maybe_initialize_distributed(coordinator_address="localhost:1",
                                        num_processes=2, process_id=0,
                                        retries=3)
    assert len(calls) == 1


def test_init_only_once_message_stays_idempotent(monkeypatch):
    """jax 0.4.37 phrases the double-init error 'distributed.initialize
    should only be called once.' (no 'already' anywhere) — it must still
    read as success on a pre-initialized runtime."""
    calls = []

    def _dup(**kw):
        calls.append(kw)
        raise RuntimeError("distributed.initialize should only be "
                           "called once.")

    monkeypatch.setattr("jax.distributed.initialize", _dup)
    assert maybe_initialize_distributed(coordinator_address="localhost:1",
                                        num_processes=2, process_id=0,
                                        retries=3)
    assert len(calls) == 1


def test_init_retry_resets_partial_state(monkeypatch):
    """jax 0.4.37 assigns global_state.service/.client BEFORE
    client.connect(), so a transient rendezvous failure leaves the
    runtime half-initialized and a bare retry would die on the
    double-init RuntimeError instead of re-attempting. The retry path
    must tear the partial state down (jax.distributed.shutdown) between
    attempts so attempt 2 genuinely re-initializes."""
    st = {"initialized": False, "connects": 0, "shutdowns": 0}

    def _partial_state_init(**kw):
        if st["initialized"]:
            raise RuntimeError("distributed.initialize should only be "
                               "called once.")
        st["initialized"] = True        # set BEFORE the connect attempt
        st["connects"] += 1
        if st["connects"] == 1:
            raise RuntimeError(
                "Gloo connectFullMesh failed: EnforceNotMet preamble "
                "size mismatch")

    def _shutdown():
        st["initialized"] = False
        st["shutdowns"] += 1

    monkeypatch.setattr("jax.distributed.initialize", _partial_state_init)
    monkeypatch.setattr("jax.distributed.shutdown", _shutdown)
    assert maybe_initialize_distributed(coordinator_address="localhost:1",
                                        num_processes=2, process_id=0,
                                        retries=3)
    assert st["connects"] == 2          # attempt 2 really re-initialized
    assert st["shutdowns"] == 1         # partial state torn down once
    assert st["initialized"]            # and the final state is live


def test_init_failed_reset_does_not_misread_double_init(monkeypatch):
    """If the between-attempts teardown fails, the double-init error on a
    RETRY means this call's own half-initialized runtime — not a
    pre-initialized one. It must surface as a failure instead of
    reporting success on a never-connected runtime that would wedge at
    the first collective."""
    st = {"initialized": False, "connects": 0}

    def _partial_state_init(**kw):
        if st["initialized"]:
            raise RuntimeError("distributed.initialize should only be "
                               "called once.")
        st["initialized"] = True        # set BEFORE the connect attempt
        st["connects"] += 1
        raise RuntimeError(
            "Gloo connectFullMesh failed: EnforceNotMet preamble "
            "size mismatch")

    def _broken_shutdown():
        raise RuntimeError("cannot shut down a half-connected client")

    monkeypatch.setattr("jax.distributed.initialize", _partial_state_init)
    monkeypatch.setattr("jax.distributed.shutdown", _broken_shutdown)
    with pytest.raises(RuntimeError, match="only be called once"):
        maybe_initialize_distributed(coordinator_address="localhost:1",
                                     num_processes=2, process_id=0,
                                     retries=3)
    assert st["connects"] == 1          # the real connect ran only once


def test_init_no_topology_is_a_noop(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "T2OMCA_MULTIHOST"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr("jax.distributed.initialize",
                        lambda **kw: pytest.fail("must not initialize"))
    assert not maybe_initialize_distributed()
