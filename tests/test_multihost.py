"""The multi-host (DCN) leg, actually multi-process (SURVEY.md §2.2
'Communication backend'; A8): two OS processes x 4 virtual CPU devices
each form one 8-device global mesh via ``maybe_initialize_distributed``
(the production entry, driven by the standard topology env vars), and the
full rollout -> insert -> train step runs sharded ACROSS the process
boundary — the gradient psum rides the cross-process collective backend.

This is the strongest distributed evidence available without a pod: the
same code path on a TPU pod only swaps gloo for ICI/DCN."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# two fresh interpreters + gloo rendezvous + full program compiles per
# test (~200 s on the 2-core CI box) — far outside the tier-1 870 s
# budget; run explicitly via `-m slow` or with no marker filter
pytestmark = pytest.mark.slow

REPO = os.path.join(os.path.dirname(__file__), "..")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_workers(extra_env=None):
    """Start the 2-process worker pair; return their stdouts."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.abspath(REPO)]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join("tests", "mp_worker.py")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)
    return outs


def _parse(outs, tag):
    vals = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith(tag + " ")]
        assert len(lines) == 1, out
        vals.append(float(lines[0].split()[1]))
    return vals


def test_two_process_train_step_agrees():
    losses = _parse(_launch_workers(), "LOSS")
    # identical loss on both processes: the psum crossed the boundary
    np.testing.assert_allclose(losses[0], losses[1], rtol=0, atol=0)


def test_two_process_checkpoint_restores_single_process(tmp_path):
    """VERDICT r4 item 6: a checkpoint SAVED FROM the 2-process mesh
    (gather-to-process-0 collective in save_checkpoint) restores in a
    plain single-process build via the model-only fallback
    (load_learner_state) and evaluates to the identical greedy metric."""
    from mp_worker import eval_fingerprint, worker_config
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import find_checkpoint, load_learner_state

    ckpt_root = str(tmp_path / "mh_ckpt")
    outs = _launch_workers({"MP_CKPT_DIR": ckpt_root})
    evals = _parse(outs, "EVAL")
    # both processes evaluate the identically-trained replicated model
    np.testing.assert_allclose(evals[0], evals[1], rtol=0, atol=0)

    found = find_checkpoint(ckpt_root)
    assert found is not None, "process 0 must have written the checkpoint"
    dirname, step = found
    assert step == 32
    assert os.path.exists(os.path.join(dirname, "meta.json"))

    # single-process restore, model-only fallback (reference semantics:
    # runner-side state starts fresh — exactly what eval_fingerprint uses)
    exp = Experiment.build(worker_config())
    ts = load_learner_state(dirname, exp.init_train_state(0))
    metric = eval_fingerprint(exp, ts.learner.params["agent"])
    np.testing.assert_allclose(metric, evals[0], rtol=0, atol=0)
