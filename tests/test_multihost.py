"""The multi-host (DCN) leg, actually multi-process (SURVEY.md §2.2
'Communication backend'; A8): two OS processes x 4 virtual CPU devices
each form one 8-device global mesh via ``maybe_initialize_distributed``
(the production entry, driven by the standard topology env vars), and the
full rollout -> insert -> train step runs sharded ACROSS the process
boundary — the gradient psum rides the cross-process collective backend.

This is the strongest distributed evidence available without a pod: the
same code path on a TPU pod only swaps gloo for ICI/DCN."""

import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.join(os.path.dirname(__file__), "..")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_train_step_agrees():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.abspath(REPO)]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        env["JAX_NUM_PROCESSES"] = "2"
        env["JAX_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join("tests", "mp_worker.py")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=900)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    losses = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("LOSS ")]
        assert len(lines) == 1, out
        losses.append(float(lines[0].split()[1]))
    # identical loss on both processes: the psum crossed the boundary
    np.testing.assert_allclose(losses[0], losses[1], rtol=0, atol=0)
