"""graftprog — the compiled-program auditor (t2omca_tpu/analysis,
docs/ANALYSIS.md): seeded-regression fixtures per GP rule, the
programs.json round-trip/ratchet/tolerance semantics, fingerprint
drift on a weak-typed scalar, and the CLI exit-code contract. The
default-registry audit itself (the same thing the scripts/t1.sh
prelude runs) is the slow half."""

import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from t2omca_tpu.analysis import (load_programs, save_programs)
from t2omca_tpu.analysis.graftprog import (GP_RULES, ProgFinding,
                                           ProgramReport, audit_program,
                                           compare_reports,
                                           fingerprint_text)
from t2omca_tpu.analysis.registry import AuditProgram

pytestmark = [pytest.mark.analysis, pytest.mark.graftprog]

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "fixtures_graftprog.py"


def _audit(fn, args, donate=(), compile=False, dtype="bfloat16"):
    return audit_program(
        "toy", AuditProgram(fn, args, donate_argnums=donate,
                            compile=compile), dtype)


# ------------------------------------------------- seeded jaxpr rules

def test_gp201_undonated_donation():
    def f(x, y):
        return x + 1.0 + 0.0 * jnp.sum(y)
    rep = _audit(jax.jit(f, donate_argnums=(0, 1)),
                 (jax.ShapeDtypeStruct((8, 8), jnp.float32),
                  jax.ShapeDtypeStruct((3,), jnp.float32)),
                 donate=(0, 1))
    assert rep.rule_count("GP201") == 1
    assert "float32[3]" in rep.rule_details["GP201"][0]


def test_gp201_survives_reaudit_of_cached_lowering():
    """jax's lowering cache suppresses the donated-buffers warning on a
    re-lower of the same jit+avals — the text-level aliasing count must
    still report the miss on the second audit."""
    def f(x, y):
        return x + 1.0 + 0.0 * jnp.sum(y)
    jf = jax.jit(f, donate_argnums=(0, 1))
    args = (jax.ShapeDtypeStruct((8, 8), jnp.float32),
            jax.ShapeDtypeStruct((3,), jnp.float32))
    first = _audit(jf, args, donate=(0, 1))
    second = _audit(jf, args, donate=(0, 1))
    assert first.rule_count("GP201") == 1
    assert second.rule_count("GP201") == 1
    assert "no input_output_alias" in second.rule_details["GP201"][0]


def test_gp201_negative_fully_aliased():
    def f(x):
        return x + 1.0
    rep = _audit(jax.jit(f, donate_argnums=(0,)),
                 (jax.ShapeDtypeStruct((8, 8), jnp.float32),),
                 donate=(0,))
    assert rep.rule_count("GP201") == 0


def test_gp202_baked_constant_and_threshold():
    big = jnp.ones((256, 256), jnp.float32)      # 256 KiB: flagged
    small = jnp.ones((4, 4), jnp.float32)        # 64 B: below threshold

    def f(x):
        return x @ big + jnp.sum(small)
    rep = _audit(jax.jit(f), (jax.ShapeDtypeStruct((8, 256),
                                                   jnp.float32),))
    assert rep.rule_count("GP202") == 1
    assert "262144 bytes" in rep.rule_details["GP202"][0]


def test_gp203_upcast_counts_and_direction():
    def f(x):
        down = x.astype(jnp.bfloat16)            # downcast: not counted
        return jnp.sum(down.astype(jnp.float32))  # upcast: counted

    rep = _audit(jax.jit(f), (jax.ShapeDtypeStruct((16,), jnp.float32),))
    assert rep.rule_count("GP203") == 1
    assert "bfloat16[16] -> float32" in rep.rule_details["GP203"][0]


def test_gp204_callback():
    def f(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    rep = _audit(jax.jit(f), (jax.ShapeDtypeStruct((4,), jnp.float32),))
    assert rep.rule_count("GP204") == 1
    assert "pure_callback" in rep.rule_details["GP204"][0]


def test_gp204_pallas_call_is_not_a_host_callback():
    """A ``pallas_call`` is a device kernel launch (Mosaic custom call /
    CPU interpreter), not a host round-trip — graftprog must never
    classify it under GP204, whatever substring its primitive name
    grows (PR 9 kernels/ layer)."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def f(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)

    rep = _audit(jax.jit(f), (jax.ShapeDtypeStruct((8, 128),
                                                   jnp.float32),))
    assert rep.rule_count("GP204") == 0


def test_gp202_skips_pallas_kernel_block_specs():
    """The kernel jaxpr's closed-over block-spec/grid machinery (and any
    constants the kernel body materializes, like a large iota grid) is
    device-kernel plumbing, not a baked host array — the GP202 walk
    treats the pallas_call as opaque. A genuine host-level closure
    constant NEXT TO the kernel must still be flagged."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        # a >16 KiB constant INSIDE the kernel body (64x128 f32 iota =
        # 32 KiB): must not trip the host-constant rule
        grid = jax.lax.broadcasted_iota(jnp.float32, (64, 128), 0)
        o_ref[...] = x_ref[...] + grid

    def gridded(x):
        return pl.pallas_call(
            kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)

    rep = _audit(jax.jit(gridded),
                 (jax.ShapeDtypeStruct((128, 128), jnp.float32),))
    assert rep.rule_count("GP202") == 0
    assert rep.rule_count("GP204") == 0

    big = jnp.ones((256, 256), jnp.float32)      # host-level: still flagged

    def with_host_const(x):
        return gridded(x) @ big

    rep = _audit(jax.jit(with_host_const),
                 (jax.ShapeDtypeStruct((128, 256), jnp.float32),))
    assert rep.rule_count("GP202") == 1


def test_flash_backward_pallas_calls_stay_opaque():
    """PR 13 backward kernels: differentiating through the flash
    attention lowers the dq/dkv pallas programs — they must get the
    SAME treatment as the forward kernel: never GP204 (a kernel launch
    is not a host callback), block-spec/grid params and kernel-internal
    f32 accumulator casts opaque to GP202/GP203. The only counted
    upcasts are the caller's own seams (here: none — all-f32 toy), and
    a genuine host constant NEXT TO the backward still trips GP202."""
    from t2omca_tpu.kernels.attention import flash_attention

    aval = jax.ShapeDtypeStruct((2, 2, 24, 8), jnp.float32)

    def loss(q, k, v):
        return (flash_attention(q, k, v, interpret=True,
                                block_q=8, block_k=8) ** 2).sum()

    grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    rep = _audit(grad, (aval, aval, aval), dtype="float32")
    assert rep.rule_count("GP204") == 0
    assert rep.rule_count("GP202") == 0
    assert rep.rule_count("GP203") == 0

    big = jnp.ones((256, 256), jnp.float32)

    def loss_with_const(q, k, v):
        dq, _, _ = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return (dq.reshape(-1, 8) @ big[:8, :8]).sum() + jnp.sum(big)

    rep = _audit(jax.jit(loss_with_const), (aval, aval, aval),
                 dtype="float32")
    assert rep.rule_count("GP202") == 1          # the host const, only
    assert rep.rule_count("GP204") == 0


def test_flash_backward_is_pallas_not_einsum_recompute():
    """The gradient of the flash kernel must run the flash BACKWARD
    kernels (three pallas_calls: residual-emitting forward, dq, dkv) —
    NOT the pre-PR-13 einsum-reference recompute, whose jaxpr had ONE
    pallas_call and a (B, H, Q, K)-shaped softmax chain in the host
    program."""
    from jax.core import ClosedJaxpr
    from t2omca_tpu.kernels.attention import flash_attention

    x = jnp.zeros((2, 2, 24, 8), jnp.float32)

    def loss(q, k, v):
        return (flash_attention(q, k, v, interpret=True,
                                block_q=8, block_k=8) ** 2).sum()

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(x, x, x)

    def count_pallas(closed):
        n = 0
        stack = [closed]
        seen = set()
        while stack:
            cj = stack.pop()
            if id(cj) in seen:
                continue
            seen.add(id(cj))
            for eqn in cj.jaxpr.eqns:
                if "pallas" in eqn.primitive.name:
                    n += 1
                    continue            # opaque, like the auditor
                for v in eqn.params.values():
                    if isinstance(v, ClosedJaxpr):
                        stack.append(v)
                    elif isinstance(v, (tuple, list)):
                        stack.extend(u for u in v
                                     if isinstance(u, ClosedJaxpr))
        return n

    assert count_pallas(jaxpr) == 3


def test_programs_json_pins_pallas_train_bytes_below_xla():
    """The PR 13 acceptance relation, enforced against the checked-in
    ratchet file (no jax, no lowering — the audit prelude keeps the
    numbers honest): under ``kernels.attention: pallas`` the lowered
    GP302 bytes AND GP301 flops of the train-path programs sit STRICTLY
    below their einsum (_ref) twins at the kernel audit scale."""
    data = json.loads(
        (REPO / "t2omca_tpu/analysis/programs.json").read_text())
    progs = data["programs"]
    for name in ("train_iter_pallas", "learner_train_pallas"):
        pal, ref = progs[name], progs[f"{name}_ref"]
        assert pal["level"] == ref["level"] == "lowered"
        assert pal["bytes_accessed"] < ref["bytes_accessed"], (
            name, pal["bytes_accessed"], ref["bytes_accessed"])
        assert pal["flops"] < ref["flops"]


def test_clean_program_no_findings_and_metrics():
    def f(x):
        return x * 2.0
    rep = _audit(jax.jit(f, donate_argnums=(0,)),
                 (jax.ShapeDtypeStruct((32, 32), jnp.float32),),
                 donate=(0,), compile=True)
    assert rep.rule_details == {}
    assert rep.level == "compiled"
    assert rep.flops and rep.flops > 0
    assert rep.peak_bytes is not None
    assert len(rep.fingerprint) == 16


def test_skip_marker_short_circuits():
    rep = audit_program("dp", AuditProgram.skipped("needs 2 devices"),
                        "float32")
    assert rep.skipped == "needs 2 devices"
    assert rep.fingerprint == ""


# ------------------------------------------------- fingerprint drift

def test_fingerprint_drift_on_weak_typed_scalar():
    """The retrace bug class ``run._strong`` exists for: a weak-typed
    scalar produces a DIFFERENT program aval than the strong input the
    driver chains back — the fingerprint must see it."""
    f = jax.jit(lambda x, t: x * t)
    x = jax.ShapeDtypeStruct((4,), jnp.bfloat16)
    weak = jnp.asarray(0.5)                # weak f32 (Python scalar):
    # adapts to x's bf16 — the compute stays narrow
    strong = jnp.zeros((), jnp.float32)    # strong f32: promotes the
    # whole expression to f32 — a different (upcast) program
    assert weak.aval.weak_type and not strong.aval.weak_type
    fp_weak = fingerprint_text(f.trace(x, weak).lower().as_text())
    fp_strong = fingerprint_text(f.trace(x, strong).lower().as_text())
    assert fp_weak != fp_strong


def test_fingerprint_stable_across_retrace():
    f = jax.jit(lambda x: x + 1)
    x = jax.ShapeDtypeStruct((4,), jnp.float32)
    assert (fingerprint_text(f.trace(x).lower().as_text())
            == fingerprint_text(
                jax.jit(lambda x: x + 1).trace(x).lower().as_text()))


# ------------------------------------- programs.json ratchet semantics

def _report(name="prog", fp="aaaa", flops=100.0, by=1000.0, peak=None,
            level="lowered", rules=None):
    return ProgramReport(name=name, fingerprint=fp, level=level,
                         flops=flops, bytes_accessed=by, peak_bytes=peak,
                         rule_details=rules or {})


def _entry(fp="aaaa", flops=100.0, by=1000.0, peak=None, tol=None,
           level="lowered", rules=None):
    e = {"fingerprint": fp, "level": level, "flops": flops,
         "bytes_accessed": by, "tolerance": tol or {},
         "justification": "test"}
    if peak is not None:
        e["peak_bytes"] = peak
    if rules:
        e["rules"] = rules
    return e


def test_ratchet_clean_match():
    new, stale = compare_reports([_report()], {"prog": _entry()})
    assert new == [] and stale == []


def test_ratchet_gp300_missing_entry_surfaces_rule_details():
    rep = _report(rules={"GP204": ["`pure_callback` ..."]})
    new, _ = compare_reports([rep], {})
    assert [f.rule for f in new] == ["GP300", "GP204"]


def test_ratchet_gp301_302_303_tolerance_boundaries():
    rep = _report(flops=112.0, by=1000.0, peak=130.0)
    base = {"prog": _entry(flops=100.0, by=1000.0, peak=100.0,
                           tol={"flops": 0.10, "peak_bytes": 0.25})}
    new, _ = compare_reports([rep], base)
    assert sorted(f.rule for f in new) == ["GP301", "GP303"]
    # exactly at tolerance: not a finding
    rep2 = _report(flops=110.0, by=1000.0, peak=125.0)
    new2, _ = compare_reports([rep2], base)
    assert new2 == []


def test_ratchet_improvement_is_stale_not_failure():
    new, stale = compare_reports(
        [_report(flops=50.0)],
        {"prog": _entry(flops=100.0, tol={"flops": 0.10})})
    assert new == []
    assert any("improved" in s for s in stale)


def test_ratchet_gp304_fingerprint_drift():
    new, _ = compare_reports([_report(fp="bbbb")],
                             {"prog": _entry(fp="aaaa")})
    assert [f.rule for f in new] == ["GP304"]


def test_ratchet_rule_count_excess_and_drop():
    rules = {"GP203": ["up1", "up2", "up3"]}
    base = {"prog": _entry(rules={"GP203": {"count": 2,
                                            "justification": "x"}})}
    new, stale = compare_reports([_report(rules=rules)], base)
    assert [f.rule for f in new] == ["GP203", "GP203"]   # excess + summary
    new2, stale2 = compare_reports(
        [_report(rules={"GP203": ["up1"]})], base)
    assert new2 == [] and any("dropped" in s for s in stale2)


def test_ratchet_level_change_and_vanished_program():
    new, stale = compare_reports(
        [_report(level="compiled")], {"prog": _entry(level="lowered"),
                                      "gone": _entry()})
    assert [f.rule for f in new] == ["GP300"]
    assert any("no longer registered" in s for s in stale)


def test_ratchet_skip_never_fails():
    rep = ProgramReport(name="dp", skipped="needs 2 devices")
    new, stale = compare_reports([rep], {"dp": _entry()})
    assert new == [] and any("skipped" in s for s in stale)


# ------------------------------------------- programs.json round-trip

def test_programs_roundtrip_preserves_justifications(tmp_path):
    p = tmp_path / "programs.json"
    rep = _report(peak=55.0, level="compiled",
                  rules={"GP203": ["up1", "up2"]})
    save_programs(p, [rep], platform="cpu")
    data = load_programs(p)
    assert data["platform"] == "cpu"
    entry = data["programs"]["prog"]
    assert entry["fingerprint"] == "aaaa"
    assert entry["peak_bytes"] == 55.0
    assert entry["rules"]["GP203"]["count"] == 2
    assert "TODO" in entry["justification"]          # new entries marked
    # hand-edit the justification + tolerance, re-save: both survive
    raw = json.loads(p.read_text())
    raw["programs"]["prog"]["justification"] = "deliberate"
    raw["programs"]["prog"]["tolerance"]["flops"] = 0.5
    raw["programs"]["prog"]["rules"]["GP203"]["justification"] = "f32 loss"
    p.write_text(json.dumps(raw))
    save_programs(p, [_report(flops=123.0, rules={"GP203": ["a", "b"]},
                              peak=55.0, level="compiled")],
                  platform="cpu", old=load_programs(p))
    entry = load_programs(p)["programs"]["prog"]
    assert entry["justification"] == "deliberate"
    assert entry["tolerance"]["flops"] == 0.5
    assert entry["rules"]["GP203"]["justification"] == "f32 loss"
    assert entry["flops"] == 123.0                   # value updated


def test_programs_save_keeps_skipped_entry(tmp_path):
    p = tmp_path / "programs.json"
    save_programs(p, [_report(name="dp")], platform="cpu")
    skipped = ProgramReport(name="dp", skipped="needs 2 devices")
    save_programs(p, [skipped], platform="cpu", old=load_programs(p))
    assert load_programs(p)["programs"]["dp"]["fingerprint"] == "aaaa"


def test_programs_version_guard(tmp_path):
    p = tmp_path / "programs.json"
    p.write_text(json.dumps({"version": 99, "programs": {}}))
    with pytest.raises(ValueError, match="version"):
        load_programs(p)


def test_checked_in_programs_baseline_is_justified():
    """Every entry (and every per-rule count) in the checked-in
    programs.json carries a real justification — the TODO marker the
    writer plants must never land on main."""
    data = load_programs()
    assert data["programs"], "checked-in programs.json is empty"
    for name, entry in data["programs"].items():
        assert "TODO" not in entry["justification"], name
        for rule, info in entry.get("rules", {}).items():
            assert rule in GP_RULES, (name, rule)
            assert "TODO" not in info["justification"], (name, rule)


def test_finding_format_and_catalog():
    f = ProgFinding("superstep", "GP201", "donated leaf x")
    assert f.format() == "superstep: GP201 donated leaf x"
    assert set(GP_RULES) == {"GP201", "GP202", "GP203", "GP204", "GP300",
                             "GP301", "GP302", "GP303", "GP304"}


# --------------------------------------------------------- CLI contract

def _cli(*args, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_cli_seeded_regressions_flip_exit_1():
    """The ISSUE acceptance gate: each planted hazard flips the CLI to
    exit 1 with the matching GP rule id (one subprocess for all four —
    a fresh jax import per rule would cost the gate ~30 s)."""
    r = _cli("--programs", "--no-baseline",
             "--program-module", str(FIXTURES),
             "--only", "seeded_gp201", "--only", "seeded_gp202",
             "--only", "seeded_gp203", "--only", "seeded_gp204")
    assert r.returncode == 1, r.stderr
    for rule, prog in [("GP201", "seeded_gp201"), ("GP202", "seeded_gp202"),
                       ("GP203", "seeded_gp203"), ("GP204", "seeded_gp204")]:
        assert f"{prog}: {rule}" in r.stdout, (rule, r.stdout)


def test_cli_clean_seeded_program_exits_0():
    r = _cli("--programs", "--no-baseline",
             "--program-module", str(FIXTURES), "--only", "seeded_clean")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new finding(s)" in r.stdout


def test_cli_unknown_program_is_usage_error():
    r = _cli("--programs", "--only", "nope")
    assert r.returncode == 2
    assert "unknown audit program" in r.stderr


def test_cli_write_programs_refuses_partial_set():
    """--write-programs writes exactly the audited set, so combining it
    with --only would silently drop every unselected baseline entry.
    Also pins that the audit flags IMPLY --programs: without the
    implication this invocation would silently run the lint path and
    exit 0 having written nothing."""
    r = _cli("--write-programs", "--only", "superstep")
    assert r.returncode == 2
    assert "cannot be combined with --only" in r.stderr


def test_cli_write_programs_corrupt_baseline_is_usage_error(tmp_path):
    """A corrupt programs.json must fail fast with the exit-2 contract
    (checked BEFORE the minutes-long audit), not a post-audit
    traceback."""
    bad = tmp_path / "programs.json"
    bad.write_text("{not json")
    r = _cli("--programs", "--write-programs",
             "--programs-baseline", str(bad), timeout=60)
    assert r.returncode == 2
    assert "unreadable baseline" in r.stderr


@pytest.mark.slow
def test_cli_default_registry_matches_checked_in_baseline():
    """The real gate prelude: the full registered-program audit against
    the checked-in programs.json exits 0 on a clean tree (and the
    seeded fixtures, which are NOT baselined, are absent)."""
    r = _cli("--programs")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new finding(s)" in r.stdout


@pytest.mark.slow
def test_registry_names_and_structure():
    from t2omca_tpu.analysis.registry import collect_default_programs
    reg = collect_default_programs()
    assert set(reg) == {"rollout", "insert", "train_iter", "superstep",
                        "dp_superstep", "learner_train", "serve_step",
                        "attn_xla", "attn_pallas", "attn_pallas_bwd",
                        "train_iter_pallas", "train_iter_pallas_ref",
                        "learner_train_pallas", "learner_train_pallas_ref",
                        "actor_step", "learner_step",
                        "env_reset", "env_step",
                        "train_iter_sight", "superstep_sight",
                        "superstep_pop", "superstep_pop_pallas",
                        "pop_dp_superstep", "pop_learner_step",
                        "dpmp_block"}
    # the donated hot programs are the compiled (memory-audited) ones
    assert reg["superstep"].compile and reg["train_iter"].compile
    assert reg["superstep"].donate_argnums == (0,)
    # mesh-bound programs exist on this host (conftest forces 8 CPU
    # devices: enough for the dp 2-mesh and the sebulba 2+2 split)
    assert reg["dp_superstep"].skip is None
    assert reg["actor_step"].skip is None
    assert reg["learner_step"].skip is None
    assert reg["learner_step"].donate_argnums == (0,)
