"""Agent/mixer family registries: RNN agent, feed-forward QMIX hypernet
mixer, VDN — the parent-lineage alternatives around the reference's
transformer pair (SURVEY.md §2.3 M7/M8 registry contracts)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.controllers.basic_mac import AGENT_REGISTRY
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.learners import QMixLearner
from t2omca_tpu.learners.qmix_learner import MIXER_REGISTRY
from t2omca_tpu.runners import ParallelRunner


def build(agent="transformer", mixer="transformer"):
    cfg = sanity_check(TrainConfig(
        agent=agent, mixer=mixer,
        batch_size_run=2, batch_size=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=5),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    return cfg, info, mac, learner, runner


def test_registries_expose_families():
    assert set(AGENT_REGISTRY) == {"transformer", "rnn"}
    assert set(MIXER_REGISTRY) == {"transformer", "qmix_ff", "vdn"}


# tier-1 budget: two combos stay in-gate and still cover every family
# (rnn+vdn, transformer+qmix_ff); the redundant pairings run as slow
@pytest.mark.parametrize("agent,mixer", [
    pytest.param("rnn", "qmix_ff", marks=pytest.mark.slow),
    ("rnn", "vdn"), ("transformer", "qmix_ff"),
    pytest.param("rnn", "transformer", marks=pytest.mark.slow),
])
def test_family_combo_trains(agent, mixer):
    cfg, info, mac, learner, runner = build(agent, mixer)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    rs, batch, stats = run(ls.params["agent"], rs, test_mode=False)
    assert batch.actions.shape == (2, 5, 3)

    w = jnp.ones((cfg.batch_size_run,))
    train = jax.jit(learner.train)
    losses = []
    for i in range(12):
        ls, tinfo = train(ls, batch, w, jnp.asarray(i), jnp.asarray(0))
        losses.append(float(tinfo["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]          # overfits one fixed batch


def test_vdn_is_exact_sum():
    _, info, _, learner, _ = build("rnn", "vdn")
    b, a = 2, info["n_agents"]
    qvals = jnp.arange(b * a, dtype=jnp.float32).reshape(b, 1, a)
    params = learner.mixer.init(
        jax.random.PRNGKey(0), qvals, jnp.zeros((b, a, 8)),
        learner.mixer.initial_hyper(b), jnp.zeros((b, info["state_shape"])),
        jnp.zeros((b, a, info["obs_shape"])))
    y, hyper = learner.mixer.apply(params, qvals, jnp.zeros((b, a, 8)),
                                   learner.mixer.initial_hyper(b),
                                   jnp.zeros((b, info["state_shape"])),
                                   jnp.zeros((b, a, info["obs_shape"])))
    np.testing.assert_allclose(np.asarray(y[..., 0]),
                               np.asarray(qvals.sum(-1)))


def test_ff_mixer_monotonic_in_agent_qs():
    _, info, _, learner, _ = build("rnn", "qmix_ff")
    b, a = 2, info["n_agents"]
    key = jax.random.PRNGKey(3)
    qvals = jax.random.normal(key, (b, 1, a))
    state = jax.random.normal(key, (b, info["state_shape"]))
    hid = jnp.zeros((b, a, 8))
    hyper = learner.mixer.initial_hyper(b)
    obs = jnp.zeros((b, a, info["obs_shape"]))
    params = learner.mixer.init(key, qvals, hid, hyper, state, obs)

    g = jax.grad(lambda qv: learner.mixer.apply(
        params, qv, hid, hyper, state, obs)[0].sum())(qvals)
    assert (np.asarray(g) >= 0).all()


def test_unknown_family_names_rejected():
    with pytest.raises(ValueError, match="unknown agent"):
        sanity_check(TrainConfig(agent="gru"))
    with pytest.raises(ValueError, match="unknown mixer"):
        sanity_check(TrainConfig(mixer="qmix"))
    with pytest.raises(ValueError, match="dropout"):
        sanity_check(TrainConfig(agent="rnn", mixer="vdn",
                                 model=ModelConfig(dropout=0.1)))
