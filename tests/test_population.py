"""graftpop: the vmapped population axis (``t2omca_tpu/population.py``,
``run.Experiment.population_superstep_program``, docs/POPULATION.md).

Pins the contracts the ISSUE-15 acceptance criteria stand on:

* P=1 training is BIT-identical to the classic superstep loop — params,
  opt_state, replay ring, PER priorities and stats all equal (the
  neutral-spec squeeze path lowers the classic program's exact
  arithmetic; even a value-neutral traced seam would perturb XLA fusion
  enough to drift a ULP, measured);
* P=2 members with different seeds diverge, while ``seed_stride=0``
  members are bit-identical to EACH OTHER (vmap applies one batched
  kernel per member — identical inputs give identical outputs) and
  member 0 tracks its solo run to float tolerance (cross-rank
  bit-parity is a CPU-XLA impossibility under vmap: batched reduces
  reassociate f32 sums — docs/POPULATION.md §parity);
* ONE donated dispatch advances all P members, compiled exactly once
  (compile_budget(1) across repeated dispatches);
* per-member knob plumbing (lr/eps/alpha spec leaves), host-side PBT
  select-and-perturb, the population stats/sight surfaces, and the
  v4→v5 single-member → PopState checkpoint lift.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu import population as graftpop
from t2omca_tpu.analysis import compile_budget
import dataclasses

from t2omca_tpu.config import (EnvConfig, ModelConfig, PBTConfig,
                               PopulationConfig, ReplayConfig, TrainConfig,
                               from_dict, sanity_check)
from t2omca_tpu.run import Experiment, run
from t2omca_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from t2omca_tpu.utils.logging import Logger
from t2omca_tpu.utils.stats import StatsAccumulator

pytestmark = pytest.mark.population


def tiny_cfg(tmp_path=None, **kw):
    """The test_superstep parity point (dense storage, sequential
    normalizer — the bit-comparable path) at test scale."""
    env_kw = kw.pop("env_kw", {})
    replay_kw = kw.pop("replay_kw", {})
    env_defaults = dict(agv_num=3, mec_num=2, num_channels=2,
                        episode_limit=6, fast_norm=False)
    env_defaults.update(env_kw)
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=False, save_model_interval=24, epsilon_anneal_time=50,
        env_args=EnvConfig(**env_defaults),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
    )
    if tmp_path is not None:
        defaults["local_results_path"] = str(tmp_path)
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def pop_cfg(p, tmp_path=None, **kw):
    pop_kw = kw.pop("pop_kw", {})
    return tiny_cfg(tmp_path, population=PopulationConfig(size=p, **pop_kw),
                    **kw)


def _pop_loop(exp, cfg, k, n_dispatches):
    """The population driver's fused path, verbatim (run.run_sequential):
    one shared gate mirror, per-member key streams, (P, K, 2) stacks."""
    p = cfg.population.size
    ts, spec = graftpop.init_population(exp, cfg)
    prog = exp.population_superstep_program(k, donate=True)
    keys = graftpop.member_keys(cfg)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, episode, filled = 0, 0, 0
    all_stats = []
    for _ in range(n_dispatches):
        rows = []
        for _ in range(k):
            episode += cfg.batch_size_run
            filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
            if filled >= cfg.batch_size:
                row = []
                for m in range(p):
                    keys[m], ks = jax.random.split(keys[m])
                    row.append(ks)
                rows.append(jnp.stack(row))
            else:
                rows.append(jnp.zeros((p,) + keys[0].shape,
                                      keys[0].dtype))
        ts, stats, infos = prog(ts, jnp.stack(rows, axis=1),
                                jnp.asarray(t_env), spec)
        t_env += k * spr
        all_stats.append(stats)
    return ts, spec, all_stats


def _classic_superstep_loop(exp, k, n_dispatches):
    cfg = exp.cfg
    ts = exp.init_train_state(cfg.seed)
    prog = exp.superstep_program(k, donate=True)
    key = jax.random.PRNGKey(cfg.seed + 1)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, episode, filled = 0, 0, 0
    all_stats = []
    for _ in range(n_dispatches):
        rows = []
        for _ in range(k):
            episode += cfg.batch_size_run
            filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
            if filled >= cfg.batch_size:
                key, ks = jax.random.split(key)
                rows.append(ks)
            else:
                rows.append(jnp.zeros_like(key))
        ts, stats, infos = prog(ts, jnp.stack(rows), jnp.asarray(t_env))
        t_env += k * spr
        all_stats.append(stats)
    return ts, all_stats


def _assert_trees_equal(a, b, strip_member=False, msg=""):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (kp, x), (_, y) in zip(la, lb):
        x, y = np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        if strip_member:
            y = y[0]
        np.testing.assert_array_equal(
            x, y, err_msg=f"{msg}{jax.tree_util.keystr(kp)}")


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_population_bare_int_shorthand_and_grids():
    cfg = tiny_cfg()
    base = dataclasses.asdict(cfg)
    c2 = from_dict({**base, "population": 4})
    assert c2.population.size == 4
    c3 = from_dict({**base, "save_model": True,
                    "population": {"size": 2, "lr": [5e-4, 1e-3],
                                   "pbt.enabled": True,
                                   "pbt.perturb": 1.5}})
    assert c3.population.lr == (5e-4, 1e-3)
    assert isinstance(c3.population.lr, tuple)
    assert c3.population.pbt.enabled and c3.population.pbt.perturb == 1.5
    # roundtrip (serve meta.json path)
    c4 = from_dict(dataclasses.asdict(c3))
    assert c4.population == c3.population


def test_sanity_rejects_incompatible_combos():
    # every REMAINING rejection names the blocking mechanism AND the
    # nearest legal alternative (graftlattice satellite contract)
    with pytest.raises(ValueError, match="vmaps the device-resident"):
        pop_cfg(2, replay_kw={"buffer_cpu_only": True})
    with pytest.raises(ValueError, match="separate solo runs"):
        pop_cfg(2, replay_kw={"buffer_cpu_only": True})
    with pytest.raises(ValueError, match="evaluate"):
        pop_cfg(2, evaluate=True)
    with pytest.raises(ValueError, match="exactly P entries"):
        pop_cfg(2, pop_kw={"lr": (1e-3,)})
    with pytest.raises(ValueError, match="must be > 0"):
        pop_cfg(2, pop_kw={"eps_scale": (1.0, -0.5)})
    with pytest.raises(ValueError, match=r"in \(0, 1\]"):
        pop_cfg(2, pop_kw={"per_alpha": (0.5, 1.5)})
    with pytest.raises(ValueError, match="prioritized"):
        pop_cfg(2, pop_kw={"per_alpha": (0.5, 0.6)},
                replay_kw={"prioritized": False})
    with pytest.raises(ValueError, match="seed_stride"):
        pop_cfg(2, pop_kw={"seed_stride": -1})
    with pytest.raises(ValueError, match="pbt.frac"):
        pop_cfg(2, pop_kw={"pbt": PBTConfig(frac=0.9)})
    with pytest.raises(ValueError, match="save_model"):
        pop_cfg(2, pop_kw={"pbt": PBTConfig(enabled=True)},
                save_model=False)
    # P=0 composes with everything (the off state)
    assert tiny_cfg(dp_devices=0).population.size == 0


def test_sanity_lattice_legal_and_gated_combos():
    """graftlattice composition surface: population x pallas and
    population x dp are LEGAL now; what remains rejected is the
    divisibility/lockstep/pbt boundary, each naming the mechanism and
    the nearest legal alternative."""
    from t2omca_tpu.config import KernelsConfig, SebulbaConfig
    # population x pallas: vmap-over-pallas — plain legal
    cfg = pop_cfg(2, kernels=KernelsConfig(attention="pallas"))
    assert cfg.population.size == 2 and cfg.kernels.attention == "pallas"
    # population x dp: member axis shards over the mesh when divisible
    cfg = pop_cfg(2, dp_devices=2)
    assert cfg.population.size == 2 and cfg.dp_devices == 2
    with pytest.raises(ValueError, match="not divisible by dp_devices"):
        pop_cfg(3, dp_devices=2)
    with pytest.raises(ValueError, match="divisible P or drop dp_devices"):
        pop_cfg(3, dp_devices=2)
    # population x sebulba: lockstep only (queue_slots=1, staleness=0)
    sb = dict(actor_devices=1, learner_devices=1)
    cfg = pop_cfg(2, sebulba=SebulbaConfig(queue_slots=1, staleness=0,
                                           **sb))
    assert cfg.population.size == 2
    with pytest.raises(ValueError, match="LOCKSTEP"):
        pop_cfg(2, sebulba=SebulbaConfig(queue_slots=2, staleness=0,
                                         **sb))
    with pytest.raises(ValueError, match="staleness=0"):
        pop_cfg(2, sebulba=SebulbaConfig(queue_slots=1, staleness=1,
                                         **sb))
    # pbt x sebulba: save-boundary exploit/explore can't reach the
    # decoupled actor thread mid-epoch
    with pytest.raises(ValueError, match="checkpoint-save boundary"):
        pop_cfg(2, pop_kw={"pbt": PBTConfig(enabled=True)},
                save_model=True,
                sebulba=SebulbaConfig(queue_slots=1, staleness=0, **sb))
    # member axis must tile each sebulba device set
    with pytest.raises(ValueError, match="divisible by sebulba"):
        pop_cfg(3, sebulba=SebulbaConfig(queue_slots=1, staleness=0,
                                         actor_devices=2,
                                         learner_devices=1))


def test_build_spec_neutral_and_gridded():
    cfg = pop_cfg(3)
    spec = graftpop.build_spec(cfg)
    np.testing.assert_array_equal(np.asarray(spec.lr_scale), [1, 1, 1])
    np.testing.assert_array_equal(np.asarray(spec.eps_scale), [1, 1, 1])
    np.testing.assert_allclose(np.asarray(spec.per_alpha),
                               cfg.replay.per_alpha)
    np.testing.assert_array_equal(np.asarray(spec.member), [0, 1, 2])
    g = pop_cfg(2, pop_kw={"lr": (cfg.lr, 2 * cfg.lr),
                           "eps_scale": (1.0, 0.5),
                           "per_alpha": (0.6, 0.8)})
    sg = graftpop.build_spec(g)
    np.testing.assert_allclose(np.asarray(sg.lr_scale), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(sg.eps_scale), [1.0, 0.5])
    np.testing.assert_allclose(np.asarray(sg.per_alpha), [0.6, 0.8])


def test_cli_bare_int_composes_with_dotted_overrides():
    """The README-documented command line: `population=4
    population.seed_stride=1` must compose in either order (the bare
    int lifts to {size: ...}; the reversed order merges instead of
    silently replacing the dict)."""
    from t2omca_tpu.config import load_config
    c = load_config(overrides=("population=4", "population.seed_stride=0"))
    assert c.population.size == 4 and c.population.seed_stride == 0
    c2 = load_config(overrides=("population.seed_stride=0",
                                "population=4"))
    assert c2.population.size == 4 and c2.population.seed_stride == 0


def test_member_seeds_stride():
    assert graftpop.member_seeds(pop_cfg(3)) == [0, 1, 2]
    assert graftpop.member_seeds(
        pop_cfg(3, pop_kw={"seed_stride": 0})) == [0, 0, 0]
    assert graftpop.member_seeds(
        pop_cfg(3, seed=7, pop_kw={"seed_stride": 10})) == [7, 17, 27]


# ---------------------------------------------------------------------------
# PBT (host-side select-and-perturb)
# ---------------------------------------------------------------------------


def _fake_pop_state(p, val=0.0):
    return {"w": jnp.arange(p, dtype=jnp.float32) + val}


def test_pbt_step_noop_without_full_perf():
    cfg = pop_cfg(4, pop_kw={"pbt": PBTConfig(enabled=True)},
                  save_model=True)
    ts = _fake_pop_state(4)
    spec = graftpop.build_spec(cfg)
    for perf in (None, [1.0, 2.0], [1.0, None, 2.0, 3.0]):
        ts2, spec2, info = graftpop.pbt_step(cfg, ts, spec, perf, 100)
        assert info is None
        assert ts2 is ts and spec2 is spec


def test_pbt_step_copies_losers_from_winners_and_perturbs():
    cfg = pop_cfg(4, pop_kw={"pbt": PBTConfig(enabled=True, frac=0.25,
                                              perturb=1.2)},
                  save_model=True)
    ts = _fake_pop_state(4)
    spec = graftpop.build_spec(cfg)
    perf = [3.0, 1.0, 2.0, 4.0]          # loser: member 1; winner: 3
    ts2, spec2, info = graftpop.pbt_step(cfg, ts, spec, perf, 100)
    assert info == {"copied": {1: 3}, "perf": perf}
    w = np.asarray(ts2["w"])
    np.testing.assert_array_equal(w, [0.0, 3.0, 2.0, 3.0])
    l1 = float(np.asarray(spec2.lr_scale)[1])
    assert any(l1 == pytest.approx(v, rel=1e-6) for v in (1.2, 1 / 1.2))
    # untouched members keep their leaves, member ids never move
    np.testing.assert_array_equal(np.asarray(spec2.member), [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(spec2.lr_scale)[[0, 2, 3]],
                                  [1.0, 1.0, 1.0])
    # deterministic in (seed, t_env): same inputs → same decisions
    ts3, spec3, info3 = graftpop.pbt_step(cfg, ts, spec, perf, 100)
    assert info3 == info
    np.testing.assert_array_equal(np.asarray(spec3.lr_scale),
                                  np.asarray(spec2.lr_scale))


def test_pbt_step_resalts_exploited_rollout_keys():
    """The exploit gather copies the donor's ``runner.key`` verbatim —
    without a re-salt the loser would replay its donor's exact
    trajectories (scenario draws + exploration). Pin: losers' rollout
    keys differ from the donor's after the step; untouched members keep
    theirs; the salt is deterministic."""
    from flax import struct

    @struct.dataclass
    class _Runner:
        key: jnp.ndarray

    @struct.dataclass
    class _State:
        w: jnp.ndarray
        runner: _Runner

    cfg = pop_cfg(4, pop_kw={"pbt": PBTConfig(enabled=True, frac=0.25)},
                  save_model=True)
    keys = jnp.stack([jax.random.PRNGKey(100 + m) for m in range(4)])
    ts = _State(w=jnp.arange(4, dtype=jnp.float32), runner=_Runner(keys))
    spec = graftpop.build_spec(cfg)
    perf = [3.0, 1.0, 2.0, 4.0]                    # loser 1 copies 3
    ts2, _spec2, info = graftpop.pbt_step(cfg, ts, spec, perf, 100)
    assert info["copied"] == {1: 3}
    k2 = np.asarray(ts2.runner.key)
    k0 = np.asarray(keys)
    # loser 1: copied from member 3 then salted — neither its old key
    # nor the donor's
    assert not np.array_equal(k2[1], k0[3])
    assert not np.array_equal(k2[1], k0[1])
    # everyone else untouched
    for m in (0, 2, 3):
        np.testing.assert_array_equal(k2[m], k0[m])
    # deterministic in (t_env, member)
    ts3, _, _ = graftpop.pbt_step(cfg, ts, spec, perf, 100)
    np.testing.assert_array_equal(np.asarray(ts3.runner.key), k2)


def test_pbt_step_rescales_copied_ring_priorities():
    """An exploited member's gathered ring stores the DONOR's
    pre-exponentiated priorities (p^alpha_donor); with a per_alpha grid
    the loser's perturbed exponent would otherwise mix bases in one
    ring — pin the rescale to p^alpha_new and the winner's ring staying
    untouched (zero tail stays zero)."""
    from flax import struct

    @struct.dataclass
    class _Buf:
        priorities: jnp.ndarray

    @struct.dataclass
    class _State:
        w: jnp.ndarray
        buffer: _Buf

    cfg = pop_cfg(2, save_model=True,
                  pop_kw={"per_alpha": (0.6, 0.8),
                          "pbt": PBTConfig(enabled=True, frac=0.5)})
    raw = np.asarray([[2.0, 3.0, 0.0], [4.0, 5.0, 0.0]], np.float32)
    ts = _State(w=jnp.arange(2, dtype=jnp.float32),
                buffer=_Buf(jnp.asarray(raw)))
    spec = graftpop.build_spec(cfg)
    ts2, spec2, info = graftpop.pbt_step(cfg, ts, spec, [1.0, 2.0], 50)
    assert info["copied"] == {0: 1}
    a_new = float(np.asarray(spec2.per_alpha)[0])
    assert a_new != pytest.approx(0.8)
    got = np.asarray(ts2.buffer.priorities)
    np.testing.assert_allclose(got[0], raw[1] ** (a_new / 0.8),
                               rtol=1e-6)
    np.testing.assert_array_equal(got[1], raw[1])
    assert got[0][2] == 0.0                    # unfilled tail inert


def test_pbt_step_p2_frac_clamps_to_disjoint_sets():
    cfg = pop_cfg(2, pop_kw={"pbt": PBTConfig(enabled=True, frac=0.5)},
                  save_model=True)
    ts = _fake_pop_state(2)
    spec = graftpop.build_spec(cfg)
    ts2, spec2, info = graftpop.pbt_step(cfg, ts, spec, [1.0, 2.0], 50)
    assert info["copied"] == {0: 1}
    np.testing.assert_array_equal(np.asarray(ts2["w"]), [1.0, 1.0])


# ---------------------------------------------------------------------------
# stats + sight population surfaces
# ---------------------------------------------------------------------------


class _FakeStats:
    """Minimal RolloutStats stand-in with a leading (P,) member axis."""

    def __init__(self, p, b, seed=0):
        r = np.random.default_rng(seed)
        self.episode_return = jnp.asarray(
            r.normal(size=(p, b)).astype(np.float32))
        self.epsilon = jnp.full((p, b), 0.25, jnp.float32)
        self.task_completion_rate = jnp.asarray(
            r.random((p, b)).astype(np.float32))


def test_stats_accumulator_population_rows_and_ema():
    acc = StatsAccumulator(population=2)
    logger = Logger()
    s = _FakeStats(2, 3)
    acc.push(s)
    assert acc.n_episodes == 6            # total across members
    acc.flush(logger, 10)
    assert "pop0_return_mean" in logger.stats
    assert "pop1_return_mean" in logger.stats
    assert "pop0_task_completion_rate_mean" in logger.stats
    r0 = float(np.asarray(s.episode_return)[0].mean())
    assert logger.stats["pop0_return_mean"][-1][1] == pytest.approx(r0)
    # aggregate row is the across-member mean
    ra = float(np.asarray(s.episode_return).mean())
    assert logger.stats["return_mean"][-1][1] == pytest.approx(ra)
    # EMA survives the flush (the PBT ranking signal)
    assert acc.member_return_ema[0] == pytest.approx(r0)
    acc.push(_FakeStats(2, 3, seed=1))
    acc.flush(logger, 20)
    assert acc.member_return_ema[0] != pytest.approx(r0)


def test_stats_accumulator_p1_keeps_solo_stream():
    acc = StatsAccumulator(population=1)
    logger = Logger()
    acc.push(_FakeStats(1, 3))
    acc.flush(logger, 10)
    assert not any(k.startswith("pop0_") for k in logger.stats)
    assert "return_mean" in logger.stats
    # but the EMA still tracks (PBT needs it even at... P=1 no-op)
    assert acc.member_return_ema[0] is not None


def test_population_sight_monitor_slices_and_names():
    from t2omca_tpu.config import SightConfig
    from t2omca_tpu.obs.sight import PopulationSightMonitor
    logger = Logger()
    mon = PopulationSightMonitor(SightConfig(enabled=True, q_div=10.0),
                                 2, logger=logger)
    info = {"loss": np.asarray([1.0, 2.0]),
            "q_taken_mean": np.asarray([0.5, 99.0]),   # member 1 diverges
            "target_mean": np.asarray([0.5, 99.0]),
            "sight_per_ess": np.asarray([0.9, 0.9])}
    newly = mon.observe(info, 10)
    assert newly == ["pop1:q_divergence"]
    assert mon.members[0].status["q_divergence"]["ok"]
    assert not mon.members[1].status["q_divergence"]["ok"]
    # per-member stat keys rode the same observation
    assert "pop0_sight_per_ess" in logger.stats
    assert "pop1_sight_per_ess" in logger.stats
    # /healthz names carry the member tag
    names = []

    class _Hub:
        def health(self, name, fn):
            names.append(name)
    mon.wire_pulse(_Hub())
    assert "sight-pop0-q_divergence" in names
    assert "sight-pop1-q_divergence" in names
    rep = mon.report()
    assert rep["population"] == 2 and len(rep["members"]) == 2


def test_learning_cli_renders_member_table():
    from t2omca_tpu.obs.sight import render_learning
    series = {
        "return_mean": [(10, 1.0), (20, 2.0)],
        "pop0_return_mean": [(10, 1.5), (20, 2.5)],
        "pop1_return_mean": [(10, 0.5), (20, 1.5)],
        "pop0_loss": [(20, 0.25)],
        "pop1_sight_alert_q_divergence": [(20, 1.0)],
    }
    out = "\n".join(render_learning("/tmp/x", series))
    assert "population members (2" in out
    assert "pop0" in out and "pop1" in out
    assert "q_divergence" in out          # member 1's standing alert


# ---------------------------------------------------------------------------
# checkpoint lift (v4 single-member → v5 PopState)
# ---------------------------------------------------------------------------


def test_lift_population_replicates_single_member_raw():
    from flax import serialization

    from t2omca_tpu.utils.checkpoint import _migrate_raw
    solo = {"w": np.arange(3, dtype=np.float32), "b": np.float32(2.0)}
    cfg = pop_cfg(2)
    spec = graftpop.build_spec(cfg)
    target = graftpop.PopState(
        ts={"w": jnp.zeros((2, 3), jnp.float32),
            "b": jnp.zeros((2,), jnp.float32)},
        spec=spec)
    raw = _migrate_raw({"format": 4},
                       serialization.to_state_dict(
                           {"w": solo["w"], "b": solo["b"]}), target)
    assert set(raw) == {"ts", "spec"}
    np.testing.assert_array_equal(raw["ts"]["w"],
                                  np.stack([solo["w"]] * 2))
    np.testing.assert_array_equal(raw["spec"]["lr_scale"], [1.0, 1.0])


@pytest.mark.slow
def test_v4_single_member_checkpoint_lifts_into_population(tmp_path):
    """A pre-population (single-member) checkpoint restores into a P=2
    population template with every member replicated from it — and the
    meta doctored to format 4 takes the same path (the lift keys on
    STRUCTURE, so v4 and v5 single-member trees both lift)."""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(cfg.seed)
    d = save_checkpoint(str(tmp_path), 24, ts)
    # doctor the sidecar to the v4 format a real pre-population run wrote
    meta_path = os.path.join(d, "meta.json")
    meta = json.load(open(meta_path))
    meta["format"] = 4
    json.dump(meta, open(meta_path, "w"))

    pcfg = pop_cfg(2)
    pexp = Experiment.build(pcfg)
    pts, spec = graftpop.init_population(pexp, pcfg)
    restored = load_checkpoint(
        d, graftpop.PopState(ts=pts, spec=spec), verify=False)
    for (kp, x), (_, y) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts)),
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(restored.ts))):
        x, y = np.asarray(x), np.asarray(y)
        path = jax.tree_util.keystr(kp)
        assert y.shape == (2,) + x.shape, path
        np.testing.assert_array_equal(y[0], x, err_msg=path)
        if ".runner" in path and "key" in path.rsplit(".", 1)[-1]:
            # members 1..P-1 get a re-salted rollout key — a verbatim
            # replica would make every member draw the SAME
            # trajectories for the rest of the run
            assert not np.array_equal(y[1], x), path
        else:
            np.testing.assert_array_equal(y[1], x, err_msg=path)
    # the template's spec came through
    np.testing.assert_array_equal(np.asarray(restored.spec.member),
                                  [0, 1])


@pytest.mark.slow
def test_population_checkpoint_roundtrips_popstate(tmp_path):
    cfg = pop_cfg(2, pop_kw={"lr": (5e-4, 1e-3)})
    exp = Experiment.build(cfg)
    ts, spec = graftpop.init_population(exp, cfg)
    ps = graftpop.PopState(ts=ts, spec=spec)
    d = save_checkpoint(str(tmp_path), 24, ps)
    ts2, spec2 = graftpop.init_population(exp, cfg)
    restored = load_checkpoint(
        d, graftpop.PopState(ts=ts2, spec=spec2), verify=True)
    _assert_trees_equal(ps, restored)


# ---------------------------------------------------------------------------
# the parity / divergence / one-dispatch contracts (compile-heavy)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_p1_population_bit_identical_to_classic_superstep_loop():
    """THE acceptance pin: a P=1 population with a neutral spec is
    bit-identical to the classic fused loop — params, opt_state, replay
    ring, PER priorities, runner state AND the emitted stats stream."""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts_c, stats_c = _classic_superstep_loop(exp, 2, 3)

    cfgp = pop_cfg(1)
    expp = Experiment.build(cfgp)
    ts_p, _spec, stats_p = _pop_loop(expp, cfgp, 2, 3)

    _assert_trees_equal(ts_c, ts_p, strip_member=True, msg="state ")
    for sc, sp in zip(stats_c, stats_p):
        _assert_trees_equal(sc, sp, strip_member=True, msg="stats ")


@pytest.mark.slow
def test_p2_seeds_diverge_and_member0_tracks_solo():
    """Default stride: the two members (seeds 0, 1) must DIVERGE —
    different rollouts, different params. Member 0 tracks its solo run
    to float tolerance over the first dispatches (cross-rank bit-parity
    under vmap is impossible: batched f32 reduces reassociate — the
    squeeze-path docstring; the exact contract lives at P=1)."""
    cfgp = pop_cfg(2)
    expp = Experiment.build(cfgp)
    ts_p, _spec, _stats = _pop_loop(expp, cfgp, 2, 2)
    params = jax.device_get(ts_p.learner.params)
    # members diverged (different seeds → different episodes → params)
    diffs = [not np.array_equal(np.asarray(x)[0], np.asarray(x)[1])
             for x in jax.tree.leaves(params)]
    assert any(diffs), "different seeds must diverge"

    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts_c, _ = _classic_superstep_loop(exp, 2, 2)
    for (kp, x), (_, y) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts_c)),
            jax.tree_util.tree_leaves_with_path(ts_p)):
        x, y = np.asarray(x), np.asarray(y)[0]
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_allclose(
                y, x, rtol=2e-3, atol=2e-3,
                err_msg=jax.tree_util.keystr(kp))
        else:
            np.testing.assert_array_equal(
                y, x, err_msg=jax.tree_util.keystr(kp))


@pytest.mark.slow
def test_p2_stride0_members_bit_identical():
    """seed_stride=0 (identical seeds, neutral grids, no salt): the two
    members are bit-identical to EACH OTHER forever — vmap applies the
    same batched kernel to identical per-member inputs. The invariant
    that makes grid comparisons controlled."""
    cfgp = pop_cfg(2, pop_kw={"seed_stride": 0})
    expp = Experiment.build(cfgp)
    ts_p, _spec, stats = _pop_loop(expp, cfgp, 2, 3)
    for kp, x in jax.tree_util.tree_leaves_with_path(
            jax.device_get(ts_p)):
        x = np.asarray(x)
        np.testing.assert_array_equal(x[0], x[1],
                                      err_msg=jax.tree_util.keystr(kp))


@pytest.mark.slow
@pytest.mark.analysis
def test_population_superstep_compiles_once():
    """compile_budget(1): 3 donated population dispatches, ONE compile
    (the t_env weak-type discipline holds on the population rank too)."""
    cfgp = pop_cfg(2)
    expp = Experiment.build(cfgp)
    ts, spec = graftpop.init_population(expp, cfgp)
    prog = expp.population_superstep_program(2, donate=True)
    keys = jnp.stack([jax.random.split(jax.random.PRNGKey(m), 2)
                      for m in range(2)])
    spr = cfgp.batch_size_run * cfgp.env_args.episode_limit
    with compile_budget(1, match="_superstep_pop"):
        for i in range(3):
            ts, stats, infos = prog(
                ts, keys, jnp.asarray(i * 2 * spr), spec)
    assert prog._cache_size() == 1
    # the donated dispatch advanced every member
    assert np.asarray(jax.device_get(ts.episode)).tolist() == [12, 12]


@pytest.mark.slow
def test_run_sequential_population_end_to_end(tmp_path):
    """The real driver at population=2: completes, logs per-member
    pop<i>_* rows, saves a PopState checkpoint that a fresh run
    resumes."""
    logger = Logger()
    cfg = pop_cfg(2, tmp_path, t_max=70, superstep=2, save_model=True,
                  test_interval=36, log_interval=24,
                  runner_log_interval=24)
    ts = run(cfg, logger)
    assert np.asarray(jax.device_get(ts.episode)).shape == (2,)
    for key in ("pop0_loss", "pop1_loss", "pop0_return_mean",
                "pop1_return_mean", "loss", "return_mean"):
        assert key in logger.stats, key
    # the checkpoint holds a PopState a fresh population run can resume
    from t2omca_tpu.utils.checkpoint import find_checkpoint
    model_dir = os.path.join(
        str(tmp_path), "models",
        os.listdir(os.path.join(str(tmp_path), "models"))[0])
    found = find_checkpoint(model_dir)
    assert found is not None
    cfg2 = pop_cfg(2, tmp_path, t_max=70, superstep=2, save_model=True,
                   checkpoint_path=model_dir, test_interval=36,
                   log_interval=24, runner_log_interval=24)
    ts2 = run(cfg2, Logger())
    assert np.asarray(jax.device_get(ts2.episode)).shape == (2,)


@pytest.mark.slow
def test_run_sequential_population_pbt_fires(tmp_path):
    """PBT at the save boundary: with runner-log flushes feeding the
    member EMA before the save cadence, the exploit/explore pass runs
    and logs pbt_copies (exactly one loser at P=2 frac=0.5)."""
    logger = Logger()
    cfg = pop_cfg(
        2, tmp_path, t_max=94, superstep=2, save_model=True,
        save_model_interval=24, test_interval=1_000_000,
        log_interval=12, runner_log_interval=12,
        pop_kw={"pbt": PBTConfig(enabled=True, frac=0.5, perturb=1.3)})
    run(cfg, logger)
    assert "pbt_copies" in logger.stats
    assert logger.stats["pbt_copies"][-1][1] == 1.0


# ---------------------------------------------------------------------------
# mixer-side padding mask (ROADMAP item 3's open remainder)
# ---------------------------------------------------------------------------


def _pad_cfg(pad: bool):
    from t2omca_tpu.config import ScenarioConfig
    env_kw = ({"scenario": ScenarioConfig(kind="uniform", min_agents=2)}
              if pad else {})
    return tiny_cfg(batch_size_run=4,
                    env_kw={"agv_num": 4, **env_kw})


def test_mask_padded_gate_is_config_static():
    from t2omca_tpu.envs.graftworld import distribution_can_pad
    from t2omca_tpu.envs.registry import make_scenario_distribution
    assert Experiment.build(_pad_cfg(True)).learner._mask_padded
    assert not Experiment.build(_pad_cfg(False)).learner._mask_padded
    # the predicate itself: fixed full-fleet never pads; uniform with
    # min_agents below the fleet does
    cfg = _pad_cfg(False)
    assert not distribution_can_pad(
        make_scenario_distribution(cfg.env_args), 4)
    cfgp = _pad_cfg(True)
    assert distribution_can_pad(
        make_scenario_distribution(cfgp.env_args), 4)


@pytest.mark.slow
def test_padding_mask_invariance_and_full_fleet_parity():
    """The ISSUE-15 satellite pins: (a) padded agents enter the mixer
    NEUTRALLY — garbage written into their stored obs changes neither
    the loss nor the updated params, bit-for-bit; (b) at full fleet the
    masked loss program is bit-identical to the unmasked one (active
    agents multiply by 1.0 — bitwise identity)."""
    cfg_pad, cfg_plain = _pad_cfg(True), _pad_cfg(False)
    exp_pad, exp_plain = Experiment.build(cfg_pad), Experiment.build(
        cfg_plain)

    ts = exp_pad.init_train_state(0)
    rollout = exp_pad.jitted_programs()[0]
    _rs, batch, _stats = rollout(ts.learner.params["agent"], ts.runner,
                                 False)
    avail = np.asarray(jax.device_get(batch.avail_actions))
    act_m = (avail[..., 1:] > 0).any(axis=(1, 3))      # (B, A)
    assert (~act_m).any(), "the uniform min_agents=2 draw must pad"
    assert act_m.any(axis=1).all(), "every lane keeps active agents"

    key = jax.random.PRNGKey(5)
    w = jnp.ones((cfg_pad.batch_size,), jnp.float32)
    ls1, info1 = exp_pad.learner.train(ts.learner, batch, w,
                                       jnp.asarray(24), jnp.asarray(4),
                                       key)
    obs = np.asarray(jax.device_get(batch.obs)).copy()
    b_idx, a_idx = np.where(~act_m)
    obs[b_idx, :, a_idx] = 777.0                       # garbage rows
    ls2, info2 = exp_pad.learner.train(
        ts.learner, batch.replace(obs=jnp.asarray(obs)), w,
        jnp.asarray(24), jnp.asarray(4), key)
    assert float(info1["loss"]) == float(info2["loss"])
    _assert_trees_equal(ls1.params, ls2.params, msg="tampered-pad ")

    # (b) full fleet: the masked program (pad-capable config) on an
    # all-active batch bit-matches the unmasked program
    ts_plain = exp_plain.init_train_state(0)
    _rs, batch_full, _ = exp_plain.jitted_programs()[0](
        ts_plain.learner.params["agent"], ts_plain.runner, False)
    lsA, infoA = exp_pad.learner.train(ts_plain.learner, batch_full, w,
                                       jnp.asarray(24), jnp.asarray(4),
                                       key)
    lsB, infoB = exp_plain.learner.train(ts_plain.learner, batch_full, w,
                                         jnp.asarray(24), jnp.asarray(4),
                                         key)
    assert float(infoA["loss"]) == float(infoB["loss"])
    _assert_trees_equal(lsA.params, lsB.params, msg="full-fleet ")


@pytest.mark.slow
def test_padding_mask_suffix_rule_spares_interior_jobless_agent():
    """An ACTIVE agent that never saw a job is avail-indistinguishable
    from a padded one — but padding is always a trailing block, so the
    suffix rule masks an idle-only-forever agent ONLY when every agent
    after it is idle-only too. Pin: an interior idle-only agent
    (followed by a job-seeing agent) still contributes to the loss —
    garbage in its obs CHANGES the result."""
    cfg = _pad_cfg(True)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    rollout = exp.jitted_programs()[0]
    _rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner, False)
    avail = np.asarray(jax.device_get(batch.avail_actions)).copy()
    act_m = (avail[..., 1:] > 0).any(axis=(1, 3))      # (B, A)
    lane = int(np.argmax(act_m.all(axis=1)))           # a full-fleet lane
    assert act_m[lane].all()
    # simulate a jobless INTERIOR agent: idle-only at every step, but
    # agents after it keep their jobs
    idle_only = np.zeros_like(avail[:, lane, 1])
    idle_only[..., 0] = 1
    avail[:, lane, 1] = idle_only
    batch_a = batch.replace(avail_actions=jnp.asarray(avail))
    key = jax.random.PRNGKey(5)
    w = jnp.ones((cfg.batch_size,), jnp.float32)
    _ls1, info1 = exp.learner.train(ts.learner, batch_a, w,
                                    jnp.asarray(24), jnp.asarray(4), key)
    obs = np.asarray(jax.device_get(batch.obs)).copy()
    obs[lane, :, 1] = 333.0
    batch_b = batch_a.replace(obs=jnp.asarray(obs))
    _ls2, info2 = exp.learner.train(ts.learner, batch_b, w,
                                    jnp.asarray(24), jnp.asarray(4), key)
    assert float(info1["loss"]) != float(info2["loss"]), \
        "interior jobless agent must NOT be masked out of the loss"


# ---------------------------------------------------------------------------
# per-member scenario decorrelation
# ---------------------------------------------------------------------------


def test_member_scenario_key_decorrelates_and_salt_gates():
    from t2omca_tpu.envs.graftworld import member_scenario_key
    k = jax.random.PRNGKey(3)
    k0 = member_scenario_key(k, jnp.asarray(0))
    k1 = member_scenario_key(k, jnp.asarray(1))
    assert not np.array_equal(np.asarray(k0), np.asarray(k1))
    # fold_in(k, 0) is NOT the identity — which is exactly why
    # scenario_salt defaults off (member 0 must match the solo stream)
    assert not np.array_equal(np.asarray(k0), np.asarray(k))


@pytest.mark.slow
def test_sample_scenarios_member_salt():
    """The runner's per-member scenario seam: different members draw
    different EnvParams from the same key chain; member=None keeps the
    pre-population draw bit-identical."""
    cfg = _pad_cfg(True)
    exp = Experiment.build(cfg)
    key = jax.random.PRNGKey(9)
    base = exp.runner._sample_scenarios(key)
    same = exp.runner._sample_scenarios(key, member=None)
    _assert_trees_equal(base, same, msg="member=None ")
    m0 = exp.runner._sample_scenarios(key, member=jnp.asarray(0))
    m1 = exp.runner._sample_scenarios(key, member=jnp.asarray(1))
    diff = any(
        not np.array_equal(np.asarray(jax.device_get(a)),
                           np.asarray(jax.device_get(b)))
        for a, b in zip(jax.tree.leaves(m0), jax.tree.leaves(m1)))
    assert diff, "member salts must decorrelate the draws"


@pytest.mark.slow
@pytest.mark.sight
def test_population_sight_keys_per_member(tmp_path):
    """graftsight over the population axis (ISSUE-15 satellite): the
    in-graph diagnostics vmap with the train step (PR 14's reduces are
    rank-polymorphic) and each member's sight_* keys land as
    pop<i>_sight_* on the same log-cadence fetch."""
    from t2omca_tpu.config import ObsConfig, SightConfig
    logger = Logger()
    cfg = pop_cfg(2, tmp_path, t_max=40, superstep=2,
                  log_interval=12, runner_log_interval=12,
                  obs=ObsConfig(sight=SightConfig(enabled=True, bins=8)))
    run(cfg, logger)
    for member in (0, 1):
        keys = [k for k in logger.stats
                if k.startswith(f"pop{member}_sight_")]
        assert any("grad_norm" in k for k in keys), keys
        assert any("per_ess" in k for k in keys), keys
        assert any("attn_entropy" in k for k in keys), keys


@pytest.mark.slow
def test_bench_population_record_schema(tmp_path):
    """The --population leg emits one schema-1 record with the
    experiment-throughput metric and the serialized A/B."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"),
         "--population", "2", "--smoke", "--iters", "1"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "experiments_per_sec"
    assert rec["schema"] == 1
    assert rec["population"] == 2
    assert rec["value"] > 0
    assert rec["serialized_experiments_per_sec"] > 0
    assert rec["population_speedup"] > 0
    assert rec["train_gate_open"] is True
