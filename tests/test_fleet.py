"""graftfleet: fault-tolerant multi-engine serving (docs/SERVING.md §fleet).

Two tiers, like tests/test_serve.py. The fleet's supervision, admission,
hedging, ladder and refresh logic is pure host code — the in-gate tests
drive a real :class:`~t2omca_tpu.serve.fleet.ServeFleet` (real threads,
real watchdogs, real supervisor) over stub frontends injected via
``frontend_factory``, so no jit and no Experiment build ever runs in the
tier-1 budget. Everything artifact-backed (refresh bit-parity, the
fingerprint gate against real lowered programs, the ``bench.py --serve
--chaos`` acceptance run) is ``slow``-marked; the chaos acceptance run
additionally carries the ``chaos`` marker so ``scripts/chaos.sh`` can
select it into the soak battery.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from t2omca_tpu.utils import resilience

REPO = os.path.join(os.path.dirname(__file__), "..")

A, D, NA, EMB = 2, 3, 4, 2      # stub model surface


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


# ---------------------------------------------------------------------------
# stub engines (in-gate: no jit, no artifact)
# ---------------------------------------------------------------------------


class _StubFrontend:
    """Duck-typed ServeFrontend: instant selects, params-observable
    actions (``actions == int(params['w']) % n_actions`` — a hot refresh
    is visible in the output), dispatch batch sizes recorded so ladder
    bucket caps are assertable."""

    def __init__(self, dtype="float32", buckets=(1, 2, 4)):
        self.dtype = dtype
        self.buckets = list(buckets)
        self.n_agents, self.obs_dim = A, D
        self.n_actions, self.emb = NA, EMB
        self._params = {"w": np.float32(1.0)}
        self.sizes = []                     # per-dispatch batch sizes
        self.calls = 0

    def select(self, obs, avail, hidden=None):
        self.calls += 1
        n = np.asarray(obs).shape[0]
        self.sizes.append(n)
        if hidden is None:
            hidden = np.zeros((n, self.n_agents, self.emb), np.float32)
        act = int(np.asarray(self._params["w"])) % self.n_actions
        return (np.full((n, self.n_agents), act, np.int32),
                np.asarray(hidden, np.float32) + 1.0)

    def warmup(self):
        pass


def _cfg(**kw):
    from t2omca_tpu.serve.fleet import FleetConfig
    base = dict(poll_s=0.005, deadline_s=3.0, dispatch_timeout_s=0.6,
                request_retries=1, retry_backoff_s=0.005,
                restart_backoff_s=0.02, restart_backoff_max_s=0.1,
                hedge_min_s=0.02, ladder_cooldown_s=0.05)
    base.update(kw)
    return FleetConfig(**base)


def _mk_fleet(n=2, cfg=None, factory=None, hub=None, artifact_dir=None,
              meta=None):
    from t2omca_tpu.serve.fleet import ServeFleet
    fleet = ServeFleet(artifact_dir, n_engines=n, cfg=cfg or _cfg(),
                       hub=hub,
                       frontend_factory=factory
                       or (lambda dtype: _StubFrontend(dtype)))
    if meta is not None:
        fleet.meta = meta
    return fleet


def _req(n=2):
    return (np.zeros((n, A, D), np.float32), np.ones((n, A, NA), np.bool_))


def _until(pred, timeout=5.0, poll=0.005):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return True
        time.sleep(poll)
    return False


# ---------------------------------------------------------------------------
# GL110: fleet phases are registered serving boundaries
# ---------------------------------------------------------------------------


def test_fleet_phases_registered():
    from t2omca_tpu.obs.spans import KNOWN_PHASES
    from test_obs import _literal_phases
    phases = _literal_phases(
        os.path.join(REPO, "t2omca_tpu", "serve", "fleet.py"),
        fn_names=("_watched",))
    assert {"fleet.load", "fleet.dispatch", "fleet.selfcheck",
            "fleet.restart", "fleet.refresh"} <= phases
    assert phases <= KNOWN_PHASES, phases - KNOWN_PHASES
    # the chaos bench leg's traffic span is registered too
    assert "bench.chaos" in KNOWN_PHASES


# ---------------------------------------------------------------------------
# the pressure ladder (unit)
# ---------------------------------------------------------------------------


def test_fleet_ladder_rungs_and_hysteresis():
    from t2omca_tpu.serve.fleet import FleetLadder
    lad = FleetLadder([1, 2, 4], "float32", "bfloat16",
                      high=0.75, low=0.25, cooldown_s=0.0,
                      max_bucket_steps=2)
    # rung order: full → bucket caps (descending) → dtype fallback
    assert lad.rungs == [(None, "float32"), (2, "float32"),
                         (1, "float32"), (1, "bfloat16")]
    assert lad.current() == (None, "float32")
    for want in ((2, "float32"), (1, "float32"), (1, "bfloat16")):
        assert lad.update(0.9, time.monotonic()) == "degrade"
        assert lad.current() == want
    assert lad.update(1.0, time.monotonic()) is None     # floor
    # hysteresis band: mid fill moves nothing
    assert lad.update(0.5, time.monotonic()) is None
    for _ in range(3):
        assert lad.update(0.1, time.monotonic()) == "restore"
    assert lad.current() == (None, "float32")
    assert lad.update(0.0, time.monotonic()) is None     # ceiling
    assert lad.degrades == 3 and lad.restores == 3
    # dwell: a second move inside the cooldown is suppressed
    lad2 = FleetLadder([1, 2], "float32", None, 0.75, 0.25,
                       cooldown_s=100.0)
    assert lad2.rungs[-1] == (1, "float32")      # no alt → no dtype rung
    assert lad2.update(1.0, now=0.0) == "degrade"
    assert lad2.update(1.0, now=1.0) is None


# ---------------------------------------------------------------------------
# admission, deadlines, retries (in-gate, stub engines)
# ---------------------------------------------------------------------------


def test_fleet_select_ok_and_hidden_carry():
    with _mk_fleet(n=2) as fleet:
        assert fleet.serving_engines() == 2
        r = fleet.select(*_req(3))
        assert r.ok and r.status == "ok"
        assert r.actions.shape == (3, A) and (r.actions == 1).all()
        assert r.hidden.shape == (3, A, EMB)
        r2 = fleet.select(*_req(3), hidden=r.hidden)
        assert (r2.hidden == r.hidden + 1.0).all()
        st = fleet.stats()
        assert st["serving"] == 2
        assert st["fleet_requests_total"] == 2


def test_fleet_sheds_past_queue_bound_never_blocks():
    with _mk_fleet(n=2, cfg=_cfg(queue_depth=2)) as fleet:
        for e in fleet.engines:
            e.pause_ev.set()
        admitted = [fleet.submit(*_req()) for _ in range(2)]
        t0 = time.monotonic()
        shed = fleet.submit(*_req())
        assert time.monotonic() - t0 < 0.5       # shed is immediate
        assert shed.done
        assert shed.result.status == "shed"
        assert "queue full" in shed.result.error
        for e in fleet.engines:
            e.pause_ev.clear()
        assert all(r.wait(5.0).ok for r in admitted)
        assert fleet.stats()["fleet_shed_total"] == 1


def test_fleet_deadline_resolves_even_with_all_engines_paused():
    with _mk_fleet(n=1) as fleet:
        fleet.engines[0].pause_ev.set()            # nothing will dispatch
        t0 = time.monotonic()
        r = fleet.select(*_req(), deadline_s=0.3)
        assert r.status == "deadline"
        assert time.monotonic() - t0 < 2.0       # bounded, not hung
        assert fleet.stats()["fleet_deadline_total"] >= 1


def test_fleet_transient_fault_retried_in_place():
    attempts = []

    def flaky(engine, attempt, rid, **kw):
        attempts.append((rid, attempt))
        if attempt == 1:
            raise RuntimeError("chaos: connection reset by peer")

    resilience.register_fault("fleet.dispatch", flaky)
    with _mk_fleet(n=1) as fleet:
        r = fleet.select(*_req())
        assert r.ok                              # retried on the SAME engine
        st = fleet.stats()
        assert st.get("fleet_restarts_total", 0) == 0   # no quarantine
        assert fleet.engines[0].restarts == 0
    # both attempts fired for the request (attempt 2 succeeded)
    rids = {rid for rid, _ in attempts}
    assert any((rid, 1) in attempts and (rid, 2) in attempts
               for rid in rids)


def test_fleet_crash_quarantines_bounces_and_rejoins():
    killed = []

    def killer(engine, attempt, rid, **kw):
        if engine == 0 and not killed:
            killed.append(rid)
            raise RuntimeError("chaos: engine killed (injected)")

    resilience.register_fault("fleet.dispatch", killer)
    with _mk_fleet(n=2) as fleet:
        fleet.engines[1].pause_ev.set()    # engine 0 must take the request
        r = fleet.select(*_req(), deadline_s=5.0)
        # the request survived the crash: bounced, re-served after the
        # backoff restart of the only unpaused engine
        assert r.ok and r.engine == 0
        assert killed
        assert _until(lambda: fleet.engines[0].state == "serving")
        assert fleet.engines[0].restarts == 1
        assert fleet.recoveries                 # quarantine→rejoin timed
        st = fleet.stats()
        assert st["fleet_engine_failures_total"] == 1
        assert st["fleet_restarts_total"] == 1


def test_fleet_stall_is_hedged_and_stalled_engine_restarts():
    hung = []

    def hanger(engine, attempt, rid, **kw):
        if engine == 0 and not hung:
            hung.append(rid)
            time.sleep(1.2)                     # >> dispatch_timeout_s

    resilience.register_fault("fleet.dispatch", hanger)
    with _mk_fleet(n=2, cfg=_cfg(dispatch_timeout_s=0.3,
                                 deadline_s=5.0)) as fleet:
        fleet.engines[1].pause_ev.set()
        req = fleet.submit(*_req())
        assert _until(lambda: hung, timeout=2.0)
        fleet.engines[1].pause_ev.clear()          # the hedge target
        r = req.wait(6.0)
        # the hedge won on the healthy peer LONG before the wedged
        # dispatch would have returned
        assert r.ok and r.engine == 1
        assert r.hedged
        assert _until(lambda: fleet.stats().get("fleet_stalls_total",
                                                0) >= 1)
        assert fleet.stats()["fleet_hedges_total"] >= 1
        # the stalled engine was quarantined and rejoined
        assert _until(lambda: fleet.engines[0].state == "serving"
                      and fleet.engines[0].restarts == 1)


def test_fleet_bounce_cap_resolves_error_not_hang():
    def always_fail(engine, attempt, rid, **kw):
        raise RuntimeError("chaos: engine killed (injected)")

    resilience.register_fault("fleet.dispatch", always_fail)
    with _mk_fleet(n=2, cfg=_cfg(max_bounces=2, deadline_s=6.0)) as fleet:
        r = fleet.select(*_req())
        assert r.status == "error"
        assert "failed on 3 engines" in r.error
        assert "chaos: engine killed" in r.error
        assert fleet.stats()["fleet_engine_failures_total"] == 3


def test_fleet_permanent_eject_after_restart_cap():
    def always_fail(engine, attempt, rid, **kw):
        raise RuntimeError("chaos: engine killed (injected)")

    resilience.register_fault("fleet.dispatch", always_fail)
    with _mk_fleet(n=1, cfg=_cfg(max_restarts=1, max_bounces=5,
                                 deadline_s=1.0)) as fleet:
        r = fleet.select(*_req())
        # the lone engine burns its restart budget and is ejected; the
        # request resolves (deadline) instead of hanging
        assert r.status in ("deadline", "error")
        assert _until(lambda: fleet.engines[0].state == "ejected")
        assert fleet.stats()["fleet_ejected_total"] == 1
        ok, detail = fleet._fleet_health()
        assert not ok and "0/1" in detail
        # with every engine ejected, admission errors out immediately
        r2 = fleet.submit(*_req())
        assert r2.done and r2.result.status == "error"
        assert "all ejected" in r2.result.error


def test_fleet_ladder_caps_dispatch_and_falls_back_to_bf16():
    made = {}

    def factory(dtype):
        fe = _StubFrontend(dtype=dtype)
        made.setdefault(dtype, []).append(fe)
        return fe

    meta = {"buckets": [1, 2, 4],
            "params": {"float32": {}, "bfloat16": {}}}
    with _mk_fleet(n=1, factory=factory, meta=meta) as fleet:
        lad = fleet._ladder
        assert lad.rungs == [(None, "float32"), (2, "float32"),
                             (1, "float32"), (1, "bfloat16")]
        fe = made["float32"][0]
        fe.sizes.clear()                        # drop the selfcheck batch
        lad.level = 1                           # cap buckets at 2
        r = fleet.select(*_req(6))
        assert r.ok and r.actions.shape == (6, A)
        assert fe.sizes and max(fe.sizes) <= 2  # chunked under the cap
        lad.level = 3                           # bf16 rung, cap 1
        r = fleet.select(*_req(3))
        assert r.ok
        assert "bfloat16" in made               # alt variant lazily loaded
        alt = made["bfloat16"][0]
        assert alt.sizes and max(alt.sizes) <= 1


# ---------------------------------------------------------------------------
# hot refresh (in-gate: fold check stubbed; the real fold is slow-tier)
# ---------------------------------------------------------------------------


def test_fleet_refresh_rolls_all_engines_and_swaps_params():
    with _mk_fleet(n=2) as fleet:
        new = {"w": np.float32(2.0)}
        fleet._fold_check = lambda ck: (new, {"t_env": 7,
                                              "buckets_checked": 0})
        out = fleet.refresh("ckpt")
        assert out["status"] == "ok"
        assert out["engines"] == 2 and out["t_env"] == 7
        assert all(e.fe._params is new for e in fleet.engines)
        assert fleet._live_params is new
        assert fleet.serving_engines() == 2
        r = fleet.select(*_req())
        assert r.ok and (r.actions == 2).all()  # traffic sees new params
        assert fleet.stats()["fleet_refresh_total"] == 1


def test_fleet_refresh_rolled_back_when_selfcheck_trips():
    def tripper(engine, stage, **kw):
        if stage == "refresh":
            raise RuntimeError("chaos: poisoned selfcheck (injected)")

    resilience.register_fault("fleet.selfcheck", tripper)
    with _mk_fleet(n=2) as fleet:
        old = [e.fe._params for e in fleet.engines]
        fleet._fold_check = lambda ck: ({"w": np.float32(3.0)},
                                        {"t_env": 9, "buckets_checked": 0})
        out = fleet.refresh("ckpt")
        assert out["status"] == "rolled_back"
        assert "poisoned selfcheck" in out["reason"]
        # every engine kept (or got back) the params it had
        assert [e.fe._params for e in fleet.engines] == old
        assert fleet.serving_engines() == 2     # never stopped serving
        assert fleet.select(*_req()).ok
        assert fleet.stats()["fleet_refresh_rollback_total"] == 1


def test_fleet_refresh_refused_keeps_serving():
    from t2omca_tpu.serve.fleet import RefreshRefused
    with _mk_fleet(n=2) as fleet:
        old = [e.fe._params for e in fleet.engines]

        def refuse(ck):
            raise RefreshRefused("fingerprint drift")

        fleet._fold_check = refuse
        out = fleet.refresh("ckpt")
        assert out["status"] == "refused"
        assert "fingerprint drift" in out["reason"]
        assert [e.fe._params for e in fleet.engines] == old
        assert fleet.serving_engines() == 2
        assert fleet.select(*_req()).ok
        assert fleet.stats()["fleet_refresh_refused_total"] == 1


def test_fleet_refresh_aborts_below_n_minus_1_and_reports_busy():
    with _mk_fleet(n=2) as fleet:
        fleet._fold_check = lambda ck: ({"w": np.float32(4.0)},
                                        {"t_env": 1, "buckets_checked": 0})
        # concurrent refresh: second caller bounces off, no queueing
        assert fleet._refresh_lock.acquire(blocking=False)
        try:
            assert fleet.refresh("ckpt") == {"status": "busy"}
        finally:
            fleet._refresh_lock.release()
        # with a peer down, swapping the survivor would drop the fleet
        # below N-1 serving → abort, params untouched
        eng1 = fleet.engines[1]
        with eng1.lock:
            eng1.gen += 1                       # supersede its worker
            eng1.state = "quarantined"
            eng1.restart_at = time.monotonic() + 60.0
        old0 = fleet.engines[0].fe._params
        out = fleet.refresh("ckpt")
        assert out["status"] == "aborted"
        assert "N-1" in out["reason"]
        assert fleet.engines[0].fe._params is old0


def test_fleet_refresh_trigger_file_arms_refresh(tmp_path):
    from t2omca_tpu.serve.fleet import REFRESH_TRIGGER
    meta = {"buckets": [1], "params": {"float32": {}}}
    with _mk_fleet(n=1, artifact_dir=str(tmp_path), meta=meta) as fleet:
        seen = []

        def fold(ck):
            seen.append(ck)
            return {"w": np.float32(3.0)}, {"t_env": 5,
                                            "buckets_checked": 0}

        fleet._fold_check = fold
        trig = tmp_path / REFRESH_TRIGGER
        trig.write_text(str(tmp_path / "ck") + "\n")
        assert _until(lambda: fleet.stats().get("fleet_refresh_total",
                                                0) == 1)
        assert not trig.exists()                # consumed, not re-armed
        assert seen == [str(tmp_path / "ck")]


# ---------------------------------------------------------------------------
# lifecycle + pulse wiring
# ---------------------------------------------------------------------------


def test_fleet_stop_resolves_everything_outstanding():
    fleet = _mk_fleet(n=2).start()
    for e in fleet.engines:
        e.pause_ev.set()
    reqs = [fleet.submit(*_req()) for _ in range(5)]
    fleet.stop()
    for req in reqs:
        r = req.wait(1.0)
        assert r.status == "error" and "shutdown" in r.error
    late = fleet.submit(*_req())
    assert late.done and late.result.status == "error"
    assert "stopped" in late.result.error
    fleet.stop()                                # idempotent


def test_fleet_health_on_pulse_hub():
    from t2omca_tpu.obs.pulse import MetricsHub
    hub = MetricsHub()
    fleet = _mk_fleet(n=2, hub=hub).start()
    try:
        ok, payload = hub.healthz()
        checks = payload["checks"]
        assert checks["fleet"]["ok"]
        assert "2/2 engines serving" in checks["fleet"]["detail"]
        assert checks["fleet_engine0"]["ok"] and checks["fleet_engine1"]["ok"]
        # supervisor exports the gauges each tick
        assert _until(lambda: "t2omca_fleet_queue_depth"
                      in hub.render_prometheus())
        assert 't2omca_fleet_engine_state{engine="0"}' \
            in hub.render_prometheus()
        # one engine down: its check flips, the FLEET check holds at N-1
        eng1 = fleet.engines[1]
        with eng1.lock:
            eng1.gen += 1
            eng1.state = "quarantined"
            eng1.last_error = "injected"
            eng1.restart_at = time.monotonic() + 60.0
        ok, payload = hub.healthz()
        assert not payload["checks"]["fleet_engine1"]["ok"]
        assert payload["checks"]["fleet"]["ok"]
    finally:
        fleet.stop()
    ok, payload = hub.healthz()
    assert not ok and not payload["checks"]["fleet"]["ok"]


# ---------------------------------------------------------------------------
# artifact-backed refresh (slow: real fold + fingerprint gate)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    return sanity_check(TrainConfig(
        batch_size_run=4, batch_size=4,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8)))


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """One smoke checkpoint + exported artifact shared by the slow
    fleet tests (same shape as tests/test_serve.py's fixture)."""
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.serve.export import export_artifact
    from t2omca_tpu.utils.checkpoint import save_checkpoint
    root = tmp_path_factory.mktemp("fleet")
    cfg = _tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    ck = os.path.join(root, "models")
    save_checkpoint(ck, 128, ts)
    art = os.path.join(root, "art")
    meta = export_artifact(cfg, ck, art, buckets=(1, 2, 4))
    return cfg, ck, art, meta


@pytest.mark.slow
def test_fleet_clean_refresh_is_bit_identical(exported):
    """The rolling-refresh parity pin: re-folding the SAME checkpoint
    through the hot-refresh path and rolling it across every engine
    changes nothing — responses before and after are bit-identical, and
    the fleet never dropped an engine doing it."""
    from t2omca_tpu.serve.fleet import FleetConfig, ServeFleet
    cfg, ck, art, meta = exported
    fleet = ServeFleet(art, n_engines=2, dtype="float32",
                       cfg=FleetConfig(poll_s=0.005)).start()
    try:
        assert fleet.serving_engines() == 2
        rng = np.random.default_rng(11)
        fe = fleet.engines[0].fe
        obs = rng.standard_normal(
            (3, fe.n_agents, fe.obs_dim)).astype(np.float32)
        avail = rng.random((3, fe.n_agents, fe.n_actions)) < 0.7
        avail[..., 0] = True
        before = fleet.select(obs, avail)
        assert before.ok
        out = fleet.refresh(ck)
        assert out["status"] == "ok", out
        assert out["engines"] == 2
        assert out["buckets_checked"] == 3      # every bucket fingerprinted
        assert fleet.serving_engines() == 2
        after = fleet.select(obs, avail)
        assert after.ok
        np.testing.assert_array_equal(before.actions, after.actions)
        np.testing.assert_array_equal(before.hidden, after.hidden)
        # a poisoned refresh against the same live fleet: refused, and
        # serving continues uninterrupted on the refreshed params
        bad = fleet.refresh(os.path.join(art, "_no_such_checkpoint"))
        assert bad["status"] == "refused"
        assert fleet.serving_engines() == 2
        assert fleet.select(obs, avail).ok
        assert fleet.stats()["fleet_refresh_refused_total"] == 1
    finally:
        fleet.stop()


@pytest.mark.slow
def test_check_refresh_dry_run_and_cli(exported, capsys):
    from t2omca_tpu.serve.__main__ import main
    from t2omca_tpu.serve.fleet import check_refresh
    cfg, ck, art, meta = exported
    out = check_refresh(art, ck)
    assert out["status"] == "compatible"
    assert out["buckets_checked"] == 3 and out["t_env"] == 128
    bad = check_refresh(art, os.path.join(art, "_no_such_checkpoint"))
    assert bad["status"] == "refused" and bad["reason"]
    # the CLI surface: exit 0 compatible, exit 2 refused / not an artifact
    assert main(["refresh", art, ck]) == 0
    assert "refresh compatible" in capsys.readouterr().out
    rc = main(["refresh", art, os.path.join(art, "_no_such_checkpoint")])
    assert rc == 2
    assert "REFUSED" in capsys.readouterr().err
    rc = main(["refresh", os.path.dirname(art), ck])
    assert rc == 2
    assert "not a serve artifact" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# chaos acceptance: bench.py --serve --chaos (slow + chaos battery)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.faultinject
def test_bench_serve_chaos_acceptance(exported):
    """The fleet-under-fire acceptance run (scripts/chaos.sh serve
    scenario): bursty open-loop traffic with engine 0 killed mid-burst,
    a dispatch hang injected on a peer and a poisoned hot refresh —
    every admitted request must resolve explicitly (ZERO silent hangs),
    the quarantined engines must restart and rejoin, and the refresh
    must be refused while serving continues."""
    cfg, ck, art, meta = exported
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--serve", "--chaos",
         "--artifact", art, "--fleet-engines", "2",
         "--chaos-seconds", "8"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_chaos_p99_ms"
    # zero silent hangs: every admitted request resolved to exactly one
    # explicit status, none via the unresolved-at-wait backstop
    assert rec["unresolved"] == 0
    assert rec["ok"] + rec["shed"] + rec["deadline"] + rec["errors"] \
        == rec["requests"]
    assert rec["ok"] > 0
    assert rec["value"] == rec["p99_ms"] and rec["p99_ms"] > 0
    assert 0.0 <= rec["shed_fraction"] <= 1.0
    # the killed engine was quarantined, restarted and rejoined
    assert rec["engine_restarts"] >= 1
    assert rec["recovery_s"] is not None and rec["recovery_s"] > 0
    assert rec["recoveries_s"]
    assert rec["ejected"] == 0
    # the injected hang tripped the per-engine watchdog
    assert rec["stalls"] >= 1
    # the poisoned refresh was REFUSED, never applied
    assert rec["refresh"] and rec["refresh"]["status"] == "refused"
    # the fleet ended RESUMABLE: every engine back in serving state
    assert rec["engines_serving_end"] == rec["engines"] == 2


@pytest.mark.slow
def test_bench_serve_chaos_partial_record_on_failure(tmp_path):
    """A chaos leg that dies on the launchpad (missing artifact) still
    files ONE parseable partial record under the chaos metric with the
    flight-recorder fields (phase + spans tail)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--serve", "--chaos",
         "--artifact", str(tmp_path / "missing")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_chaos_p99_ms"
    assert rec["value"] is None
    assert rec["error"]
    assert "phase" in rec and "spans_tail" in rec
