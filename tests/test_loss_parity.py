"""End-to-end learning parity: the jax QMIX learner's loss trajectory vs
the PyTorch oracle (BASELINE.json north-star quality target — "loss curve
matches the PyTorch CPU reference path"; SURVEY.md §7.4(2)).

Both sides receive the IDENTICAL sequence of real rollout batches and IS
weights and run the IDENTICAL optimizer (Adam lr=1e-3 eps=1e-5 under
global-norm-10 clipping) for 20 train steps in LOCKSTEP: each step the
torch oracle is re-synced to the jax params, both compute the loss and
apply their own update, and the per-step losses AND post-update parameters
must agree tightly. Lockstep is deliberate — free-running trajectories
diverge chaotically through the double-Q argmax (a ~1e-6 f32 forward
difference flips a target action choice and macroscopically changes the
loss a few steps later), which would force uselessly loose tolerances;
re-syncing pins every step's full learner math — the double-Q target
construction, both recurrent unrolls from t=0, Q7 bootstrapping, the
IS-weighted masked MSE, and (via the post-update parameter check, with
torch's Adam moments persisting across steps) the optimizer wiring — at
f32-forward precision for all 20 steps.

Scale: config 1's model/env point (4 AGVs x 2 MEC, d_model=64, reference
parity mode fast_norm=False => dense storage + sequential normalizer),
with the episode horizon shortened 150->12 to keep the torch python-loop
oracle tractable (the math is horizon-independent)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment

import oracle_torch as oracle
from test_models_parity import to_torch_params

N_STEPS = 20


def _cfg():
    return sanity_check(TrainConfig(
        batch_size_run=4, batch_size=4, lr=1e-3, optim_eps=1e-5,
        grad_norm_clip=10.0, gamma=0.99, double_q=True,
        env_args=EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                           episode_limit=12, fast_norm=False),
        model=ModelConfig(emb=64, heads=3, depth=2, mixer_emb=64,
                          mixer_heads=3, mixer_depth=2),
        replay=ReplayConfig(buffer_size=8, prioritized=False),
    ))


def _collect_batches(exp, ts, n):
    """n rollout batches under the FIXED initial params (data collection is
    decoupled so both learners see the identical sequence)."""
    rollout = jax.jit(exp.runner.run, static_argnames="test_mode")
    params = ts.learner.params["agent"]
    rs = ts.runner
    batches = []
    for _ in range(n):
        rs, batch, _ = rollout(params, rs, test_mode=False)
        batches.append(jax.device_get(batch))
    return batches


def _to_torch(batch):
    return {
        "obs": torch.tensor(np.asarray(batch.obs, np.float32)),
        "state": torch.tensor(np.asarray(batch.state, np.float32)),
        "avail": torch.tensor(np.asarray(batch.avail_actions, np.int64)),
        "actions": torch.tensor(np.asarray(batch.actions, np.int64)),
        "reward": torch.tensor(np.asarray(batch.reward, np.float32)),
        "terminated": torch.tensor(
            np.asarray(batch.terminated, np.float32)),
        "filled": torch.tensor(np.asarray(batch.filled, np.float32)),
    }


def test_qmix_loss_trajectory_matches_torch_oracle():
    cfg = _cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    batches = _collect_batches(exp, ts, N_STEPS)
    # fixed non-uniform IS weights, max-normalized like the PER path
    w = jax.random.uniform(jax.random.PRNGKey(42),
                           (N_STEPS, cfg.batch_size), minval=0.3)
    w = np.asarray(w / w.max(axis=1, keepdims=True), np.float32)

    # (episode pinned at 0: no target sync inside the 20-step horizon)
    train = jax.jit(exp.learner.train)

    # ---- torch oracle from the same initial weights
    ag = exp.mac.agent
    mx = exp.learner.mixer
    agent_kw = dict(n_entities=ag.n_entities, feat_dim=ag.feat_dim,
                    emb=ag.emb, heads=ag.heads, depth=ag.depth)
    mixer_kw = dict(n_agents=mx.n_agents, n_entities=mx.n_entities,
                    feat_dim=mx.feat_dim, emb=mx.emb, heads=mx.heads,
                    depth=mx.depth, state_entity_mode=mx.state_entity_mode,
                    pos=mx.qmix_pos_func, pos_beta=mx.qmix_pos_func_beta)

    p0 = jax.device_get(ts.learner.params)
    p_ag = {k: v.clone().requires_grad_(True)
            for k, v in to_torch_params(p0["agent"]["params"]).items()}
    p_mx = {k: v.clone().requires_grad_(True)
            for k, v in to_torch_params(p0["mixer"]["params"]).items()}
    tp_ag = {k: v.detach().clone() for k, v in p_ag.items()}
    tp_mx = {k: v.detach().clone() for k, v in p_mx.items()}
    leaves = list(p_ag.values()) + list(p_mx.values())
    opt = torch.optim.Adam(leaves, lr=cfg.lr, eps=cfg.optim_eps)

    # ---- lockstep: both sides step together from the same params
    ls = ts.learner
    losses_j, losses_t = [], []
    for i, batch in enumerate(batches):
        cur = jax.device_get(ls.params)
        with torch.no_grad():
            for k, v in to_torch_params(cur["agent"]["params"]).items():
                p_ag[k].copy_(v)
            for k, v in to_torch_params(cur["mixer"]["params"]).items():
                p_mx[k].copy_(v)
        loss = oracle.qmix_episode_loss(
            p_ag, p_mx, tp_ag, tp_mx, _to_torch(batch),
            torch.tensor(w[i]), gamma=cfg.gamma,
            n_agents=exp.mac.n_agents, agent_kw=agent_kw,
            mixer_kw=mixer_kw, double_q=cfg.double_q)
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(leaves, cfg.grad_norm_clip)
        opt.step()
        losses_t.append(float(loss.detach()))

        # jax takes the same step from the same params
        ls, info = train(ls, jax.tree.map(jnp.asarray, batch),
                         jnp.asarray(w[i]), jnp.asarray(0),
                         jnp.asarray(0, jnp.int32))
        losses_j.append(float(info["loss"]))
        # post-update parameter parity pins the grad + clip + Adam wiring
        # (torch's moments persist across steps, fed by matched grads).
        # Isolated elements may legitimately deviate — an f32 near-tie in
        # the double-Q argmax can resolve differently across frameworks,
        # changing a handful of gradient entries — so the gate bounds the
        # OUTLIER FRACTION (≤0.1%) and the worst excursion (a few lr-scale
        # updates) instead of demanding all-element closeness; a real
        # wiring bug moves most elements at lr scale every step.
        new = jax.device_get(ls.params)
        for flat, tree in ((p_ag, new["agent"]["params"]),
                           (p_mx, new["mixer"]["params"])):
            for k, v in to_torch_params(tree).items():
                a = flat[k].detach().numpy()
                b = v.numpy()
                diff = np.abs(a - b)
                bad = diff > (5e-5 + 2e-3 * np.abs(b))
                assert bad.mean() <= 1e-3, (
                    f"step {i}: {bad.sum()}/{bad.size} elements of {k} "
                    f"diverged (max |d|={diff.max():.2e})")
                assert diff.max() <= 5e-3, (
                    f"step {i}: {k} max |d|={diff.max():.2e} exceeds a "
                    f"few lr-scale updates")

    losses_j, losses_t = np.asarray(losses_j), np.asarray(losses_t)
    # every step's loss at f32-forward precision (lockstep: no chaos)
    np.testing.assert_allclose(losses_j, losses_t, rtol=5e-4)
    # and the jax trajectory actually moved
    assert losses_j[-1] != losses_j[0]
