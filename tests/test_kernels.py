"""Coverage for the rollout hot-path kernel layer (t2omca_tpu/kernels/,
docs/PERF.md): the Pallas fused attention kernel vs the einsum path, the
single-scatter time-major ring insert, and the bf16 acting-dtype mode —
the PR-9 parity contracts the CPU tier-1 gate pins.

The pallas kernel runs in interpreter mode here (interpret auto-selects
off-TPU), so every assertion below holds for the exact kernel body that
lowers to Mosaic on a real chip."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, KernelsConfig, ModelConfig,
                               ReplayConfig, TrainConfig, from_dict,
                               sanity_check)
from t2omca_tpu.kernels.attention import (NEG_MASK_VALUE,
                                          _reference_attention,
                                          flash_attention)
from t2omca_tpu.models.transformer import MultiHeadAttention


def _rand(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


def _mask_bias(mask):
    return None if mask is None else jnp.where(mask, 0.0, NEG_MASK_VALUE)


# ------------------------------------------------------- kernel vs einsum

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_flash_matches_einsum_f32(causal, masked):
    """f32 parity: online softmax vs max-subtracted softmax is the same
    math under a different association — per-element error must sit at
    float-reassociation scale, orders below any training tolerance."""
    rng = np.random.default_rng(0)
    b, h, t, d = 2, 3, 9, 16
    q, k, v = (_rand(rng, (b, h, t, d)) for _ in range(3))
    mask = jnp.asarray(rng.random((b, 1, t, t)) > 0.3) if masked else None
    out = flash_attention(q, k, v, mask=mask, causal=causal)
    ref = _reference_attention(q, k, v, _mask_bias(mask), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=2e-6)


def test_flash_odd_shapes_padding():
    """Token/head dims that don't divide the tile sizes exercise the
    pad-and-mask tail path (t_q=5, t_k=7, d=12 — none tile-aligned)."""
    rng = np.random.default_rng(1)
    q = _rand(rng, (2, 2, 5, 12))
    k = _rand(rng, (2, 2, 7, 12))
    v = _rand(rng, (2, 2, 7, 12))
    mask = jnp.asarray(rng.random((2, 1, 5, 7)) > 0.4)
    out = flash_attention(q, k, v, mask=mask)
    ref = _reference_attention(q, k, v, _mask_bias(mask), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=2e-6)


def test_flash_small_blocks_multi_tile():
    """Explicit tiny tiles force a real multi-block online-softmax pass
    (several k-block iterations carrying the running max/denominator)."""
    rng = np.random.default_rng(2)
    q, k, v = (_rand(rng, (1, 2, 40, 8)) for _ in range(3))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = _reference_attention(q, k, v, None, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=2e-6)


def test_flash_bf16_within_tolerance():
    """bf16 inputs, f32 accumulators: the kernel is *better*-conditioned
    than the einsum bf16 path (which softmaxes in bf16), so comparing
    against the f32 reference bounds both."""
    rng = np.random.default_rng(3)
    q, k, v = (_rand(rng, (2, 2, 17, 8), jnp.bfloat16) for _ in range(3))
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _reference_attention(q.astype(jnp.float32),
                               k.astype(jnp.float32),
                               v.astype(jnp.float32), None, False)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.02)


def test_flash_fully_masked_row_matches_einsum_degenerate():
    """All-masked rows degrade to the einsum path's uniform distribution
    (replacement semantics — an additive bias would silently cancel)."""
    rng = np.random.default_rng(4)
    q, k, v = (_rand(rng, (1, 1, 4, 8)) for _ in range(3))
    mask = jnp.ones((1, 1, 4, 4), bool).at[0, 0, 2].set(False)
    out = flash_attention(q, k, v, mask=mask)
    ref = _reference_attention(q, k, v, _mask_bias(mask), False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=2e-6)
    # the degenerate row really is the uniform mean of V
    np.testing.assert_allclose(np.asarray(out)[0, 0, 2],
                               np.asarray(v).mean(axis=2)[0, 0],
                               rtol=1e-5, atol=1e-5)


def _grad_pair(q, k, v, mask, causal, **kw):
    """(flash grads, einsum-reference grads) for a sum-of-squares loss —
    the flash side runs the PR 13 backward kernels (P recomputed in
    VMEM from the saved m/l residuals), the reference side is
    ``jax.grad`` through the einsum path."""
    bias = _mask_bias(mask)

    def loss_p(q, k, v):
        return (flash_attention(q, k, v, mask=mask, causal=causal,
                                **kw).astype(jnp.float32) ** 2).sum()

    def loss_r(q, k, v):
        return (_reference_attention(
            q, k, v, bias, causal).astype(jnp.float32) ** 2).sum()

    return (jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v),
            jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_flash_backward_matches_einsum_f32(causal, masked):
    """The flash backward kernels must yield the einsum VJP's gradients
    at the same inputs to float-reassociation scale — the learner
    unrolls train straight through the kernel (mask-replacement and
    causal cotangent-zeroing semantics identical)."""
    rng = np.random.default_rng(5)
    q, k, v = (_rand(rng, (2, 2, 7, 8)) for _ in range(3))
    mask = jnp.asarray(rng.random((2, 1, 7, 7)) > 0.3) if masked else None
    gp, gr = _grad_pair(q, k, v, mask, causal)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_backward_pad_tails_and_per_head_mask():
    """Backward with explicit tiny blocks over non-dividing token counts
    (t_q=5, t_k=7 at 4-blocks): the recomputed P tiles carry real pad
    columns/rows whose cotangents must vanish exactly; the (B, H, ...)
    per-head mask exercises the backward's head-indexed bias specs."""
    rng = np.random.default_rng(6)
    q = _rand(rng, (2, 2, 5, 12))
    k = _rand(rng, (2, 2, 7, 12))
    v = _rand(rng, (2, 2, 7, 12))
    mask = jnp.asarray(rng.random((2, 2, 5, 7)) > 0.4)   # per-head
    gp, gr = _grad_pair(q, k, v, mask, False, block_q=4, block_k=4)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_backward_multi_k_block():
    """Several key blocks per query block: the backward's inner loop
    recomputes MULTIPLE P tiles against one residual pair — the case
    where a fused-lse residual (m + log l) or a per-block renormalize
    bug would surface."""
    rng = np.random.default_rng(7)
    q, k, v = (_rand(rng, (1, 2, 40, 8)) for _ in range(3))
    gp, gr = _grad_pair(q, k, v, None, False, block_q=16, block_k=16)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_backward_all_masked_row():
    """All-masked rows: the forward degrades to uniform-over-keys, and
    the einsum VJP still routes cotangent into V through those uniform
    weights while zeroing dQ/dK (every logit was replaced). The m/l
    residuals are kept SEPARATE precisely so the backward's recomputed
    P survives this case in f32 (m = −1e9 swallows log l)."""
    rng = np.random.default_rng(8)
    q, k, v = (_rand(rng, (1, 1, 4, 8)) for _ in range(3))
    mask = jnp.ones((1, 1, 4, 4), bool).at[0, 0, 2].set(False)
    gp, gr = _grad_pair(q, k, v, mask, False)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    # the masked row's uniform weights really do carry dV
    assert float(jnp.abs(gp[2]).max()) > 0.0
    # ... and its dq is exactly zero (all logits were replaced)
    assert float(jnp.abs(np.asarray(gp[0])[0, 0, 2]).max()) == 0.0


def test_flash_backward_bf16_within_tolerance():
    """bf16 inputs: backward recompute + accumulation stay f32 inside
    the kernels, so gradients sit within the established bf16 ULP
    tolerance of the f32 einsum reference."""
    rng = np.random.default_rng(9)
    q, k, v = (_rand(rng, (2, 2, 17, 8), jnp.bfloat16) for _ in range(3))
    gp, _ = _grad_pair(q, k, v, None, False)
    gr32 = jax.grad(
        lambda a, b, c: (_reference_attention(a, b, c, None, False)
                         ** 2).sum(), argnums=(0, 1, 2))(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32))
    for a, b in zip(gp, gr32):
        assert a.dtype == jnp.bfloat16
        scale = float(jnp.abs(b).max())
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), rtol=0.05,
                                   atol=0.02 * max(scale, 1.0))


# ------------------------------------------------- module-level switch

@pytest.mark.parametrize("standard_heads", [False, True])
def test_mha_pallas_matches_xla(standard_heads):
    """MultiHeadAttention(attn_impl=pallas) == the einsum module over
    the SAME params — both the Q1 full-emb and standard head
    geometries."""
    rng = np.random.default_rng(6)
    x = _rand(rng, (3, 7, 16))
    kw = dict(emb=16, heads=2, standard_heads=standard_heads)
    mx = MultiHeadAttention(**kw)
    mp = MultiHeadAttention(**kw, attn_impl="pallas")
    params = mx.init(jax.random.PRNGKey(0), x, x)
    np.testing.assert_allclose(np.asarray(mx.apply(params, x, x)),
                               np.asarray(mp.apply(params, x, x)),
                               rtol=1e-5, atol=1e-5)


def test_mha_rejects_unknown_impl():
    x = jnp.zeros((1, 2, 8))
    m = MultiHeadAttention(emb=8, heads=2, attn_impl="cuda")
    with pytest.raises(AssertionError):
        m.init(jax.random.PRNGKey(0), x, x)


# ------------------------------------------------------- config plumbing

def test_kernels_config_sanity_and_merge():
    cfg = sanity_check(TrainConfig(kernels=KernelsConfig(
        attention="pallas")))
    assert cfg.kernels.attention == "pallas"
    with pytest.raises(ValueError, match="kernels.attention"):
        sanity_check(TrainConfig(kernels=KernelsConfig(attention="cuda")))
    # nested-dict + flat-key routing, and the meta.json roundtrip
    cfg = from_dict({"kernels": {"attention": "pallas"},
                     "model": {"act_dtype": "bfloat16"}})
    assert cfg.kernels.attention == "pallas"
    assert cfg.model.act_dtype == "bfloat16"
    rt = from_dict(dataclasses.asdict(cfg))
    assert rt.kernels.attention == "pallas"


def test_act_dtype_sanity():
    with pytest.raises(ValueError, match="act_dtype"):
        sanity_check(TrainConfig(model=ModelConfig(act_dtype="float16")))


# ----------------------------------------- integration (tiny Experiment)

def _tiny_cfg(**kw):
    model_kw = kw.pop("model", {})
    return sanity_check(TrainConfig(
        batch_size_run=2, batch_size=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1, **model_kw),
        replay=ReplayConfig(buffer_size=8), **kw))


@pytest.fixture(scope="module")
def tiny_exp():
    from t2omca_tpu.run import Experiment
    exp = Experiment.build(_tiny_cfg())
    ts = exp.init_train_state(0)
    rs, tm, _ = exp.runner.run_raw(ts.learner.params["agent"], ts.runner)
    return exp, ts, tm


def test_single_scatter_insert_bit_identical(tiny_exp):
    """insert_time_major (ONE combined-index scatter per leaf) must stay
    bit-identical to insert_episode_batch(to_batch()) — including across
    ring wraparound, where the slot set is non-contiguous."""
    exp, _, tm = tiny_exp
    buf = exp.buffer
    st = buf.init()
    for _ in range(5):                  # 10 episodes through capacity 8
        a = buf.insert_time_major(st, tm)
        b = buf.insert_episode_batch(st, tm.to_batch())
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            assert (np.asarray(la) == np.asarray(lb)).all()
        st = a
    assert int(st.episodes_in_buffer) == buf.capacity


def test_acting_default_bit_identical_to_train_forward(tiny_exp):
    """act_dtype unset: the acting fold + acting=True forward must be
    bit-identical to the training-path forward (the serving f32 parity
    contract rides on this)."""
    exp, ts, _ = tiny_exp
    mac = exp.mac
    p = ts.learner.params["agent"]
    rng = np.random.default_rng(7)
    obs = _rand(rng, (2, mac.n_agents, exp.env.obs_dim))
    hid = mac.init_hidden(2)
    fp = mac.prepare_acting_params(p)
    q_act, h_act = mac.forward_qslice(fp, obs, hid, acting=True)
    q_tr, h_tr = mac.forward_qslice(fp, obs, hid, acting=False)
    assert (np.asarray(q_act) == np.asarray(q_tr)).all()
    assert (np.asarray(h_act) == np.asarray(h_tr)).all()


def test_bf16_acting_within_tolerance(tiny_exp):
    """model.act_dtype=bfloat16 over an f32 train dtype: acting q-values
    stay within the established bf16 tolerance of the f32 path, greedy
    actions agree, and the TRAIN-path forward is untouched (bit-equal
    params/unroll dtype)."""
    from t2omca_tpu.run import Experiment
    exp32, ts, _ = tiny_exp
    expb = Experiment.build(_tiny_cfg(model={"act_dtype": "bfloat16"}))
    mac32, macb = exp32.mac, expb.mac
    assert macb.act_agent is None or macb.act_agent.dtype == jnp.bfloat16
    p = ts.learner.params["agent"]
    rng = np.random.default_rng(8)
    obs = _rand(rng, (2, mac32.n_agents, exp32.env.obs_dim))
    hid = mac32.init_hidden(2)
    avail = jnp.ones((2, mac32.n_agents, mac32.n_actions))

    fp32 = mac32.prepare_acting_params(p)
    fpb = macb.prepare_acting_params(p)
    # the acting fold really is bf16 (params halved per scan step)
    assert fpb["tf"]["blocks"][0]["wqk"].dtype == jnp.bfloat16
    q32, _ = mac32.forward_qslice(fp32, obs, hid, acting=True)
    qb, _ = macb.forward_qslice(fpb, obs, hid, acting=True)
    np.testing.assert_allclose(np.asarray(qb), np.asarray(q32),
                               rtol=0.05, atol=0.05)
    a32, _, _ = mac32.select_actions(fp32, obs, avail, hid,
                                     jax.random.PRNGKey(0), jnp.asarray(0),
                                     test_mode=True)
    ab, _, _ = macb.select_actions(fpb, obs, avail, hid,
                                   jax.random.PRNGKey(0), jnp.asarray(0),
                                   test_mode=True)
    assert (np.asarray(a32) == np.asarray(ab)).mean() > 0.9
    # train path untouched: learner-side forward ignores act_dtype
    qt32, _ = mac32.forward_qslice(p, obs, hid)
    qtb, _ = macb.forward_qslice(p, obs, hid)
    assert (np.asarray(qt32) == np.asarray(qtb)).all()


def test_bf16_acting_dense_path_uses_act_agent():
    """The DENSE acting path under act_dtype=bfloat16: BasicMAC.forward
    (acting=True) must route through the bf16 act_agent module clone,
    produce q within the bf16 tolerance of the f32 module, and leave
    the train-path forward (acting=False) bit-identical."""
    from t2omca_tpu.run import Experiment
    exp32 = Experiment.build(_tiny_cfg(model={"use_qslice": False}))
    expb = Experiment.build(_tiny_cfg(model={"use_qslice": False,
                                             "act_dtype": "bfloat16"}))
    mac32, macb = exp32.mac, expb.mac
    assert macb.act_agent is not None
    assert macb.act_agent.dtype == jnp.bfloat16
    assert macb.agent.dtype == jnp.float32      # train module untouched
    ts = exp32.init_train_state(0)
    p = ts.learner.params["agent"]
    rng = np.random.default_rng(9)
    obs = _rand(rng, (2, mac32.n_agents, exp32.env.obs_dim))
    hid = mac32.init_hidden(2)
    # dense path: prepare_acting_params pre-casts the raw tree
    pb = macb.prepare_acting_params(p)
    assert jax.tree.leaves(pb)[0].dtype == jnp.bfloat16
    q32, h32 = mac32.forward(p, obs, hid, acting=True)
    qb, hb = macb.forward(pb, obs, hid, acting=True)
    np.testing.assert_allclose(np.asarray(qb), np.asarray(q32),
                               rtol=0.05, atol=0.05)
    # train-path forward ignores act_dtype AND the acting clone
    qt32, _ = mac32.forward(p, obs, hid)
    qtb, _ = macb.forward(p, obs, hid)
    assert (np.asarray(qt32) == np.asarray(qtb)).all()
    # the full select_actions greedy path agrees across dtypes
    avail = jnp.ones((2, mac32.n_agents, mac32.n_actions))
    a32, _, _ = mac32.select_actions(
        mac32.prepare_acting_params(p), obs, avail, hid,
        jax.random.PRNGKey(0), jnp.asarray(0), test_mode=True)
    ab, _, _ = macb.select_actions(pb, obs, avail, hid,
                                   jax.random.PRNGKey(0), jnp.asarray(0),
                                   test_mode=True)
    assert (np.asarray(a32) == np.asarray(ab)).mean() > 0.9


def test_export_fold_stays_train_dtype_under_act_dtype():
    """The serving exporter folds at the TRAIN dtype even when the
    training config sets act_dtype=bfloat16 — the artifact's canonical
    f32 variant must never silently contain bf16 leaves
    (serve/export.py f32 bit-parity contract)."""
    from t2omca_tpu.run import Experiment
    expb = Experiment.build(_tiny_cfg(model={"act_dtype": "bfloat16"}))
    ts = expb.init_train_state(0)
    p = ts.learner.params["agent"]
    folded = expb.mac.prepare_acting_params(p, dtype=expb.mac.agent.dtype)
    for leaf in jax.tree.leaves(folded):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            assert leaf.dtype == jnp.float32, leaf.dtype


@pytest.mark.slow    # full rollout jit x2 (dense acting, ~40 s on 2 cores)
def test_dense_rollout_pallas_matches_xla():
    """End-to-end: the dense-acting rollout under kernels.attention=
    pallas selects bit-identical actions to the einsum path at f32 (the
    selector argmax absorbs reassociation-scale q differences), so the
    env stream — and therefore the whole episode batch — matches."""
    from t2omca_tpu.run import Experiment
    outs = {}
    for mode in ("xla", "pallas"):
        exp = Experiment.build(_tiny_cfg(
            model={"use_qslice": False},
            kernels=KernelsConfig(attention=mode)))
        ts = exp.init_train_state(0)
        _, batch, stats = exp.runner.run(ts.learner.params["agent"],
                                         ts.runner)
        outs[mode] = (batch, stats)
    bx, sx = outs["xla"]
    bp, sp = outs["pallas"]
    assert (np.asarray(bx.actions) == np.asarray(bp.actions)).all()
    np.testing.assert_allclose(np.asarray(sx.episode_return),
                               np.asarray(sp.episode_return),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------- learner-unroll threading (PR 13)

def test_transformer_rows_pallas_matches_xla_fwd_and_grad():
    """The qslice sliced attention under attn_impl=pallas (one flash
    call over the R·H query rows, k0 as keys AND values) must match the
    einsum branch — forward and gradients — at f32: this is the exact
    lowering the learner unrolls dispatch under kernels.attention:
    pallas."""
    from t2omca_tpu.models.transformer import Transformer
    from t2omca_tpu.ops.query_slice import (fold_transformer,
                                            transformer_rows)
    rng = np.random.default_rng(10)
    emb, heads, depth = 16, 2, 2
    tf = Transformer(emb=emb, heads=heads, depth=depth)
    k0 = _rand(rng, (3, 9, emb))
    params = tf.init(jax.random.PRNGKey(0), k0, k0)

    def rows(p, impl):
        folded = fold_transformer(p["params"], emb=emb, heads=heads,
                                  head_dim=emb, depth=depth,
                                  dtype=jnp.float32)
        out = transformer_rows(folded, k0, k0[:, -4:, :], emb=emb,
                               heads=heads, depth=depth,
                               attn_impl=impl)
        return out

    ox = rows(params, "xla")
    op = rows(params, "pallas")
    np.testing.assert_allclose(np.asarray(op), np.asarray(ox),
                               rtol=1e-5, atol=1e-5)

    gx = jax.grad(lambda p: (rows(p, "xla") ** 2).sum())(params)
    gp = jax.grad(lambda p: (rows(p, "pallas") ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gx)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_acting_and_serving_ignore_pallas_mode(tiny_exp):
    """The kernel switch must land ONLY on the learner unroll: the
    qslice acting forward (select_actions path) and the default
    forward_qslice (serving's serve_step calls it with no attn_impl)
    stay bit-identical between kernel modes — the serving artifact's
    lowering can never depend on a training-run perf knob."""
    from t2omca_tpu.run import Experiment
    exp32, ts, _ = tiny_exp
    expp = Experiment.build(_tiny_cfg(kernels=KernelsConfig(
        attention="pallas")))
    p = ts.learner.params["agent"]
    rng = np.random.default_rng(11)
    obs = _rand(rng, (2, exp32.mac.n_agents, exp32.env.obs_dim))
    hid = exp32.mac.init_hidden(2)
    for acting in (True, False):
        qx, _ = exp32.mac.forward_qslice(p, obs, hid, acting=acting)
        qp, _ = expp.mac.forward_qslice(p, obs, hid, acting=acting)
        assert (np.asarray(qx) == np.asarray(qp)).all()


@pytest.mark.slow   # two Experiment builds + a train step each (~40 s)
def test_qslice_train_step_pallas_matches_xla():
    """End-to-end learner parity on the qslice path (the audit config's
    shape): one train step under kernels.attention=pallas — agent AND
    mixer unrolls lowering through the flash forward + backward kernels
    — matches the einsum mode's loss exactly at f32 display precision
    and its gradients/updated params to reassociation scale."""
    from t2omca_tpu.run import Experiment
    outs = {}
    for mode in ("xla", "pallas"):
        exp = Experiment.build(_tiny_cfg(
            kernels=KernelsConfig(attention=mode)))
        assert exp.mac.use_qslice
        ts = exp.init_train_state(0)
        _, batch, _ = exp.runner.run(ts.learner.params["agent"],
                                     ts.runner)
        small = jax.tree.map(lambda x: x[:2], batch)
        ls, info = exp.learner.train(ts.learner, small, jnp.ones((2,)),
                                     jnp.asarray(0), jnp.asarray(0))
        outs[mode] = (ls, info)
    ix, ip = outs["xla"][1], outs["pallas"][1]
    np.testing.assert_allclose(float(ip["loss"]), float(ix["loss"]),
                               rtol=1e-6)
    np.testing.assert_allclose(float(ip["grad_norm"]),
                               float(ix["grad_norm"]), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(outs["pallas"][0].params),
                    jax.tree.leaves(outs["xla"][0].params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.slow   # two dense Experiment builds + train compiles (~60 s)
def test_dense_train_step_grads_pallas_matches_xla():
    """E2E DENSE train-step grad parity (the ISSUE 13 pin): with the
    qslice fast path off, the learner unroll runs MultiHeadAttention —
    under pallas mode its custom VJP is now the flash backward, and one
    full QMIX update (agent + mixer, online + target unrolls) must
    reproduce the einsum mode's loss and gradient norm."""
    from t2omca_tpu.run import Experiment
    outs = {}
    for mode in ("xla", "pallas"):
        exp = Experiment.build(_tiny_cfg(
            model={"use_qslice": False},
            kernels=KernelsConfig(attention=mode)))
        ts = exp.init_train_state(0)
        _, batch, _ = exp.runner.run(ts.learner.params["agent"],
                                     ts.runner)
        small = jax.tree.map(lambda x: x[:2], batch)
        _, info = exp.learner.train(ts.learner, small, jnp.ones((2,)),
                                    jnp.asarray(0), jnp.asarray(0))
        outs[mode] = info
    np.testing.assert_allclose(float(outs["pallas"]["loss"]),
                               float(outs["xla"]["loss"]), rtol=1e-6)
    np.testing.assert_allclose(float(outs["pallas"]["grad_norm"]),
                               float(outs["xla"]["grad_norm"]),
                               rtol=1e-4)


@pytest.mark.slow   # full pallas-mode superstep compile (~60 s)
@pytest.mark.analysis
def test_pallas_superstep_compile_budget():
    """The pallas-mode fused superstep compiles exactly ONCE across
    repeated dispatches — the flash kernels (forward-with-residuals +
    the two backward programs, all behind lru-cached custom_vjp builds)
    must not defeat jit caching with fresh callable identities per
    trace."""
    from t2omca_tpu.analysis import compile_budget
    from t2omca_tpu.run import Experiment
    cfg = _tiny_cfg(kernels=KernelsConfig(attention="pallas"),
                    superstep=2)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    superstep = exp.superstep_program(2)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    with compile_budget(1, match="_superstep") as log:
        for i in range(3):
            ts, stats, infos = superstep(ts, keys,
                                         jnp.asarray(i * 16, jnp.int32))
    assert log.count == 1
    assert np.isfinite(
        np.asarray(jax.device_get(stats.episode_return))).all()
