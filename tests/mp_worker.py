"""Worker for the multi-process (multi-host leg) test — NOT a test module.

Launched twice by ``test_multihost.py`` with the standard JAX topology
env vars (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
``JAX_PROCESS_ID``) set, exactly the scheduler contract
``parallel.distributed.maybe_initialize_distributed`` consumes in
production (wired at ``t2omca_tpu/__main__.py``). Each process owns 4
virtual CPU devices; the global mesh spans both processes, so the data
axis crosses the process boundary and every collective in the train step
takes the DCN leg (gloo on CPU; ICI/DCN on a real pod)."""

import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)
# CPU cross-process collectives backend (jaxlib ships gloo); a TPU pod
# uses the ICI/DCN fabric instead, so this stays test-side
jax.config.update("jax_cpu_collectives_implementation", "gloo")


def main() -> int:
    import jax.numpy as jnp

    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    from t2omca_tpu.parallel import (DataParallel, make_mesh,
                                     maybe_initialize_distributed)
    from t2omca_tpu.run import Experiment

    assert maybe_initialize_distributed(), "topology env vars must be set"
    assert jax.device_count() == 8, jax.device_count()
    assert len(jax.local_devices()) == 4

    cfg = sanity_check(TrainConfig(
        batch_size_run=8, batch_size=8,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=16),
    ))
    exp = Experiment.build(cfg)
    mesh = make_mesh(8)
    dp = DataParallel(exp, mesh)
    # every process computes the identical initial state (same seed);
    # shard() places each process's local shards of the global arrays
    ts = dp.shard(exp.init_train_state(0))
    rollout, insert, train_iter = dp.jitted_programs()

    rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                           test_mode=False)
    obs_leaf = jax.tree.leaves(batch.obs)[0]
    assert len(obs_leaf.sharding.device_set) == 8, "episode axis not global"
    ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                    episode=ts.episode + cfg.batch_size_run)
    ts, info = train_iter(ts, jax.random.PRNGKey(1), jnp.asarray(32))
    loss = float(jax.device_get(info["loss"]))
    assert jnp.isfinite(loss)
    leaf = jax.tree.leaves(ts.learner.params)[0]
    assert leaf.sharding.is_fully_replicated, "params must stay replicated"
    # the parent compares this line across both processes: identical loss
    # proves the gradient psum crossed the process boundary coherently
    print(f"LOSS {loss:.10f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
