"""Worker for the multi-process (multi-host leg) test — NOT a test module.

Launched twice by ``test_multihost.py`` with the standard JAX topology
env vars (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
``JAX_PROCESS_ID``) set, exactly the scheduler contract
``parallel.distributed.maybe_initialize_distributed`` consumes in
production (wired at ``t2omca_tpu/__main__.py``). Each process owns 4
virtual CPU devices; the global mesh spans both processes, so the data
axis crosses the process boundary and every collective in the train step
takes the DCN leg (gloo on CPU; ICI/DCN on a real pod).

With ``MP_CKPT_DIR`` set, the worker additionally saves a full-state
checkpoint from the 2-process mesh (the gather-to-process-0 path in
``utils.checkpoint.save_checkpoint``) and prints a deterministic greedy
evaluation fingerprint of the trained model; the parent then restores
the checkpoint model-only in a plain single-process build and asserts
the identical fingerprint (SURVEY.md §5(4) + A8).

With ``MP_CHAOS=1`` additionally set, process 1 SIGKILLs itself after
the collective save and process 0 runs the graftmorph coordinated-
preemption exit path against the dead peer: announce, bounded barrier
(must fail, not hang), degraded per-host shard save, and the
all-shards-or-skip fallback to the newest COMPLETE save
(docs/RESILIENCE.md §6) — then exits 0.

The jax config setup lives under ``__main__`` so the parent test process
can import :func:`worker_config` / :func:`eval_fingerprint` without
mutating its own already-initialized backend.
"""

import os
import sys


def worker_config():
    """The shared tiny config — the parent's single-process restore must
    build the identical model."""
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    return sanity_check(TrainConfig(
        batch_size_run=8, batch_size=8,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=16),
    ))


def eval_fingerprint(exp, agent_params) -> float:
    """Deterministic greedy-eval metric: mean episode return of one
    test-mode rollout from a FIXED runner seed, on the default local
    device (host-local numpy params in, so no mesh/topology leaks into
    the program — both mp_worker processes and the parent's restored
    single-process build must produce the identical float on CPU)."""
    import jax
    import numpy as np

    params = jax.device_get(agent_params)     # host-local, uncommitted
    rs = exp.runner.init_state(jax.random.PRNGKey(7))
    run = jax.jit(exp.runner.run, static_argnames="test_mode")
    _, _, stats = run(params, rs, test_mode=True)
    return float(np.mean(np.asarray(
        jax.device_get(stats.episode_return))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from t2omca_tpu.parallel import (DataParallel, make_mesh,
                                     maybe_initialize_distributed)
    from t2omca_tpu.run import Experiment

    assert maybe_initialize_distributed(), "topology env vars must be set"
    assert jax.device_count() == 8, jax.device_count()
    assert len(jax.local_devices()) == 4

    cfg = worker_config()
    exp = Experiment.build(cfg)
    mesh = make_mesh(8)
    dp = DataParallel(exp, mesh)
    # every process computes the identical initial state (same seed), so
    # each can build its LOCAL shards of the global arrays directly
    # (make_array_from_callback) — zero cross-process traffic. The
    # obvious dp.shard()/device_put route funnels its per-device
    # transfers through the gloo tcp pair concurrently, which races on
    # an oversubscribed CPU box (pre-existing jaxlib flake: gloo
    # EnforceNotMet preamble-size mismatch — observed even for a single
    # scalar leaf). On a real TPU pod dp.shard is ICI/DCN traffic and
    # stays the production path.
    import numpy as np

    def _place(x, s):
        arr = np.asarray(jax.device_get(x))
        return jax.make_array_from_callback(arr.shape, s,
                                            lambda idx: arr[idx])

    init = exp.init_train_state(0)
    ts = jax.tree.map(_place, init, dp.state_shardings(init))
    rollout, insert, train_iter = dp.jitted_programs()

    # block after every program: the driver's async dispatch is the point
    # in production, but on the gloo CPU transport two overlapping
    # executables whose collectives interleave on one tcp pair race the
    # transport (observed flake: gloo EnforceNotMet preamble-size
    # mismatch, a pre-existing jaxlib/gloo issue on oversubscribed CPU) —
    # the worker is a correctness fixture, so serialize for determinism
    rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                           test_mode=False)
    jax.block_until_ready((rs, batch))
    obs_leaf = jax.tree.leaves(batch.obs)[0]
    assert len(obs_leaf.sharding.device_set) == 8, "episode axis not global"
    ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                    episode=ts.episode + cfg.batch_size_run)
    jax.block_until_ready(ts.buffer)
    ts, info = train_iter(ts, jax.random.PRNGKey(1), jnp.asarray(32))
    jax.block_until_ready(ts)
    loss = float(jax.device_get(info["loss"]))
    assert jnp.isfinite(loss)
    leaf = jax.tree.leaves(ts.learner.params)[0]
    assert leaf.sharding.is_fully_replicated, "params must stay replicated"
    # the parent compares this line across both processes: identical loss
    # proves the gradient psum crossed the process boundary coherently
    print(f"LOSS {loss:.10f}", flush=True)

    ckpt_dir = os.environ.get("MP_CKPT_DIR")
    if ckpt_dir:
        from t2omca_tpu.utils.checkpoint import save_checkpoint
        # collective: both processes must call; process 0 writes
        save_checkpoint(ckpt_dir, 32, ts)
        # %.17g round-trips the float64 exactly — the parent asserts
        # bit-equality against its own single-process restore
        print(f"EVAL {eval_fingerprint(exp, ts.learner.params['agent']):.17g}",
              flush=True)

    if ckpt_dir and os.environ.get("MP_CHAOS") == "1":
        # graftmorph chaos acceptance (docs/RESILIENCE.md §6): SIGKILL
        # one of the two gloo hosts, then drive the SURVIVOR through the
        # driver's coordinated-preemption exit path against the corpse.
        import signal
        import time

        from t2omca_tpu.parallel import distributed as dist
        from t2omca_tpu.utils.checkpoint import (find_checkpoint,
                                                 save_checkpoint_shards,
                                                 verify_checkpoint)
        if jax.process_index() == 1:
            # the victim: die the hard way — no atexit, no handler, no
            # goodbye to the coordinator; exactly what a spot-VM reclaim
            # looks like to the surviving host. The parent must NOT
            # assert this process's returncode (-SIGKILL by design).
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(1.0)                 # let the SIGKILL actually land
        t_cut = 48
        dist.announce_shutdown(t_cut)
        # the bounded barrier against a dead peer: must fail INSIDE the
        # timeout instead of hanging (a collective save here would block
        # forever on the gloo transport — that is the whole point of the
        # degrade-to-shards protocol)
        target, ok = dist.negotiate_stop_step(t_cut, timeout_s=3.0)
        assert not ok, "barrier must degrade against a dead peer"
        assert target == t_cut
        # degraded exit: zero collectives — this host's shard only
        save_checkpoint_shards(ckpt_dir, t_cut, ts)
        # all-shards-or-skip gate: shard 0-of-2 alone is NOT valid; the
        # newest RESUMABLE save is the complete collective one at 32
        assert not verify_checkpoint(os.path.join(ckpt_dir, str(t_cut)))
        found = find_checkpoint(ckpt_dir)
        assert found is not None, "completeness gate skipped everything"
        print(f"CKPT {found[1]}", flush=True)
        # skip atexit: jax.distributed.shutdown would wait on the dead
        # peer's never-arriving disconnect. The exit STATUS is the
        # survivor's contract, not its teardown.
        sys.stdout.flush()
        os._exit(0)
    return 0


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:
        # older JAX (0.4.x): same lazy-backend fallback as tests/conftest.py
        # — but REPLACE any inherited count (the parent pytest process
        # exports 8; each of the 2 workers must present 4 local devices)
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=4")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    # CPU cross-process collectives backend (jaxlib ships gloo); a TPU pod
    # uses the ICI/DCN fabric instead, so this stays test-side
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    sys.exit(main())
