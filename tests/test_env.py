"""Unit tests for the pure-functional MultiAgvOffloading environment.

SURVEY.md §4's recommended pyramid, layer 1: collision resolution, reward
branches (each branch of environment_multi_mec.py:229-293 enumerated), queue
pop/age/expire/generate ordering, availability masks, obs/state shapes,
teleport mobility, and vmap independence.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import EnvConfig
from t2omca_tpu.envs import EnvState, MultiAgvOffloadingEnv
from t2omca_tpu.envs.normalization import NormState


def make_env(**kw) -> MultiAgvOffloadingEnv:
    defaults = dict(agv_num=4, mec_num=2, num_channels=2, episode_limit=10,
                    obs_entity_mode=True, state_entity_mode=True)
    defaults.update(kw)
    return MultiAgvOffloadingEnv(EnvConfig(**defaults))


def manual_state(env, mec_index, jobs, deadlines=None, pos=None) -> EnvState:
    """Build a deterministic EnvState. jobs: list of per-agent lists of
    (data_size, deadline)."""
    a, j = env.n_agents, env.max_jobs
    data = np.zeros((a, j), np.float32)
    dl = np.zeros((a, j), np.float32)
    valid = np.zeros((a, j), bool)
    for i, joblist in enumerate(jobs):
        for s, (d, t) in enumerate(joblist):
            data[i, s], dl[i, s], valid[i, s] = d, t, True
    if pos is None:
        pos = np.asarray(env.mec_positions())[np.asarray(mec_index)]
    return EnvState(
        time_slot=jnp.zeros((), jnp.int32),
        mec_index=jnp.asarray(mec_index, jnp.int32),
        pos=jnp.asarray(pos, jnp.float32),
        job_data=jnp.asarray(data), job_deadline=jnp.asarray(dl),
        job_valid=jnp.asarray(valid),
        last_ack=jnp.zeros((a,), jnp.int32),
        last_action=jnp.zeros((a,), jnp.int32),
        task_num=jnp.asarray([len(x) for x in jobs], jnp.int32),
        task_success=jnp.zeros((a,), jnp.int32),
        remain_delay=jnp.zeros((a,), jnp.float32),
        norm=NormState.create(env.obs_dim),
    )


KEY = jax.random.PRNGKey(0)


# --------------------------------------------------------------- collisions

def test_collision_same_channel_same_mec():
    env = make_env()
    st = manual_state(env, [0, 0, 1, 1],
                      [[(8000, 100)]] * 4)
    # agents 0,1 under MEC0 pick channel 1 -> collide; 2,3 under MEC1 pick
    # channels 1,2 -> both succeed (Q14: channel reuse across MECs)
    *_, = out = env.step(st, jnp.asarray([1, 1, 1, 2]), KEY)
    st2 = out[0]
    np.testing.assert_array_equal(np.asarray(st2.last_ack), [-1, -1, 1, 1])
    info = out[3]
    assert float(info.conflict_ratio) == 0.5


def test_action0_never_collides():
    env = make_env()
    st = manual_state(env, [0, 0, 0, 0], [[(8000, 100)]] * 4)
    out = env.step(st, jnp.asarray([0, 0, 0, 0]), KEY)
    np.testing.assert_array_equal(np.asarray(out[0].last_ack), [0, 0, 0, 0])
    assert float(out[3].conflict_ratio) == 0.0


def test_channel_utilization_counts_action0_slot():
    """Reference quirk: utilization sums all C+1 slots of the masked per-MEC
    bincount, including the action-0 slot (environment_multi_mec.py:319-329)."""
    env = make_env()
    st = manual_state(env, [0, 0, 1, 1], [[(8000, 100)]] * 4)
    # MEC0: one local (count[0]=1), one on ch1; MEC1: two locals (count 2 -> 0)
    out = env.step(st, jnp.asarray([0, 1, 0, 0]), KEY)
    # masked counts: MEC0 [1,1,0], MEC1 [0,0,0] -> sum=2; /(C=2 * M=2) = 0.5
    assert float(out[3].channel_utilization_rate) == pytest.approx(0.5)


# --------------------------------------------------------------- reward branches

def expected_local_delay(env, data):
    return round(env.computation_cycles * data / env.cfg.user_compute_cap * 1000, 2)


def test_reward_local_success_branch():
    env = make_env()
    data = 8000.0
    ld = expected_local_delay(env, data)      # 50.0 ms at 5 GHz
    st = manual_state(env, [0, 0, 1, 1],
                      [[(data, 100.0)], [], [], []])
    out = env.step(st, jnp.asarray([0, 0, 0, 0]), KEY)
    st2, reward, _, info = out[0], out[1], out[2], out[3]
    # deadline 100 - 50 > 0: success, no reward contribution
    assert float(reward) == 0.0
    assert int(st2.task_success[0]) == 1
    # remain_delay += latency_max - deadline + local_delay = 100-100+50
    assert float(st2.remain_delay[0]) == pytest.approx(ld)


def test_reward_local_miss_branch():
    env = make_env()
    st = manual_state(env, [0, 0, 1, 1], [[(8000.0, 40.0)], [], [], []])
    out = env.step(st, jnp.asarray([0, 0, 0, 0]), KEY)
    # local delay 50 > deadline 40 -> overtime penalty latency_max
    assert float(out[1]) == -100.0
    assert float(out[3].overtime_penalty) == 100.0
    assert int(out[0].task_success[0]) == 0


def test_reward_collision_branches():
    env = make_env()
    # two colliding agents under MEC0: one job expiring (deadline<=5), one not
    st = manual_state(env, [0, 0, 1, 1],
                      [[(8000.0, 5.0)], [(8000.0, 50.0)], [], []])
    out = env.step(st, jnp.asarray([1, 1, 0, 0]), KEY)
    np.testing.assert_array_equal(np.asarray(out[0].last_ack)[:2], [-1, -1])
    # only the expiring job is penalized (environment_multi_mec.py:257-259)
    assert float(out[1]) == -100.0


def test_reward_offload_success_branch():
    env = make_env()
    data = 8000.0
    st = manual_state(env, [0, 0, 1, 1], [[(data, 100.0)], [], [], []])
    out = env.step(st, jnp.asarray([1, 0, 0, 0]), KEY)
    st2, reward = out[0], out[1]
    ld = expected_local_delay(env, data)
    p = env.default_params()
    od = float(env._offload_delay(
        jnp.asarray([data]), st.pos[:1], st.mec_index[:1],
        p.replace(tx_scale=p.tx_scale[:1],
                  compute_scale=p.compute_scale[:1]))[0])
    assert od < ld, "offloading should beat local compute in the spec regime"
    assert float(reward) == pytest.approx(ld - od, abs=1e-3)
    assert int(st2.task_success[0]) == 1
    assert float(st2.remain_delay[0]) == pytest.approx(od, abs=1e-3)


def test_reward_empty_buffer_skipped():
    env = make_env()
    st = manual_state(env, [0, 1, 0, 1], [[], [], [], []])
    out = env.step(st, jnp.asarray([0, 0, 0, 0]), KEY)
    assert float(out[1]) == 0.0
    assert float(out[3].overtime_penalty) == 0.0


# --------------------------------------------------------------- queue dynamics

def test_queue_pop_age_expire_order():
    env = make_env(job_prob=0.0)  # disable generation to isolate dynamics
    # agent 0: head job + second job with deadline 5 (will expire after aging)
    st = manual_state(env, [0, 0, 1, 1],
                      [[(8000.0, 100.0), (6000.0, 5.0)], [], [], []])
    out = env.step(st, jnp.asarray([0, 0, 0, 0]), KEY)
    st2 = out[0]
    # head popped (ack=0), second aged 5->0 then expired -> queue empty
    assert not bool(st2.job_valid[0, 0])


def test_queue_no_pop_on_collision():
    env = MultiAgvOffloadingEnv(EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                                          episode_limit=10, job_prob=0.0))
    st = manual_state(env, [0, 0, 1, 1],
                      [[(8000.0, 100.0)], [(6000.0, 100.0)], [], []])
    out = env.step(st, jnp.asarray([1, 1, 0, 0]), KEY)     # collide
    st2 = out[0]
    # job kept, aged by 5
    assert bool(st2.job_valid[0, 0])
    assert float(st2.job_deadline[0, 0]) == 95.0
    assert float(st2.job_data[0, 0]) == 8000.0


def test_queue_fifo_preserved_after_expiry_compaction():
    env = MultiAgvOffloadingEnv(EnvConfig(agv_num=1, mec_num=1, num_channels=2,
                                          episode_limit=10, job_prob=0.0))
    # head expires (collide so no pop), later jobs survive in order
    st = manual_state(env, [0], [[(1000.0, 5.0), (2000.0, 50.0),
                                  (3000.0, 80.0)]])
    out = env.step(st, jnp.asarray([1]), KEY)              # lone agent: ack=1!
    # ack=1 pops head; remaining [2000@45, 3000@75]
    st2 = out[0]
    np.testing.assert_allclose(np.asarray(st2.job_data[0, :2]), [2000, 3000])
    np.testing.assert_allclose(np.asarray(st2.job_deadline[0, :2]), [45, 75])
    assert not bool(st2.job_valid[0, 2])


def test_generation_appends_at_tail_and_counts():
    env = MultiAgvOffloadingEnv(EnvConfig(agv_num=2, mec_num=1, num_channels=2,
                                          episode_limit=10, job_prob=1.0))
    st = manual_state(env, [0, 0], [[(8000.0, 100.0)], []])
    out = env.step(st, jnp.asarray([0, 0]), KEY)
    st2 = out[0]
    # agent0: head popped, new job appended -> exactly 1 valid, deadline 100
    assert int(st2.job_valid[0].sum()) == 1
    assert float(st2.job_deadline[0, 0]) == 100.0
    assert int(st2.task_num[0]) == 2       # initial + generated
    assert int(st2.task_num[1]) == 1


# --------------------------------------------------------------- avail actions

def test_avail_actions_modes():
    env = make_env()
    st = manual_state(env, [0, 0, 1, 1], [[(8000.0, 100.0)], [], [], []])
    avail = np.asarray(env.get_avail_actions(st))
    np.testing.assert_array_equal(avail[0], [1, 1, 1])     # job: all legal
    np.testing.assert_array_equal(avail[1], [1, 0, 0])     # empty: idle only

    env_eo = MultiAgvOffloadingEnv(dataclasses.replace(env.cfg, edge_only=True))
    avail = np.asarray(env_eo.get_avail_actions(st))
    np.testing.assert_array_equal(avail[0], [0, 1, 1])     # local forbidden
    np.testing.assert_array_equal(avail[1], [1, 0, 0])


# --------------------------------------------------------------- obs/state

def test_obs_entity_structure():
    env = make_env()
    st = manual_state(env, [0, 1, 0, 1], [[(8000.0, 100.0)]] * 4)
    raw = np.asarray(env._raw_obs(st, env.default_params()))
    assert raw.shape == (4, 4 * 9)
    rows = raw.reshape(4, 4, 9)
    # observer 0 (MEC0) sees agents 0,2 (same MEC); rows for 1,3 are zeros
    assert rows[0, 1].sum() == 0 and rows[0, 3].sum() == 0
    assert rows[0, 2].sum() != 0
    # is_self flag only on own row
    assert rows[0, 0, 8] == 1 and rows[0, 2, 8] == 0
    # ack onehot for ack=0 is [0,1,0]
    np.testing.assert_array_equal(rows[0, 0, :3], [0, 1, 0])


def test_state_layout_and_shapes():
    env = make_env()
    st = manual_state(env, [0, 1, 0, 1], [[(8000.0, 100.0)]] * 4)
    gs = np.asarray(env.get_state(st))
    assert gs.shape == (env.state_dim,) == (4 * 8,)
    # first 12 entries = 4 agents' ack one-hots
    np.testing.assert_array_equal(gs[:12].reshape(4, 3),
                                  [[0, 1, 0]] * 4)
    info = env.get_env_info()
    assert info["obs_shape"] == 36 and info["state_shape"] == 32
    assert info["obs_entity_feats"] == 9 and info["state_entity_feats"] == 8
    assert info["n_actions"] == 3 and info["n_agents"] == 4


# --------------------------------------------------------------- episode / reset

def test_terminates_exactly_at_episode_limit():
    env = MultiAgvOffloadingEnv(EnvConfig(agv_num=2, mec_num=1, num_channels=2,
                                          episode_limit=3))
    st, *_ = env.reset(KEY)
    key = KEY
    for t in range(3):
        key, k = jax.random.split(key)
        st, _, term, info, *_ = env.step(st, jnp.zeros(2, jnp.int32), k)
        assert bool(term) == (t == 2)
    assert bool(info.episode_limit)
    assert 0.0 <= float(info.task_completion_rate) <= 1.0


def test_reset_reseeds_and_clears():
    env = make_env()
    st, obs, gs, avail = env.reset(KEY)
    assert obs.shape == (4, env.obs_dim)
    assert gs.shape == (env.state_dim,)
    assert avail.shape == (4, env.n_actions)
    assert int(st.task_success.sum()) == 0
    # positions inside serving MEC circle
    d = np.linalg.norm(np.asarray(st.pos)
                       - np.asarray(env.mec_positions())[np.asarray(st.mec_index)],
                       axis=1)
    assert (d <= env.cfg.communication_range_m + 1e-5).all()


def test_teleport_mobility_every_slot():
    env = make_env()
    st, *_ = env.reset(KEY)
    out = env.step(st, jnp.zeros(4, jnp.int32), jax.random.PRNGKey(7))
    assert not np.allclose(np.asarray(st.pos), np.asarray(out[0].pos))


# --------------------------------------------------------------- vmap behavior

def test_vmap_lanes_are_independent():
    env = make_env()
    keys = jax.random.split(KEY, 3)
    st, obs, gs, avail = jax.vmap(env.reset)(keys)
    assert st.pos.shape == (3, 4, 2)
    # different lanes, different worlds (Q8 seed-offset equivalent)
    assert not np.allclose(np.asarray(st.pos[0]), np.asarray(st.pos[1]))

    step_keys = jax.random.split(jax.random.PRNGKey(9), 3)
    actions = jnp.zeros((3, 4), jnp.int32)
    st2, reward, term, info, obs2, gs2, avail2 = jax.vmap(env.step)(
        st, actions, step_keys)
    assert reward.shape == (3,)
    # normalizer stats diverge per lane (carried in state, not shared)
    assert not np.allclose(np.asarray(st2.norm.mean[0]),
                           np.asarray(st2.norm.mean[1]))


def test_step_is_jittable_and_deterministic():
    env = make_env()
    st, *_ = env.reset(KEY)
    step = jax.jit(env.step)
    a = jnp.zeros(4, jnp.int32)
    out1 = step(st, a, jax.random.PRNGKey(3))
    out2 = step(st, a, jax.random.PRNGKey(3))
    np.testing.assert_allclose(np.asarray(out1[1]), np.asarray(out2[1]))
    np.testing.assert_allclose(np.asarray(out1[0].pos), np.asarray(out2[0].pos))


def test_fast_norm_env_equivalence():
    """fast_norm changes only get_obs: running statistics stay in lockstep
    with the sequential reference path along a shared trajectory, and the
    normalized observations converge (O(A/n) transient)."""
    env_seq = make_env(fast_norm=False)   # sequential reference path
    env_fast = make_env(fast_norm=True)
    st, obs_seq, *_ = env_seq.reset(KEY)
    fast_norm = env_fast.get_obs(st.replace(norm=NormState.create(
        env_fast.obs_dim)))[0].norm
    key = jax.random.PRNGKey(11)
    devs = []
    for t in range(40):
        key, ka, ks = jax.random.split(key, 3)
        actions = jax.random.randint(ka, (env_seq.n_agents,), 0,
                                     env_seq.n_actions)
        avail = env_seq.get_avail_actions(st)
        actions = jnp.where(avail[jnp.arange(4), actions] > 0, actions, 0)
        st, _, _, _, obs_seq, _, _ = env_seq.step(st, actions, ks)
        # same post-step state, fast normalizer carried independently
        fst, obs_fast = env_fast.get_obs(st.replace(norm=fast_norm))
        fast_norm = fst.norm
        devs.append(float(jnp.abs(obs_fast - obs_seq).max()))
    assert int(fast_norm.n) == int(st.norm.n)
    np.testing.assert_allclose(np.asarray(fast_norm.mean),
                               np.asarray(st.norm.mean), rtol=1e-3, atol=1e-3)
    # the two paths' outputs converge after warm-up
    assert np.mean(devs[-10:]) < np.mean(devs[:10])
    assert devs[-1] < 0.15, devs[-5:]


def test_state_last_action_flag():
    """state_last_action prepends per-agent action one-hots to the global
    state (reference declares the flag at :11, concat slot at :196)."""
    env = make_env(state_last_action=True)
    base = make_env()
    assert env.state_dim == base.state_dim + 4 * env.n_actions
    assert env.state_entity_feats == base.state_entity_feats + env.n_actions

    st, *_ = env.reset(KEY)
    actions = jnp.asarray([0, 1, 2, 0])
    avail = env.get_avail_actions(st)
    actions = jnp.where(avail[jnp.arange(4), actions] > 0, actions, 0)
    st2, _, _, _, _, gstate, _ = env.step(st, actions, jax.random.PRNGKey(1))
    la = np.asarray(gstate[:4 * env.n_actions]).reshape(4, env.n_actions)
    np.testing.assert_allclose(la, np.eye(env.n_actions)[np.asarray(actions)])


def test_fuzz_invariants_over_random_trajectories():
    """Structural invariants under 3 seeds x 60 random (legal) steps:
    whatever the action sequence, the state must stay well-formed —
    counters monotone and ordered, queue entries consistent, positions
    finite and inside the deployment disc, normalizer stats sane. Guards
    the queue pop->age->expire->generate pipeline against edge-case
    regressions no enumerated test covers."""
    env = make_env(episode_limit=60)
    a = env.n_agents
    r_max = 2.0 * env.cfg.mec_radius_m * max(env.cfg.mec_num, 1)
    for seed in range(3):
        key = jax.random.PRNGKey(100 + seed)
        st, *_ = env.reset(key)
        prev_task_num = np.zeros(a, np.int64)
        for t in range(60):
            key, ka, ks = jax.random.split(key, 3)
            avail = env.get_avail_actions(st)
            actions = jax.random.randint(ka, (a,), 0, env.n_actions)
            actions = jnp.where(avail[jnp.arange(a), actions] > 0,
                                actions, 0)
            st, reward, term, info, obs, gstate, _ = env.step(
                st, actions, ks)

            assert int(st.time_slot) == t + 1
            # counters: generated grows monotonically, successes bounded
            tn = np.asarray(st.task_num, np.int64)
            assert (tn >= prev_task_num).all()
            prev_task_num = tn
            assert (np.asarray(st.task_success) <= tn).all()
            # queue slots: invalid entries must be zeroed; valid entries
            # positive-sized with non-negative remaining deadline
            valid = np.asarray(st.job_valid)
            data = np.asarray(st.job_data)
            dl = np.asarray(st.job_deadline)
            assert (data[~valid] == 0).all() and (dl[~valid] == 0).all()
            assert (data[valid] > 0).all()
            assert (dl[valid] >= 0).all()
            # geometry: finite positions within the deployment extent
            pos = np.asarray(st.pos)
            assert np.isfinite(pos).all() and (np.abs(pos) <= r_max).all()
            # serving MEC ids in range; ack flags in the contract set
            mi = np.asarray(st.mec_index)
            assert ((mi >= 0) & (mi < env.cfg.mec_num)).all()
            assert np.isin(np.asarray(st.last_ack), [-1, 0, 1]).all()
            # normalizer: counters advance, stats finite, std >= 0
            assert np.isfinite(np.asarray(st.norm.mean)).all()
            assert (np.asarray(st.norm.std) >= 0).all()
            # outputs finite
            assert np.isfinite(float(reward))
            assert np.isfinite(np.asarray(obs)).all()
            assert np.isfinite(np.asarray(gstate)).all()
