"""graftsight: in-graph learning-dynamics telemetry + RL-health
detectors (t2omca_tpu/obs/sight.py, docs/OBSERVABILITY.md §6).

Fast: config surface, histogram/entropy math, module grouping, the
learner's sight keys + bit-parity with sight off, train_info_zeros
aval mirror, Logger vector degrade, SightMonitor detector units, the
jax-free learning CLI (+ torn-tail regression), programs.json twins.

Slow: the K>1 classic driver path and the sebulba lockstep path with
vector-valued train_info keys end-to-end, the injected-pathology
acceptance (detector trips within one log cadence → /healthz 503 +
flight mark + post-mortem CLI verdict), the zero-extra-transfer /
one-compile pins, and the sight-off fingerprint pin.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from t2omca_tpu.config import (EnvConfig, ModelConfig, ObsConfig,
                               ReplayConfig, ResilienceConfig, SightConfig,
                               TrainConfig, from_dict, load_config,
                               sanity_check)
from t2omca_tpu.obs import sight
from t2omca_tpu.obs.spans import KNOWN_PHASES, SpanRecorder
from t2omca_tpu.utils.logging import Logger

pytestmark = pytest.mark.sight

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_RUN = os.path.join(REPO, "tests", "fixtures_sight_run")


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_sight_config_default_off_and_roundtrip():
    cfg = TrainConfig()
    assert cfg.obs.sight.enabled is False
    cfg2 = from_dict({"obs": {"sight": {"enabled": True, "bins": 8}}})
    assert cfg2.obs.sight.enabled and cfg2.obs.sight.bins == 8
    # dotted CLI override routes through the nested block
    cfg3 = load_config(overrides=("obs.sight.enabled=true",
                                  "obs.sight.q_div=100.0"))
    assert cfg3.obs.sight.enabled and cfg3.obs.sight.q_div == 100.0
    # asdict → from_dict is the serve meta.json round trip
    import dataclasses
    cfg4 = from_dict(dataclasses.asdict(cfg2))
    assert cfg4.obs.sight == cfg2.obs.sight


def test_sight_config_sanity_rejects():
    def bad(**kw):
        return TrainConfig(obs=ObsConfig(sight=SightConfig(**kw)))
    with pytest.raises(ValueError, match="bins"):
        sanity_check(bad(bins=2))
    with pytest.raises(ValueError, match="window"):
        sanity_check(bad(window=1))
    with pytest.raises(ValueError, match="ess_min"):
        sanity_check(bad(ess_min=2.0))
    with pytest.raises(ValueError, match="td_range"):
        sanity_check(bad(td_range=0.0))
    with pytest.raises(ValueError, match="q_div"):
        sanity_check(bad(q_div=0.0))
    # valid block passes
    sanity_check(bad(enabled=True))


def test_module_group_names_static():
    assert sight.module_group_names(TrainConfig()) == ("agent_tf",
                                                      "embed", "mixer")
    assert sight.module_group_names(
        TrainConfig(agent="rnn", mixer="vdn")) == ("embed",)
    assert sight.module_group_names(
        TrainConfig(mixer="vdn")) == ("agent_tf", "embed")


# ---------------------------------------------------------------------------
# in-graph math units
# ---------------------------------------------------------------------------

def test_masked_histogram_matches_numpy_and_clips():
    x = jnp.asarray([-100.0, -0.5, 0.1, 0.4, 0.9, 100.0])
    m = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0, 1.0])
    h = np.asarray(sight.masked_histogram(x, m, -1.0, 1.0, 4))
    # masked value (0.9) excluded; ±100 clip into the edge bins;
    # edges [-1,-.5,0,.5,1]: -0.5 sits at the bin-1 left edge
    assert h.sum() == pytest.approx(1.0)
    assert h[0] == pytest.approx(1 / 5)      # -100 (clipped)
    assert h[1] == pytest.approx(1 / 5)      # -0.5
    assert h[2] == pytest.approx(2 / 5)      # 0.1 and 0.4
    assert h[3] == pytest.approx(1 / 5)      # +100 (clipped)


def test_buffer_sight_info_host_entropy_extremes():
    uniform = sight.buffer_sight_info_host(np.ones(64, np.float32), 64)
    assert float(uniform["sight_priority_entropy_norm"]) \
        == pytest.approx(1.0, abs=1e-5)
    delta = np.zeros(64, np.float32)
    delta[3] = 1.0
    collapsed = sight.buffer_sight_info_host(delta, 64)
    assert float(collapsed["sight_priority_entropy_norm"]) \
        == pytest.approx(0.0, abs=1e-5)
    empty = sight.buffer_sight_info_host(np.zeros(8, np.float32), 0)
    assert float(empty["sight_priority_entropy"]) == 0.0


def test_buffer_sight_info_device_matches_host():
    pri = np.asarray([0.5, 0.25, 0.125, 0.125, 7.0, 9.0], np.float32)
    dev = jax.device_get(sight.buffer_sight_info(
        jnp.asarray(pri), jnp.asarray(4)))
    host = sight.buffer_sight_info_host(pri, 4)
    assert float(dev["sight_priority_entropy"]) == pytest.approx(
        float(host["sight_priority_entropy"]), rel=1e-5)
    assert float(dev["sight_priority_entropy_norm"]) == pytest.approx(
        float(host["sight_priority_entropy_norm"]), rel=1e-5)


# ---------------------------------------------------------------------------
# learner integration (tiny Experiment, one train step)
# ---------------------------------------------------------------------------

def _tiny_cfg(**kw):
    obs_kw = kw.pop("obs_kw", {})
    defaults = dict(
        batch_size_run=2, batch_size=4, save_model=False,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        obs=ObsConfig(sight=SightConfig(enabled=True, bins=8,
                                        **obs_kw.pop("sight_kw", {})),
                      **obs_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _one_train(cfg):
    """Fill the tiny ring and run ONE train_iter; returns (ts2, info)."""
    from t2omca_tpu.run import Experiment
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    for _ in range(2):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
    return exp, ts, train_iter(ts, jax.random.PRNGKey(7),
                               jnp.asarray(100))


def test_sight_keys_present_and_training_bit_identical():
    """The tentpole parity contract: sight ON adds the diagnostic keys
    but leaves the trained params (and the base info keys) BIT-identical
    to sight OFF — the diagnostics are read-only passengers."""
    cfg_on = _tiny_cfg()
    cfg_off = cfg_on.replace(obs=ObsConfig())
    _, _, (ts_on, info_on) = _one_train(cfg_on)
    _, _, (ts_off, info_off) = _one_train(cfg_off)

    sight_keys = {k for k in info_on if k.startswith("sight_")}
    assert {"sight_grad_norm_agent_tf", "sight_grad_norm_embed",
            "sight_grad_norm_mixer", "sight_update_norm_mixer",
            "sight_per_ess", "sight_target_drift", "sight_td_hist",
            "sight_q_taken_hist", "sight_target_hist",
            "sight_attn_entropy_agent", "sight_attn_entropy_mixer",
            "sight_priority_entropy", "sight_priority_entropy_norm"
            } <= sight_keys
    assert not any(k.startswith("sight_") for k in info_off)

    info_on = jax.device_get(info_on)
    assert info_on["sight_td_hist"].shape == (8,)
    assert info_on["sight_td_hist"].sum() == pytest.approx(1.0, abs=1e-5)
    assert info_on["sight_attn_entropy_agent"].shape == (1,)
    assert 0.0 <= float(info_on["sight_attn_entropy_agent"][0]) <= 1.0 + 1e-5
    assert 0.0 < float(info_on["sight_per_ess"]) <= 1.0 + 1e-5
    assert np.isfinite(info_on["sight_target_drift"])

    # params bit-identical; base info keys bit-identical
    for a, b in zip(jax.tree.leaves(ts_on.learner.params),
                    jax.tree.leaves(ts_off.learner.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    off = jax.device_get(info_off)
    for k in off:
        np.testing.assert_array_equal(np.asarray(info_on[k]),
                                      np.asarray(off[k]))


def test_train_info_zeros_mirrors_sight_avals():
    """The superstep lax.cond requires both branches to return one
    pytree: train_info_zeros must mirror train's sight keys aval-exact
    (shape, dtype, weak-type via the astype strip)."""
    cfg = _tiny_cfg()
    exp, ts, (_, info) = _one_train(cfg)
    zeros = exp.learner.train_info_zeros(cfg.batch_size)
    # the priority-entropy keys are appended by the driver programs in
    # BOTH cond branches (run.py _sight_buf), not by the learner —
    # everything else must mirror exactly
    assert set(zeros) == {k for k in info
                          if not k.startswith("sight_priority_entropy")}
    assert "sight_priority_entropy" not in zeros
    for k in zeros:
        za, ia = (np.asarray(jax.device_get(zeros[k])),
                  np.asarray(jax.device_get(info[k])))
        assert za.shape == ia.shape and za.dtype == ia.dtype, k


def test_attention_entropy_uniform_when_logits_zero():
    """Zeroed query projections ⇒ all attention logits 0 ⇒ uniform
    distribution ⇒ normalized entropy exactly 1 — pins the probe's
    normalization AND its layer plumbing."""
    cfg = _tiny_cfg()
    from t2omca_tpu.run import Experiment
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    params = ts.learner.params["agent"]
    zeroed = jax.tree_util.tree_map_with_path(
        lambda path, x: (jnp.zeros_like(x)
                         if any(getattr(p, "key", None) == "toqueries"
                                for p in path) else x), params)
    b, a = 2, cfg.env_args.agv_num
    obs_t0 = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, a, exp.learner.obs_dim)),
        jnp.float32)
    ents = jax.device_get(sight.agent_attention_entropy(
        exp.learner, zeroed, obs_t0, None))
    assert ents.shape == (cfg.model.depth,)
    assert float(ents[0]) == pytest.approx(1.0, abs=1e-4)


# ---------------------------------------------------------------------------
# Logger vector degrade (satellite: non-scalar stats)
# ---------------------------------------------------------------------------

def test_logger_vector_stat_degrades_to_summary(tmp_path):
    logger = Logger()
    logger.setup_json(str(tmp_path))
    hist = np.asarray([0.0, 0.25, 0.5, 0.25], np.float32)
    logger.log_stat("sight_td_hist", hist, 100)     # must not raise
    logger.log_stat("loss", 1.5, 100)
    logger.print_recent_stats()                     # console path survives
    logger.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    by_key = {l["key"]: l["value"] for l in lines}
    # metrics.jsonl keeps FULL fidelity; the in-memory history (console
    # path) holds the scalar summary (the mean)
    assert by_key["sight_td_hist"] == pytest.approx(list(map(float, hist)))
    assert by_key["loss"] == 1.5
    assert logger.stats["sight_td_hist"][-1][1] == pytest.approx(
        float(hist.mean()))


def test_logger_scalar_path_unchanged(tmp_path):
    logger = Logger()
    logger.setup_json(str(tmp_path))
    logger.log_stat("x", 2, 1)
    logger.log_stat("x", jnp.asarray(3.0), 2)       # 0-d array stays scalar
    logger.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    assert [l["value"] for l in lines] == [2.0, 3.0]


# ---------------------------------------------------------------------------
# SightMonitor detector units
# ---------------------------------------------------------------------------

def _mk_monitor(tmp_path=None, rec=None, **kw):
    cfg = SightConfig(enabled=True, window=3, **kw)
    logger = Logger()
    if tmp_path is not None:
        logger.setup_json(str(tmp_path))
    return sight.SightMonitor(cfg, logger=logger, rec=rec), logger


def _healthy(t):
    return {
        "loss": 10.0 / (t + 1), "q_taken_mean": -5.0, "target_mean": -5.2,
        "grad_norm": 1.0,
        "sight_grad_norm_agent_tf": 0.5, "sight_grad_norm_embed": 0.2,
        "sight_grad_norm_mixer": 0.4, "sight_per_ess": 0.8,
        "sight_priority_entropy_norm": 0.9,
        "sight_attn_entropy_agent": np.asarray([0.7]),
        "sight_attn_entropy_mixer": np.asarray([0.5]),
    }


def test_monitor_healthy_stream_stays_green():
    mon, _ = _mk_monitor()
    for i in range(5):
        assert mon.observe(_healthy(i), i * 100) == []
    assert all(v["ok"] for v in mon.status.values())
    assert mon.trips_total == 0


def test_monitor_priority_collapse_trips_on_one_observation(tmp_path):
    rec = SpanRecorder(ring_size=16)
    mon, logger = _mk_monitor(tmp_path, rec=rec)
    bad = dict(_healthy(0), sight_priority_entropy_norm=0.01)
    trips = mon.observe(bad, 500)
    assert trips == ["priority_collapse"]
    assert not mon.status["priority_collapse"]["ok"]
    assert "entropy" in mon.status["priority_collapse"]["detail"]
    # recovery transitions back and logs 0 (no duplicate trip)
    assert mon.observe(_healthy(1), 600) == []
    assert mon.status["priority_collapse"]["ok"]
    assert mon.trips_total == 1
    # alert logged (trip AND clear transitions) + flight mark emitted
    logger.close()
    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    assert {"key": "sight_alert_priority_collapse", "value": 1.0,
            "t": 500} in lines
    assert {"key": "sight_alert_priority_collapse", "value": 0.0,
            "t": 600} in lines
    marks = [e for e in rec.tail() if e.get("event") == "mark"]
    assert any(m.get("kind") == "sight"
               and m.get("detector") == "priority_collapse" for m in marks)


def test_monitor_q_divergence_and_ess():
    mon, _ = _mk_monitor(q_div=100.0)
    assert mon.observe(dict(_healthy(0), q_taken_mean=5e3), 1) \
        == ["q_divergence"]
    mon2, _ = _mk_monitor(ess_min=0.5)
    assert mon2.observe(dict(_healthy(0), sight_per_ess=0.1), 1) \
        == ["priority_collapse"]
    assert "ESS" in mon2.status["priority_collapse"]["detail"]


def test_monitor_attention_collapse_names_layer():
    mon, _ = _mk_monitor(attn_entropy_min=0.2)
    bad = dict(_healthy(0),
               sight_attn_entropy_mixer=np.asarray([0.6, 0.01]))
    assert mon.observe(bad, 1) == ["attention_collapse"]
    assert "mixer layer 1" in mon.status["attention_collapse"]["detail"]


def test_monitor_windowed_plateau_and_starvation():
    mon, _ = _mk_monitor(plateau_rel=0.05, grad_starvation=1e-3)
    flat = dict(_healthy(0), loss=1.0, sight_grad_norm_embed=1e-7)
    # needs a FULL window (3): no trip on the first two observations
    assert mon.observe(dict(flat), 1) == []
    assert mon.observe(dict(flat), 2) == []
    trips = mon.observe(dict(flat), 3)
    assert set(trips) == {"loss_plateau", "grad_starvation"}
    assert "embed" in mon.status["grad_starvation"]["detail"]


def test_monitor_total_gradient_death_trips_starvation():
    """Complete gradient death (every module's norm exactly 0) must
    trip grad_starvation after a full window — the strictly-worse case
    must not read as 'warming up' forever (review-pass fix)."""
    mon, _ = _mk_monitor(grad_starvation=1e-3)
    dead = dict(_healthy(0), sight_grad_norm_agent_tf=0.0,
                sight_grad_norm_embed=0.0, sight_grad_norm_mixer=0.0)
    assert mon.observe(dict(dead), 1) == []
    assert mon.observe(dict(dead), 2) == []
    assert "grad_starvation" in mon.observe(dict(dead), 3)


def test_spark_survives_poisoned_cells():
    """The post-mortem renderer must survive (and show) NaN/Inf cells —
    the Logger keeps poisoned bins at full fidelity on purpose, and
    pathological runs are exactly the CLI's use case (review-pass
    fix)."""
    assert sight._spark([0.1, float("nan"), 0.5]) == ".!@"
    assert "!" in sight._spark([float("inf"), 1.0])
    assert sight._spark([float("nan")]) == "-"
    assert sight._spark([]) == "-"
    # a NaN loss mid-series must not kill the health-table trend either
    lines = sight.render_learning(
        "x", {"loss": [(0, 1.0), (1, float("nan")), (2, 0.5)],
              "sight_td_hist": [(2, [0.5, float("nan"), 0.5])]})
    assert any("loss" in l for l in lines)


def test_monitor_healthz_wiring_flips_endpoint():
    from t2omca_tpu.obs.pulse import MetricsHub
    hub = MetricsHub()
    mon, _ = _mk_monitor()
    mon.wire_pulse(hub)
    ok, payload = hub.healthz()
    assert ok and all(c["ok"] for c in payload["checks"].values())
    mon.observe(dict(_healthy(0), sight_priority_entropy_norm=0.0), 10)
    ok, payload = hub.healthz()
    assert not ok
    assert payload["status"] == "degraded"
    assert not payload["checks"]["sight-priority_collapse"]["ok"]
    # report() carries the verdicts for the stall-diagnosis extra
    rep = mon.report()
    assert rep["trips_total"] == 1
    assert not rep["detectors"]["priority_collapse"]["ok"]


# ---------------------------------------------------------------------------
# learning CLI (jax-free; tolerant reader)
# ---------------------------------------------------------------------------

def test_learning_cli_renders_fixture_and_is_jax_free():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; from t2omca_tpu.obs.__main__ import main; "
         f"rc = main(['learning', {FIXTURE_RUN!r}]); "
         "assert 'jax' not in sys.modules, 'learning CLI imports jax'; "
         "sys.exit(rc)"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    assert "learning health" in out
    assert "TRIPPED" in out                  # the seeded alert renders
    assert "hetfleet" in out                 # per-slice learning curves
    assert "verdict:" in out
    assert "PER priority entropy" in out


def test_learning_cli_torn_tail_regression(tmp_path):
    """A killed run's torn final metrics line must warn + render, never
    crash (the PR 12 torn-tail contract, extended to the learning CLI)."""
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    shutil.copy(os.path.join(FIXTURE_RUN, "metrics.jsonl"),
                run_dir / "metrics.jsonl")
    with open(run_dir / "metrics.jsonl", "a") as f:
        f.write('{"key": "loss", "value": 0.1')     # torn mid-write
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.obs", "learning",
         str(run_dir)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "torn final line" in proc.stderr
    assert "learning health" in proc.stdout


def test_learning_cli_usage_errors(tmp_path):
    assert sight.learning_main(str(tmp_path / "nope")) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert sight.learning_main(str(empty)) == 2


def test_sight_detect_phase_registered():
    assert "sight.detect" in KNOWN_PHASES


def test_programs_json_carries_justified_sight_twins():
    with open(os.path.join(REPO, "t2omca_tpu", "analysis",
                           "programs.json")) as f:
        programs = json.load(f)["programs"]
    for name in ("train_iter_sight", "superstep_sight"):
        entry = programs[name]
        assert "TODO" not in entry["justification"]
        assert entry["flops"] > 0 and entry["bytes_accessed"] > 0
        gp203 = entry["rules"]["GP203"]
        assert gp203["count"] > 0 and "TODO" not in gp203["justification"]


# ---------------------------------------------------------------------------
# slow: driver paths, acceptance, pins
# ---------------------------------------------------------------------------

def _driver_cfg(tmp_path, port=0, **kw):
    obs_kw = kw.pop("obs_kw", {})
    sight_kw = kw.pop("sight_kw", {})
    res_kw = kw.pop("res_kw", {})
    defaults = dict(
        t_max=120, batch_size_run=2, batch_size=4,
        test_interval=1_000_000, test_nepisode=2, log_interval=12,
        runner_log_interval=1_000_000, save_model=False,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        resilience=ResilienceConfig(stall_grace_s=0.0, **res_kw),
        obs=ObsConfig(enabled=True, flush_every=1, pulse_port=port,
                      sight=SightConfig(enabled=True, bins=8, **sight_kw),
                      **obs_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _run_dir(tmp_path):
    return [d for d in glob.glob(os.path.join(str(tmp_path), "*"))
            if os.path.isdir(d) and os.path.basename(d) != "models"][0]


def _metric_series(run_dir):
    series = {}
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            ev = json.loads(line)
            series.setdefault(ev["key"], []).append(ev["value"])
    return series


@pytest.mark.slow
def test_superstep_driver_logs_vector_sight_stats(tmp_path):
    """Satellite: the classic K>1 path — (K, bins) stacked histograms
    flow through the driver's per-row extraction and the Logger without
    corrupting scalar keys; metrics.jsonl carries full-fidelity
    vectors."""
    from t2omca_tpu.run import run
    cfg = _driver_cfg(tmp_path, superstep=4)
    run(cfg, Logger())
    series = _metric_series(_run_dir(tmp_path))
    hists = series["sight_td_hist"]
    assert hists and all(isinstance(h, list) and len(h) == 8
                         for h in hists)
    assert all(isinstance(v, float) for v in series["loss"])
    assert all(isinstance(v, float)
               for v in series["sight_priority_entropy_norm"])
    ents = series["sight_attn_entropy_agent"]
    assert ents and all(isinstance(e, list) and len(e) == 1 for e in ents)


@pytest.mark.slow
def test_sebulba_lockstep_logs_sight_stats(tmp_path):
    """Satellite: the sebulba lockstep path emits the same sight keys
    (the re-homed learner_step carries the in-graph block)."""
    from t2omca_tpu.config import SebulbaConfig
    from t2omca_tpu.run import run
    cfg = _driver_cfg(
        tmp_path,
        sebulba=SebulbaConfig(actor_devices=1, learner_devices=1,
                              queue_slots=1, staleness=0))
    run(cfg, Logger())
    series = _metric_series(_run_dir(tmp_path))
    assert series.get("sight_td_hist")
    assert all(len(h) == 8 for h in series["sight_td_hist"])
    assert series.get("sight_priority_entropy_norm")


def _get(url, timeout=1.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.getcode(), r.read().decode()


class _HealthPoller(threading.Thread):
    def __init__(self, port):
        super().__init__(daemon=True)
        self.url = f"http://127.0.0.1:{port}/healthz"
        self.seen = []
        self.stop = threading.Event()

    def run(self):
        while not self.stop.is_set():
            try:
                self.seen.append(_get(self.url))
            except urllib.error.HTTPError as e:
                self.seen.append((e.code, e.read().decode()))
            except Exception:
                pass
            time.sleep(0.05)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_injected_pathology_trips_detector_within_one_cadence(tmp_path):
    """Acceptance: a forced NaN-free Q blow-up (q_div threshold below
    any real Q scale) trips sight-q_divergence at the FIRST log cadence
    — /healthz returns 503 naming the check, flight_recorder.json
    carries the sight mark + verdict block, and the jax-free
    `obs learning` CLI renders the TRIPPED verdict post-mortem."""
    from t2omca_tpu.run import run
    port = _free_port()
    cfg = _driver_cfg(tmp_path, port=port,
                      sight_kw=dict(q_div=1e-9))
    poller = _HealthPoller(port)
    poller.start()
    try:
        run(cfg, Logger())
    finally:
        poller.stop.set()
        poller.join(timeout=5)
    # live: 503 naming the detector
    degraded = [(code, body) for code, body in poller.seen if code == 503]
    assert degraded, "healthz never degraded during the run"
    payload = json.loads(degraded[-1][1])
    assert not payload["checks"]["sight-q_divergence"]["ok"]
    run_dir = _run_dir(tmp_path)
    # the trip persisted the flight ring with the sight mark + verdicts
    with open(os.path.join(run_dir, "flight_recorder.json")) as f:
        flight = json.load(f)
    assert any(e.get("kind") == "sight"
               and e.get("detector") == "q_divergence"
               for e in flight["events"])
    assert not flight["sight"]["detectors"]["q_divergence"]["ok"]
    # the trip landed within ONE log cadence of the first train info
    series = _metric_series(run_dir)
    assert series["sight_alert_q_divergence"][0] == 1.0
    # post-mortem: the jax-free CLI renders the verdict
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.obs", "learning", run_dir],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "q_divergence" in proc.stdout and "TRIPPED" in proc.stdout
    # sight.detect span landed and is registered
    events = [json.loads(l)
              for l in open(os.path.join(run_dir, "spans.jsonl"))
              if l.strip()]
    phases = {e.get("phase") for e in events if e.get("event") == "span"}
    assert "sight.detect" in phases
    assert phases <= KNOWN_PHASES, phases - KNOWN_PHASES


@pytest.mark.slow
@pytest.mark.analysis
def test_sight_superstep_one_compile_and_no_transfers(tmp_path):
    """Acceptance pin: sight on adds ZERO extra dispatches/transfers —
    the K>1 superstep still compiles exactly ONCE and a warm dispatch
    runs clean under the transfer guard (no hidden device_get from the
    diagnostics)."""
    from t2omca_tpu.analysis.guards import compile_budget, no_transfer
    from t2omca_tpu.run import Experiment
    cfg = _tiny_cfg(superstep=4)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    sup = exp.superstep_program(4)
    # t_envs precomputed OUTSIDE the guard: the guarded dispatch must
    # see only device-resident args (a Python-scalar add in the block
    # would be its own h2d, masking what the test pins)
    t_envs = [jnp.asarray(t, jnp.int32) for t in (0, 48, 96)]
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(4)])
    with compile_budget(1, match="_superstep"):
        ts, _, infos = sup(ts, keys, t_envs[0])
        with no_transfer():
            ts, _, infos = sup(ts, keys, t_envs[1])
        ts, _, infos = sup(ts, keys, t_envs[2])
    assert "sight_td_hist" in infos
    row = jax.tree.map(lambda x: x[2], infos)
    assert np.asarray(jax.device_get(row["sight_td_hist"])).shape == (8,)


@pytest.mark.slow
@pytest.mark.graftprog
def test_sight_off_fingerprints_match_checked_in_baseline():
    """Acceptance pin: obs.sight off ⇒ the
    train_iter/superstep/learner_train/dp_superstep fingerprints are
    byte-identical to the checked-in (pre-sight) baselines — the static
    gate compiles out entirely, zero re-baseline. Audited in a
    SUBPROCESS (the CLI's own environment: conftest's
    matmul-precision override changes lowered text in-process) — a
    drift would fire GP304 and exit 1."""
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--programs",
         "--only", "train_iter", "--only", "superstep",
         "--only", "learner_train", "--only", "dp_superstep"],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "0 new finding(s)" in proc.stdout
