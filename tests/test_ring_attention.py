"""Sequence-parallel attention (ring + Ulysses) vs dense reference on the
virtual 8-device mesh (SURVEY.md §4(5): distributed without a cluster)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from t2omca_tpu.parallel import make_mesh
from t2omca_tpu.parallel.ring_attention import (ring_attention,
                                                ulysses_attention)


def _dense(q, k, v):
    logits = jnp.einsum("...qd,...kd->...qk", q, k)
    return jnp.einsum("...qk,...kd->...qd",
                      jax.nn.softmax(logits, axis=-1), v)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8, axis_names=("sp",))


def test_ring_attention_matches_dense(mesh):
    b, t, d = 2, 32, 16                      # 32 tokens → 4 per device
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, t, d))
    k = jax.random.normal(ks[1], (b, t, d))
    v = jax.random.normal(ks[2], (b, t, d))

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None),) * 3,
        out_specs=P(None, "sp", None))
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_dense(q, k, v)),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_with_head_batch(mesh):
    """Extra leading axes (batch, heads) broadcast through the ring."""
    b, h, t, d = 2, 3, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, t, d))
    k = jax.random.normal(ks[1], (b, h, t, d))
    v = jax.random.normal(ks[2], (b, h, t, d))
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_dense(q, k, v)),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_attention_matches_dense(mesh):
    b, t, h, d = 2, 16, 8, 4                 # 8 heads / 8 devices
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, t, h, d))
    k = jax.random.normal(ks[1], (b, t, h, d))
    v = jax.random.normal(ks[2], (b, t, h, d))

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None, None),) * 3,
        out_specs=P(None, "sp", None, None))
    out = jax.jit(uly)(q, k, v)

    # dense reference over (b, h, t, d)
    qd, kd, vd = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    ref = _dense(qd, kd, vd).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_grad_flows(mesh):
    """The online-softmax ring is differentiable (needed if SP ever spans
    the learner's entity axis)."""
    b, t, d = 1, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, t, d))
    k = jax.random.normal(ks[1], (b, t, d))
    v = jax.random.normal(ks[2], (b, t, d))
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None),) * 3,
        out_specs=P(None, "sp", None))

    g = jax.grad(lambda q: jax.jit(ring)(q, k, v).sum())(q)
    g_ref = jax.grad(lambda q: _dense(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)
