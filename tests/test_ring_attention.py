"""Sequence-parallel attention (ring + Ulysses) vs dense reference on the
virtual 8-device mesh (SURVEY.md §4(5): distributed without a cluster)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from t2omca_tpu.parallel.compat import shard_map

from t2omca_tpu.parallel import make_mesh
from t2omca_tpu.parallel.ring_attention import (ring_attention,
                                                ulysses_attention)


def _dense(q, k, v):
    logits = jnp.einsum("...qd,...kd->...qk", q, k)
    return jnp.einsum("...qk,...kd->...qd",
                      jax.nn.softmax(logits, axis=-1), v)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8, axis_names=("sp",))


def test_ring_attention_matches_dense(mesh):
    b, t, d = 2, 32, 16                      # 32 tokens → 4 per device
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, t, d))
    k = jax.random.normal(ks[1], (b, t, d))
    v = jax.random.normal(ks[2], (b, t, d))

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None),) * 3,
        out_specs=P(None, "sp", None))
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_dense(q, k, v)),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_with_head_batch(mesh):
    """Extra leading axes (batch, heads) broadcast through the ring."""
    b, h, t, d = 2, 3, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, h, t, d))
    k = jax.random.normal(ks[1], (b, h, t, d))
    v = jax.random.normal(ks[2], (b, h, t, d))
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_dense(q, k, v)),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_attention_matches_dense(mesh):
    b, t, h, d = 2, 16, 8, 4                 # 8 heads / 8 devices
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, t, h, d))
    k = jax.random.normal(ks[1], (b, t, h, d))
    v = jax.random.normal(ks[2], (b, t, h, d))

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None, None),) * 3,
        out_specs=P(None, "sp", None, None))
    out = jax.jit(uly)(q, k, v)

    # dense reference over (b, h, t, d)
    qd, kd, vd = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    ref = _dense(qd, kd, vd).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_ring_attention_grad_flows(mesh):
    """The online-softmax ring is differentiable (needed if SP ever spans
    the learner's entity axis)."""
    b, t, d = 1, 16, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, t, d))
    k = jax.random.normal(ks[1], (b, t, d))
    v = jax.random.normal(ks[2], (b, t, d))
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp"),
        mesh=mesh,
        in_specs=(P(None, "sp", None),) * 3,
        out_specs=P(None, "sp", None))

    g = jax.grad(lambda q: jax.jit(ring)(q, k, v).sum())(q)
    g_ref = jax.grad(lambda q: _dense(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=1e-4, rtol=1e-4)


def test_ring_attention_kv_mask_matches_dense(mesh):
    """Padded key positions (global token count not a multiple of the axis
    size) must be excluded from every softmax."""
    b, t_real, d = 2, 13, 8
    tp = 16                                   # padded to 8 devices x 2
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, tp, d))
    k = jax.random.normal(ks[1], (b, tp, d))
    v = jax.random.normal(ks[2], (b, tp, d))
    valid = jnp.arange(tp) < t_real
    kv_mask = jnp.broadcast_to(valid[None], (b, tp))

    ring = shard_map(
        lambda q, k, v, m: ring_attention(q, k, v, "sp", m),
        mesh=mesh,
        in_specs=(P(None, "sp", None),) * 3 + (P(None, "sp"),),
        out_specs=P(None, "sp", None))
    out = jax.jit(ring)(q, k, v, kv_mask)

    dense = _dense(q[:, :t_real], k[:, :t_real], v[:, :t_real])
    np.testing.assert_allclose(np.asarray(out[:, :t_real]),
                               np.asarray(dense), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("gate", [False, True])
def test_sp_mixer_matches_dense_mixer(mesh, gate):
    """mixer_apply_sp (token axis sharded over 8 devices, ring attention)
    must reproduce TransformerMixer.apply exactly — the config-5 consumer
    of the SP layer (SURVEY.md §2.2 extension point). Parametrized over
    zero_init_gate so the SP readout honors the gate param when present
    (gate value perturbed off its 0-init below to make the check real)."""
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.parallel.sp_mixer import mixer_apply_sp

    a, n_ent, feat, emb = 5, 5, 8, 16
    mixer = TransformerMixer(n_agents=a, n_entities=n_ent, feat_dim=feat,
                             emb=emb, heads=2, depth=2,
                             state_entity_mode=True, zero_init_gate=gate)
    b = 3
    ks = jax.random.split(jax.random.PRNGKey(4), 6)
    qvals = jax.random.normal(ks[0], (b, 1, a))
    hidden = jax.random.normal(ks[1], (b, a, emb))
    hyper = jax.random.normal(ks[2], (b, 3, emb))
    states = jax.random.normal(ks[3], (b, n_ent * feat))
    obs = jax.random.normal(ks[4], (b, a, 8))
    params = mixer.init(ks[5], qvals, hidden, hyper, states, obs)
    if gate:   # open the gate so equality is a non-trivial check
        params = jax.tree.map(lambda x: x, params)
        params["params"]["out_gate"] = jnp.full((1,), 0.7)

    y_dense, hyp_dense = mixer.apply(params, qvals, hidden, hyper, states,
                                     obs)
    y_sp, hyp_sp = jax.jit(
        lambda p, q_, h_, hy, s_, o_: mixer_apply_sp(
            mixer, p, q_, h_, hy, s_, o_, mesh))(
        params, qvals, hidden, hyper, states, obs)

    np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_dense),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(hyp_sp), np.asarray(hyp_dense),
                               atol=1e-4, rtol=1e-4)


def test_sp_mixer_monotonic_and_q12(mesh):
    """Q12 fallback (obs entities) + monotonicity survive the SP path."""
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.parallel.sp_mixer import mixer_apply_sp

    a, feat, emb = 4, 6, 8
    mixer = TransformerMixer(n_agents=a, n_entities=1, feat_dim=feat,
                             emb=emb, heads=2, depth=1,
                             state_entity_mode=False)
    b = 2
    ks = jax.random.split(jax.random.PRNGKey(5), 6)
    qvals = jax.random.normal(ks[0], (b, 1, a))
    hidden = jax.random.normal(ks[1], (b, a, emb))
    hyper = jax.random.normal(ks[2], (b, 3, emb))
    states = jax.random.normal(ks[3], (b, 4))
    obs = jax.random.normal(ks[4], (b, a, feat))
    params = mixer.init(ks[5], qvals, hidden, hyper, states, obs)

    y_dense, _ = mixer.apply(params, qvals, hidden, hyper, states, obs)
    def sp(qv):
        y, _ = mixer_apply_sp(mixer, params, qv, hidden, hyper, states,
                              obs, mesh)
        return y
    np.testing.assert_allclose(np.asarray(sp(qvals)), np.asarray(y_dense),
                               atol=1e-4, rtol=1e-4)
    g = jax.grad(lambda qv: sp(qv).sum())(qvals)
    assert (np.asarray(g) >= 0).all()


@pytest.mark.slow   # SP backward compile (~18 s); SP forward equivalence stays in-gate
def test_sp_mixer_param_grads_finite_with_padding(mesh):
    """Gradients through the masked ring attention must stay finite even
    when a device's whole key block is padding (double-where NaN guard)."""
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.parallel.sp_mixer import mixer_apply_sp

    a, n_ent, feat, emb = 5, 5, 8, 16   # 13 tokens -> pad 16, last block all-pad
    mixer = TransformerMixer(n_agents=a, n_entities=n_ent, feat_dim=feat,
                             emb=emb, heads=2, depth=1,
                             state_entity_mode=True)
    b = 2
    ks = jax.random.split(jax.random.PRNGKey(6), 6)
    qvals = jax.random.normal(ks[0], (b, 1, a))
    hidden = jax.random.normal(ks[1], (b, a, emb))
    hyper = jax.random.normal(ks[2], (b, 3, emb))
    states = jax.random.normal(ks[3], (b, n_ent * feat))
    obs = jax.random.normal(ks[4], (b, a, 8))
    params = mixer.init(ks[5], qvals, hidden, hyper, states, obs)

    def loss(p):
        y, _ = mixer_apply_sp(mixer, p, qvals, hidden, hyper, states, obs,
                              mesh)
        return (y ** 2).sum()

    g = jax.grad(loss)(params)
    leaves = jax.tree.leaves(g)
    assert leaves and all(np.isfinite(np.asarray(x)).all() for x in leaves)
