"""graftworld scenario subsystem (ISSUE 11, docs/ENVS.md): EnvParams
threading + default-scenario bit-parity goldens, padded-agent masking
invariants, distribution samplers, registry entries, per-slice stats,
and the one-dispatch multi-family acceptance path."""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               ScenarioConfig, TrainConfig, load_config,
                               sanity_check)
from t2omca_tpu.envs import graftworld
from t2omca_tpu.envs.graftworld import (FAMILY_IDS, FAMILY_NAMES,
                                        FixedScenario, MixtureScenario,
                                        UniformScenario,
                                        family_distribution,
                                        make_distribution)
from t2omca_tpu.envs.mec_offload import EnvParams
from t2omca_tpu.envs.registry import (ALIASES, REGISTRY, make_env, resolve,
                                      scenario_config)

pytestmark = pytest.mark.scenarios

KEY = jax.random.PRNGKey(0)


def digest(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()[:16]


def tiny_env(**kw):
    defaults = dict(agv_num=4, mec_num=2, num_channels=2, episode_limit=10)
    defaults.update(kw)
    return make_env(EnvConfig(**defaults))


# ------------------------------------------------------- default parity

#: golden digests captured from the PRE-graftworld env/runner on this
#: box (jax 0.4.37, CPU, f32): the default EnvParams must reproduce the
#: fixed scenario BIT-identically — acceptance criterion of ISSUE 11.
#: If a deliberate env-semantics change moves these, recapture via the
#: recipe in docs/ENVS.md §parity.
ENV_GOLDEN = "b517edfaa286d819"
ENV_STATE_GOLDEN = "60b154d8b4a185c8"
RUNNER_GOLDEN = "30d99a1c21118889"
RUNNER_STATS_GOLDEN = "91066c60eb50c847"


def _env_rollout_digests(params_b=None):
    env = tiny_env()
    B = 3
    keys = jax.random.split(jax.random.PRNGKey(0), B)
    if params_b is None:
        st, obs, gs, avail = jax.vmap(env.reset)(keys)
    else:
        st, obs, gs, avail = jax.vmap(env.reset)(keys, None, params_b)
    out = [obs, gs, avail]
    k = jax.random.PRNGKey(1)
    for _ in range(4):
        k, k_act, k_step = jax.random.split(k, 3)
        logits = jnp.where(avail > 0, 0.0, -1e9)
        acts = jax.vmap(lambda kk, lg: jax.random.categorical(kk, lg))(
            jax.random.split(k_act, B), logits)
        step_keys = jax.random.split(k_step, B)
        if params_b is None:
            st, reward, term, info, obs, gs, avail = jax.vmap(env.step)(
                st, acts, step_keys)
        else:
            st, reward, term, info, obs, gs, avail = jax.vmap(env.step)(
                st, acts, step_keys, params_b)
        out += [reward, term, obs, gs, avail,
                info.reward, info.delay_reward, info.overtime_penalty,
                info.channel_utilization_rate, info.conflict_ratio,
                info.task_completion_rate, info.task_completion_delay]
    return digest(out), digest(st)


def test_default_path_matches_pre_graftworld_goldens():
    """params=None (the implicit default scenario) is bit-identical to
    the pre-graftworld fixed env."""
    d_out, d_st = _env_rollout_digests(None)
    assert d_out == ENV_GOLDEN
    assert d_st == ENV_STATE_GOLDEN


def test_explicit_default_params_bit_identical():
    """An explicitly vmapped default EnvParams pytree takes the same
    traced path as any sampled scenario — and still reproduces the
    fixed scenario bit-exactly (every knob is a neutral element)."""
    env = tiny_env()
    params_b = jax.vmap(lambda _: env.default_params())(jnp.arange(3))
    d_out, d_st = _env_rollout_digests(params_b)
    assert d_out == ENV_GOLDEN
    assert d_st == ENV_STATE_GOLDEN


def _tiny_train_cfg(**env_kw):
    env_args = dict(agv_num=3, mec_num=2, num_channels=2, episode_limit=6)
    env_args.update(env_kw)
    return sanity_check(TrainConfig(
        batch_size_run=3,
        env_args=EnvConfig(**env_args),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
    ))


def _runner_digests(cfg):
    from t2omca_tpu.controllers import BasicMAC
    from t2omca_tpu.learners import QMixLearner
    from t2omca_tpu.runners import ParallelRunner
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    ls = learner.init_state(jax.random.PRNGKey(0))
    runner = ParallelRunner(env, mac, cfg)
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    rs, batch, stats = run(ls.params["agent"], rs, test_mode=False)
    rs, batch2, stats2 = run(ls.params["agent"], rs, test_mode=True)
    return (digest([batch.obs, batch.state, batch.reward, batch.actions,
                    batch.avail_actions, batch2.reward, batch2.actions]),
            digest([stats.episode_return, stats.reward,
                    stats.conflict_ratio, stats.task_completion_rate,
                    stats2.episode_return]),
            stats)


def test_runner_default_scenario_matches_goldens():
    """The full rollout program — scenario sampling folded in — is
    bit-identical to the pre-graftworld runner at the default scenario,
    and every lane carries the baseline family tag."""
    d_batch, d_stats, stats = _runner_digests(_tiny_train_cfg())
    assert d_batch == RUNNER_GOLDEN
    assert d_stats == RUNNER_STATS_GOLDEN
    assert np.asarray(stats.scenario).tolist() == [0, 0, 0]


# ------------------------------------------------------- padded masking

def _padded_rollout(n_active=2, steps=8, a=4):
    """Roll the env with a fixed padded-fleet scenario; force the padded
    agents through avail-legal random actions like the selector would."""
    env = tiny_env(agv_num=a)
    p = env.default_params().replace(n_active=jnp.asarray(n_active,
                                                          jnp.int32))
    st, obs, gs, avail = env.reset(KEY, None, p)
    k = jax.random.PRNGKey(2)
    infos, avails, acks, rewards = [], [avail], [], []
    for _ in range(steps):
        k, k_act, k_step = jax.random.split(k, 3)
        logits = jnp.where(avail > 0, 0.0, -1e9)
        acts = jax.random.categorical(k_act, logits)
        st, reward, term, info, obs, gs, avail = env.step(
            st, acts, k_step, p)
        infos.append(info)
        avails.append(avail)
        acks.append(st.last_ack)
        rewards.append(reward)
    return env, p, st, infos, avails, acks, rewards


def test_padded_agents_masked_everywhere():
    """Invariants (ISSUE 11 satellite): padded agents only ever expose
    action 0, never hold jobs, never ACK, never generate tasks — so
    their reward/priority contribution is exactly zero."""
    env, p, st, infos, avails, acks, _ = _padded_rollout()
    pad = slice(2, None)                       # agents 2..3 are padded
    for av in avails:
        av = np.asarray(av)
        assert (av[pad, 0] == 1).all()
        assert (av[pad, 1:] == 0).all()
    for ack in acks:
        assert (np.asarray(ack)[pad] == 0).all()
    assert not np.asarray(st.job_valid)[pad].any()
    assert (np.asarray(st.task_num)[pad] == 0).all()
    assert (np.asarray(st.task_success)[pad] == 0).all()
    assert (np.asarray(st.remain_delay)[pad] == 0.0).all()
    # unique negative mec sentinel: invisible to every active agent
    mi = np.asarray(st.mec_index)
    assert (mi[pad] < 0).all() and len(set(mi[pad].tolist())) == 2
    # critic priority: padded agents score nothing above the noise floor
    scores = np.asarray(env.get_critic_score(st, KEY, p))
    assert scores.shape == (4,)


def test_padded_reward_equals_active_subfleet():
    """A padded 4-agent env and a true 2-agent env see the same REWARD
    STRUCTURE: padded agents contribute zero, so total reward comes from
    active agents only (exact equality is not expected — key streams
    differ — but the padded lanes' zero contribution is provable from
    the masked counters)."""
    env, p, st, infos, _, _, rewards = _padded_rollout()
    # conflict ratio divides by n_active, not the static fleet size
    for info in infos:
        cr = float(np.asarray(info.conflict_ratio))
        assert 0.0 <= cr <= 1.0
    # all tasks (and therefore all reward events) belong to active agents
    assert int(np.asarray(st.task_num)[:2].sum()) \
        == int(np.asarray(st.task_num).sum())


def test_conflict_ratio_uses_active_count():
    """Two active agents forced onto the same channel under one MEC:
    conflict_ratio = 2/n_active, not 2/agv_num."""
    env = tiny_env(agv_num=4, mec_num=1)
    p = env.default_params().replace(
        n_active=jnp.asarray(2, jnp.int32),
        job_prob=jnp.asarray(1.0, jnp.float32))
    st, *_ = env.reset(KEY, None, p)
    # both active agents transmit on channel 1 -> collision
    _, _, _, info, *_ = env.step(st, jnp.asarray([1, 1, 0, 0]), KEY, p)
    has_job = np.asarray(st.job_valid)[:2, 0]
    expected = float(has_job.sum()) / 2.0   # colliders / ACTIVE agents
    assert float(np.asarray(info.conflict_ratio)) == pytest.approx(expected)


# ------------------------------------------------------- distributions

def test_fixed_scenario_overrides_and_family_tag():
    env = tiny_env()
    p = FixedScenario(family="interference").sample(KEY, env)
    assert int(p.family) == FAMILY_IDS["interference"]
    assert float(p.interference_w) > 0.0
    assert float(p.gain_scale) < 1.0
    p2 = FixedScenario(overrides=(("job_prob", 0.9),)).sample(KEY, env)
    assert float(p2.job_prob) == pytest.approx(0.9)
    assert int(p2.family) == 0


def test_hetfleet_fixed_point_is_deterministic_gradient():
    env = tiny_env()
    p = FixedScenario(family="hetfleet").sample(KEY, env)
    cs = np.asarray(p.compute_scale)
    assert cs.shape == (4,)
    assert cs[0] == pytest.approx(0.5) and cs[-1] == pytest.approx(2.0)
    # deterministic: key-independent
    p2 = FixedScenario(family="hetfleet").sample(jax.random.PRNGKey(9), env)
    np.testing.assert_array_equal(cs, np.asarray(p2.compute_scale))


def test_uniform_scenario_draws_inside_ranges():
    env = tiny_env()
    dist = UniformScenario(family="surge")
    ranges = dict((n, (lo, hi)) for n, lo, hi in dist.effective_ranges())
    for seed in range(20):
        p = dist.sample(jax.random.PRNGKey(seed), env)
        assert int(p.family) == FAMILY_IDS["surge"]
        for name, (lo, hi) in ranges.items():
            v = np.asarray(getattr(p, name))
            assert (v >= lo).all() and (v < hi).all()


def test_uniform_min_agents_randomizes_fleet_size():
    env = tiny_env()
    dist = UniformScenario(family="hetfleet", min_agents=2)
    sizes = {int(dist.sample(jax.random.PRNGKey(s), env).n_active)
             for s in range(40)}
    assert sizes <= {2, 3, 4} and len(sizes) > 1


def test_mixture_spans_families_and_respects_weights():
    env = tiny_env()
    dist = MixtureScenario(components=tuple(
        family_distribution(f) for f in FAMILY_NAMES))
    fams = [int(dist.sample(jax.random.PRNGKey(s), env).family)
            for s in range(120)]
    counts = np.bincount(fams, minlength=4)
    assert (counts > 0).all()               # every family appears
    # a zero-weight component never appears
    dist0 = MixtureScenario(
        components=tuple(family_distribution(f) for f in FAMILY_NAMES),
        weights=(0.0, 1.0, 0.0, 0.0))
    fams0 = {int(dist0.sample(jax.random.PRNGKey(s), env).family)
             for s in range(40)}
    assert fams0 == {FAMILY_IDS["hetfleet"]}


def test_mixture_is_one_program_no_per_family_recompile():
    """One jitted (sample -> reset -> step) program serves every family:
    the compile budget allows exactly ONE compile across draws that land
    in different mixture components (acceptance criterion of ISSUE 11)."""
    from t2omca_tpu.analysis.guards import compile_budget
    env = tiny_env()
    dist = MixtureScenario(components=tuple(
        family_distribution(f) for f in FAMILY_NAMES))

    @jax.jit
    def scenario_step(key):
        p = dist.sample(key, env)
        st, obs, gs, avail = env.reset(key, None, p)
        return env.step(st, jnp.zeros(env.n_agents, jnp.int32), key, p)[1]

    with compile_budget(1, match="scenario_step"):
        seen = set()
        for s in range(24):
            k = jax.random.PRNGKey(s)
            seen.add(int(dist.sample(k, env).family))
            scenario_step(k).block_until_ready()
    assert len(seen) >= 3                  # draws really spanned families


# ------------------------------------------------------- registry

def test_registry_aliases_resolve_to_canonical_entry():
    for alias, canonical in ALIASES.items():
        c, entry = resolve(alias)
        assert c == canonical
        assert entry is REGISTRY[canonical]


def test_registry_unknown_key_names_keys_and_aliases_separately():
    with pytest.raises(KeyError) as ei:
        resolve("no_such_env")
    msg = str(ei.value)
    assert "canonical keys" in msg and "aliases" in msg
    assert "multi_mec -> multi_agv_offloading" in msg


def test_registry_family_keys_carry_default_scenarios():
    assert scenario_config(EnvConfig(key="multi_agv_surge")).family \
        == "surge"
    assert scenario_config(EnvConfig(key="hetfleet")).family == "hetfleet"
    assert scenario_config(EnvConfig(key="multi_agv_scenarios")).kind \
        == "mixture"
    # an explicit scenario config beats the registry default
    explicit = EnvConfig(key="multi_agv_surge",
                         scenario=ScenarioConfig(kind="fixed",
                                                 family="baseline"))
    assert scenario_config(explicit).family == "baseline"
    # default key -> fixed baseline (the pre-graftworld behavior)
    assert scenario_config(EnvConfig()) \
        == ScenarioConfig(kind="fixed", family="baseline")


def test_config_mirrors_pin_graftworld_names():
    """config.sanity_check mirrors graftworld's name sets (it cannot
    import the jax-dependent module); obs/report mirrors the family
    names (it must stay jax-free). Pin both mirrors."""
    from t2omca_tpu.obs.report import SCENARIO_FAMILY_NAMES, SLICE_METRICS
    from t2omca_tpu.utils.stats import SLICE_KEYS
    assert tuple(SCENARIO_FAMILY_NAMES) == tuple(FAMILY_NAMES)
    assert tuple(key for _, key in SLICE_METRICS) \
        == ("return_mean",) + tuple(k + "_mean" for k in SLICE_KEYS)
    env_params_fields = {f.name for f in
                         dataclasses.fields(EnvParams)} - {"family"}
    assert set(graftworld.RANDOMIZABLE_FIELDS) == env_params_fields
    # sanity_check accepts every family/kind graftworld knows
    for fam in FAMILY_NAMES:
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(kind="uniform", family=fam))))
    for kind in ("fixed", "uniform", "mixture"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(kind=kind))))


def test_sanity_check_rejects_bad_scenarios():
    with pytest.raises(ValueError, match="scenario.kind"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(kind="nope"))))
    with pytest.raises(ValueError, match="scenario.family"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(family="nope"))))
    with pytest.raises(ValueError, match="randomizable"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(kind="uniform",
                                    ranges=(("bogus", 0.0, 1.0),)))))
    with pytest.raises(ValueError, match="deadline_ms"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(
                kind="uniform", ranges=(("deadline_ms", 50.0, 500.0),)))))
    with pytest.raises(ValueError, match="min_agents"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(min_agents=99))))
    with pytest.raises(ValueError, match="weights"):
        sanity_check(TrainConfig(env_args=EnvConfig(
            scenario=ScenarioConfig(kind="mixture",
                                    families=("baseline", "surge"),
                                    weights=(1.0,)))))


def test_yaml_and_cli_scenario_surface(tmp_path):
    cfg_file = tmp_path / "scn.yaml"
    cfg_file.write_text(
        "env_args:\n"
        "  agv_num: 6\n"
        "  scenario:\n"
        "    kind: mixture\n"
        "    families: [baseline, surge]\n"
        "    weights: [0.5, 0.5]\n"
        "    min_agents: 3\n")
    cfg = load_config(str(cfg_file))
    scn = cfg.env_args.scenario
    assert scn.kind == "mixture"
    assert scn.families == ("baseline", "surge")
    assert scn.weights == (0.5, 0.5)
    assert scn.min_agents == 3
    # CLI dotted override path
    cfg2 = load_config(None, ("env_args.scenario.kind=uniform",
                              "env_args.scenario.family=interference"))
    assert cfg2.env_args.scenario.kind == "uniform"
    assert cfg2.env_args.scenario.family == "interference"
    # the resolved distribution builds
    make_distribution(scn)


# ------------------------------------------------------- per-slice stats

class RecordingLogger:
    def __init__(self):
        self.logged = []

    def log_stat(self, key, value, t):
        self.logged.append((key, value, t))

    def get(self, key):
        vals = [v for k, v, _ in self.logged if k == key]
        return vals[-1] if vals else None


def _fake_stats(returns, scenario, **kw):
    from tests.test_metrics import FakeStats
    return FakeStats(episode_return=np.asarray(returns, np.float32),
                     epsilon=np.array(0.1),
                     scenario=np.asarray(scenario, np.int32), **kw)


def test_accumulator_reports_per_slice_metrics():
    from t2omca_tpu.utils.stats import StatsAccumulator
    acc = StatsAccumulator()
    acc.push(_fake_stats([1.0, 3.0, 10.0], [0, 0, 2],
                         conflict_ratio=np.asarray([0.5, 0.5, 0.0]),
                         deadline_miss_rate=np.asarray([0.2, 0.4, 0.0])))
    acc.push(_fake_stats([5.0], [2],
                         conflict_ratio=np.asarray([1.0]),
                         deadline_miss_rate=np.asarray([0.5])))
    log = RecordingLogger()
    acc.flush(log, t_env=100, prefix="test_")
    # overall keys unchanged
    assert log.get("test_return_mean") == pytest.approx(np.mean(
        [1, 3, 10, 5]))
    # slice 0: two episodes
    assert log.get("test_slice0_n") == 2
    assert log.get("test_slice0_return_mean") == pytest.approx(2.0)
    assert log.get("test_slice0_conflict_ratio_mean") == pytest.approx(0.5)
    assert log.get("test_slice0_deadline_miss_rate_mean") \
        == pytest.approx(0.3)
    # slice 2: spans both pushes
    assert log.get("test_slice2_n") == 2
    assert log.get("test_slice2_return_mean") == pytest.approx(7.5)
    assert log.get("test_slice2_conflict_ratio_mean") == pytest.approx(0.5)
    # flush clears the slices
    log2 = RecordingLogger()
    acc.flush(log2, t_env=200, prefix="test_")
    assert log2.get("test_slice0_n") is None


def test_accumulator_single_slice_keeps_legacy_stream():
    """A single-family run (the default scenario) must emit EXACTLY the
    pre-graftworld keys — no slice rows."""
    from t2omca_tpu.utils.stats import StatsAccumulator
    acc = StatsAccumulator()
    acc.push(_fake_stats([1.0, 2.0], [0, 0]))
    log = RecordingLogger()
    acc.flush(log, t_env=50)
    assert all("slice" not in k for k, _, _ in log.logged)


def test_rollout_stats_carry_scenario_and_miss_rate():
    """End-to-end: a mixture config's rollout tags each lane with its
    family and the per-slice keys reach the logger via the accumulator."""
    from t2omca_tpu.controllers import BasicMAC
    from t2omca_tpu.learners import QMixLearner
    from t2omca_tpu.runners import ParallelRunner
    from t2omca_tpu.utils.stats import StatsAccumulator
    cfg = _tiny_train_cfg(agv_num=4, scenario=ScenarioConfig(
        kind="mixture", min_agents=2))
    cfg = dataclasses.replace(cfg, batch_size_run=8)
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    ls = learner.init_state(jax.random.PRNGKey(0))
    runner = ParallelRunner(env, mac, cfg)
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    acc = StatsAccumulator()
    fams = set()
    for _ in range(4):
        rs, batch, stats = run(ls.params["agent"], rs, test_mode=True)
        fams.update(np.asarray(stats.scenario).tolist())
        acc.push(stats)
    assert len(fams) >= 3                  # one dispatch spans families
    log = RecordingLogger()
    acc.flush(log, t_env=100, prefix="test_")
    for f in sorted(fams):
        assert log.get(f"test_slice{f}_n") is not None
        assert log.get(f"test_slice{f}_deadline_miss_rate_mean") is not None


def test_report_renders_slice_table(tmp_path):
    """`obs report` (jax-free) renders the per-slice table from
    metrics.jsonl."""
    import json
    from t2omca_tpu.obs.report import render_slices, scenario_slices
    lines = [
        {"key": "test_slice0_n", "value": 8.0, "t": 100},
        {"key": "test_slice0_return_mean", "value": -5.0, "t": 100},
        {"key": "test_slice2_n", "value": 4.0, "t": 100},
        {"key": "test_slice2_return_mean", "value": -9.0, "t": 100},
        {"key": "test_slice2_deadline_miss_rate_mean", "value": 0.25,
         "t": 100},
        {"key": "return_mean", "value": -6.0, "t": 100},
    ]
    with open(tmp_path / "metrics.jsonl", "w") as f:
        for ev in lines:
            f.write(json.dumps(ev) + "\n")
    slices = scenario_slices(str(tmp_path))
    assert slices["test"][0]["return_mean"] == -5.0
    assert slices["test"][2]["deadline_miss_rate_mean"] == 0.25
    text = "\n".join(render_slices(slices))
    assert "baseline" in text and "interference" in text
    assert "scenario slices" in text
    # negative returns RENDER (the generic _fmt would dash them — and
    # the worst families are exactly what this table exists to show)
    assert "-5.0" in text and "-9.0" in text


# ------------------------------------------------------- checkpoints

def test_v3_checkpoint_migrates_to_v4_exactly(tmp_path):
    """Format v4 added RunnerState.env_params; a v3 full-state checkpoint
    (no such field) must restore EXACTLY via the migration shim — replay,
    normalizer stats, RNG state intact, env_params injected from the
    template (consumed by nothing: the rollout resamples scenarios at
    every episode start)."""
    import json as _json
    import os
    from flax import serialization
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    cfg = _tiny_train_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    d = save_checkpoint(str(tmp_path / "ckpt"), 40, ts)

    # doctor the on-disk checkpoint into v3: strip runner.env_params and
    # mark the meta format
    with open(os.path.join(d, "state.msgpack"), "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    del raw["runner"]["env_params"]
    blob = serialization.msgpack_serialize(raw)
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(blob)
    meta_p = os.path.join(d, "meta.json")
    meta = _json.load(open(meta_p))
    meta["format"] = 3
    # the content checksum covered the undoctored bytes
    meta.pop("sha256", None)
    meta.pop("bytes", None)
    _json.dump(meta, open(meta_p, "w"))

    template = exp.init_train_state(3)
    restored = load_checkpoint(d, template)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(restored))):
        if ".env_params" in jax.tree_util.keystr(kp):
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))
    # env_params came back from the template (the seed-3 fresh draw)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(template.runner.env_params)),
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(restored.runner.env_params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))


# ------------------------------------------------------- acceptance

@pytest.mark.slow
def test_one_dispatch_trains_across_three_families():
    """ISSUE 11 acceptance: one (vmapped) dispatch trains a single
    policy across a sampled distribution spanning >= 3 scenario
    families — rollout + insert + train run end-to-end on a mixture
    config with fleet-size randomization, and the train step updates
    params with finite loss."""
    from t2omca_tpu.run import Experiment
    cfg = sanity_check(TrainConfig(
        batch_size_run=8, batch_size=8,
        env_args=EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                           episode_limit=6,
                           scenario=ScenarioConfig(kind="mixture",
                                                   min_agents=2)),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=16),
    ))
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(cfg.seed)
    rollout, insert, train_iter = exp.jitted_programs()
    fams = set()
    key = jax.random.PRNGKey(3)
    for i in range(2):
        rs, batch, stats = rollout(ts.learner.params["agent"], ts.runner,
                                   test_mode=False)
        fams.update(np.asarray(stats.scenario).tolist())
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
    assert len(fams) >= 3
    key, k = jax.random.split(key)
    ts, info = train_iter(ts, k, jnp.asarray(96))
    assert bool(np.asarray(info["all_finite"]))
    assert np.isfinite(float(np.asarray(info["loss"])))
