"""Chaos soak (docs/RESILIENCE.md §5; runner: ``scripts/chaos.sh``):
cycle every fault-injection hook point against the real driver and assert
the ONE invariant that matters for production: **whatever happens, the
run ends in a resumable state** — a ``verify_checkpoint``-passing
checkpoint on disk that a fresh driver can load and carry to t_max.

Each scenario is a full ``run()`` on the tiny CPU config (fresh compile),
so the module is ``slow``-marked and additionally carries the ``chaos``
marker so the soak runner can select exactly this battery:

    bash scripts/chaos.sh [N]     # N cycles of the whole battery
"""

import glob
import os
import signal
import time

import jax
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               ResilienceConfig, TrainConfig, sanity_check)
from t2omca_tpu.run import run
from t2omca_tpu.utils import resilience
from t2omca_tpu.utils.checkpoint import find_checkpoint, verify_checkpoint
from t2omca_tpu.utils.logging import Logger

pytestmark = [pytest.mark.slow, pytest.mark.chaos, pytest.mark.faultinject]


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def chaos_cfg(results_dir, **res_kw):
    # dispatch_timeout carries wide headroom over a warm tiny-config
    # dispatch so a loaded CI box cannot trip it spuriously; the injected
    # hang (2.5 s below) still dwarfs it
    res = dict(dispatch_timeout=0.75, stall_grace_s=0.0,
               dispatch_retries=1, retry_backoff_s=0.01, max_restores=2)
    res.update(res_kw)
    return sanity_check(TrainConfig(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=True, save_model_interval=12, superstep=2,
        local_results_path=str(results_dir), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        resilience=ResilienceConfig(**res),
    ))


def _inject_hang():
    fired = []

    def _hang(t_env, **kw):
        if t_env >= 24 and not fired:
            fired.append(t_env)
            time.sleep(2.5)                    # >> dispatch_timeout

    resilience.register_fault("dispatch.superstep", _hang)


def _inject_transient_dispatch():
    def _flaky(t_env, attempt, **kw):
        if t_env == 24 and attempt == 1:
            raise RuntimeError("chaos: connection reset by peer")

    resilience.register_fault("dispatch.superstep", _flaky)


def _inject_persistent_fused():
    def _always(t_env, **kw):
        raise RuntimeError("chaos: fused dispatch socket closed")

    resilience.register_fault("dispatch.superstep", _always)


def _inject_transient_wait():
    # the production steady-state blocking point: an async device fault
    # surfaces at the run-ahead block_until_ready, not at the dispatch
    # call — must route to the ladder's restore rung, not kill the run
    seen = []

    def _wait_fault(t_env, **kw):
        seen.append(t_env)
        if len(seen) == 1:
            raise RuntimeError("chaos: connection reset by peer")

    resilience.register_fault("dispatch.wait", _wait_fault)


def _inject_flaky_gather():
    seen = []

    def _gather(t_env, **kw):
        seen.append(t_env)
        if len(seen) == 1:
            raise RuntimeError("chaos: collective timed out")

    resilience.register_fault("collective.gather", _gather)


def _inject_checkpoint_crash():
    seen = []

    def _crash(dirname, t_env, **kw):
        seen.append(t_env)
        if len(seen) == 2:                     # the SECOND save dies
            raise RuntimeError("chaos: crash mid-checkpoint")

    resilience.register_fault("checkpoint.staged", _crash)


def _inject_sigterm():
    def _preempt(t_env, guard, **kw):
        if t_env >= 24:
            signal.raise_signal(signal.SIGTERM)

    resilience.register_fault("driver.iteration", _preempt)


def _inject_preempt_barrier_timeout():
    # graftmorph (docs/RESILIENCE.md §6): preemption whose stop-step
    # negotiation FAILS (peer died mid-barrier) — the exit must degrade
    # to the per-host shard save, which on one host is a complete (and
    # therefore valid, resumable) checkpoint
    def _trip(t_env, guard, **kw):
        if guard is not None and t_env >= 24:
            guard.request("chaos-preempt")

    def _barrier_dies(**kw):
        raise RuntimeError("chaos: peer died mid-negotiation")

    resilience.register_fault("driver.iteration", _trip)
    resilience.register_fault("preempt.barrier", _barrier_dies)


def _inject_shard_save_crash():
    # the degraded path's own failure: the barrier dies AND the
    # fallback shard write dies — the exit must still be orderly and
    # leave the last cadence save as the resume point
    def _trip(t_env, guard, **kw):
        if guard is not None and t_env >= 24:
            guard.request("chaos-preempt")

    def _barrier_dies(**kw):
        raise RuntimeError("chaos: peer died mid-negotiation")

    def _shard_dies(**kw):
        raise RuntimeError("chaos: disk full mid-shard-write")

    resilience.register_fault("driver.iteration", _trip)
    resilience.register_fault("preempt.barrier", _barrier_dies)
    resilience.register_fault("checkpoint.shard_save", _shard_dies)


#: (name, injector, may_raise) — may_raise names the exception type a
#: scenario is ALLOWED to kill the run with; resumability must hold
#: either way.
SCENARIOS = [
    ("hang_at_superstep", _inject_hang, None),
    ("transient_dispatch", _inject_transient_dispatch, None),
    ("persistent_fused_degrades", _inject_persistent_fused, None),
    ("transient_runahead_wait", _inject_transient_wait, None),
    ("flaky_checkpoint_gather", _inject_flaky_gather, None),
    ("crash_mid_checkpoint", _inject_checkpoint_crash, RuntimeError),
    ("sigterm_preemption", _inject_sigterm, None),
    ("preempt_barrier_timeout_shard_save",
     _inject_preempt_barrier_timeout, None),
    ("shard_save_crash_keeps_cadence_save",
     _inject_shard_save_crash, None),
]


@pytest.mark.parametrize("name,inject,may_raise",
                         SCENARIOS, ids=[s[0] for s in SCENARIOS])
def test_chaos_run_always_ends_resumable(tmp_path, name, inject, may_raise):
    results = tmp_path / name
    cfg = chaos_cfg(results)
    inject()
    try:
        run(cfg, Logger())
    except Exception as e:              # noqa: BLE001 — asserted below
        assert may_raise is not None and isinstance(e, may_raise), \
            f"scenario {name} must not kill the run with {e!r}"
    finally:
        resilience.clear_faults()

    # THE invariant: a valid checkpoint exists, newest-first selection
    # skips anything torn, and a fresh fault-free driver resumes it to
    # the original target
    model_dirs = glob.glob(os.path.join(results, "models", "*"))
    assert model_dirs, f"scenario {name} left no checkpoint directory"
    found = find_checkpoint(model_dirs[0])
    assert found is not None, f"scenario {name} left no valid checkpoint"
    dirname, step = found
    assert verify_checkpoint(dirname)
    assert 0 < step <= cfg.t_max + 2 * cfg.superstep * 12

    ts = run(cfg.replace(checkpoint_path=model_dirs[0]), Logger())
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max, \
        f"scenario {name}: resume did not reach t_max"


def test_chaos_scenarios_cover_every_hook_point():
    """The battery must keep covering each documented injection point as
    hooks are added (a new hook point without a chaos scenario is a
    regression in this file)."""
    import inspect
    covered = set()
    for _, inject, _ in SCENARIOS:
        covered.update(
            line.split('"')[1]
            for line in inspect.getsource(inject).splitlines()
            if "register_fault(" in line)
    assert {"dispatch.superstep", "dispatch.wait", "collective.gather",
            "checkpoint.staged", "driver.iteration", "preempt.barrier",
            "checkpoint.shard_save"} <= covered
