"""graftserve: the AOT policy-serving subsystem (docs/SERVING.md).

Two tiers, matching the tier-1 budget reality (the 870s gate is nearly
full): the host-side batching logic — bucket pick, mask-correct
padding, session carry, meta round-trip, CLI usage errors — runs
in-gate with no jit; everything that compiles (export → load → serve
round-trips, the bench leg, the DP sharded resume) is ``slow``-marked.
The serve PROGRAM itself is still statically gated on every t1 run:
the graftprog prelude lowers+compiles ``serve_step`` and ratchets its
FLOPs/bytes/fingerprint (analysis/programs.json).
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

# ---------------------------------------------------------------------------
# host-side batching logic (in-gate: no jit, no Experiment build)
# ---------------------------------------------------------------------------


def test_pick_bucket_boundaries():
    from t2omca_tpu.serve.frontend import pick_bucket
    buckets = [1, 2, 4, 8]
    assert pick_bucket(1, buckets) == 1
    assert pick_bucket(2, buckets) == 2
    assert pick_bucket(3, buckets) == 4          # boundary + 1 pads up
    assert pick_bucket(4, buckets) == 4          # exact bucket, no pad
    assert pick_bucket(5, buckets) == 8
    assert pick_bucket(8, buckets) == 8
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        pick_bucket(9, buckets)
    with pytest.raises(ValueError, match=">= 1"):
        pick_bucket(0, buckets)


def test_pad_request_mask_correct():
    from t2omca_tpu.serve.frontend import pad_request
    rng = np.random.default_rng(0)
    a, d, na = 3, 5, 4
    obs = rng.standard_normal((3, a, d)).astype(np.float32)
    avail = rng.random((3, a, na)) < 0.5
    hidden = rng.standard_normal((3, a, 2)).astype(np.float32)
    po, pa, ph = pad_request(obs, avail, hidden, 8)
    assert po.shape == (8, a, d) and pa.shape == (8, a, na)
    assert ph.shape == (8, a, 2)
    # real rows untouched
    assert np.array_equal(po[:3], obs)
    assert np.array_equal(pa[:3], avail)
    assert np.array_equal(ph[:3], hidden)
    # pad rows: zero obs/hidden, avail legalizes ONLY action 0 (never
    # an all-unavailable row — masked argmax stays well-defined)
    assert not po[3:].any() and not ph[3:].any()
    assert pa.dtype == np.bool_
    assert pa[3:, :, 0].all() and not pa[3:, :, 1:].any()
    # exact-bucket batches pass through without a copy
    o2, a2, h2 = pad_request(obs, avail.astype(np.bool_), hidden, 3)
    assert o2 is obs and h2 is hidden


def test_session_store_carries_and_evicts():
    from t2omca_tpu.serve.frontend import SessionStore

    class _FakeHub:
        def __init__(self):
            self.counts = {}

        def inc(self, name, delta=1.0, **labels):
            self.counts[name] = self.counts.get(name, 0) + delta

        def set(self, name, value, **labels):
            pass

    class _FakeFrontend:
        n_agents, emb = 2, 4

        def __init__(self):
            self.seen_hidden = []
            self._hub = _FakeHub()

        def select(self, obs, avail, hidden=None):
            self.seen_hidden.append(np.array(hidden))
            n = np.asarray(obs).shape[0]
            # new hidden = old + 1 so carry is observable
            return (np.zeros((n, 2), np.int32), hidden + 1.0)

    fe = _FakeFrontend()
    store = SessionStore(fe, max_sessions=2)
    obs1 = np.zeros((2, 2, 3), np.float32)
    avail1 = np.ones((2, 2, 5), np.bool_)
    _, fresh = store.select(["a", "b"], obs1, avail1)
    assert not fe.seen_hidden[0].any()           # fresh sessions: zeros
    assert fresh.dtype == np.bool_ and fresh.all()
    _, fresh = store.select(["a", "b"], obs1, avail1)
    assert (fe.seen_hidden[1] == 1.0).all()      # carried hidden
    assert not fresh.any()                       # both carries live
    # LRU eviction at max_sessions=2: "a"/"b" touched, "c" pushes out
    # the least recently used ("a" after "b" re-touch below)
    store.select(["b"], obs1[:1], avail1[:1])
    store.select(["c"], obs1[:1], avail1[:1])
    assert len(store) == 2
    assert store.evicted == 1                    # "a" silently dropped...
    assert fe._hub.counts["serve_session_evicted"] == 1   # ...NOT silently
    # the eviction sentinel: "a" believes it is live, fresh=True says
    # its carry is gone and it restarted from zeros mid-conversation
    _, fresh = store.select(["a"], obs1[:1], avail1[:1])
    assert not fe.seen_hidden[-1].any()
    assert fresh.all()
    assert store.evicted == 2                    # re-adding "a" evicted "b"
    assert fe._hub.counts["serve_session_evicted"] == 2
    store.end("c")
    assert len(store) == 1                       # just the re-added "a"
    with pytest.raises(ValueError, match="session ids"):
        store.select(["a"], obs1, avail1)


def _stub_frontend(buckets=(1, 2, 4), a=3, d=5, na=4, emb=8):
    """A ServeFrontend over fake compiled steps: real host logic
    (validate → chunk → pad → dispatch → unpad), zero jit."""
    from t2omca_tpu.obs.spans import NULL_RECORDER
    from t2omca_tpu.serve.frontend import ServeFrontend
    meta = {"buckets": list(buckets), "n_agents": a, "obs_dim": d,
            "n_actions": na, "emb": emb}
    fe = ServeFrontend("/nonexistent", meta, mac=None, params=None,
                       dtype="float32", use_exported=False,
                       rec=NULL_RECORDER)
    dispatched = []

    def fake_step(params, obs, avail, hidden):
        n = obs.shape[0]
        dispatched.append(n)
        # actions: lowest legal action; hidden: +1 so carry/stitching
        # mistakes are observable per row
        acts = np.argmax(avail, axis=-1).astype(np.int32)
        return acts, hidden + 1.0

    fe._steps = {b: fake_step for b in buckets}
    return fe, dispatched


def test_frontend_validate_rejects_malformed_requests():
    fe, dispatched = _stub_frontend()
    good_obs = np.zeros((2, 3, 5), np.float32)
    good_avail = np.ones((2, 3, 4), np.bool_)
    with pytest.raises(ValueError, match="obs must be"):
        fe.select(np.zeros((2, 3), np.float32), good_avail)   # ndim
    with pytest.raises(ValueError, match="obs must be"):
        fe.select(np.zeros((2, 3, 6), np.float32), good_avail)  # obs_dim
    with pytest.raises(ValueError, match="avail must be"):
        fe.select(good_obs, np.ones((2, 3, 5), np.bool_))     # n_actions
    with pytest.raises(ValueError, match="avail must be"):
        fe.select(good_obs, np.ones((3, 3, 4), np.bool_))     # row count
    with pytest.raises(ValueError, match="hidden must be"):
        fe.select(good_obs, good_avail,
                  np.zeros((2, 3, 7), np.float32))            # emb
    with pytest.raises(ValueError, match="hidden must be"):
        fe.select(good_obs, good_avail,
                  np.zeros((1, 3, 8), np.float32))            # row count
    # a rejected request dispatched NOTHING (validation precedes pad)
    assert dispatched == []


def test_frontend_chunks_ragged_bursts_past_max_bucket():
    """Ragged burst schedule straddling the max bucket: every dispatch
    lands on a compiled bucket shape (never above bmax), and the
    stitched outputs keep per-row order and carried hidden across the
    chunk seams."""
    from t2omca_tpu.serve.frontend import pick_bucket
    fe, dispatched = _stub_frontend(buckets=(1, 2, 4))
    rng = np.random.default_rng(9)
    for n in (7, 4, 9, 1, 5, 13, 3):         # ragged, mostly > bmax=4
        obs = rng.standard_normal((n, 3, 5)).astype(np.float32)
        avail = rng.random((n, 3, 4)) < 0.5
        avail[..., 0] = True
        del dispatched[:]
        # per-row-distinct hidden: a chunk-seam row swap would show
        hidden_in = rng.standard_normal((n, 3, 8)).astype(np.float32)
        actions, hidden = fe.select(obs, avail, hidden_in)
        # every dispatch is a compiled bucket, none above the max
        assert all(b in (1, 2, 4) for b in dispatched), dispatched
        # chunk cover: full chunks of bmax + one bucketed remainder
        want = [4] * (n // 4)
        if n % 4:
            want.append(pick_bucket(n % 4, [1, 2, 4]))
        assert dispatched == want, (n, dispatched)
        # stitched per-row: action = first legal action of that row
        np.testing.assert_array_equal(
            actions, np.argmax(avail, axis=-1).astype(np.int32),
            err_msg=f"n={n}")
        np.testing.assert_array_equal(hidden, hidden_in + 1.0,
                                      err_msg=f"n={n}")


# ---------------------------------------------------------------------------
# atomic artifact writes (satellite: torn-write safety for binary blobs)
# ---------------------------------------------------------------------------


def test_write_bytes_atomic_survives_torn_write(tmp_path, monkeypatch):
    from t2omca_tpu.utils.ioutil import write_bytes_atomic
    target = tmp_path / "params.msgpack"
    write_bytes_atomic(str(target), b"v1-good")
    assert target.read_bytes() == b"v1-good"
    # a crash between tmp write and publish must leave the OLD blob
    # intact and no tmp litter for the next export to trip on
    real_replace = os.replace

    def torn(src, dst):
        raise OSError("injected: crash before publish")

    monkeypatch.setattr(os, "replace", torn)
    with pytest.raises(OSError, match="crash before publish"):
        write_bytes_atomic(str(target), b"v2-half-written")
    monkeypatch.setattr(os, "replace", real_replace)
    assert target.read_bytes() == b"v1-good"     # old blob untouched
    assert os.listdir(tmp_path) == ["params.msgpack"]   # no tmp leftovers
    # and a clean retry publishes
    write_bytes_atomic(str(target), b"v2-good")
    assert target.read_bytes() == b"v2-good"


def test_export_writes_no_raw_binary_handles():
    """Source pin for the atomic-write satellite: serve/export.py must
    route EVERY write through the atomic helpers (tmp + fsync + rename)
    — a raw ``open(..., "wb")`` write would reintroduce the torn-blob
    window the sha256 check can only detect, not prevent."""
    src_path = os.path.join(REPO, "t2omca_tpu", "serve", "export.py")
    with open(src_path) as f:
        src = f.read()
    assert '"wb"' not in src and "'wb'" not in src
    assert "write_bytes_atomic" in src and "write_json_atomic" in src


def test_train_config_dict_roundtrip():
    from t2omca_tpu.config import EnvConfig, ModelConfig, TrainConfig, \
        from_dict, sanity_check
    cfg = sanity_check(TrainConfig(
        batch_size_run=4, superstep=2,
        env_args=EnvConfig(agv_num=5, episode_limit=9),
        model=ModelConfig(emb=16, heads=2, mixer_emb=16, dtype="bfloat16")))
    back = from_dict(dataclasses.asdict(cfg))
    assert back == cfg


def test_serve_phases_registered_and_spanned():
    """GL110 contract for the serving boundaries: every literal phase
    the serve modules record is in KNOWN_PHASES, and the front-end's
    three request stages are all present (an unregistered phase would
    be a serving boundary with no flight/report coverage)."""
    from t2omca_tpu.obs.spans import KNOWN_PHASES
    from test_obs import _literal_phases
    phases = set()
    for mod in ("frontend.py", "export.py"):
        phases |= _literal_phases(
            os.path.join(REPO, "t2omca_tpu", "serve", mod),
            fn_names=("_watched",))
    assert {"serve.load", "serve.pad", "serve.dispatch",
            "serve.unpad", "serve.export"} <= phases
    assert phases <= KNOWN_PHASES, phases - KNOWN_PHASES
    # the report CLI maps the dispatch span onto the ratcheted program
    from t2omca_tpu.obs.report import PHASE_PROGRAMS
    assert PHASE_PROGRAMS["serve.dispatch"] == "serve_step"


def test_serve_cli_usage_errors(tmp_path, capsys):
    from t2omca_tpu.serve.__main__ import main
    # export against an empty checkpoint dir: clean exit 2, no artifact
    out = tmp_path / "art"
    rc = main(["export", str(tmp_path / "nothing"), "--out", str(out)])
    assert rc == 2
    assert "no valid checkpoint" in capsys.readouterr().err
    assert not out.exists()
    # info on a non-artifact dir
    rc = main(["info", str(tmp_path)])
    assert rc == 2
    assert "unreadable artifact" in capsys.readouterr().err
    # stray non-override positional
    with pytest.raises(SystemExit):
        main(["export", "ckpt", "not-an-override"])
    # overrides only make sense for export
    with pytest.raises(SystemExit):
        main(["info", str(tmp_path), "a=b"])


# ---------------------------------------------------------------------------
# export → load → serve round-trip (slow: Experiment build + compiles)
# ---------------------------------------------------------------------------


def _tiny_cfg():
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    return sanity_check(TrainConfig(
        batch_size_run=4, batch_size=4,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8)))


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """One smoke checkpoint + exported artifact shared by the slow
    round-trip tests (the export compiles 2 dtypes × 3 buckets)."""
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.serve.export import export_artifact
    from t2omca_tpu.utils.checkpoint import save_checkpoint
    root = tmp_path_factory.mktemp("serve")
    cfg = _tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    ck = os.path.join(root, "models")
    save_checkpoint(ck, 128, ts)
    art = os.path.join(root, "art")
    meta = export_artifact(cfg, ck, art, buckets=(1, 2, 4))
    return cfg, exp, ts, art, meta


@pytest.mark.slow
def test_export_artifact_layout_and_provenance(exported):
    cfg, exp, ts, art, meta = exported
    assert meta["format"] == 1
    assert meta["checkpoint"]["t_env"] == 128
    assert meta["checkpoint"]["state_sha256"]      # provenance chain
    assert meta["buckets"] == [1, 2, 4]
    assert meta["folded"] == exp.mac.use_qslice
    for dt in ("float32", "bfloat16"):
        p = meta["params"][dt]
        assert os.path.isfile(os.path.join(art, p["file"]))
        for b in (1, 2, 4):
            entry = meta["programs"][dt][str(b)]
            assert entry["fingerprint"]
            assert os.path.isfile(os.path.join(art, entry["file"]))
    # bf16 variant actually halves the big leaves
    assert (meta["params"]["bfloat16"]["bytes"]
            < 0.75 * meta["params"]["float32"]["bytes"])
    # export-time compiles populated the warm-start cache
    assert os.listdir(os.path.join(art, "compile_cache"))


@pytest.mark.slow
def test_serve_bit_parity_with_training_select_actions(exported):
    """The K=1-parity convention for serving: f32 greedy actions from
    the exported artifact bit-match the training path's
    ``select_actions(test_mode=True)``, with the recurrent hidden
    carried across requests, at ragged sizes incl. batch=1, a
    bucket-boundary size, and a beyond-max-bucket batch (chunking)."""
    import jax
    import jax.numpy as jnp
    from t2omca_tpu.serve.frontend import ServeFrontend
    cfg, exp, ts, art, meta = exported
    fe = ServeFrontend.load(art, dtype="float32")
    mac = exp.mac
    env_info = exp.env.get_env_info()
    a, d, na = mac.n_agents, env_info["obs_shape"], env_info["n_actions"]
    params = jax.device_put(
        mac.prepare_acting_params(ts.learner.params["agent"]))
    sel = jax.jit(lambda p, o, av, h, k: mac.select_actions(
        p, o, av, h, k, jnp.asarray(10_000), test_mode=True))
    rng = np.random.default_rng(7)
    for n in (1, 3, 4, 7):       # batch=1, boundary+1, exact, > max bucket
        h_ref = np.zeros((n, a, mac.emb), np.float32)
        h_fe = None
        for step in range(3):    # hidden carried across request steps
            obs = rng.standard_normal((n, a, d)).astype(np.float32)
            avail = rng.random((n, a, na)) < 0.7
            avail[..., 0] = True
            a_ref, h2, _ = sel(params, obs, avail.astype(np.int32),
                               h_ref, jax.random.PRNGKey(step))
            a_fe, h_fe = fe.select(obs, avail, h_fe)
            np.testing.assert_array_equal(np.asarray(a_ref), a_fe,
                                          err_msg=f"n={n} step={step}")
            np.testing.assert_array_equal(
                np.asarray(h2, dtype=np.float32), h_fe,
                err_msg=f"hidden n={n} step={step}")
            h_ref = np.asarray(h2)


@pytest.mark.slow
def test_serve_bf16_variant_within_tolerance(exported):
    """The bf16 param variant tracks the f32 serve outputs within the
    established bf16 tolerance (tests/test_bf16.py convention) on the
    carried hidden; actions may flip on near-ties, so the pin is the
    representation, not the argmax."""
    from t2omca_tpu.serve.frontend import ServeFrontend
    cfg, exp, ts, art, meta = exported
    fe32 = ServeFrontend.load(art, dtype="float32")
    fe16 = ServeFrontend.load(art, dtype="bfloat16")
    a, d = fe32.n_agents, fe32.obs_dim
    rng = np.random.default_rng(3)
    obs = rng.standard_normal((4, a, d)).astype(np.float32)
    avail = np.ones((4, a, fe32.n_actions), np.bool_)
    _, h32 = fe32.select(obs, avail)
    _, h16 = fe16.select(obs, avail)
    np.testing.assert_allclose(h16, h32, atol=0.15, rtol=0.15)


@pytest.mark.slow
def test_serve_warm_dispatch_never_retraces(exported):
    """Warm-path pin (compile_budget): after warm-up, repeated serving
    at any bucket — including ragged sizes padding into it and carried
    hidden fed back — compiles NOTHING. The aval-stability contract
    that makes AOT serving AOT."""
    from t2omca_tpu.analysis.guards import compile_budget
    from t2omca_tpu.serve.frontend import ServeFrontend
    cfg, exp, ts, art, meta = exported
    fe = ServeFrontend.load(art, dtype="float32")
    fe.warmup()
    a, d, na = fe.n_agents, fe.obs_dim, fe.n_actions
    rng = np.random.default_rng(1)
    hidden = None
    with compile_budget(0):
        for n in (1, 2, 3, 4, 4):
            obs = rng.standard_normal((n, a, d)).astype(np.float32)
            avail = np.ones((n, a, na), np.bool_)
            _, h = fe.select(obs, avail, hidden)
            hidden = h if n == 4 else None


@pytest.mark.slow
def test_serve_compile_cache_warms_fresh_process(exported):
    """Cache semantics (docs/SERVING.md): a FRESH serving process
    loading the artifact hits the persistent compile cache the export
    wrote — pinned by running a loader subprocess and asserting the
    cache gained no new entries (a cold miss would write one) while
    producing actions identical to this process's."""
    cfg, exp, ts, art, meta = exported
    from t2omca_tpu.serve.frontend import ServeFrontend
    fe = ServeFrontend.load(art, dtype="float32")
    a, d, na = fe.n_agents, fe.obs_dim, fe.n_actions
    rng = np.random.default_rng(5)
    obs = rng.standard_normal((2, a, d)).astype(np.float32)
    avail = np.ones((2, a, na), np.bool_)
    ours, _ = fe.select(obs, avail)
    cache = os.path.join(art, "compile_cache")
    before = set(os.listdir(cache))
    code = (
        "import numpy as np, json, sys\n"
        "from t2omca_tpu.serve.frontend import ServeFrontend\n"
        f"fe = ServeFrontend.load({art!r}, dtype='float32')\n"
        f"rng = np.random.default_rng(5)\n"
        f"obs = rng.standard_normal((2, {a}, {d})).astype(np.float32)\n"
        f"avail = np.ones((2, {a}, {na}), bool)\n"
        "actions, _ = fe.select(obs, avail)\n"
        "print(json.dumps(actions.tolist()))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    theirs = np.asarray(json.loads(proc.stdout.strip().splitlines()[-1]))
    np.testing.assert_array_equal(ours, theirs)
    after = set(os.listdir(cache))
    # -atime sidecars may update; no NEW -cache entries = warm start
    new_entries = {f for f in after - before if f.endswith("-cache")}
    assert not new_entries, f"fresh process cold-compiled: {new_entries}"


# ---------------------------------------------------------------------------
# DP sharded resume (the serve exporter shares the host-restore path)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_dp_resume_restores_sharded_without_single_device_copy(tmp_path):
    """``load_checkpoint_sharded`` (ADVICE r5): restoring into the
    sharded abstract template is bit-identical to the classic
    load-then-shard sequence, leaf for leaf, sharding for sharding —
    and the restored state dispatches."""
    import jax
    from t2omca_tpu.parallel import DataParallel, make_mesh
    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import (load_checkpoint,
                                             load_checkpoint_sharded,
                                             save_checkpoint)
    cfg = _tiny_cfg().replace(dp_devices=2)
    exp = Experiment.build(cfg)
    dp = DataParallel(exp, make_mesh(2))
    ts = exp.init_train_state(0)
    save_checkpoint(str(tmp_path), 64, ts)
    d = os.path.join(str(tmp_path), "64")

    classic = dp.shard(load_checkpoint(d, exp.init_train_state(1)))
    shapes = jax.eval_shape(lambda: exp.init_train_state(1))
    sharded = load_checkpoint_sharded(d, shapes,
                                      dp.state_shardings(shapes))
    flat_c = jax.tree_util.tree_leaves_with_path(classic)
    flat_s = jax.tree_util.tree_leaves_with_path(sharded)
    assert len(flat_c) == len(flat_s)
    for (kp, lc), (_, ls) in zip(flat_c, flat_s):
        key = jax.tree_util.keystr(kp)
        assert lc.sharding == ls.sharding, key
        np.testing.assert_array_equal(np.asarray(jax.device_get(lc)),
                                      np.asarray(jax.device_get(ls)),
                                      err_msg=key)
    rollout, _, _ = dp.jitted_programs()
    _, batch, _ = rollout(sharded.learner.params["agent"],
                          sharded.runner, test_mode=False)
    assert len(jax.tree.leaves(batch.obs)[0].sharding.device_set) == 2


# ---------------------------------------------------------------------------
# bench + CLI e2e (slow: subprocesses)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_serve_record_schema(exported):
    """``bench.py --serve`` emits the BENCH-style record: p50/p99
    decision latency + decisions/s/chip + the serve span phases."""
    cfg, exp, ts, art, meta = exported
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--serve",
         "--artifact", art, "--iters", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_decisions_per_sec"
    assert rec["unit"] == "decisions/s/chip"
    assert rec["value"] > 0
    assert 0 < rec["p50_ms"] <= rec["p99_ms"]
    assert rec["buckets"] == meta["buckets"]
    assert 1 in rec["request_sizes"]             # batch=1 latency counted
    for phase in ("serve.load", "serve.pad", "serve.dispatch",
                  "serve.unpad"):
        assert phase in rec["spans"], rec["spans"].keys()


@pytest.mark.slow
def test_bench_serve_partial_record_on_failure(tmp_path):
    """A failing serve leg (bad artifact) still leaves ONE parseable
    partial record filed under the serve metric — the training legs'
    flight-recorder contract."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--serve",
         "--artifact", str(tmp_path / "missing")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["metric"] == "serve_decisions_per_sec"
    assert rec["value"] is None
    assert rec["error"]


@pytest.mark.slow
def test_serve_export_cli_and_info(exported, tmp_path):
    """The CLI surface end-to-end: export a second artifact from the
    shared checkpoint with overrides, then ``info`` summarizes it."""
    cfg, exp, ts, art, meta = exported
    ck = os.path.join(os.path.dirname(art), "models")
    out = str(tmp_path / "art2")
    overrides = [
        "batch_size_run=4", "batch_size=4",
        "env_args.agv_num=3", "env_args.mec_num=2",
        "env_args.num_channels=2", "env_args.episode_limit=6",
        "model.emb=8", "model.heads=2", "model.depth=1",
        "model.mixer_emb=8", "model.mixer_heads=2",
        "model.mixer_depth=1", "replay.buffer_size=8"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.serve", "export", ck,
         "--out", out, "--buckets", "1,2", "--dtypes", "float32",
         "--no-blobs", *overrides],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "artifact written" in proc.stdout
    proc = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.serve", "info", out],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "buckets: [1, 2]" in proc.stdout
    assert "params[float32]" in proc.stdout
    # --no-blobs artifacts still serve (config-rebuild fallback)
    from t2omca_tpu.serve.frontend import ServeFrontend
    fe = ServeFrontend.load(out, dtype="float32")
    a_out, _ = fe.select(
        np.zeros((2, fe.n_agents, fe.obs_dim), np.float32),
        np.ones((2, fe.n_agents, fe.n_actions), np.bool_))
    assert a_out.shape == (2, fe.n_agents)
