"""Data-parallel mesh tests on the 8-virtual-device CPU mesh
(SURVEY.md §4(5): 'distributed without a cluster')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.parallel import DataParallel, make_mesh
from t2omca_tpu.run import Experiment


@pytest.fixture(scope="module")
def dp_setup():
    assert len(jax.devices()) >= 8, "conftest must fake 8 devices"
    cfg = sanity_check(TrainConfig(
        batch_size_run=8, batch_size=8,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=5),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=16),
    ))
    exp = Experiment.build(cfg)
    mesh = make_mesh(8)
    dp = DataParallel(exp, mesh)
    ts = dp.shard(exp.init_train_state(0))
    return cfg, exp, dp, ts


def test_mesh_construction():
    mesh = make_mesh(8)
    assert mesh.shape == {"data": 8}


def test_divisibility_guard():
    cfg = sanity_check(TrainConfig(
        batch_size_run=3, batch_size=8,
        env_args=EnvConfig(agv_num=3, mec_num=2, episode_limit=5),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1)))
    exp = Experiment.build(cfg)
    with pytest.raises(ValueError, match="divisible"):
        DataParallel(exp, make_mesh(8))


@pytest.mark.slow   # double init + device_put of the ring (~30 s incl. fixture)
def test_init_sharded_equals_shard_of_init(dp_setup):
    """dp.init_sharded builds the state BORN sharded (jit out_shardings —
    no single-device full-ring transient at startup); it must be
    value-identical and placement-identical to shard(init_train_state)."""
    cfg, exp, dp, ts = dp_setup
    ts2 = dp.init_sharded(0)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ts),
            jax.tree_util.tree_leaves_with_path(ts2)):
        k = jax.tree_util.keystr(kp)
        assert a.sharding == b.sharding, (k, a.sharding, b.sharding)
        if "learner" in k:     # params/optimizer must be bit-identical
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=k)
        else:
            # env-reset math under jit fuses differently: rel ~1e-8
            # reassociation on a few env-state leaves (init_sharded doc)
            np.testing.assert_allclose(
                np.asarray(a).astype(np.float64),
                np.asarray(b).astype(np.float64),
                rtol=1e-6, atol=1e-3, err_msg=k)


@pytest.mark.slow   # DP program compiles (~20 s); the chained-compile test keeps mesh coverage in-gate
def test_sharded_rollout_and_train_step(dp_setup):
    cfg, exp, dp, ts = dp_setup
    rollout, insert, train_iter = dp.jitted_programs()

    rs, batch, stats = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
    # env lanes stay sharded across the data axis (obs is a
    # CompactEntityObs pytree under the default fast-path stack)
    obs_leaf = jax.tree.leaves(batch.obs)[0]
    assert obs_leaf.shape[0] == 8
    assert not obs_leaf.sharding.is_fully_replicated
    assert len(obs_leaf.sharding.device_set) == 8
    ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                    episode=ts.episode + cfg.batch_size_run)

    ts2, info = train_iter(ts, jax.random.PRNGKey(1), jnp.asarray(40))
    assert np.isfinite(float(info["loss"]))
    assert info["td_errors_abs"].shape == (cfg.batch_size,)
    # params remain replicated (grads were psum'd by GSPMD)
    leaf = jax.tree.leaves(ts2.learner.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_dp_chained_programs_compile_exactly_once(dp_setup):
    """Same single-compile pin as the single-chip variant
    (tests/test_driver.py), but over the mesh: the DataParallel output
    constraints must return every chained state at the exact placement
    ``shard`` gives its inputs, or iteration 2 runs a second
    differently-sharded executable."""
    cfg, exp, dp, ts = dp_setup
    rollout, insert, train_iter = dp.jitted_programs()
    key = jax.random.PRNGKey(3)
    t_env = 0
    for i in range(3):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
        t_env += cfg.batch_size_run * cfg.env_args.episode_limit
        ts, _ = train_iter(ts, jax.random.fold_in(key, i),
                           jnp.asarray(t_env))
    assert rollout._cache_size() == 1
    assert insert._cache_size() == 1
    assert train_iter._cache_size() == 1


@pytest.mark.slow   # single-device + DP train compiles (~26 s)
def test_dp_matches_single_device_loss(dp_setup):
    """The sharded loss equals the unsharded loss on identical inputs —
    the DP axis is arithmetic-neutral."""
    cfg, exp, dp, ts = dp_setup
    rollout, insert, train_iter = dp.jitted_programs()
    rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                           test_mode=False)
    w = jnp.ones((cfg.batch_size,))
    batch_local = jax.device_get(batch)
    batch_local = jax.tree.map(jnp.asarray, batch_local)

    _, info_dp = jax.jit(exp.learner.train)(
        ts.learner, batch, w, jnp.asarray(0), jnp.asarray(0))
    ls_local = jax.device_get(ts.learner)
    ls_local = jax.tree.map(jnp.asarray, ls_local)
    _, info_local = jax.jit(exp.learner.train)(
        ls_local, batch_local, w, jnp.asarray(0), jnp.asarray(0))
    np.testing.assert_allclose(float(info_dp["loss"]),
                               float(info_local["loss"]), rtol=2e-4)


def test_maybe_initialize_distributed_noop_single_host(monkeypatch):
    """Without a coordinator topology the helper must not touch the
    runtime (single-host runs unaffected)."""
    from t2omca_tpu.parallel import maybe_initialize_distributed
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert maybe_initialize_distributed() is False
