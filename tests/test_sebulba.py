"""Sebulba decoupled actor/learner loop (``run.run_sebulba``,
``parallel/sebulba.py``, ``config.sebulba``): disjoint actor/learner
device meshes with a device-resident trajectory queue.

Pins the ROADMAP-item-2 contract: the lockstep mode (queue_slots=1,
staleness=0) is BIT-identical to the classic K=1 three-program loop on
a forced multi-device CPU host (the DP test trick), the queue's
ring-of-slots wraparound is content-exact, backpressure bounds the
in-flight batches at queue_slots, the staleness bound serializes the
actor against the learner, and a wedged learner dispatch trips the
watchdog while the actor thread exits resumably (the chaos scenario)."""

import glob
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ObsConfig,
                               ReplayConfig, ResilienceConfig,
                               SebulbaConfig, TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment, run_sequential, sebulba_eligible
from t2omca_tpu.utils import resilience
from t2omca_tpu.utils.checkpoint import find_checkpoint, verify_checkpoint
from t2omca_tpu.utils.logging import Logger


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def tiny_cfg(tmp_path=None, **kw):
    """The test_superstep parity point (fast_norm off, dense storage)
    at test scale."""
    env_kw = kw.pop("env_kw", {})
    replay_kw = kw.pop("replay_kw", {})
    res_kw = kw.pop("res_kw", {})
    seb_kw = kw.pop("seb_kw", None)
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=False, save_model_interval=24,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False, **env_kw),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
        resilience=ResilienceConfig(**res_kw),
    )
    if seb_kw is not None:
        defaults["sebulba"] = SebulbaConfig(**seb_kw)
    if tmp_path is not None:
        defaults["local_results_path"] = str(tmp_path)
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y),
                              equal_nan=True) for x, y in zip(la, lb))


# ---------------------------------------------------------------- config

def test_sebulba_config_sanity():
    ok = tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1))
    assert sebulba_eligible(ok)
    assert not sebulba_eligible(tiny_cfg())
    with pytest.raises(ValueError, match="set together"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=0))
    with pytest.raises(ValueError, match="queue_slots"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1,
                             queue_slots=0))
    with pytest.raises(ValueError, match="staleness"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1,
                             staleness=-1))
    with pytest.raises(ValueError, match="buffer_cpu_only"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1),
                 replay_kw=dict(buffer_cpu_only=True, prioritized=True))
    with pytest.raises(ValueError, match="dp_devices"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1),
                 dp_devices=2)
    with pytest.raises(ValueError, match="superstep"):
        tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1),
                 superstep=4)
    with pytest.raises(ValueError, match="divisible"):
        tiny_cfg(seb_kw=dict(actor_devices=3, learner_devices=1))


def test_partition_devices_disjoint_and_bounded():
    from t2omca_tpu.parallel.mesh import partition_devices
    actor, learner = partition_devices(2, 2)
    assert len(actor) == 2 and len(learner) == 2
    assert not set(actor) & set(learner)
    with pytest.raises(ValueError, match="hint"):
        partition_devices(8, 8)
    with pytest.raises(ValueError, match=">= 1"):
        partition_devices(0, 2)


# ---------------------------------------------------------------- lockstep

def test_sebulba_lockstep_bit_identical_to_classic(tmp_path):
    """THE correctness anchor (ROADMAP item 2 / acceptance criterion):
    queue_slots=1 + staleness=0 on a 1+1 device split ends on EXACTLY
    the classic K=1 loop's train state — params, opt state, replay ring
    contents, PER priorities, runner state, episode counter — on the
    conftest-forced multi-device CPU host. test_interval=24 makes the
    test cadence fire MID-training (t_env 24, 48, ...) — the test
    rollouts consume runner-state keys and must see exactly the
    post-train params the classic loop's cadence sees, so a
    stale-params test rollout breaks this equality."""
    cfg_classic = tiny_cfg(tmp_path, test_interval=24)
    cfg_seb = tiny_cfg(tmp_path, test_interval=24, seb_kw=dict(
        actor_devices=1, learner_devices=1, queue_slots=1, staleness=0))
    ts1 = run_sequential(Experiment.build(cfg_classic), Logger(),
                         str(tmp_path / "classic"))
    ts2 = run_sequential(Experiment.build(cfg_seb), Logger(),
                         str(tmp_path / "sebulba"))
    h1, h2 = jax.device_get(ts1), jax.device_get(ts2)
    assert _leaves_equal(h1.learner, h2.learner)
    assert _leaves_equal(h1.buffer, h2.buffer)
    assert _leaves_equal(h1.runner, h2.runner)
    assert _leaves_equal(h1.episode, h2.episode)


# ---------------------------------------------------------------- queue

def _machinery(queue_slots, **cfg_kw):
    cfg = tiny_cfg(seb_kw=dict(actor_devices=1, learner_devices=1,
                               queue_slots=queue_slots), **cfg_kw)
    exp = Experiment.build(cfg)
    from t2omca_tpu.parallel.sebulba import make_sebulba
    seb = make_sebulba(exp)
    return cfg, exp, seb


def test_queue_wraparound_contents_match_direct_insert():
    """5 rollout batches through a 2-slot queue (slots reused: 0,1,0,1,0)
    must land in the replay ring exactly as direct ``insert_time_major``
    calls would — slot reuse can never leak one batch's episodes into
    another's ring slots, including across the ring's own wraparound
    (capacity 8, 10 episodes inserted)."""
    cfg, exp, seb = _machinery(queue_slots=2)
    actor_step, queue_put, queue_get, _ = seb.programs()
    rs, ls = seb.init_states(cfg.seed)
    q = seb.init_queue()
    params = seb.publish_params(ls.learner.params["agent"])

    # reference: the same emissions inserted directly (no queue)
    ref_buf = jax.device_get(ls.buffer)
    ref_buf = jax.tree.map(jnp.asarray, ref_buf)
    tms = []
    rs_ref = rs
    for _ in range(5):
        rs_ref, tm, _ = actor_step(params, rs_ref, test_mode=False)
        tms.append(tm)
        ref_buf = exp.buffer.insert_time_major(ref_buf,
                                               jax.device_get(tm))

    # through the queue, slots cycling 0,1,0,1,0
    for i, tm in enumerate(tms):
        slot = jnp.asarray(i % 2, jnp.int32)
        q = queue_put(q, slot, seb.to_learner(tm))
        ls, q = queue_get(ls, q, slot)

    got = jax.device_get(ls.buffer)
    want = jax.device_get(ref_buf)
    assert _leaves_equal(got.storage, want.storage)
    assert int(got.insert_pos) == int(want.insert_pos)
    assert int(got.episodes_in_buffer) == int(want.episodes_in_buffer)
    np.testing.assert_array_equal(np.asarray(got.priorities),
                                  np.asarray(want.priorities))


@pytest.mark.slow   # threaded producer/consumer with real dispatches
def test_queue_backpressure_bounds_inflight_batches():
    """SPSC discipline: with a deliberately slow consumer the producer
    must block at queue_slots in-flight batches (never overwrite an
    unconsumed slot), and with a slow producer the consumer must block
    at empty — every batch is produced and consumed exactly once."""
    cfg, exp, seb = _machinery(queue_slots=2)
    actor_step, queue_put, queue_get, _ = seb.programs()
    rs, ls = seb.init_states(cfg.seed)
    q = seb.init_queue()
    params = seb.publish_params(ls.learner.params["agent"])
    n = 6
    cond = threading.Condition()
    shared = {"q": q, "put": 0, "got": 0, "max_depth": 0, "error": None}

    def producer(rs=rs):
        try:
            for i in range(n):
                rs, tm, stats = actor_step(params, rs, test_mode=False)
                jax.block_until_ready(stats.epsilon)
                tm_l = seb.to_learner(tm)
                with cond:
                    while shared["put"] - shared["got"] >= 2:
                        cond.wait(5.0)
                    shared["q"] = queue_put(
                        shared["q"],
                        jnp.asarray(shared["put"] % 2, jnp.int32), tm_l)
                    shared["put"] += 1
                    shared["max_depth"] = max(shared["max_depth"],
                                              shared["put"] - shared["got"])
                    cond.notify_all()
        except Exception as e:  # noqa: BLE001 — surfaced below
            with cond:
                shared["error"] = e
                cond.notify_all()

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    nonempty_waits = 0
    for i in range(n):
        time.sleep(0.15)                  # slow consumer: queue fills
        with cond:
            while shared["put"] <= i and shared["error"] is None:
                nonempty_waits += 1
                cond.wait(5.0)
            assert shared["error"] is None, shared["error"]
            ls, shared["q"] = queue_get(ls, shared["q"],
                                        jnp.asarray(i % 2, jnp.int32))
            shared["got"] = i + 1
            cond.notify_all()
    th.join(timeout=30)
    assert not th.is_alive()
    assert shared["put"] == shared["got"] == n
    # the slow consumer made the producer hit (and respect) the bound
    assert shared["max_depth"] == 2
    assert int(jax.device_get(ls.buffer.episodes_in_buffer)) == \
        min(n * cfg.batch_size_run, cfg.replay.buffer_size)


# ---------------------------------------------------------------- staleness

@pytest.mark.slow
def test_staleness_bound_serializes_actor_against_learner(tmp_path):
    """staleness=0 forbids rollout/train overlap: with a slowed learner
    dispatch, no ``actor.dispatch`` span may overlap any
    ``learner.dispatch`` span in time. staleness=2 on the same config
    must overlap (that is what the knob buys) — both read from the
    spans.jsonl telemetry of real driver runs."""
    def spans_of(run_dir, phase):
        out = []
        with open(os.path.join(run_dir, "spans.jsonl")) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") == "span" and ev.get("phase") == phase:
                    t0 = float(ev["t0"])
                    out.append((t0, t0 + ev["wall_ms"] / 1e3))
        return out

    def max_overlap_s(a_spans, b_spans):
        """Largest pairwise interval overlap — thresholded by the
        callers, because span t0 has millisecond resolution and its
        wall clock is a different clock than perf_counter, so adjacent
        intervals can spuriously 'overlap' by a few ms on a loaded
        box (and genuine overlaps under the 0.3s learner sleep are
        two orders of magnitude larger)."""
        return max((min(a1, b1) - max(a0, b0)
                    for a0, a1 in a_spans for b0, b1 in b_spans),
                   default=0.0)

    def run_with(staleness, name):
        # slow BOTH phases (the hooks fire inside the spans): the tiny
        # config's warm rollout is ~2 ms against a ~300 ms train, so
        # without the actor-side sleep the overlap window is
        # structurally microscopic even when overlap is allowed — with
        # both sides at hundreds of ms, allowed overlap is macroscopic
        # and forbidden overlap stays zero
        resilience.clear_faults()
        resilience.register_fault(
            "actor.dispatch", lambda **kw: time.sleep(0.25))
        resilience.register_fault(
            "learner.dispatch", lambda **kw: time.sleep(0.2))
        cfg = tiny_cfg(tmp_path, t_max=120,
                       obs=ObsConfig(enabled=True),
                       seb_kw=dict(actor_devices=1, learner_devices=1,
                                   queue_slots=4, staleness=staleness))
        run_dir = str(tmp_path / name)
        run_sequential(Experiment.build(cfg), Logger(), run_dir)
        return (spans_of(run_dir, "actor.dispatch"),
                spans_of(run_dir, "learner.dispatch"))

    actor0, learner0 = run_with(0, "lockstep")
    assert actor0 and learner0
    assert max_overlap_s(actor0, learner0) < 0.025, \
        "staleness=0 must serialize rollouts against train dispatches"
    actor2, learner2 = run_with(2, "overlapped")
    assert actor2 and learner2
    assert max_overlap_s(actor2, learner2) > 0.05, \
        "staleness=2 with a slow learner must overlap the phases"


# ---------------------------------------------------------------- chaos

@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.faultinject
def test_chaos_wedged_learner_trips_watchdog_actor_exits_resumable(
        tmp_path):
    """The tentpole's chaos scenario: a wedged learner dispatch (well
    past ``dispatch_timeout``) fires the watchdog — stall diagnosis on
    disk, guard tripped — while the ACTOR thread exits cleanly, and the
    run ends RESUMABLE: a verified checkpoint exists and a fresh
    fault-free driver resumes it to t_max."""
    hang = {"fired": False}

    def wedge(t_env=0, attempt=1, **kw):
        # one wedge, after the phase is warm (the compile exemption
        # means the FIRST occurrence is unbounded by design)
        if t_env >= 36 and not hang["fired"]:
            hang["fired"] = True
            time.sleep(3.0)

    resilience.register_fault("learner.dispatch", wedge)
    cfg = tiny_cfg(
        tmp_path, t_max=120, save_model=True, save_model_interval=12,
        seb_kw=dict(actor_devices=1, learner_devices=1, queue_slots=2,
                    staleness=1),
        res_kw=dict(dispatch_timeout=0.75, stall_grace_s=0.0,
                    emergency_checkpoint=True))
    run_sequential(Experiment.build(cfg), Logger(), str(tmp_path / "r"))
    assert hang["fired"]

    # the watchdog fired and left its diagnosis
    model_dirs = glob.glob(os.path.join(str(tmp_path), "models", "*"))
    assert model_dirs
    diag_path = os.path.join(model_dirs[0], "stall_diagnosis.json")
    assert os.path.exists(diag_path)
    with open(diag_path) as f:
        diag = json.load(f)
    assert diag["phase"] == "learner.dispatch"

    # the actor thread exited (no lingering producer)
    assert not any(t.name == "t2omca-sebulba-actor" and t.is_alive()
                   for t in threading.enumerate())

    # resumable: a verified checkpoint + a fault-free resume to t_max
    found = find_checkpoint(model_dirs[0])
    assert found is not None
    assert verify_checkpoint(found[0])
    resilience.clear_faults()
    cfg2 = cfg.replace(checkpoint_path=model_dirs[0],
                       resilience=ResilienceConfig())
    ts = run_sequential(Experiment.build(cfg2), Logger(),
                        str(tmp_path / "resume"))
    assert int(jax.device_get(ts.episode)) > 0
    assert int(jax.device_get(ts.runner.t_env)) >= 0  # completed cleanly
