"""Welford normalizer + reward scaling: quirk-level parity with the reference
``normalization.py`` (C2), verified against a direct NumPy transcription."""

import jax.numpy as jnp
import numpy as np

from t2omca_tpu.envs.normalization import (NormState, RewardScaleState,
                                           normalize, reset_reward_scale,
                                           scale_reward, welford_update)


class NumpyOracle:
    """Independent transcription of reference RunningMeanStd semantics."""

    def __init__(self, dim):
        self.n, self.mean, self.S = 0, np.zeros(dim), np.zeros(dim)
        self.std = np.zeros(dim)

    def update(self, x):
        x = np.asarray(x, float)
        self.n += 1
        if self.n == 1:
            self.mean, self.std = x.copy(), x.copy()   # Q5
        else:
            old = self.mean.copy()
            self.mean = old + (x - old) / self.n
            self.S = self.S + (x - old) * (x - self.mean)
            self.std = np.sqrt(self.S / self.n)

    def norm(self, x, update=True):
        if update:
            self.update(x)
        return (x - self.mean) / (self.std + 1e-8)


def test_welford_matches_oracle_sequence():
    rng = np.random.default_rng(0)
    xs = rng.normal(3.0, 2.0, size=(50, 4)).astype(np.float32)
    oracle = NumpyOracle(4)
    st = NormState.create(4)
    for x in xs:
        st, y = normalize(st, jnp.asarray(x))
        y_ref = oracle.norm(x)
        np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st.mean), oracle.mean, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st.std), oracle.std, rtol=1e-4)


def test_first_sample_quirk_q5():
    st = NormState.create(3)
    x = jnp.asarray([2.0, -1.0, 5.0])
    st, y = normalize(st, x)
    # first sample: std = x, mean = x -> normalized output exactly 0
    np.testing.assert_allclose(np.asarray(st.std), np.asarray(x))
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-6)


def test_no_update_path_q4():
    st = NormState.create(2)
    st, _ = normalize(st, jnp.asarray([1.0, 2.0]))
    st2, _ = normalize(st, jnp.asarray([5.0, 5.0]), update=False)
    assert int(st2.n) == int(st.n)
    np.testing.assert_allclose(np.asarray(st2.mean), np.asarray(st.mean))


def test_reward_scaling_matches_oracle():
    rng = np.random.default_rng(1)
    rs = RewardScaleState.create(gamma=0.9, dim=1)
    R, o = np.zeros(1), NumpyOracle(1)
    for r in rng.normal(size=20).astype(np.float32):
        rs, y = scale_reward(rs, jnp.asarray([r]))
        R = 0.9 * R + r
        o.update(R)
        np.testing.assert_allclose(np.asarray(y), r / (o.std + 1e-8),
                                   rtol=1e-4, atol=1e-5)
    rs = reset_reward_scale(rs)
    np.testing.assert_allclose(np.asarray(rs.r), 0.0)
