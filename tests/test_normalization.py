"""Welford normalizer + reward scaling: quirk-level parity with the reference
``normalization.py`` (C2), verified against a direct NumPy transcription."""

import jax.numpy as jnp
import numpy as np

from t2omca_tpu.envs.normalization import (NormState, RewardScaleState,
                                           normalize, normalize_batch,
                                           reset_reward_scale, scale_reward,
                                           welford_update,
                                           welford_update_batch)


class NumpyOracle:
    """Independent transcription of reference RunningMeanStd semantics."""

    def __init__(self, dim):
        self.n, self.mean, self.S = 0, np.zeros(dim), np.zeros(dim)
        self.std = np.zeros(dim)

    def update(self, x):
        x = np.asarray(x, float)
        self.n += 1
        if self.n == 1:
            self.mean, self.std = x.copy(), x.copy()   # Q5
        else:
            old = self.mean.copy()
            self.mean = old + (x - old) / self.n
            self.S = self.S + (x - old) * (x - self.mean)
            self.std = np.sqrt(self.S / self.n)

    def norm(self, x, update=True):
        if update:
            self.update(x)
        return (x - self.mean) / (self.std + 1e-8)


def test_welford_matches_oracle_sequence():
    rng = np.random.default_rng(0)
    xs = rng.normal(3.0, 2.0, size=(50, 4)).astype(np.float32)
    oracle = NumpyOracle(4)
    st = NormState.create(4)
    for x in xs:
        st, y = normalize(st, jnp.asarray(x))
        y_ref = oracle.norm(x)
        np.testing.assert_allclose(np.asarray(y), y_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st.mean), oracle.mean, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(st.std), oracle.std, rtol=1e-4)


def test_first_sample_quirk_q5():
    st = NormState.create(3)
    x = jnp.asarray([2.0, -1.0, 5.0])
    st, y = normalize(st, x)
    # first sample: std = x, mean = x -> normalized output exactly 0
    np.testing.assert_allclose(np.asarray(st.std), np.asarray(x))
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-6)


def test_no_update_path_q4():
    st = NormState.create(2)
    st, _ = normalize(st, jnp.asarray([1.0, 2.0]))
    st2, _ = normalize(st, jnp.asarray([5.0, 5.0]), update=False)
    assert int(st2.n) == int(st.n)
    np.testing.assert_allclose(np.asarray(st2.mean), np.asarray(st.mean))


def test_batched_welford_stats_match_sequential():
    """The order-free batched merge (fast_norm path) must produce the SAME
    running statistics as A sequential updates once n >= 1 (Chan's combine
    telescopes); starting from n == 0 it skips only the Q5 std quirk."""
    rng = np.random.default_rng(2)
    a, dim = 8, 5
    st_seq = NormState.create(dim)
    st_bat = NormState.create(dim)
    for step in range(12):
        xs = rng.normal(2.0, 3.0, size=(a, dim)).astype(np.float32)
        for x in xs:
            st_seq = welford_update(st_seq, jnp.asarray(x))
        st_bat = welford_update_batch(st_bat, jnp.asarray(xs))
        assert int(st_bat.n) == int(st_seq.n) == a * (step + 1)
        np.testing.assert_allclose(np.asarray(st_bat.mean),
                                   np.asarray(st_seq.mean), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(st_bat.s),
                                   np.asarray(st_seq.s), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(st_bat.std),
                                   np.asarray(st_seq.std), rtol=1e-4,
                                   atol=1e-5)


def test_batched_normalize_converges_to_sequential():
    """Normalized outputs: each agent sees post-merge stats instead of its
    own prefix — an O(A/n) transient. After warm-up the two paths must agree
    to tight tolerance (the fast_norm equivalence contract)."""
    rng = np.random.default_rng(3)
    a, dim = 8, 5
    st_seq = NormState.create(dim)
    st_bat = NormState.create(dim)
    max_dev = []
    for step in range(60):
        xs = jnp.asarray(rng.normal(1.0, 2.0, size=(a, dim)).astype(np.float32))
        ys_seq = []
        for x in xs:
            st_seq, y = normalize(st_seq, x)
            ys_seq.append(np.asarray(y))
        st_bat, ys_bat = normalize_batch(st_bat, xs)
        max_dev.append(np.abs(np.stack(ys_seq) - np.asarray(ys_bat)).max())
    # deviation decays roughly as A/n: late-phase obs agree tightly
    assert max_dev[-1] < 0.02, max_dev[-5:]
    assert np.mean(max_dev[-10:]) < np.mean(max_dev[:10])


def test_batched_normalize_no_update_path():
    st = NormState.create(2)
    st = welford_update_batch(st, jnp.ones((4, 2)) * jnp.asarray([1.0, 2.0]))
    st2, _ = normalize_batch(st, jnp.full((4, 2), 9.0), update=False)
    assert int(st2.n) == int(st.n)
    np.testing.assert_allclose(np.asarray(st2.mean), np.asarray(st.mean))


def test_reward_scaling_matches_oracle():
    rng = np.random.default_rng(1)
    rs = RewardScaleState.create(gamma=0.9, dim=1)
    R, o = np.zeros(1), NumpyOracle(1)
    for r in rng.normal(size=20).astype(np.float32):
        rs, y = scale_reward(rs, jnp.asarray([r]))
        R = 0.9 * R + r
        o.update(R)
        np.testing.assert_allclose(np.asarray(y), r / (o.std + 1e-8),
                                   rtol=1e-4, atol=1e-5)
    rs = reset_reward_scale(rs)
    np.testing.assert_allclose(np.asarray(rs.r), 0.0)


def test_factored_batch_update_matches_materialized():
    """welford_update_batch_factored on (rows, mask) ≡ welford_update_batch
    on the materialized entity matrix, for fresh and warmed states."""
    import jax
    import jax.numpy as jnp
    from t2omca_tpu.envs.normalization import (
        NormState, welford_update_batch, welford_update_batch_factored)

    a, f = 5, 9
    key = jax.random.PRNGKey(0)
    rows = jax.random.uniform(key, (a, f - 1), minval=-2.0, maxval=2.0)
    mec = jax.random.randint(jax.random.fold_in(key, 1), (a,), 0, 2)
    same = mec[:, None] == mec[None, :]

    raw = jnp.where(same[:, :, None],
                    jnp.broadcast_to(rows[None], (a, a, f - 1)), 0.0)
    raw = jnp.concatenate([raw, jnp.eye(a)[:, :, None]], axis=2)
    raw = raw.reshape(a, a * f)

    for warm in (0, 3):
        st = NormState.create(a * f)
        for w in range(warm):
            st = welford_update_batch(
                st, jax.random.normal(jax.random.fold_in(key, 10 + w),
                                      (a, a * f)))
        direct = welford_update_batch(st, raw)
        factored = welford_update_batch_factored(st, rows, same)
        np.testing.assert_allclose(factored.mean, direct.mean,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(factored.s, direct.s,
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(factored.std, direct.std,
                                   rtol=1e-5, atol=1e-6)
        assert int(factored.n) == int(direct.n)
