"""Seeded-regression programs for the graftprog CLI tests.

Each program plants exactly one hazard class the auditor must catch
(ISSUE acceptance: every seeded regression flips ``python -m
t2omca_tpu.analysis --programs`` to exit 1 with the matching GP rule).
Loaded via ``--program-module tests/fixtures_graftprog.py``; everything
is abstract avals — nothing here ever executes.
"""


def register_audit_programs(ctx):
    import jax
    import jax.numpy as jnp

    from t2omca_tpu.analysis.registry import AuditProgram

    del ctx
    f32 = jnp.float32

    # GP201: `y` is marked donated but never flows to an output — XLA
    # cannot alias it, the buffer is silently copied (2x memory class)
    def _undonated(x, y):
        return x + 1.0 + 0.0 * jnp.sum(y) * 0.0

    # GP202: a (256, 256) f32 "weight" captured by closure — baked into
    # the program as a 256 KiB constant
    big = jnp.ones((256, 256), f32)

    def _baked(x):
        return x @ big

    # GP203: bf16 input upcast to f32 mid-program (the audit config's
    # compute dtype is bfloat16)
    def _upcast(x):
        return jnp.sum(x.astype(f32))

    # GP204: a host callback inside the program
    def _callback(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    # clean control: none of the rules fire
    def _clean(x):
        return x * 2.0

    vec = jax.ShapeDtypeStruct((8, 8), f32)
    return {
        "seeded_gp201": AuditProgram(
            jax.jit(_undonated, donate_argnums=(0, 1)),
            (vec, jax.ShapeDtypeStruct((3,), f32)),
            donate_argnums=(0, 1)),
        "seeded_gp202": AuditProgram(
            jax.jit(_baked), (jax.ShapeDtypeStruct((8, 256), f32),)),
        "seeded_gp203": AuditProgram(
            jax.jit(_upcast), (jax.ShapeDtypeStruct((16,), jnp.bfloat16),)),
        "seeded_gp204": AuditProgram(jax.jit(_callback), (vec,)),
        "seeded_clean": AuditProgram(
            jax.jit(_clean, donate_argnums=(0,)), (vec,),
            donate_argnums=(0,)),
    }
