"""Equivalence of the query-slice agent forward (ops/query_slice) with the
dense flax module.

The reduction is exact algebra (layer-0-pinned keys + token-0-only readout,
see ops/query_slice.py docstring), so forward outputs AND gradients must
match the dense ``TransformerAgent.apply`` up to float reassociation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import EnvConfig, ModelConfig, TrainConfig, sanity_check
from t2omca_tpu.controllers.basic_mac import BasicMAC
from t2omca_tpu.models.agent import TransformerAgent
from t2omca_tpu.ops.query_slice import agent_forward_qslice


def _build(emb=32, heads=2, depth=2, n_agents=3, n_entities=5, feat=9,
           n_actions=4, standard_heads=False, dtype=jnp.float32, seed=0):
    agent = TransformerAgent(
        n_agents=n_agents, n_entities=n_entities, feat_dim=feat, emb=emb,
        heads=heads, depth=depth, n_actions=n_actions,
        standard_heads=standard_heads, dtype=dtype)
    k = jax.random.PRNGKey(seed)
    kp, ko, kh = jax.random.split(k, 3)
    b = 4
    obs = jax.random.normal(ko, (b, n_agents, n_entities * feat))
    hidden = jax.random.normal(kh, (b, n_agents, emb))
    params = agent.init(kp, obs, hidden)
    return agent, params, obs, hidden


def _qslice(agent, params, obs, hidden):
    return agent_forward_qslice(
        params, obs, hidden, n_entities=agent.n_entities,
        feat_dim=agent.feat_dim, emb=agent.emb, heads=agent.heads,
        depth=agent.depth, n_actions=agent.n_actions,
        standard_heads=agent.standard_heads, dtype=agent.dtype)


@pytest.mark.parametrize("standard_heads", [False, True])
@pytest.mark.parametrize("depth", [1, 2, 3])
def test_forward_matches_dense(standard_heads, depth):
    agent, params, obs, hidden = _build(depth=depth,
                                        standard_heads=standard_heads)
    q_ref, h_ref = agent.apply(params, obs, hidden)
    q_qs, h_qs = _qslice(agent, params, obs, hidden)
    np.testing.assert_allclose(q_qs, q_ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_qs, h_ref, rtol=2e-4, atol=2e-5)


def test_forward_matches_dense_odd_shapes():
    # heads that don't divide emb (full-emb head mode), odd entity counts
    agent, params, obs, hidden = _build(emb=24, heads=3, n_entities=7,
                                        feat=11, n_actions=5)
    q_ref, h_ref = agent.apply(params, obs, hidden)
    q_qs, h_qs = _qslice(agent, params, obs, hidden)
    np.testing.assert_allclose(q_qs, q_ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_qs, h_ref, rtol=2e-4, atol=2e-5)


def test_forward_matches_dense_bf16():
    agent, params, obs, hidden = _build(standard_heads=True, heads=4,
                                        dtype=jnp.bfloat16)
    q_ref, h_ref = agent.apply(params, obs, hidden)
    q_qs, h_qs = _qslice(agent, params, obs, hidden)
    # bf16 mantissa ~8 bits; reassociation error accumulates over 2 blocks
    np.testing.assert_allclose(q_qs, q_ref, rtol=0.05, atol=0.05)
    np.testing.assert_allclose(h_qs, h_ref, rtol=0.05, atol=0.05)


def test_recurrent_unroll_matches_dense():
    """Hidden carried through several steps stays in lockstep."""
    agent, params, obs, hidden = _build()
    h_d = h_q = hidden
    key = jax.random.PRNGKey(7)
    for t in range(4):
        obs_t = jax.random.normal(jax.random.fold_in(key, t), obs.shape)
        q_d, h_d = agent.apply(params, obs_t, h_d)
        q_q, h_q = _qslice(agent, params, obs_t, h_q)
        np.testing.assert_allclose(q_q, q_d, rtol=5e-4, atol=5e-5)
        np.testing.assert_allclose(h_q, h_d, rtol=5e-4, atol=5e-5)


def test_gradients_match_dense():
    """Same function ⇒ same gradients (the learner may unroll through it)."""
    agent, params, obs, hidden = _build()

    def loss_dense(p):
        q, h = agent.apply(p, obs, hidden)
        return (q ** 2).sum() + (h * 0.3).sum()

    def loss_qs(p):
        q, h = _qslice(agent, p, obs, hidden)
        return (q ** 2).sum() + (h * 0.3).sum()

    from jax.flatten_util import ravel_pytree
    g_d = jax.grad(loss_dense)(params)
    g_q = jax.grad(loss_qs)(params)
    flat_d, _ = ravel_pytree(g_d)
    flat_q, _ = ravel_pytree(g_q)
    np.testing.assert_allclose(flat_q, flat_d, rtol=1e-3, atol=1e-4)


def _build_noisy(seed=3):
    agent = TransformerAgent(
        n_agents=3, n_entities=5, feat_dim=9, emb=32, heads=2, depth=2,
        n_actions=4, noisy=True)
    k = jax.random.PRNGKey(seed)
    kp, ko, kh = jax.random.split(k, 3)
    b = 4
    obs = jax.random.normal(ko, (b, 3, 5 * 9))
    hidden = jax.random.normal(kh, (b, 3, 32))
    params = agent.init(kp, obs, hidden)
    return agent, params, obs, hidden


def test_noisy_eval_mode_matches_dense():
    """Noisy agents are qslice-eligible (round 5: the noise is q-head-only)
    — in deterministic/eval mode both paths use the mu weights and must
    agree like any other config."""
    agent, params, obs, hidden = _build_noisy()
    q_ref, h_ref = agent.apply(params, obs, hidden)   # deterministic=True
    q_qs, h_qs = _qslice(agent, params, obs, hidden)  # noise_key=None
    np.testing.assert_allclose(q_qs, q_ref, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_qs, h_ref, rtol=2e-4, atol=2e-5)


def test_noisy_qslice_noise_semantics():
    """With a noise key the qslice head perturbs Q (one factored-Gaussian
    draw per call, shared across the batch like the dense module) and
    leaves the hidden stream untouched; same key → same sample."""
    from t2omca_tpu.ops.query_slice import agent_forward_qslice

    agent, params, obs, hidden = _build_noisy()

    def fwd(key):
        return agent_forward_qslice(
            params, obs, hidden, n_entities=5, feat_dim=9, emb=32,
            heads=2, depth=2, n_actions=4, noise_key=key)

    q_mu, h_mu = fwd(None)
    q_a, h_a = fwd(jax.random.PRNGKey(11))
    q_a2, _ = fwd(jax.random.PRNGKey(11))
    q_b, _ = fwd(jax.random.PRNGKey(12))
    np.testing.assert_array_equal(np.asarray(h_a), np.asarray(h_mu))
    np.testing.assert_array_equal(np.asarray(q_a), np.asarray(q_a2))
    assert not np.allclose(q_a, q_mu)
    assert not np.allclose(q_a, q_b)
    assert np.isfinite(np.asarray(q_a)).all()


def test_noisy_gradients_flow_to_sigma_through_qslice():
    """The learner unrolls noisy configs through the qslice forward —
    sigma params must receive gradient through it."""
    from t2omca_tpu.ops.query_slice import agent_forward_qslice

    agent, params, obs, hidden = _build_noisy()

    def loss(p):
        q, h = agent_forward_qslice(
            p, obs, hidden, n_entities=5, feat_dim=9, emb=32, heads=2,
            depth=2, n_actions=4, noise_key=jax.random.PRNGKey(5))
        return (q ** 2).sum()

    g = jax.grad(loss)(params)["params"]["q_basic"]
    for name in ("w_mu", "w_sigma", "b_mu", "b_sigma"):
        assert np.abs(np.asarray(g[name])).max() > 0, name


def test_noisy_config_is_fast_path_eligible():
    """The reference's own selector must resolve to the full fast stack
    (the round-5 enabler for the 16-agent campaign's arm B)."""
    from t2omca_tpu.ops.query_slice import (agent_qslice_eligible,
                                            entity_store_eligible)
    cfg = sanity_check(TrainConfig(action_selector="noisy-new"))
    assert agent_qslice_eligible(cfg)
    assert entity_store_eligible(cfg)
    mac = _noisy_mac(cfg)
    assert mac.use_qslice and mac.use_entity_tables
    # dropout still excludes the stack reduction
    cfg2 = sanity_check(TrainConfig(
        action_selector="noisy-new",
        model=ModelConfig(dropout=0.1)))
    assert not agent_qslice_eligible(cfg2)


def _noisy_mac(cfg):
    from t2omca_tpu.envs.registry import make_env
    env = make_env(cfg.env_args)
    return BasicMAC.build(cfg, env.get_env_info())


@pytest.mark.parametrize("state_entity_mode", [True, False])
@pytest.mark.parametrize("pos_func", ["abs", "softplus"])
def test_mixer_forward_matches_dense(state_entity_mode, pos_func):
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.ops.query_slice import mixer_forward_qslice

    n_agents, n_entities, feat, emb = 3, 3, 8, 16
    mixer = TransformerMixer(
        n_agents=n_agents, n_entities=n_entities, feat_dim=feat, emb=emb,
        heads=2, depth=2, qmix_pos_func=pos_func,
        state_entity_mode=state_entity_mode)
    k = jax.random.PRNGKey(5)
    b = 4
    qvals = jax.random.normal(jax.random.fold_in(k, 0), (b, 1, n_agents))
    hiddens = jax.random.normal(jax.random.fold_in(k, 1), (b, n_agents, emb))
    hyper = jax.random.normal(jax.random.fold_in(k, 2), (b, 3, emb))
    states = jax.random.normal(jax.random.fold_in(k, 3),
                               (b, n_entities * feat))
    obs = jax.random.normal(jax.random.fold_in(k, 4),
                            (b, n_agents, n_entities * feat))
    params = mixer.init(k, qvals, hiddens, hyper, states, obs)

    q_ref, hy_ref = mixer.apply(params, qvals, hiddens, hyper, states, obs)
    q_qs, hy_qs = mixer_forward_qslice(
        params, qvals, hiddens, hyper, states, obs,
        n_agents=n_agents, n_entities=n_entities, feat_dim=feat, emb=emb,
        heads=2, depth=2, pos_func=pos_func, pos_func_beta=1.0,
        state_entity_mode=state_entity_mode)
    np.testing.assert_allclose(q_qs, q_ref, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(hy_qs, hy_ref, rtol=5e-4, atol=5e-5)


def test_mixer_gradients_match_dense():
    """The learner differentiates through mixer_forward_qslice — pin its
    backward against the dense module, through the pre-fold."""
    from jax.flatten_util import ravel_pytree
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.ops.query_slice import mixer_forward_qslice

    n_agents, n_entities, feat, emb = 3, 3, 8, 16
    mixer = TransformerMixer(
        n_agents=n_agents, n_entities=n_entities, feat_dim=feat, emb=emb,
        heads=2, depth=2)
    k = jax.random.PRNGKey(11)
    b = 4
    qvals = jax.random.normal(jax.random.fold_in(k, 0), (b, 1, n_agents))
    hiddens = jax.random.normal(jax.random.fold_in(k, 1), (b, n_agents, emb))
    hyper = jax.random.normal(jax.random.fold_in(k, 2), (b, 3, emb))
    states = jax.random.normal(jax.random.fold_in(k, 3),
                               (b, n_entities * feat))
    obs = jax.random.normal(jax.random.fold_in(k, 4),
                            (b, n_agents, n_entities * feat))
    params = mixer.init(k, qvals, hiddens, hyper, states, obs)

    def loss_dense(p):
        q, hy = mixer.apply(p, qvals, hiddens, hyper, states, obs)
        return (q ** 2).sum() + (hy * 0.3).sum()

    def loss_qs(p):
        q, hy = mixer_forward_qslice(
            p, qvals, hiddens, hyper, states, obs,
            n_agents=n_agents, n_entities=n_entities, feat_dim=feat,
            emb=emb, heads=2, depth=2, pos_func="abs", pos_func_beta=1.0)
        return (q ** 2).sum() + (hy * 0.3).sum()

    flat_d, _ = ravel_pytree(jax.grad(loss_dense)(params))
    flat_q, _ = ravel_pytree(jax.grad(loss_qs)(params))
    np.testing.assert_allclose(flat_q, flat_d, rtol=2e-3, atol=2e-4)


def test_prefolded_params_match_unfolded():
    """prepare_acting_params + forward_qslice ≡ raw-params forward_qslice."""
    agent, params, obs, hidden = _build()
    q_raw, h_raw = _qslice(agent, params, obs, hidden)
    from t2omca_tpu.ops.query_slice import fold_agent_params
    folded = fold_agent_params(params, emb=agent.emb, heads=agent.heads,
                               depth=agent.depth,
                               standard_heads=agent.standard_heads,
                               dtype=agent.dtype)
    q_f, h_f = _qslice(agent, folded, obs, hidden)
    np.testing.assert_array_equal(q_f, q_raw)
    np.testing.assert_array_equal(h_f, h_raw)


@pytest.mark.slow   # full T-step learner unroll both paths (~45 s); forward+grad equivalence pinned above
def test_learner_loss_matches_dense_path():
    """End-to-end: the learner's loss/priorities with qslice unrolls match
    the dense-path learner bit-for-tolerance on the same batch."""
    import dataclasses
    from t2omca_tpu.run import Experiment

    def build(use_qslice):
        cfg = sanity_check(TrainConfig(
            batch_size_run=2, batch_size=2, test_nepisode=2,
            env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                               episode_limit=6),
            model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                              mixer_heads=2, mixer_depth=1,
                              use_qslice=use_qslice),
        ))
        return Experiment.build(cfg)

    exp_qs, exp_d = build(True), build(False)
    assert exp_qs.mac.use_qslice and not exp_d.mac.use_qslice
    ts = exp_qs.init_train_state(0)
    rs, batch, _ = jax.jit(exp_qs.runner.run)(
        ts.learner.params["agent"], ts.runner)
    w = jnp.ones((2,))
    _, info_qs = exp_qs.learner.train(
        ts.learner, batch, w, jnp.asarray(0), jnp.asarray(0))
    _, info_d = exp_d.learner.train(
        ts.learner, batch, w, jnp.asarray(0), jnp.asarray(0))
    np.testing.assert_allclose(info_qs["loss"], info_d["loss"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(info_qs["td_errors_abs"],
                               info_d["td_errors_abs"],
                               rtol=1e-3, atol=1e-4)


def test_mac_build_resolves_eligibility():
    env_info = {"n_agents": 3, "n_entities": 3, "obs_entity_feats": 9,
                "obs_shape": 27, "n_actions": 4, "state_shape": 24,
                "episode_limit": 5}
    cfg = sanity_check(TrainConfig(
        env_args=EnvConfig(agv_num=3, mec_num=2, episode_limit=5),
        model=ModelConfig(emb=16, heads=2, depth=1,
                          mixer_emb=16, mixer_heads=2)))
    assert BasicMAC.build(cfg, env_info).use_qslice

    # dropout>0 → dense fallback (dropout must actually be sampled)
    import dataclasses
    cfg_do = cfg.replace(model=dataclasses.replace(cfg.model, dropout=0.1))
    assert not BasicMAC.build(cfg_do, env_info).use_qslice

    # noisy selector stays on the fast path (round 5: noise is q-head-only
    # — the sliced stack is deterministic, the head samples from a key)
    cfg_noisy = cfg.replace(action_selector="noisy-new")
    assert BasicMAC.build(cfg_noisy, env_info).use_qslice

    # rnn agent → dense fallback
    cfg_rnn = cfg.replace(agent="rnn", mixer="vdn")
    assert not BasicMAC.build(cfg_rnn, env_info).use_qslice


def test_select_actions_matches_dense_greedy():
    """Greedy rollout actions agree between the qslice and dense paths."""
    import dataclasses
    env_info = {"n_agents": 3, "n_entities": 3, "obs_entity_feats": 9,
                "obs_shape": 27, "n_actions": 4, "state_shape": 24,
                "episode_limit": 5}
    cfg = sanity_check(TrainConfig(
        env_args=EnvConfig(agv_num=3, mec_num=2, episode_limit=5),
        model=ModelConfig(emb=16, heads=2, depth=1,
                          mixer_emb=16, mixer_heads=2)))
    mac_qs = BasicMAC.build(cfg, env_info)
    cfg_dense = cfg.replace(
        model=dataclasses.replace(cfg.model, use_qslice=False))
    mac_dense = BasicMAC.build(cfg_dense, env_info)
    assert mac_qs.use_qslice and not mac_dense.use_qslice

    key = jax.random.PRNGKey(3)
    params = mac_qs.init_params(key, 27)
    obs = jax.random.normal(jax.random.fold_in(key, 1), (6, 3, 27))
    avail = jnp.ones((6, 3, 4), jnp.int32)
    hidden = mac_qs.init_hidden(6)
    t_env = jnp.asarray(0)
    a_qs, h_qs, _ = mac_qs.select_actions(
        params, obs, avail, hidden, key, t_env, test_mode=True)
    a_d, h_d, _ = mac_dense.select_actions(
        params, obs, avail, hidden, key, t_env, test_mode=True)
    np.testing.assert_array_equal(a_qs, a_d)
    np.testing.assert_allclose(h_qs, h_d, rtol=5e-4, atol=5e-5)
