"""Fault-tolerance suite (docs/RESILIENCE.md): crash-safe checkpoints,
``find_checkpoint`` edge cases + skip-back, the non-finite guard rail, and
preemption handling — exercised through deterministic fault injectors
(``t2omca_tpu.utils.resilience``) and short ``run_sequential`` runs on the
CPU backend.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               ResilienceConfig, TrainConfig, load_config,
                               sanity_check)
from t2omca_tpu.run import Experiment, run
from t2omca_tpu.utils import resilience
from t2omca_tpu.utils.checkpoint import (CheckpointIntegrityError,
                                         find_checkpoint, load_checkpoint,
                                         prune_checkpoints, save_checkpoint,
                                         verify_checkpoint)
from t2omca_tpu.utils.logging import Logger


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    """Every test starts and ends with an empty injector registry."""
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def tiny_cfg(tmp_path, **kw):
    replay_kw = kw.pop("replay_kw", {})
    res_kw = kw.pop("res_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=24,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=True, save_model_interval=12,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
        resilience=ResilienceConfig(**res_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _save_steps(tmp_path, steps):
    """Write real (tiny but complete) checkpoints at the given steps."""
    cfg = tiny_cfg(tmp_path)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    root = str(tmp_path / "ckpt")
    for s in steps:
        save_checkpoint(root, s, ts)
    return root, exp, ts


# ---------------------------------------------------------------------------
# find_checkpoint edge cases
# ---------------------------------------------------------------------------

def test_find_checkpoint_empty_and_missing_dir(tmp_path):
    assert find_checkpoint(str(tmp_path / "nope")) is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert find_checkpoint(str(empty)) is None


@pytest.mark.slow   # Experiment build + real checkpoints for a listdir edge case
def test_find_checkpoint_ignores_non_numeric_entries(tmp_path):
    root, _, _ = _save_steps(tmp_path, [10])
    os.makedirs(os.path.join(root, "tb_logs"))
    os.makedirs(os.path.join(root, "tmp.99"))         # staging leftover
    with open(os.path.join(root, "20"), "w") as f:    # FILE named like a step
        f.write("not a directory")
    assert find_checkpoint(root) == (os.path.join(root, "10"), 10)


def test_load_step_nearest_tie_prefers_smaller_step(tmp_path):
    root, _, _ = _save_steps(tmp_path, [10, 30])
    # 20 is equidistant from 10 and 30: the tie must resolve
    # deterministically to the SMALLER step (sorted candidate order)
    assert find_checkpoint(root, load_step=20)[1] == 10
    assert find_checkpoint(root, load_step=29)[1] == 30


# ---------------------------------------------------------------------------
# crash-safe write + integrity skip-back
# ---------------------------------------------------------------------------

def test_truncated_top_checkpoint_skips_back(tmp_path):
    root, _, _ = _save_steps(tmp_path, [10, 20])
    state_p = os.path.join(root, "20", "state.msgpack")
    blob = open(state_p, "rb").read()
    with open(state_p, "wb") as f:
        f.write(blob[: len(blob) // 2])               # torn write
    assert not verify_checkpoint(os.path.join(root, "20"))
    # the acceptance bar: a truncated state.msgpack is NEVER selected;
    # resume falls back to the newest VALID step
    assert find_checkpoint(root) == (os.path.join(root, "10"), 10)


def test_bitflip_detected_by_checksum_and_skipped(tmp_path):
    root, _, _ = _save_steps(tmp_path, [10, 20])
    state_p = os.path.join(root, "20", "state.msgpack")
    blob = bytearray(open(state_p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF                      # same size, bad bytes
    with open(state_p, "wb") as f:
        f.write(bytes(blob))
    assert not verify_checkpoint(os.path.join(root, "20"))
    assert find_checkpoint(root)[1] == 10


def test_corrupt_checkpoint_direct_load_raises_integrity(tmp_path):
    root, exp, _ = _save_steps(tmp_path, [10])
    state_p = os.path.join(root, "10", "state.msgpack")
    blob = bytearray(open(state_p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(state_p, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(CheckpointIntegrityError, match="integrity"):
        load_checkpoint(os.path.join(root, "10"), exp.init_train_state(0))


@pytest.mark.faultinject
def test_crash_mid_save_leaves_previous_checkpoint_usable(tmp_path):
    """A crash between the state write and the publish rename must leave
    only a tmp.* leftover; the previous step stays the resume target, and
    a later save of the same step succeeds over the leftover."""
    root, exp, ts = _save_steps(tmp_path, [10])

    def _crash(dirname, t_env):
        raise RuntimeError("injected crash mid-checkpoint")

    resilience.register_fault("checkpoint.staged", _crash)
    with pytest.raises(RuntimeError, match="injected crash"):
        save_checkpoint(root, 20, ts)
    assert os.path.isdir(os.path.join(root, "tmp.20"))
    assert not os.path.isdir(os.path.join(root, "20"))
    assert find_checkpoint(root) == (os.path.join(root, "10"), 10)

    resilience.clear_faults()
    d = save_checkpoint(root, 20, ts)                 # retry over leftover
    assert verify_checkpoint(d)
    assert find_checkpoint(root)[1] == 20


@pytest.mark.faultinject
def test_torn_but_published_write_caught_by_checksum(tmp_path):
    """Even if a torn state file somehow gets published (injector
    truncates the staged blob AFTER hashing), the checksum catches it on
    scan and selection skips back."""
    root, _, ts = _save_steps(tmp_path, [10])

    def _truncate(dirname, t_env):
        p = os.path.join(dirname, "state.msgpack")
        blob = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(blob[: len(blob) // 3])

    resilience.register_fault("checkpoint.staged", _truncate)
    save_checkpoint(root, 20, ts)                     # publishes torn bytes
    resilience.clear_faults()
    assert os.path.isdir(os.path.join(root, "20"))
    assert not verify_checkpoint(os.path.join(root, "20"))
    assert find_checkpoint(root)[1] == 10


def test_resave_same_step_replaces_published_dir(tmp_path):
    root, _, ts = _save_steps(tmp_path, [10])
    d = save_checkpoint(root, 10, ts)                 # emergency-at-cadence
    assert verify_checkpoint(d)
    assert find_checkpoint(root)[1] == 10


def test_retention_keeps_last_k_and_every_nth(tmp_path):
    root, _, _ = _save_steps(tmp_path, [10, 20, 30, 40, 50, 60])
    os.makedirs(os.path.join(root, "tmp.70"))         # crash leftover
    removed = prune_checkpoints(root, keep_last=2, keep_every=30)
    assert sorted(removed) == [10, 20, 40]
    kept = sorted(int(n) for n in os.listdir(root) if n.isdigit())
    assert kept == [30, 50, 60]
    assert not os.path.exists(os.path.join(root, "tmp.70"))
    assert all(verify_checkpoint(os.path.join(root, str(s))) for s in kept)


# ---------------------------------------------------------------------------
# non-finite guard rail
# ---------------------------------------------------------------------------

@pytest.mark.faultinject
def test_nonfinite_step_is_noop_on_params_and_opt(tmp_path):
    """An injected NaN loss at step k: all_finite trips, params AND
    optimizer state pass through bit-identical; the next (clean) step
    trains normally."""
    cfg = tiny_cfg(tmp_path, res_kw=dict(inject_nan_at_step=0))
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    for i in range(2):                                # fill replay >= batch
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)

    before = jax.device_get(ts.learner)
    prio_before = np.asarray(jax.device_get(ts.buffer.priorities))
    ts, info = train_iter(ts, jax.random.PRNGKey(1), jnp.asarray(12))
    info = jax.device_get(info)
    assert not bool(info["all_finite"])
    assert not np.isfinite(info["loss"])
    after = jax.device_get(ts.learner)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(before.params),
            jax.tree_util.tree_leaves_with_path(after.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))
    for a, b in zip(jax.tree_util.tree_leaves(before.opt_state),
                    jax.tree_util.tree_leaves(after.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # priorities untouched too (a NaN priority would win every PER draw)
    prio_after = np.asarray(jax.device_get(ts.buffer.priorities))
    np.testing.assert_array_equal(prio_before, prio_after)
    assert np.isfinite(prio_after).all()
    # train_steps still counts the attempt (fault step indices stay
    # monotonic across skips)
    assert int(after.train_steps) == int(before.train_steps) + 1

    # next step (train_steps=1 != inject_nan_at_step) trains normally
    ts2, info2 = train_iter(ts, jax.random.PRNGKey(2), jnp.asarray(24))
    assert bool(jax.device_get(info2["all_finite"]))
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(after.params),
                        jax.tree_util.tree_leaves(
                            jax.device_get(ts2.learner.params))))
    assert moved, "clean step after a skipped one must update params"


@pytest.mark.faultinject
def test_nan_injection_recovers_end_to_end(tmp_path):
    """Driver-level recovery: a NaN at train step k trips the guard, the
    driver restores the newest checkpoint (saved the same iteration, so
    its train_steps is already past k) and the run completes."""
    cfg = tiny_cfg(tmp_path, t_max=120,
                   res_kw=dict(inject_nan_at_step=2, nonfinite_tolerance=1,
                               max_restores=2))
    ts = run(cfg, Logger())
    # the run went the distance and kept training after the restore
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    assert int(jax.device_get(ts.learner.train_steps)) > 3
    # the guard logged the event into the metric stream
    keys = set()
    for p in glob.glob(os.path.join(tmp_path, "*", "metrics.jsonl")):
        with open(p) as f:
            keys.update(json.loads(line)["key"] for line in f)
    assert "nonfinite_steps" in keys
    # params came out finite
    leaves = jax.tree_util.tree_leaves(
        jax.device_get(ts.learner.params))
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)


@pytest.mark.faultinject
@pytest.mark.slow   # full run() to an abort; the recover-with-checkpoint path stays in-gate
def test_nan_without_checkpoint_aborts_with_diagnosis(tmp_path):
    cfg = tiny_cfg(tmp_path, save_model=False,
                   res_kw=dict(inject_nan_at_step=0, nonfinite_tolerance=1))
    with pytest.raises(RuntimeError, match="diverged"):
        run(cfg, Logger())


# ---------------------------------------------------------------------------
# preemption handling
# ---------------------------------------------------------------------------

def test_shutdown_guard_latches_real_signal():
    prev = signal.getsignal(signal.SIGTERM)
    with resilience.ShutdownGuard.install() as guard:
        assert guard.installed and not guard.triggered
        signal.raise_signal(signal.SIGTERM)
        assert guard.triggered
        assert guard.signame == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is prev


@pytest.mark.faultinject
@pytest.mark.slow   # full run() (~22 s); same guard path runs in-gate at K>1 in test_superstep
def test_sigterm_writes_emergency_checkpoint_and_returns(tmp_path):
    """A real SIGTERM mid-run: the loop breaks at the next iteration
    boundary, writes one emergency checkpoint, and returns normally (the
    CLI then exits 0) — preemption loses at most one iteration, not up to
    save_model_interval steps."""
    cfg = tiny_cfg(tmp_path, t_max=100_000, save_model_interval=10_000)

    def _preempt(t_env, guard):
        if t_env >= 24:
            signal.raise_signal(signal.SIGTERM)

    resilience.register_fault("driver.iteration", _preempt)
    ts = run(cfg, Logger())                           # returns, no raise
    stopped_at = int(jax.device_get(ts.runner.t_env))
    assert stopped_at < cfg.t_max, "run must have stopped early"

    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    found = find_checkpoint(model_dir)
    assert found is not None
    dirname, step = found
    # the emergency checkpoint is the NEWEST step and covers the stop
    # point (save_model_interval alone would have left step 12)
    assert step >= 24
    assert verify_checkpoint(dirname)
    exp = Experiment.build(tiny_cfg(tmp_path, t_max=100_000,
                                    save_model_interval=10_000))
    restored = load_checkpoint(dirname, exp.init_train_state(1))
    leaves = jax.tree_util.tree_leaves(
        jax.device_get(restored.learner.params))
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    # default SIGTERM disposition restored after the run
    assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL


@pytest.mark.slow
@pytest.mark.faultinject
def test_sigterm_subprocess_exits_zero(tmp_path):
    """Full black-box preemption: SIGTERM to a real training process →
    exit code 0 + a loadable emergency checkpoint (acceptance criterion).
    Marked slow: pays a fresh interpreter + jit compile."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "t2omca_tpu", "train",
         "t_max=1000000", "batch_size_run=2", "batch_size=4",
         "env_args.agv_num=3", "env_args.episode_limit=6",
         "model.emb=8", "model.heads=2", "model.depth=1",
         "model.mixer_emb=8", "model.mixer_heads=2", "model.mixer_depth=1",
         "replay.buffer_size=8", "test_interval=1000000",
         "log_interval=120", "runner_log_interval=120",
         "save_model_interval=1000000",
         f"local_results_path={tmp_path}"],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # wait until the loop is demonstrably spinning (a checkpoint-free
        # signal: the cadence log line), then preempt
        deadline = time.time() + 300
        for line in proc.stdout:
            if "t_env:" in line or time.time() > deadline:
                break
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out
    model_dirs = glob.glob(os.path.join(tmp_path, "models", "*"))
    assert model_dirs, out
    assert find_checkpoint(model_dirs[0]) is not None


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_resilience_config_sanity_and_overrides():
    with pytest.raises(ValueError, match="nonfinite_tolerance"):
        sanity_check(TrainConfig(
            resilience=ResilienceConfig(nonfinite_tolerance=-1)))
    with pytest.raises(ValueError, match="max_restores"):
        sanity_check(TrainConfig(
            resilience=ResilienceConfig(max_restores=-1)))
    with pytest.raises(ValueError, match="keep_last"):
        sanity_check(TrainConfig(
            resilience=ResilienceConfig(keep_last=-1)))
    with pytest.raises(ValueError, match="tests nothing"):
        sanity_check(TrainConfig(resilience=ResilienceConfig(
            inject_nan_at_step=5, nonfinite_tolerance=0)))
    # CLI-style overrides route into the sub-config, dotted or flat
    cfg = load_config(overrides=("resilience.keep_last=3",
                                 "nonfinite_tolerance=7"))
    assert cfg.resilience.keep_last == 3
    assert cfg.resilience.nonfinite_tolerance == 7


@pytest.mark.slow   # full run() with pruning (~24 s); prune_checkpoints logic pinned directly above
def test_retention_runs_inside_driver(tmp_path):
    """keep_last wired through run_sequential: after training, at most
    keep_last checkpoints remain on disk."""
    cfg = tiny_cfg(tmp_path, res_kw=dict(keep_last=2))
    run(cfg, Logger())
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    steps = [n for n in os.listdir(model_dir) if n.isdigit()]
    assert 0 < len(steps) <= 2
