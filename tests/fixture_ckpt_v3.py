"""Generator for the checked-in v3 fixture checkpoint
(``tests/fixtures_ckpt_v3/``) — the e2e anchor for the full
v3→v4→v5 migration chain (``utils/checkpoint._migrate_raw`` +
``_lift_population``; docs/RESILIENCE.md §2).

Shim-based migration tests synthesize the OLD tree from the NEW one
(delete a key, call the shim), which silently co-evolves with the code
under test: if a refactor changed what "v3" means, those tests would
keep passing against the wrong bytes. The fixture pins real v3-era
bytes in git instead. It is produced from the CURRENT writer by
deleting the one runner field the v3 era predates (``env_params``,
added v3→v4; ``rscale`` arrived v2→v3 and so IS present in a v3 tree)
and stamping ``format: 3`` with no topology stamp — byte-for-byte what
a v3-era writer published.

Regenerate (only when the fixture config below must change — the WHOLE
POINT is that the bytes stay frozen):

    python -m tests.fixture_ckpt_v3

The test half lives in ``tests/test_elastic.py``
(``test_v3_fixture_migrates_*``) and restores these bytes into a bare
v4 template and a P=2 population template.
"""

from __future__ import annotations

import hashlib
import json
import os

FIXTURE_STEP = 24
FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures_ckpt_v3")


def fixture_cfg(tmp_path="/tmp/v3fix"):
    """The frozen fixture config — the test rebuilds templates from
    EXACTLY this shape. Mirrors the resilience tiny config at its
    smallest: the checked-in blob must stay a few hundred KiB."""
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    return sanity_check(TrainConfig(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=24,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
    ))


def main() -> str:
    from flax import serialization

    from t2omca_tpu.run import Experiment
    from t2omca_tpu.utils.checkpoint import save_checkpoint

    cfg = fixture_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(cfg.seed)
    save_checkpoint(FIXTURE_DIR, FIXTURE_STEP, ts)

    d = os.path.join(FIXTURE_DIR, str(FIXTURE_STEP))
    state_path = os.path.join(d, "state.msgpack")
    with open(state_path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    # the v3 era predates env_params (added v3→v4) but HAS rscale
    # (added v2→v3) — delete exactly the one field so the restore
    # exercises the real v3→v4 inject shim, then v4→v5 lifting
    del raw["runner"]["env_params"]
    blob = serialization.msgpack_serialize(raw)
    with open(state_path, "wb") as f:
        f.write(blob)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    # a v3 writer stamped format 3 and knew nothing of topology
    meta.update(format=3, sha256=hashlib.sha256(blob).hexdigest(),
                bytes=len(blob))
    meta.pop("topology", None)
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump(meta, f)
    return d


if __name__ == "__main__":
    print(main())
