"""The fused training superstep (``run.Experiment.superstep_program``,
``config.superstep``): one donated XLA program scanning K rollout → ring
insert → gated sample+train iterations per dispatch (Anakin/Podracer,
PAPERS.md). Pins the contract the driver relies on: bit-identical
training vs the classic three-program loop (RNG key threading preserved),
gate correctness across the ``can_sample``/``accumulated_episodes``
boundary, one-dispatch-per-K in the real driver, donation safety, and
the resilience interplay (ShutdownGuard at a dispatch boundary,
non-finite guard inside the scan)."""

import glob
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.analysis import (CompileBudgetExceeded, compile_budget,
                                 no_transfer)
from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               ResilienceConfig, TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment, run, superstep_eligible
from t2omca_tpu.utils import resilience
from t2omca_tpu.utils.checkpoint import find_checkpoint
from t2omca_tpu.utils.logging import Logger


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


def tiny_cfg(tmp_path=None, **kw):
    """Shrunk config-1 parity point (configs/config1_cpu_parity.yaml knobs:
    fast_norm off → sequential normalizer, dense obs storage — the
    bit-comparable path) at test scale."""
    env_kw = kw.pop("env_kw", {})
    replay_kw = kw.pop("replay_kw", {})
    res_kw = kw.pop("res_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=False, save_model_interval=24,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False, **env_kw),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
        resilience=ResilienceConfig(**res_kw),
    )
    if tmp_path is not None:
        defaults["local_results_path"] = str(tmp_path)
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _three_program_loop(exp, n_iters, accumulated=0):
    """The classic driver train path, verbatim (run.run_sequential K=1):
    host-gated train, conditional key split."""
    cfg = exp.cfg
    ts = exp.init_train_state(cfg.seed)
    rollout, insert, train_iter = exp.jitted_programs()
    key = jax.random.PRNGKey(cfg.seed + 1)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, episode, filled = 0, 0, 0
    infos = []
    for _ in range(n_iters):
        rs, batch, stats = rollout(ts.learner.params["agent"], ts.runner,
                                   test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
        t_env += spr
        episode += cfg.batch_size_run
        filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
        if filled >= cfg.batch_size and episode >= accumulated:
            key, k_sample = jax.random.split(key)
            ts, info = train_iter(ts, k_sample, jnp.asarray(t_env))
            infos.append(info)
    return ts, infos


def _superstep_loop(exp, k, n_dispatches, accumulated=0, donate=False):
    """The driver's K>1 path, verbatim: host mirror of the gate drives
    the conditional key splits; zeros for skipped rows."""
    cfg = exp.cfg
    ts = exp.init_train_state(cfg.seed)
    superstep = exp.superstep_program(k, donate=donate)
    key = jax.random.PRNGKey(cfg.seed + 1)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, episode, filled = 0, 0, 0
    all_stats, kept = [], []
    for _ in range(n_dispatches):
        rows, gated = [], []
        for _ in range(k):
            episode += cfg.batch_size_run
            filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
            g = filled >= cfg.batch_size and episode >= accumulated
            gated.append(g)
            if g:
                key, k_sample = jax.random.split(key)
                rows.append(k_sample)
            else:
                rows.append(jnp.zeros_like(key))
        ts, stats, infos = superstep(ts, jnp.stack(rows),
                                     jnp.asarray(t_env))
        t_env += k * spr
        all_stats.append(stats)
        kept.extend(jax.tree.map(lambda x, i=i: x[i], infos)
                    for i, g in enumerate(gated) if g)
    return ts, all_stats, kept


def test_superstep_bit_identical_to_three_program_loop():
    """8 iterations at the parity config: K=4 (2 dispatches) must end on
    EXACTLY the params/opt-state/priorities of the K=1 three-program loop
    — same values, same RNG streams, gate opening mid-dispatch (buffer
    fills at iteration 2, accumulated_episodes passes at iteration 3)."""
    cfg = tiny_cfg(accumulated_episodes=6)
    exp = Experiment.build(cfg)
    ts1, infos1 = _three_program_loop(exp, 8, accumulated=6)
    ts4, _, infos4 = _superstep_loop(exp, 4, 2, accumulated=6)

    assert int(jax.device_get(ts1.learner.train_steps)) == 6   # iters 3..8
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts1)),
            jax.tree_util.tree_leaves_with_path(jax.device_get(ts4))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))
    # per-step train infos line up too (losses bit-equal)
    assert len(infos1) == len(infos4)
    for a, b in zip(infos1, infos4):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a["loss"])),
                                      np.asarray(jax.device_get(b["loss"])))


@pytest.mark.slow   # extra K=3 compile (~17 s); gate boundaries also pinned by the parity + dispatch tests
def test_superstep_gate_counts_train_steps():
    """Gate arithmetic on the carried counters: with buffer capacity 8 and
    batch 4, training starts at iteration 2; accumulated_episodes=10
    delays it to iteration 5 (episode 10) — wherever that lands inside a
    dispatch."""
    cfg = tiny_cfg(accumulated_episodes=10)
    exp = Experiment.build(cfg)
    ts, _, kept = _superstep_loop(exp, 3, 2, accumulated=10)
    # iterations 5 and 6 of 6 train
    assert int(jax.device_get(ts.learner.train_steps)) == 2
    assert len(kept) == 2
    assert all(bool(jax.device_get(i["all_finite"])) for i in kept)


@pytest.mark.slow   # extra donated compile (~19 s); the in-gate run() test executes the donated program
def test_superstep_donation_updates_in_place():
    """donate=True must consume the input TrainState (ring updated in
    place — the HBM contract the production driver relies on) and keep a
    single compiled executable across chained dispatches."""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    superstep = exp.superstep_program(2, donate=True)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    pre_leaves = [x for x in jax.tree.leaves(ts) if isinstance(x, jax.Array)]
    ts, stats, infos = superstep(ts, keys, jnp.zeros((), jnp.int32))
    ts, stats, infos = superstep(ts, keys, jnp.asarray(24, jnp.int32))
    assert all(x.is_deleted() for x in pre_leaves), \
        "superstep must consume (donate) the train state"
    assert superstep._cache_size() == 1
    ret = np.asarray(jax.device_get(stats.episode_return))
    assert ret.shape[0] == 2 and np.isfinite(ret).all()
    assert int(jax.device_get(ts.episode)) == 8


def test_run_sequential_issues_one_dispatch_per_k(tmp_path, monkeypatch):
    """The real driver at superstep=3: exactly ONE fused dispatch per 3
    iterations — counted by wrapping the program the driver builds."""
    calls = []
    orig = Experiment.superstep_program

    def counting(self, k, **kw):
        prog = orig(self, k, **kw)

        def wrapped(*a, **k2):
            calls.append(1)
            return prog(*a, **k2)
        return wrapped

    monkeypatch.setattr(Experiment, "superstep_program", counting)
    # spr = 12; t_max=70 → dispatches at t_env 0 and 36 (72 > 70 ends)
    cfg = tiny_cfg(tmp_path, t_max=70, superstep=3, save_model=True,
                   log_interval=36, runner_log_interval=36)
    ts = run(cfg, Logger())
    assert len(calls) == 2
    t_end = int(jax.device_get(ts.runner.t_env))
    assert t_end == 2 * 3 * 12                     # K-aligned boundary
    assert int(jax.device_get(ts.learner.train_steps)) == 5  # iters 2..6


def test_superstep_ineligible_on_host_buffer(tmp_path):
    """buffer_cpu_only keeps the three-program path (eligibility
    predicate; the host-buffer driver e2e itself is
    test_driver::test_host_buffer_branch_end_to_end) and
    superstep_program must refuse the host buffer outright."""
    cfg = tiny_cfg(tmp_path, superstep=2,
                   replay_kw=dict(buffer_cpu_only=True))
    assert not superstep_eligible(cfg)
    assert superstep_eligible(tiny_cfg(superstep=2))
    assert not superstep_eligible(tiny_cfg())          # K=1: classic loop
    exp = Experiment.build(cfg)
    with pytest.raises(ValueError, match="buffer_cpu_only"):
        exp.superstep_program(2)


@pytest.mark.faultinject
def test_shutdown_guard_exits_at_dispatch_boundary(tmp_path):
    """SIGTERM mid-run under superstep=2: the orderly exit lands at a
    DISPATCH boundary (t_env a multiple of K·B·T) with the emergency
    checkpoint covering it — preemption loses at most K iterations."""
    cfg = tiny_cfg(tmp_path, t_max=100_000, superstep=2, save_model=True,
                   save_model_interval=10_000)

    def _preempt(t_env, guard):
        if t_env >= 48:
            signal.raise_signal(signal.SIGTERM)

    resilience.register_fault("driver.iteration", _preempt)
    ts = run(cfg, Logger())
    stopped_at = int(jax.device_get(ts.runner.t_env))
    assert stopped_at < cfg.t_max
    assert stopped_at % (2 * 12) == 0              # dispatch-aligned
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    found = find_checkpoint(model_dir)
    assert found is not None and found[1] >= 48
    assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL


@pytest.mark.faultinject
def test_nonfinite_guard_trips_inside_scan(tmp_path):
    """resilience.inject_nan_at_step inside the fused scan: the tripped
    sub-iteration must be a no-op on params (guard inside jit) and the
    driver must see its all_finite flag through the stacked infos at the
    log cadence."""
    # one injected step → streak 1 < the default tolerance 3: the guard
    # skips the update but no restore escalation fires
    cfg = tiny_cfg(tmp_path, t_max=60, superstep=2, save_model=False,
                   log_interval=12, res_kw=dict(inject_nan_at_step=1))
    ts = run(cfg, Logger())
    leaves = jax.tree.leaves(jax.device_get(ts.learner.params))
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    # the injected step was counted (nonfinite_steps metric logged)
    import json
    rows = []
    for p in glob.glob(os.path.join(tmp_path, "*", "metrics.jsonl")):
        with open(p) as f:
            rows.extend(json.loads(l)["key"] for l in f)
    assert "nonfinite_steps" in rows


# --------------------------------------------------------------------------
# tracing-hygiene enforcement at the program level (t2omca_tpu/analysis,
# docs/ANALYSIS.md): the fused superstep's whole value is ONE compile and
# ZERO host round-trips per K iterations — pinned here with the runtime
# guards. Cheap toy-program guard tests (always in gate): tests/test_analysis.py.


@pytest.mark.slow   # full superstep compile (~17 s) x2
@pytest.mark.analysis
def test_superstep_program_compile_budget():
    """`Experiment.superstep_program` compiles exactly ONCE across K
    dispatches — a silent retrace would erase the dispatch-amortization
    win (the bug class run._strong exists to stop). And the budget must
    FAIL when the program is made to retrace: passing a raw Python
    scalar for t_env0 (instead of the committed int32 array the driver
    passes) flips the aval to weak-typed and recompiles."""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    superstep = exp.superstep_program(2)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    with compile_budget(1, match="_superstep") as log:
        for i in range(3):
            ts, stats, infos = superstep(ts, keys,
                                         jnp.asarray(i * 24, jnp.int32))
    assert log.count == 1
    assert np.isfinite(
        np.asarray(jax.device_get(stats.episode_return))).all()

    # retrace demonstration (ISSUE 3 acceptance): same computation, but
    # one dispatch passes a Python scalar -> weak_type aval -> recompile
    prog2 = exp.superstep_program(2)
    ts2 = exp.init_train_state(0)
    with pytest.raises(CompileBudgetExceeded, match="_superstep"):
        with compile_budget(1, match="_superstep"):
            ts2, _, _ = prog2(ts2, keys, jnp.asarray(0, jnp.int32))
            prog2(ts2, keys, 24)


@pytest.mark.slow   # mesh-sharded superstep compile on the 8-device CPU mesh
@pytest.mark.analysis
def test_dataparallel_superstep_compile_budget():
    """`DataParallel.superstep_program` too: the constraint hooks pin
    output shardings to the canonical input placement, so dispatch 2+
    reuses the executable — GSPMD choosing a different output sharding
    would silently compile a second program every iteration."""
    from t2omca_tpu.parallel import DataParallel, make_mesh
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    dp = DataParallel(exp, make_mesh(2))
    ts = dp.init_sharded(cfg.seed)           # born sharded, outside budget
    superstep = dp.superstep_program(2)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    with compile_budget(1, match="_superstep") as log:
        for i in range(3):
            ts, stats, infos = superstep(ts, keys,
                                         jnp.asarray(i * 24, jnp.int32))
    assert log.count == 1
    assert int(jax.device_get(ts.episode)) == 12


@pytest.mark.slow   # rollout+insert+train compiles (~15 s)
@pytest.mark.analysis
def test_train_iter_compile_budget():
    """The classic-loop learner step (`_train_iter`) holds one compile
    across iterations at fixed shapes — the driver feeds back
    weak-type-stripped state (run._strong) precisely so iteration 2
    doesn't silently recompile."""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(cfg.seed)
    rollout, insert, train_iter = exp.jitted_programs()
    key = jax.random.PRNGKey(cfg.seed + 1)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env = 0
    for _ in range(2):                       # fill to batch_size episodes
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
        t_env += spr
    with compile_budget(1, match="_train_iter") as log:
        for _ in range(3):
            key, k = jax.random.split(key)
            t_env += spr
            ts, info = train_iter(ts, k, jnp.asarray(t_env))
    assert log.count == 1
    assert int(jax.device_get(ts.learner.train_steps)) == 3


@pytest.mark.slow   # superstep compile (~17 s)
@pytest.mark.analysis
def test_superstep_no_implicit_transfer_between_dispatches():
    """One fused dispatch on the K>1 path runs with ZERO implicit host
    transfers: every per-dispatch input is a committed device array
    (keys stack, int32 t_env), every output stays on device. A Python
    scalar sneaking into the dispatch args — simultaneously a retrace
    hazard, see above — is exactly what the guard rejects. (On this CPU
    backend only the host→device direction has teeth; on a real
    accelerator no_transfer() also rejects implicit device→host
    fetches between boundaries.)"""
    cfg = tiny_cfg()
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    superstep = exp.superstep_program(2)
    keys = jax.random.split(jax.random.PRNGKey(1), 2)
    ts, stats, infos = superstep(ts, keys, jnp.asarray(0, jnp.int32))
    # compile + constant upload happened above; dispatch 2 must be clean
    t1 = jnp.asarray(24, jnp.int32)
    with no_transfer():
        ts, stats, infos = superstep(ts, keys, t1)
        jax.block_until_ready(stats.epsilon)   # barrier, not a transfer
    # seeded hazard: a per-dispatch Python scalar is an implicit upload
    with pytest.raises(Exception, match="[Tt]ransfer"):
        with no_transfer():
            superstep(ts, keys, 48)
    # explicit cadence-boundary fetches stay allowed under the guard
    with no_transfer():
        assert int(jax.device_get(ts.episode)) == 8
