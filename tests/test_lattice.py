"""graftlattice: the rank-polymorphic superstep compositions
(docs/POPULATION.md §composition, docs/PERF.md §lattice) — the
population axis composed with the other graft axes through the one
shared superstep core (``run._superstep_fn``):

* **vmap-over-pallas** — the member axis vmapped over the fused
  flash-attention kernels: P=1 pallas is BIT-identical to the classic
  pallas superstep loop (the neutral-spec squeeze path), and at P=2 the
  ACTING path stays bit-identical between kernel modes while the train
  step matches at measured vmapped-kernel tolerances (looser than the
  solo tests/test_kernels.py pins — the batched grid reassociates);
* **population-over-dp** — whole members sharded over a device mesh
  (``parallel.population_shardings``) reproduce the replicated
  single-device run on the conftest-forced multi-device CPU host:
  control/integer state bit-equal, floats at ULP scale (SPMD retiling);
* **population × Sebulba** — the vmapped learner in lockstep behind the
  device-resident queue ends on the classic population driver's train
  state (the solo lockstep anchor lifted to rank P: control state
  bit-equal, floats at ULP scale — bitwise holds at the P=1 squeeze);
* the ``--lattice`` bench matrix leg and the argparse composition gates.

The combo-rejection pins (which illegal lattice points raise, naming
the blocking mechanism and the nearest legal alternative) live in
tests/test_population.py::test_sanity_lattice_legal_and_gated_combos.
"""

import argparse
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu import population as graftpop
from t2omca_tpu.config import (EnvConfig, KernelsConfig, ModelConfig,
                               PopulationConfig, ReplayConfig,
                               SebulbaConfig, TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment, run_sequential
from t2omca_tpu.utils.logging import Logger

pytestmark = pytest.mark.lattice


def tiny_cfg(tmp_path=None, **kw):
    """The test_superstep parity point (dense storage, sequential
    normalizer — the bit-comparable path) at test scale."""
    env_kw = kw.pop("env_kw", {})
    replay_kw = kw.pop("replay_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=False, save_model_interval=24, epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False, **env_kw),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
    )
    if tmp_path is not None:
        defaults["local_results_path"] = str(tmp_path)
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def pop_cfg(p, tmp_path=None, **kw):
    return tiny_cfg(tmp_path, population=PopulationConfig(size=p), **kw)


def _assert_trees_equal(a, b, strip_member=False, msg=""):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (kp, x), (_, y) in zip(la, lb):
        x, y = np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
        if strip_member:
            y = y[0]
        np.testing.assert_array_equal(
            x, y, err_msg=f"{msg}{jax.tree_util.keystr(kp)}")


def _assert_trees_ulp_close(a, b, msg=""):
    """Integer/bool/control leaves bit-equal; float leaves at f32 ULP
    scale (rtol 1e-4, atol 1e-6). The cross-LAYOUT contract for rank-P
    programs: two batched lowerings of the same math (vmapped-fused vs
    vmapped-split, single-device vs member-sharded) tile their f32
    reduces differently, so bitwise equality holds only within one
    layout (docs/POPULATION.md §parity); control flow must still agree
    exactly. Measured drift shapes on this CPU: params ~5e-7 rel, but
    small-magnitude adam moments show the same ~1e-7 ABSOLUTE drift at
    up to 2.4e-5 relative — hence the atol floor and the 1e-4 rtol
    headroom (a real composition bug — wrong member's data, dropped
    train — lands at rel ~1, orders away)."""
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert len(la) == len(lb)
    for (kp, x), (_, y) in zip(la, lb):
        x = np.asarray(jax.device_get(x))
        y = np.asarray(jax.device_get(y))
        name = f"{msg}{jax.tree_util.keystr(kp)}"
        if np.issubdtype(x.dtype, np.inexact):
            np.testing.assert_allclose(x, y, rtol=1e-4, atol=1e-6,
                                       err_msg=name)
        else:
            np.testing.assert_array_equal(x, y, err_msg=name)


def _pop_dispatches(exp, cfg, n_dispatches, keyseed=0, shardings=None):
    """k=1 population dispatches with the driver's gate discipline:
    zero keys while the ring is below the train batch, per-member split
    streams once it can sample (tests/test_population.py::_pop_loop)."""
    p = cfg.population.size
    ts, spec = graftpop.init_population(exp, cfg)
    prog = exp.population_superstep_program(1)
    keys = [jax.random.PRNGKey(cfg.seed + keyseed + m) for m in range(p)]
    if shardings is not None:
        ts = jax.device_put(ts, shardings(ts))
        spec = jax.device_put(spec, shardings(spec))
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, filled = 0, 0
    all_infos = []
    for _ in range(n_dispatches):
        filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
        if filled >= cfg.batch_size:
            row = []
            for m in range(p):
                keys[m], ks = jax.random.split(keys[m])
                row.append(ks)
            kstack = jnp.stack(row)[:, None, :]
        else:
            kstack = jnp.zeros((p, 1) + keys[0].shape, keys[0].dtype)
        if shardings is not None:
            kstack = jax.device_put(kstack, shardings(kstack))
        ts, stats, infos = prog(ts, kstack, jnp.asarray(t_env), spec)
        t_env += spr
        all_infos.append(infos)
    return ts, all_infos


# ------------------------------------------------------- vmap-over-pallas

@pytest.mark.slow   # two pallas-mode superstep compiles (~90 s)
def test_p1_pallas_population_bit_identical_to_classic_pallas():
    """The P=1 double-bypass contract survives UNDER the pallas kernel
    mode: a neutral single-member population lowers the classic pallas
    superstep's exact arithmetic — params, opt_state, replay ring and
    runner state all bit-equal after gated train dispatches."""
    kernels = KernelsConfig(attention="pallas")
    cfg = tiny_cfg(kernels=kernels)
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(cfg.seed)
    prog = exp.superstep_program(1)
    key = jax.random.PRNGKey(cfg.seed + 1)
    spr = cfg.batch_size_run * cfg.env_args.episode_limit
    t_env, filled = 0, 0
    for _ in range(3):
        filled = min(filled + cfg.batch_size_run, exp.buffer.capacity)
        if filled >= cfg.batch_size:
            key, ks = jax.random.split(key)
            kstack = ks[None]
        else:
            kstack = jnp.zeros((1,) + key.shape, key.dtype)
        ts, _, _ = prog(ts, kstack, jnp.asarray(t_env))
        t_env += spr

    cfgp = pop_cfg(1, kernels=kernels)
    expp = Experiment.build(cfgp)
    ts_p, _ = _pop_dispatches(expp, cfgp, 3, keyseed=1)
    _assert_trees_equal(ts, ts_p, strip_member=True, msg="state ")


@pytest.mark.slow   # two P=2 population superstep compiles (~90 s)
def test_p2_pallas_superstep_matches_xla_at_kernel_tolerances():
    """vmap-over-pallas vs vmap-over-xla: identical seeds/keys through
    the P=2 population superstep in both kernel modes.

    Two-layer contract, each layer at its honest tolerance:

    * the ACTING path is bit-identical between modes even under vmap —
      every ring storage leaf (obs, state, actions, rewards, masks) and
      the full runner state are asserted bit-equal, so the first gated
      train consumes EXACTLY the same inputs in both modes (the solo
      qslice bit-parity of tests/test_kernels.py survives batching);
    * the TRAIN step matches at vmapped-kernel tolerances, measured on
      this CPU: the batched flash grid reassociates the f32
      forward/backward reduces more aggressively than the solo kernel
      (the solo pins — loss 1e-6, grad_norm 1e-4 — do NOT transfer),
      observed loss 8.2e-5 rel / grad_norm 1.2e-2 rel (on an ~3e5
      audit-scale norm) / params 7.3e-5 abs after the first gated
      train, pinned here with ~3x headroom."""
    outs = {}
    for mode in ("xla", "pallas"):
        cfgp = pop_cfg(2, kernels=KernelsConfig(attention=mode))
        expp = Experiment.build(cfgp)
        outs[mode] = _pop_dispatches(expp, cfgp, 3)
    ts_x, infos_x = outs["xla"]
    ts_p, infos_p = outs["pallas"]
    # acting layer: ring storage + runner state bit-equal across modes
    _assert_trees_equal(jax.device_get(ts_x.buffer.storage),
                        jax.device_get(ts_p.buffer.storage),
                        msg="ring ")
    _assert_trees_equal(jax.device_get(ts_x.runner),
                        jax.device_get(ts_p.runner), msg="runner ")
    # train layer: the gated third dispatch trained on identical inputs
    np.testing.assert_allclose(
        np.asarray(jax.device_get(infos_p[-1]["loss"]), np.float64),
        np.asarray(jax.device_get(infos_x[-1]["loss"]), np.float64),
        rtol=3e-4)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(infos_p[-1]["grad_norm"]), np.float64),
        np.asarray(jax.device_get(infos_x[-1]["grad_norm"]), np.float64),
        rtol=5e-2)
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(ts_p.learner.params)),
            jax.tree_util.tree_leaves_with_path(
                jax.device_get(ts_x.learner.params))):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-3, err_msg=jax.tree_util.keystr(kp))


# ------------------------------------------------------ population-over-dp

@pytest.mark.slow   # sharded + replicated population compiles (~60 s)
def test_population_over_dp_sharded_matches_replicated():
    """Whole members sharded over the mesh (one batched program, the
    member axis split 4-ways — ``parallel.population_shardings``)
    reproduce the replicated single-device population run with zero
    cross-member communication: every integer leaf (ring write indices,
    episode counters, stored actions — the CONTROL state) is bit-equal,
    and float leaves agree at ULP scale. Measured CPU fact the
    tolerance stands on: there is no psum to reassociate, but SPMD
    partitioning retiles each member's reduces (batch-P arrays on one
    device vs batch-P/D shards per device), which drifts f32 sums by
    ~1 ULP exactly like the documented P=1 vmap story
    (docs/POPULATION.md §parity) — observed max 5.5e-7 relative on
    params and ~1e-7 absolute (2.4e-5 relative) on small-magnitude
    adam moments after the first gated train."""
    from t2omca_tpu.parallel import make_mesh, population_shardings
    cfgp = pop_cfg(4)
    expp = Experiment.build(cfgp)
    ts_rep, _ = _pop_dispatches(expp, cfgp, 3)

    mesh = make_mesh(4)
    exps = Experiment.build(cfgp)
    ts_sh, _ = _pop_dispatches(
        exps, cfgp, 3,
        shardings=lambda tree: population_shardings(mesh, tree))
    _assert_trees_ulp_close(ts_rep, jax.device_get(ts_sh), msg="state ")


# ---------------------------------------------------- population x sebulba

@pytest.mark.slow   # two full tiny driver runs (~150 s)
def test_population_sebulba_lockstep_matches_population_classic(tmp_path):
    """The rank-P lift of the solo lockstep anchor
    (tests/test_sebulba.py): a P=2 population behind the 1+1 device
    split at queue_slots=1/staleness=0 ends on the classic population
    driver's train state — every control/integer leaf (stored actions,
    ring write indices, episode counters, t_env) bit-equal, float
    leaves at f32 ULP scale. Measured CPU fact the tolerance stands on:
    the per-member losses/returns are IDENTICAL at every log cadence
    (same trajectories, same train sequence), but the vmapped SPLIT
    learner program and the vmapped FUSED superstep tile their batched
    f32 reduces differently — observed max 1 ULP (1.1e-7 rel) on final
    params. The bitwise version of this anchor lives at P=1, where both
    paths squeeze to the verbatim solo programs (tests/test_sebulba.py
    pins solo lockstep ≡ solo classic bit-exactly)."""
    cfg_classic = pop_cfg(2, tmp_path, test_interval=24)
    cfg_seb = pop_cfg(2, tmp_path, test_interval=24,
                      sebulba=SebulbaConfig(actor_devices=1,
                                            learner_devices=1,
                                            queue_slots=1, staleness=0))
    ts1 = run_sequential(Experiment.build(cfg_classic), Logger(),
                         str(tmp_path / "classic"))
    ts2 = run_sequential(Experiment.build(cfg_seb), Logger(),
                         str(tmp_path / "sebulba"))
    h1, h2 = jax.device_get(ts1), jax.device_get(ts2)
    _assert_trees_ulp_close(h1.learner, h2.learner, msg="learner ")
    _assert_trees_ulp_close(h1.buffer, h2.buffer, msg="buffer ")
    _assert_trees_ulp_close(h1.runner, h2.runner, msg="runner ")
    _assert_trees_ulp_close(h1.episode, h2.episode, msg="episode ")


# ------------------------------------------------------------- bench legs

def test_daemon_matrix_has_lattice_leg():
    """--daemon's A/B matrix gained the lattice leg, and --legs
    validates it by name."""
    import bench
    ns = argparse.Namespace(smoke=True, iters=1, artifact=None,
                            legs=None)
    legs = dict(bench._daemon_legs(ns))
    assert legs["lattice"] == ["--lattice", "--smoke", "--iters", "1"]
    ns.legs = "lattice"
    assert [n for n, _ in bench._daemon_legs(ns)] == ["lattice"]
    ns.legs = "nope"
    with pytest.raises(SystemExit, match="lattice"):
        bench._daemon_legs(ns)


def test_bench_population_rejects_ab_kernels_with_alternative():
    """--population --kernels ab is rejected NAMING the legal
    single-mode alternatives (the lattice composition gate)."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"),
         "--population", "4", "--kernels", "ab", "--smoke"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "--kernels pallas or --kernels xla" in r.stderr
    assert "--lattice" in r.stderr


@pytest.mark.slow   # a full smoke pop x sebulba bench child (~3 min)
def test_bench_population_sebulba_record_schema():
    """--population P --sebulba emits one schema-1 record carrying the
    lockstep headline, the serialized A/B and the population-classic
    context ratio."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"),
         "--population", "2", "--sebulba", "--smoke", "--iters", "1"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "env_steps_per_sec"
    assert rec["schema"] == 1
    assert rec["population"] == 2
    assert rec["sebulba"] == {"actor_devices": 1, "learner_devices": 1,
                              "queue_slots": 1, "staleness": 0}
    assert rec["value"] > 0
    assert rec["serialized_env_steps_per_sec"] > 0
    assert rec["overlap_speedup"] > 0
    assert rec["population_classic_env_steps_per_sec"] > 0
    assert rec["lockstep_vs_classic"] > 0
    assert rec["serial_solo_env_steps_per_sec"] > 0
    # the compounded population x overlap ratio over the pre-lattice
    # serial-campaign baseline. Schema-presence only: the acceptance
    # reading (>= 1) is taken from the RECORDED P=4 smoke
    # (`bench.py --population 4 --sebulba`, docs/POPULATION.md) — a
    # timing ratio asserted inside a unit test on a shared 1-core CI
    # host measures the host's load, not the lattice.
    assert rec["lockstep_vs_serial_solo"] > 0
    assert rec["host_cores"] >= 1


@pytest.mark.slow   # a pallas-mode smoke bench child (~3 min)
def test_bench_population_kernels_record_schema():
    """--population P --kernels pallas composes: the record carries the
    kernel mode next to the population A/B."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"),
         "--population", "2", "--kernels", "pallas", "--smoke",
         "--iters", "1"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "experiments_per_sec"
    assert rec["population"] == 2
    assert rec["kernels"] == "pallas"
    assert rec["value"] > 0
