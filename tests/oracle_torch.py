"""PyTorch oracle for golden-value parity tests.

A compact, independent transcription of the reference math (SURVEY.md §2.1
C5–C7; quirks Q1/Q2/Q11/Q12) used only by tests: we load identical weights
into both this oracle and the flax modules and require matching outputs.
This is the "pinned oracle" strategy of SURVEY.md §7.4(2) — the learner and
several reference modules were never released, so parity is defined against
this spec, not against running the reference.

Functional style on purpose (no nn.Module graph): takes a flat dict of
tensors whose keys mirror the flax param tree.
"""

from __future__ import annotations

import torch
import torch.nn.functional as F


def mha(p, prefix, q, k, heads):
    """Full-emb-head attention: projections emb->emb*heads, q/k scaled by
    head_dim**-0.25 (quirk Q1)."""
    b, t_q, e = q.shape
    t_k = k.shape[1]
    kk = k @ p[f"{prefix}/tokeys"]            # (b, t_k, h*e)
    qq = q @ p[f"{prefix}/toqueries"]
    vv = k @ p[f"{prefix}/tovalues"]
    kk = kk.view(b, t_k, heads, e).transpose(1, 2) / e ** 0.25
    qq = qq.view(b, t_q, heads, e).transpose(1, 2) / e ** 0.25
    vv = vv.view(b, t_k, heads, e).transpose(1, 2)
    dot = qq @ kk.transpose(-1, -2)
    attn = F.softmax(dot, dim=-1)
    out = (attn @ vv).transpose(1, 2).reshape(b, t_q, heads * e)
    return out @ p[f"{prefix}/unifyheads"] + p[f"{prefix}/unifyheads_b"]


def layer_norm(p, prefix, x):
    return F.layer_norm(x, (x.shape[-1],), p[f"{prefix}/scale"], p[f"{prefix}/bias"])


def block(p, prefix, q, k, heads):
    """Post-LN block, residual adds the query input (quirk Q2)."""
    att = mha(p, f"{prefix}/attention", q, k, heads)
    x = layer_norm(p, f"{prefix}/norm1", att + q)
    ff = F.relu(x @ p[f"{prefix}/ff1"] + p[f"{prefix}/ff1_b"])
    ff = ff @ p[f"{prefix}/ff2"] + p[f"{prefix}/ff2_b"]
    return layer_norm(p, f"{prefix}/norm2", ff + x)


def transformer(p, prefix, q, k, heads, depth):
    """Keys pinned to the layer-0 input across blocks (reference
    transformer.py:126,140 tuple threading)."""
    x = q
    for i in range(depth):
        x = block(p, f"{prefix}/block_{i}", x, k, heads)
    return x


def agent_forward(p, inputs, hidden, *, n_entities, feat_dim, emb, heads, depth):
    """TransformerAgent: hidden token prepended, token 0 out (C6)."""
    b, a, _ = inputs.shape
    x = inputs.reshape(b * a, n_entities, feat_dim)
    h = hidden.reshape(b * a, 1, emb)
    embs = x @ p["feat_embedding"] + p["feat_embedding_b"]
    tokens = torch.cat([h, embs], dim=1)
    out = transformer(p, "transformer", tokens, tokens, heads, depth)
    h_new = out[:, 0:1, :]
    qv = h_new @ p["q_basic"] + p["q_basic_b"]
    return qv.reshape(b, a, -1), h_new.reshape(b, a, emb)


def mixer_forward(p, qvals, hidden_states, hyper_weights, states, obs, *,
                  n_agents, n_entities, feat_dim, emb, heads, depth,
                  state_entity_mode=True, pos="abs", pos_beta=1.0):
    """TransformerMixer: hypernet weights read off positional tokens (C7/Q11)."""
    b = qvals.shape[0]
    if state_entity_mode:
        inp = states.reshape(b, n_entities, feat_dim)
    else:
        inp = obs.reshape(b, n_agents * n_entities, feat_dim)
    embs = inp @ p["feat_embedding"] + p["feat_embedding_b"]
    tokens = torch.cat([embs, hidden_states, hyper_weights], dim=1)
    out = transformer(p, "transformer", tokens, tokens, heads, depth)
    w1 = out[:, -3 - n_agents:-3, :]
    b1 = out[:, -3, :].view(b, 1, emb)
    w2 = out[:, -2, :].view(b, emb, 1)
    b2 = F.relu(out[:, -1, :] @ p["hyper_b2"] + p["hyper_b2_b"]).view(b, 1, 1)

    def pos_fn(x):
        if pos == "softplus":
            # torch.nn.Softplus(beta=b) == softplus(b*x)/b
            return F.softplus(x, beta=pos_beta)
        if pos == "quadratic":
            return 0.5 * x ** 2
        if pos == "abs":
            return torch.abs(x)
        return x

    w1, w2 = pos_fn(w1), pos_fn(w2)
    hid = F.elu(qvals @ w1 + b1)
    y = hid @ w2 + b2
    return y, out[:, -3:, :]


# --------------------------------------------------------------------- QMIX

def qmix_episode_loss(p_ag, p_mx, tp_ag, tp_mx, batch, weights, *, gamma,
                      n_agents, agent_kw, mixer_kw, double_q=True):
    """The full QMIX loss on one episode batch — the oracle for
    ``learners/qmix_learner.py:_loss`` (M8 contract, SURVEY.md §3.3):
    double-Q targets with avail masking, BOTH recurrent streams carried
    from t=0 (agent hidden token + mixer hyper tokens; the target mixer
    unrolls over all T+1 steps and its outputs [1:] are the bootstraps),
    time-limit steps bootstrap (Q7: ``terminated`` excludes them), and the
    importance-weighted masked MSE.

    ``batch``: dict of torch tensors — obs ``(B, T+1, A, O)``,
    state ``(B, T+1, S)``, avail ``(B, T+1, A, n)``, actions ``(B, T, A)``
    long, reward/terminated/filled ``(B, T)``. ``agent_kw``/``mixer_kw``
    forward to :func:`agent_forward` / :func:`mixer_forward`.
    """
    obs, state = batch["obs"], batch["state"]
    avail, actions = batch["avail"], batch["actions"]
    reward, term, mask = (batch["reward"], batch["terminated"],
                          batch["filled"])
    b, t1 = obs.shape[0], obs.shape[1]
    t = t1 - 1
    emb = agent_kw["emb"]

    def unroll_agent(p):
        hidden = torch.zeros(b, n_agents, emb)
        qs, hs = [], []
        for i in range(t1):
            q, hidden = agent_forward(p, obs[:, i], hidden, **agent_kw)
            qs.append(q)
            hs.append(hidden)
        return torch.stack(qs, 1), torch.stack(hs, 1)   # (B, T+1, A, ...)

    qs, hs = unroll_agent(p_ag)
    with torch.no_grad():
        target_qs, target_hs = unroll_agent(tp_ag)

    chosen = qs[:, :t].gather(-1, actions.unsqueeze(-1)).squeeze(-1)

    masked_all = qs.masked_fill(avail <= 0, -torch.inf)
    if double_q:
        best = masked_all.argmax(dim=-1, keepdim=True)
        target_max = target_qs.gather(-1, best).squeeze(-1)  # (B, T+1, A)
    else:
        target_max = target_qs.masked_fill(avail <= 0,
                                           -torch.inf).max(dim=-1).values

    memb = mixer_kw["emb"]

    def unroll_mixer(p, qv_seq, h_seq, steps, grad=True):
        hyper = torch.zeros(b, 3, memb)
        outs = []
        ctx = torch.enable_grad() if grad else torch.no_grad()
        with ctx:
            for i in steps:
                y, hyper = mixer_forward(
                    p, qv_seq[:, i].unsqueeze(1), h_seq[:, i], hyper,
                    state[:, i], obs[:, i], **mixer_kw)
                outs.append(y[:, 0, 0])
        return torch.stack(outs, 1)                      # (B, len(steps))

    q_tot = unroll_mixer(p_mx, chosen, hs, range(t))
    target_q_tot = unroll_mixer(tp_mx, target_max, target_hs, range(t1),
                                grad=False)[:, 1:]

    targets = reward + gamma * (1.0 - term) * target_q_tot
    td = (q_tot - targets.detach()) * mask
    denom = torch.clamp(mask.sum(), min=1.0)
    return (weights[:, None] * td ** 2).sum() / denom
