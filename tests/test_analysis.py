"""Tracing-hygiene enforcement (t2omca_tpu/analysis, docs/ANALYSIS.md):
per-rule positive/negative fixtures for graftlint, baseline round-trip,
the zero-new-findings ratchet over the real package, and the runtime
guards (compile_budget / no_transfer) on toy programs — the cheap,
always-in-gate half; the superstep-program-level enforcement lives in
tests/test_superstep.py (slow: full jit compiles)."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from t2omca_tpu.analysis import (RULES, CompileBudgetExceeded,
                                 compile_budget, diff_baseline,
                                 lint_package, lint_source, load_baseline,
                                 no_transfer, save_baseline)

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]


def rules_of(src, path="fixture.py", hot=None):
    return [f.rule for f in lint_source(src, path, hot=hot)]


# --------------------------------------------------------------- GL101

def test_gl101_if_on_traced_param_in_jitted_fn():
    src = """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""
    fs = lint_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GL101"]
    assert fs[0].line == 5 and "if" in fs[0].code


def test_gl101_while_in_scan_body_and_derived_local():
    src = """
import jax, jax.numpy as jnp
def outer(xs):
    def body(c, x):
        y = jnp.abs(x)
        while y > 1:
            y = y - 1
        return c, y
    return jax.lax.scan(body, 0, xs)
"""
    assert rules_of(src) == ["GL101"]


def test_gl101_negatives_static_none_isinstance_config():
    src = """
import jax
from functools import partial

@partial(jax.jit, static_argnames="mode")
def f(x, mode):
    if mode:                     # static arg: branch is fine
        return x
    return -x

@jax.jit
def g(x, key):
    if key is None:              # identity vs None: static on tracers
        return x
    if isinstance(x, tuple):     # type test: static
        return x[0]
    return x + 1

def h(cfg, x):                   # not traced at all
    if cfg:
        return x
"""
    assert rules_of(src) == []


def test_gl101_static_argnums_call_site():
    src = """
import jax
def f(x, n):
    if n > 2:
        return x
    return -x
jf = jax.jit(f, static_argnums=(1,))
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL102

def test_gl102_concretizing_calls_on_tracers():
    src = """
import jax, jax.numpy as jnp, numpy as np
@jax.jit
def f(x):
    a = float(x)
    b = jnp.sum(x).item()
    c = np.square(x)
    jax.device_get(x)
    return a + b + c
"""
    assert sorted(rules_of(src)) == ["GL102"] * 4


def test_gl102_negative_static_numpy_and_host_code():
    src = """
import jax, numpy as np
@jax.jit
def f(x):
    n = np.prod((2, 3))          # static shape math: no tracer touched
    return x * n

def host(arr):
    return float(np.asarray(arr).mean())   # not traced code
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL103

def test_gl103_host_rng_in_traced_code():
    src = """
import jax, random
import numpy as np
@jax.jit
def f(x):
    return x + np.random.randn(3) * random.random()
"""
    assert sorted(rules_of(src)) == ["GL103", "GL103"]


def test_gl103_negative_jax_random():
    src = """
import jax
@jax.jit
def f(x, key):
    return x + jax.random.normal(key, x.shape)
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL104

def test_gl104_jnp_in_python_for_loop():
    src = """
import jax, jax.numpy as jnp
@jax.jit
def f(x):
    for _ in range(100):
        x = jnp.sin(x)
    return x
"""
    fs = lint_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GL104"]
    assert "lax.scan" in fs[0].message


def test_gl104_negative_host_loop():
    src = """
import jax.numpy as jnp
def driver(prog, ts):
    out = []
    for i in range(3):           # host loop around dispatches: fine
        ts, info = prog(ts, jnp.asarray(i))
        out.append(info)
    return ts, out
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL105

HOST_SYNC = """
import jax
def poll(x):
    jax.block_until_ready(x)
    return jax.device_get(x)
"""


def test_gl105_hot_path_only():
    hot = lint_source(HOST_SYNC, "t2omca_tpu/run.py")
    assert [f.rule for f in hot] == ["GL105", "GL105"]
    assert lint_source(HOST_SYNC, "t2omca_tpu/utils/stats.py") == []
    # runners/* glob
    assert rules_of(HOST_SYNC, "t2omca_tpu/runners/episode_runner.py") \
        == ["GL105", "GL105"]


def test_gl105_method_style_block_until_ready():
    src = "def wait(arr):\n    arr.block_until_ready()\n"
    assert rules_of(src, "t2omca_tpu/learners/qmix_learner.py") == ["GL105"]


# --------------------------------------------------------------- GL106

def test_gl106_time_in_traced_code():
    src = """
import jax, time, datetime
@jax.jit
def f(x):
    return x + time.time()

def host_cadence():
    return time.time(), datetime.datetime.now()   # host code: fine
"""
    assert rules_of(src) == ["GL106"]


# --------------------------------------------------------------- GL107

def test_gl107_shared_allocation_across_fields():
    """The exact NormState.create bug class PR 2 hit: one zeros buffer
    for mean/s/std trips XLA's donate-twice check."""
    src = """
import jax.numpy as jnp
def create(shape):
    z = jnp.zeros(shape)
    return NormState(mean=z, s=z, std=z)
"""
    fs = lint_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GL107"]
    assert "donate" in fs[0].message


def test_gl107_negative_distinct_buffers_and_read_aliasing():
    src = """
import jax.numpy as jnp
def create(shape):
    return NormState(mean=jnp.zeros(shape), s=jnp.zeros(shape),
                     std=jnp.zeros(shape))

def read_alias(shape):
    z = jnp.zeros(shape)
    return jnp.maximum(z, z)     # reads may alias; only state may not
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL108

def test_gl108_dead_import():
    src = "import os\nimport sys\nprint(sys.argv)\n"
    fs = lint_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GL108"]
    assert "`os`" in fs[0].message


def test_gl108_negatives_init_all_and_annotations():
    # __init__.py is a re-export surface
    assert rules_of("import os\n", "t2omca_tpu/sub/__init__.py") == []
    # __all__ strings count as use
    assert rules_of('from a import b\n__all__ = ["b"]\n') == []
    # annotation-only use counts (PEP 563 keeps Name nodes in the AST)
    assert rules_of(
        "from typing import Optional\ndef f(x: Optional[int]): pass\n"
    ) == []


# --------------------------------------------------------------- GL109

def test_gl109_module_level_capture():
    src = """
import jax, jax.numpy as jnp
TABLE = jnp.arange(1000)
@jax.jit
def f(x):
    return x + TABLE
"""
    fs = lint_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GL109"]
    assert "`TABLE`" in fs[0].message and "line 3" in fs[0].message


def test_gl109_nontraced_builder_capture():
    src = """
import jax, jax.numpy as jnp
def make():
    table = jnp.ones((256, 256))
    @jax.jit
    def f(x):
        return x @ table
    return f
"""
    assert rules_of(src) == ["GL109"]


def test_gl109_negatives_param_shadow_and_traced_source():
    src = """
import jax, jax.numpy as jnp

def init():                      # unrelated scope, same name
    weights = jnp.zeros((8, 8))
    return weights

def apply(weights, x):           # the capture resolves to THIS param
    def body(c, t):
        return c @ weights + t, None
    return jax.lax.scan(body, x, None, length=3)

@jax.jit
def g(x):
    y = jnp.abs(x)               # bound locally: a tracer, not a const
    def inner(z):
        return z + y
    return inner(x)
"""
    assert rules_of(src) == []


def test_gl109_negative_nested_param_shadows_module_array():
    src = """
import jax, jax.numpy as jnp
W = jnp.ones((256, 256))
@jax.jit
def f(x, ws):
    def body(carry, W):              # param shadows the module array
        return carry @ W, None
    return jax.lax.scan(body, x, ws)
"""
    assert rules_of(src) == []


def test_gl109_negative_class_attribute_is_not_a_closure_binding():
    src = """
import jax, jax.numpy as jnp
class Cfg:
    TABLE = jnp.arange(1000)     # attribute (Cfg.TABLE), not a capture
@jax.jit
def f(x):
    return x + Cfg.TABLE.shape[0]
"""
    assert rules_of(src) == []
    # ...and a class attr must not shadow a REAL module-level array
    src2 = """
import jax, jax.numpy as jnp
class C:
    TABLE = jnp.zeros(())
TABLE = jnp.arange(1000)
@jax.jit
def f(x):
    return x + TABLE
"""
    assert rules_of(src2) == ["GL109"]


def test_gl109_negative_static_metadata_capture():
    src = """
import jax, jax.numpy as jnp
sd = jnp.dtype("bfloat16")       # static metadata, not an array
@jax.jit
def f(x):
    return x.astype(sd)
"""
    assert rules_of(src) == []


def test_gl109_suppression():
    src = """
import jax, jax.numpy as jnp
TABLE = jnp.arange(10)
@jax.jit
def f(x):
    return x + TABLE  # graftlint: disable=GL109
"""
    assert rules_of(src) == []


# --------------------------------------------------------------- GL110

_GL110_SRC = """
def loop(_watched, _sync_point, _dispatch):
    with _watched("dispatch.superstep", None):
        pass
    _sync_point("fetch.train_stats", lambda: None)
    _dispatch("dispatch.bogus", lambda: None, None)
    _dispatch(phase="fetch.bogus", fn=lambda: None)
"""


def test_gl110_unregistered_phase_flagged():
    phases = {"dispatch.superstep", "fetch.train_stats"}
    fs = lint_source(_GL110_SRC, "fixture.py", span_phases=phases)
    assert sorted(f.rule for f in fs) == ["GL110", "GL110"]
    msgs = " | ".join(f.message for f in fs)
    assert "dispatch.bogus" in msgs           # positional literal
    assert "fetch.bogus" in msgs              # phase= keyword literal
    assert "KNOWN_PHASES" in msgs


def test_gl110_disabled_without_registry_and_skips_dynamic():
    # no span_phases (registry absent) -> rule disarmed entirely
    assert lint_source(_GL110_SRC, "fixture.py") == []
    # dynamic phases are invisible to AST: never flagged
    src = """
def f(_watched, name):
    with _watched(name, None):
        pass
"""
    assert lint_source(src, "fixture.py", span_phases=set()) == []


def test_gl110_registry_parsed_from_spans_module(tmp_path):
    """``lint_package`` arms GL110 from the real obs/spans.py — parsed
    by AST, never imported — and the real driver is clean against it."""
    from t2omca_tpu.analysis.graftlint import collect_span_phases
    phases = collect_span_phases(REPO)
    assert phases is not None
    assert "dispatch.superstep" in phases and "bench.probe" in phases
    # a repo without the registry file disarms the rule (None)
    assert collect_span_phases(tmp_path) is None
    # and an unregistered phase in a package file WOULD be a gate
    # failure: prove the plumbing end-to-end through lint_package
    pkg = tmp_path / "t2omca_tpu"
    (pkg / "obs").mkdir(parents=True)
    (pkg / "obs" / "spans.py").write_text(
        'KNOWN_PHASES = frozenset({"dispatch.good"})\n')
    (pkg / "driver.py").write_text(_GL110_SRC.replace(
        "dispatch.superstep", "dispatch.good"))
    found = lint_package(tmp_path)
    gl110 = [f for f in found if f.rule == "GL110"]
    assert {f.message.split("'")[1] for f in gl110} == \
        {"dispatch.bogus", "fetch.bogus", "fetch.train_stats"}


# --------------------------------------------------------------- GL112

_GL112_SRC = """
from flax import serialization

def load(blob, template, raw):
    params = serialization.msgpack_restore(blob)
    agent = serialization.from_state_dict(template, raw)
    return params, agent
"""


def test_gl112_raw_deserialize_in_ckpt_modules():
    """Both flax deserializers flag in the driver and serve modules —
    the checkpoint-door contract (docs/ANALYSIS.md GL112)."""
    for path in ("t2omca_tpu/run.py", "t2omca_tpu/serve/export2.py"):
        fs = lint_source(_GL112_SRC, path)
        assert [f.rule for f in fs] == ["GL112", "GL112"], path
        msgs = " | ".join(f.message for f in fs)
        assert "msgpack_restore" in msgs
        assert "from_state_dict" in msgs
        assert "utils/checkpoint" in msgs


def test_gl112_scoped_to_ckpt_globs_and_alias_resolved():
    # utils/checkpoint.py IS the sanctioned door; library code elsewhere
    # may deserialize whatever it owns — neither is in CKPT_PATH_GLOBS
    assert lint_source(_GL112_SRC, "t2omca_tpu/utils/checkpoint.py") == []
    assert lint_source(_GL112_SRC, "t2omca_tpu/components/foo.py") == []
    # alias-resolved: `import flax.serialization as ser` still flags,
    # and an unresolvable receiver falls back to the attribute name
    src = """
import flax.serialization as ser

def load(blob, codec):
    a = ser.msgpack_restore(blob)
    b = codec().from_state_dict(None, blob)
    return a, b
"""
    fs = lint_source(src, "t2omca_tpu/serve/x.py")
    assert [f.rule for f in fs] == ["GL112", "GL112"]
    # a same-named call on a RESOLVABLE non-flax receiver is not a raw
    # checkpoint load (the fallback only covers opaque receivers)
    clean = """
import mylib

def load(blob):
    return mylib.msgpack_restore(blob)
"""
    assert lint_source(clean, "t2omca_tpu/serve/x.py") == []


# ---------------------------------------------------------- suppression

def test_inline_suppression_and_skip_file():
    src = """
import jax
@jax.jit
def f(x):
    if x > 0:  # graftlint: disable=GL101
        return x
    return -x
"""
    assert rules_of(src) == []
    # disabling a DIFFERENT rule does not suppress
    assert rules_of(src.replace("GL101", "GL105")) == ["GL101"]
    skip = "# graftlint: skip-file\n" + src
    assert rules_of(skip) == []
    # a lowercase/typo'd rule list suppresses THAT rule (normalized),
    # never the whole line; a junk list suppresses nothing
    assert rules_of(src.replace("GL101", "gl101")) == []
    assert rules_of(src.replace("GL101", "bogus")) == ["GL101"]


def test_traced_dataflow_reaches_fixpoint():
    """Taint chains written in reverse definition order still propagate
    (the fixpoint loop must iterate until the set stops growing)."""
    src = """
import jax
@jax.jit
def f(x):
    w = 0
    z = 0
    y = 0
    for _ in range(2):
        w = z
        z = y
        y = x
    if w > 0:
        return w
    return -w
"""
    assert "GL101" in rules_of(src)


# ------------------------------------------------------------- baseline

def test_baseline_round_trip_and_ratchet(tmp_path):
    src_v1 = """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""
    findings = lint_source(src_v1, "pkg/mod.py")
    assert len(findings) == 1
    bl_path = tmp_path / "baseline.json"
    save_baseline(bl_path, findings)
    baseline = load_baseline(bl_path)
    # round-trip: the same findings are fully baselined
    new, stale = diff_baseline(findings, baseline)
    assert new == [] and stale == []
    # a SECOND occurrence of the same hazard (same code text, new line)
    # exceeds the baselined count -> new
    src_v2 = src_v1 + """
@jax.jit
def g(x):
    if x > 0:
        return x
    return -x
"""
    new, stale = diff_baseline(lint_source(src_v2, "pkg/mod.py"), baseline)
    assert len(new) == 1 and new[0].rule == "GL101"
    # fixing the hazard leaves a stale entry, never a failure
    new, stale = diff_baseline(lint_source("", "pkg/mod.py"), baseline)
    assert new == [] and len(stale) == 1
    # unjustified entries carry the TODO marker for review
    assert json.loads(bl_path.read_text())["findings"][0][
        "justification"].startswith("TODO")


def test_baseline_identity_survives_line_shift(tmp_path):
    src = """
import jax
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
"""
    bl_path = tmp_path / "baseline.json"
    save_baseline(bl_path, lint_source(src, "pkg/mod.py"))
    shifted = "\n\n# a new header comment\n" + src
    new, stale = diff_baseline(lint_source(shifted, "pkg/mod.py"),
                               load_baseline(bl_path))
    assert new == [] and stale == []


# ------------------------------------------------- the real package gate

def test_real_package_zero_new_findings():
    """The ratchet over t2omca_tpu/ itself: every current finding is
    either fixed or baselined with a justification — new hazards fail
    here (and in scripts/lint.sh before the tier-1 pytest batch)."""
    findings = lint_package(REPO)
    baseline = load_baseline()
    new, _stale = diff_baseline(findings, baseline)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)
    # and every baselined acceptance carries a real justification
    for key, entry in baseline.items():
        assert entry["justification"] and \
            not entry["justification"].startswith("TODO"), key


def test_cli_exit_codes(tmp_path):
    """0 on the clean repo; 1 with rule ID + file:line once a hazard is
    seeded (the ISSUE acceptance demo, via a copied mini-package)."""
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    # seeded hazard in a scratch tree (repo-shaped so hot-path globs work)
    pkg = tmp_path / "t2omca_tpu"
    pkg.mkdir()
    hazard = pkg / "seeded.py"
    hazard.write_text(
        "import jax\n@jax.jit\ndef f(x):\n    if x > 0:\n"
        "        return x\n    return -x\n")
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--root",
         str(tmp_path), "--no-baseline", str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "GL101" in r.stdout and "t2omca_tpu/seeded.py:4" in r.stdout
    # a corrupt baseline is an internal error (2), never "new findings"
    bad = tmp_path / "bad_baseline.json"
    bad.write_text('{"version": 99, "findings": []}')
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--baseline",
         str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2 and "baseline" in r.stderr


def test_rule_catalog_documented():
    """Every rule ID is in docs/ANALYSIS.md and vice versa (the catalog
    is the user-facing contract)."""
    doc = (REPO / "docs" / "ANALYSIS.md").read_text()
    from t2omca_tpu.analysis.graftprog import GP_RULES
    for rule in list(RULES) + list(GP_RULES):
        assert rule in doc, f"{rule} missing from docs/ANALYSIS.md"


# ------------------------------------------------------- runtime guards

def test_compile_budget_counts_and_raises():
    import jax
    import jax.numpy as jnp

    def poly(x):
        return x * x + 3.0

    prog = jax.jit(poly)
    with compile_budget(1, match="poly") as log:
        for _ in range(4):
            prog(jnp.ones(3))            # one compile, then cache hits
    assert log.count == 1 and any("poly" in n for n in log.names)

    prog2 = jax.jit(lambda x: x - 1.0)
    with pytest.raises(CompileBudgetExceeded, match="retracing"):
        with compile_budget(1):
            prog2(jnp.ones(3))
            prog2(jnp.ones(4))           # shape change -> retrace


def test_compile_budget_match_filters_unrelated_compiles():
    import jax
    import jax.numpy as jnp

    def matched_fn(x):
        return x + 2.0

    prog = jax.jit(matched_fn)
    with compile_budget(1, match="matched_fn") as log:
        prog(jnp.ones(5))
        # unrelated op compiles (bare jnp ops are their own tiny
        # programs) must not count against the budget
        jnp.arange(7.0) * 3
    assert log.count == 1


def test_no_transfer_blocks_implicit_host_to_device():
    import jax
    import jax.numpy as jnp
    import numpy as np

    prog = jax.jit(lambda a, t: a * t)
    x = jnp.arange(3.0)
    t = jnp.asarray(2, jnp.int32)
    prog(x, t)                           # compile outside the guard
    with no_transfer():
        prog(x, t)                       # all-device dispatch: clean
    with pytest.raises(Exception, match="[Tt]ransfer"):
        with no_transfer():
            prog(x, 2)                   # python scalar sneaks into args
    with pytest.raises(Exception, match="[Tt]ransfer"):
        with no_transfer():
            prog(np.ones(3, np.float32), t)   # numpy arg -> implicit H2D
    # explicit transfers stay allowed: the cadence-boundary contract
    with no_transfer():
        jax.device_get(x)
