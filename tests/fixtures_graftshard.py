"""Seeded comms-regression fixtures for the graftshard CLI acceptance
test (``tests/test_graftshard.py`` — the ``fixtures_graftprog``
pattern): four toy MESH programs, each tripping exactly ONE GP4xx rule
when audited with ``--comms --program-module`` against the crafted
baseline the test writes (exclusivity comes from the baseline: GP401/402
are ratchets, so each fixture's baseline entry accepts everything except
the one hazard it seeds). Never imported by the package.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from t2omca_tpu.parallel.mesh import make_mesh

#: fixture mesh width — matches the smallest real audit mesh
N_DEV = 2


def _sharded(shape, mesh, spec, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype,
                                sharding=NamedSharding(mesh, spec))


def register_audit_programs(ctx):
    from t2omca_tpu.analysis.registry import AuditProgram

    if len(jax.devices()) < N_DEV:
        skip = AuditProgram.skipped(f"needs >= {N_DEV} devices")
        return {f"seeded_gp40{i}": skip for i in range(1, 5)}
    mesh = make_mesh(N_DEV)
    x = _sharded((8, 4), mesh, P("data"))

    # GP401: a collective kind (the mean's all-reduce) the crafted
    # baseline's empty census never accepted
    def center(v):
        return v - jnp.mean(v)
    center.__name__ = center.__qualname__ = "_seeded_gp401"

    # GP402: same collective, baselined kind-count generous but the
    # bytes budget pinned to 1 with tolerance 0
    def center2(v):
        return v - jnp.mean(v)
    center2.__name__ = center2.__qualname__ = "_seeded_gp402"

    # GP403: forced replication of the full sharded input — the
    # compiled program must all-gather the whole leaf (>= the largest
    # sharded input's unsharded size) to satisfy the replicated output
    def regather(v):
        return v * jnp.float32(2.0)
    regather.__name__ = regather.__qualname__ = "_seeded_gp403"
    gather_jit = jax.jit(regather,
                         out_shardings=NamedSharding(mesh, P()))

    # GP404: the donated arg carries NO stamped sharding, so GSPMD
    # propagates the sharded companion's layout onto its entry — the
    # caller's (undeclared) buffer is resharded on dispatch and the
    # donation frees the copy, not the original
    def bump(w, v):
        return w + v
    bump.__name__ = bump.__qualname__ = "_seeded_gp404"
    resharded_jit = jax.jit(bump, donate_argnums=(0,))

    return {
        "seeded_gp401": AuditProgram(
            jax.jit(center), (x,),
            description="unbaselined all-reduce (GP401 seed)"),
        "seeded_gp402": AuditProgram(
            jax.jit(center2), (x,),
            description="collective bytes past a 1-byte budget "
                        "(GP402 seed)"),
        "seeded_gp403": AuditProgram(
            gather_jit, (x,),
            description="full-leaf all-gather via a forced replicated "
                        "output (GP403 seed)"),
        "seeded_gp404": AuditProgram(
            resharded_jit,
            (jax.ShapeDtypeStruct((8, 4), jnp.float32), x),
            donate_argnums=(0,),
            description="donated leaf unstamped, GSPMD shards its entry "
                        "layout (GP404 seed)"),
    }


def crafted_baseline() -> dict:
    """The programs.json payload the acceptance test writes: each entry
    accepts everything EXCEPT its program's seeded hazard, so every
    fixture fails with exactly one rule id."""
    generous = {"count": 99, "bytes": 10 ** 9,
                "axes": ["data"]}
    just = "seeded-fixture baseline (tests/fixtures_graftshard.py)"
    return {
        "version": 1,
        "platform": "cpu",
        "programs": {
            # empty census: ANY collective kind is unbaselined -> GP401
            "seeded_gp401": {"comms": {
                "collectives": {}, "bytes": 10 ** 9,
                "tolerance": 0.0, "justification": just}},
            # kinds accepted, bytes budget 1 with zero tolerance -> GP402
            "seeded_gp402": {"comms": {
                "collectives": {"all-reduce": dict(generous)},
                "bytes": 1, "tolerance": 0.0, "justification": just}},
            # kinds + bytes generous, GP403 count 0 -> GP403 only
            "seeded_gp403": {"comms": {
                "collectives": {"all-gather": dict(generous),
                                "all-reduce": dict(generous)},
                "bytes": 10 ** 9, "tolerance": 0.0,
                "justification": just}},
            # no collectives in an elementwise program; GP404 count 0
            "seeded_gp404": {"comms": {
                "collectives": {}, "bytes": 0,
                "tolerance": 0.0, "justification": just}},
        },
    }
