"""Loss-scale levers (config.py: td_loss / huber_delta / reward_unit).

VERDICT r4 weak #2: per-step rewards are O(10^2) so the default MSE drives
grad_norm to 1e4-1e5 against grad_norm_clip=10 — every update is clipped to
a direction-only step. These tests pin the two flag-gated remedies:

- ``td_loss="huber"`` (2x-scaled Huber): exactly the MSE inside
  ``|td| <= huber_delta`` and linear outside, so delta->inf IS the MSE and
  each TD element's gradient contribution is bounded by 2*delta.
- ``reward_unit=u``: training with it is bit-identical to training with
  rewards pre-divided by u (static unit change, no state).

Both default OFF; the defaults-guard test keeps every parity config and all
committed learning evidence byte-identical.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.components import PrioritizedReplayBuffer
from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.learners import QMixLearner


@pytest.fixture(scope="module")
def setup():
    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=3,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=10),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    ls = learner.init_state(jax.random.PRNGKey(0))

    from t2omca_tpu.runners import ParallelRunner
    runner = ParallelRunner(env, mac, cfg)
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    buf = PrioritizedReplayBuffer(
        capacity=10, episode_limit=cfg.env_args.episode_limit,
        n_agents=info["n_agents"], n_actions=info["n_actions"],
        obs_dim=info["obs_shape"], state_dim=info["state_shape"],
        alpha=0.6, beta0=0.4, t_max=1000)
    bs = buf.insert_episode_batch(buf.init(), batch)
    sample, idx, w = buf.sample(bs, jax.random.PRNGKey(2), cfg.batch_size, 0)
    return cfg, learner, ls, sample, w


def _with_cfg(learner, **kw):
    return dataclasses.replace(learner, cfg=learner.cfg.replace(**kw))


def _loss_and_grads(learner, ls, sample, w):
    grads, info = jax.grad(learner._loss, has_aux=True)(
        ls.params, ls.target_params, sample, w)
    import optax
    return float(info["loss"]), float(optax.global_norm(grads)), grads


def test_levers_off_by_default():
    cfg = TrainConfig()
    assert cfg.td_loss == "mse"
    assert cfg.reward_unit == 1.0


def test_huber_inf_delta_matches_mse(setup):
    cfg, learner, ls, sample, w = setup
    l_mse, g_mse, grads_mse = _loss_and_grads(learner, ls, sample, w)
    hub = _with_cfg(learner, td_loss="huber", huber_delta=1e9)
    l_h, g_h, grads_h = _loss_and_grads(hub, ls, sample, w)
    assert l_h == l_mse
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 grads_mse, grads_h)


def test_huber_bounds_gradient_scale(setup):
    cfg, learner, ls, sample, w = setup
    # inflate rewards 1000x: the MSE gradient explodes linearly with the
    # TD scale; the Huber gradient is bounded per element by 2*delta
    big = dataclasses.replace(sample, reward=sample.reward * 1000.0)
    _, g_mse, _ = _loss_and_grads(learner, ls, big, w)
    hub = _with_cfg(learner, td_loss="huber", huber_delta=1.0)
    _, g_h, _ = _loss_and_grads(hub, ls, big, w)
    assert g_h < g_mse / 50.0
    # and it is still a descent signal, not zero
    assert g_h > 0.0


def test_reward_unit_equals_prescaled_rewards(setup):
    cfg, learner, ls, sample, w = setup
    u = 100.0
    lev = _with_cfg(learner, reward_unit=u)
    l_a, g_a, grads_a = _loss_and_grads(lev, ls, sample, w)
    pre = dataclasses.replace(sample, reward=sample.reward / u)
    l_b, g_b, grads_b = _loss_and_grads(learner, ls, pre, w)
    assert l_a == l_b
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 grads_a, grads_b)


def test_reward_unit_shrinks_gradients(setup):
    cfg, learner, ls, sample, w = setup
    _, g_raw, _ = _loss_and_grads(learner, ls, sample, w)
    lev = _with_cfg(learner, reward_unit=100.0)
    _, g_u, _ = _loss_and_grads(lev, ls, sample, w)
    assert g_u < g_raw


def test_train_step_with_levers_runs_and_is_finite(setup):
    cfg, learner, ls, sample, w = setup
    lev = _with_cfg(learner, td_loss="huber", huber_delta=10.0,
                    reward_unit=100.0)
    ls2, info = jax.jit(lev.train)(ls, sample, w, jnp.asarray(0),
                                   jnp.asarray(2))
    assert np.isfinite(float(info["loss"]))
    assert np.isfinite(float(info["grad_norm"]))
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b),
                           ls.params, ls2.params)
    assert any(jax.tree.leaves(changed))


def test_sanity_check_validates_lever_flags():
    with pytest.raises(ValueError, match="td_loss"):
        sanity_check(TrainConfig(td_loss="l1"))
    with pytest.raises(ValueError, match="huber_delta"):
        sanity_check(TrainConfig(td_loss="huber", huber_delta=0.0))
    with pytest.raises(ValueError, match="reward_unit"):
        sanity_check(TrainConfig(reward_unit=-1.0))
    with pytest.raises(ValueError, match="double-scale"):
        sanity_check(TrainConfig(
            reward_unit=100.0,
            env_args=EnvConfig(reward_scaling=True)))
