"""Loss-scale levers (config.py: td_loss / huber_delta / reward_unit).

VERDICT r4 weak #2: per-step rewards are O(10^2) so the default MSE drives
grad_norm to 1e4-1e5 against grad_norm_clip=10 — every update is clipped to
a direction-only step. These tests pin the two flag-gated remedies:

- ``td_loss="huber"`` (2x-scaled Huber): exactly the MSE inside
  ``|td| <= huber_delta`` and linear outside, so delta->inf IS the MSE and
  each TD element's gradient contribution is bounded by 2*delta.
- ``reward_unit=u``: training with it is bit-identical to training with
  rewards pre-divided by u (static unit change, no state).

Both default OFF; the defaults-guard test keeps every parity config and all
committed learning evidence byte-identical.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.components import PrioritizedReplayBuffer
from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.learners import QMixLearner


@pytest.fixture(scope="module")
def setup():
    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=3,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=10),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    ls = learner.init_state(jax.random.PRNGKey(0))

    from t2omca_tpu.runners import ParallelRunner
    runner = ParallelRunner(env, mac, cfg)
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    buf = PrioritizedReplayBuffer(
        capacity=10, episode_limit=cfg.env_args.episode_limit,
        n_agents=info["n_agents"], n_actions=info["n_actions"],
        obs_dim=info["obs_shape"], state_dim=info["state_shape"],
        alpha=0.6, beta0=0.4, t_max=1000)
    bs = buf.insert_episode_batch(buf.init(), batch)
    sample, idx, w = buf.sample(bs, jax.random.PRNGKey(2), cfg.batch_size, 0)
    return cfg, learner, ls, sample, w


def _with_cfg(learner, **kw):
    return dataclasses.replace(learner, cfg=learner.cfg.replace(**kw))


def _loss_and_grads(learner, ls, sample, w):
    grads, info = jax.grad(learner._loss, has_aux=True)(
        ls.params, ls.target_params, sample, w)
    import optax
    return float(info["loss"]), float(optax.global_norm(grads)), grads


def test_levers_off_by_default():
    cfg = TrainConfig()
    assert cfg.td_loss == "mse"
    assert cfg.reward_unit == 1.0


@pytest.mark.slow   # huge-delta recompile (~12 s); the gradient-bound huber test stays in-gate
def test_huber_inf_delta_matches_mse(setup):
    cfg, learner, ls, sample, w = setup
    l_mse, g_mse, grads_mse = _loss_and_grads(learner, ls, sample, w)
    hub = _with_cfg(learner, td_loss="huber", huber_delta=1e9)
    l_h, g_h, grads_h = _loss_and_grads(hub, ls, sample, w)
    assert l_h == l_mse
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 grads_mse, grads_h)


def test_huber_bounds_gradient_scale(setup):
    cfg, learner, ls, sample, w = setup
    # inflate rewards 1000x: the MSE gradient explodes linearly with the
    # TD scale; the Huber gradient is bounded per element by 2*delta
    big = dataclasses.replace(sample, reward=sample.reward * 1000.0)
    _, g_mse, _ = _loss_and_grads(learner, ls, big, w)
    hub = _with_cfg(learner, td_loss="huber", huber_delta=1.0)
    _, g_h, _ = _loss_and_grads(hub, ls, big, w)
    assert g_h < g_mse / 50.0
    # and it is still a descent signal, not zero
    assert g_h > 0.0


def test_reward_unit_equals_prescaled_rewards(setup):
    cfg, learner, ls, sample, w = setup
    u = 100.0
    lev = _with_cfg(learner, reward_unit=u)
    l_a, g_a, grads_a = _loss_and_grads(lev, ls, sample, w)
    pre = dataclasses.replace(sample, reward=sample.reward / u)
    l_b, g_b, grads_b = _loss_and_grads(learner, ls, pre, w)
    assert l_a == l_b
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 grads_a, grads_b)


def test_reward_unit_shrinks_gradients(setup):
    cfg, learner, ls, sample, w = setup
    _, g_raw, _ = _loss_and_grads(learner, ls, sample, w)
    lev = _with_cfg(learner, reward_unit=100.0)
    _, g_u, _ = _loss_and_grads(lev, ls, sample, w)
    assert g_u < g_raw


def test_train_step_with_levers_runs_and_is_finite(setup):
    cfg, learner, ls, sample, w = setup
    lev = _with_cfg(learner, td_loss="huber", huber_delta=10.0,
                    reward_unit=100.0)
    ls2, info = jax.jit(lev.train)(ls, sample, w, jnp.asarray(0),
                                   jnp.asarray(2))
    assert np.isfinite(float(info["loss"]))
    assert np.isfinite(float(info["grad_norm"]))
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b),
                           ls.params, ls2.params)
    assert any(jax.tree.leaves(changed))


def _mixer_inputs(emb=16, a=3, n_ent=3, feat=8, b=4):
    k = jax.random.PRNGKey(5)
    return (jax.random.normal(jax.random.fold_in(k, 0), (b, 1, a)),
            jax.random.normal(jax.random.fold_in(k, 1), (b, a, emb)),
            jax.random.normal(jax.random.fold_in(k, 2), (b, 3, emb)),
            jax.random.normal(jax.random.fold_in(k, 3), (b, n_ent * feat)),
            jax.random.normal(jax.random.fold_in(k, 4),
                              (b, a, n_ent * feat)))


def test_mixer_zero_init_gate_outputs_zero_and_learns():
    """mixer_zero_init: q_tot is EXACTLY 0 at init (the O(emb) readout
    init scale is gated away), the recurrent hyper tokens are untouched,
    and the gate parameter receives gradient (it can open)."""
    from t2omca_tpu.models.mixer import TransformerMixer

    emb, a, n_ent, feat = 16, 3, 3, 8
    qv, hid, hyper, st, obs = _mixer_inputs(emb, a, n_ent, feat)
    kw = dict(n_agents=a, n_entities=n_ent, feat_dim=feat, emb=emb,
              heads=2, depth=2, state_entity_mode=True)
    gated = TransformerMixer(zero_init_gate=True, **kw)
    plain = TransformerMixer(**kw)
    params = gated.init(jax.random.PRNGKey(7), qv, hid, hyper, st, obs)

    y, hy = gated.apply(params, qv, hid, hyper, st, obs)
    np.testing.assert_array_equal(np.asarray(y), 0.0)
    # ungated output from the SAME underlying weights is O(10+) — the
    # gate is doing real work
    p_plain = {"params": {k: v for k, v in params["params"].items()
                          if k != "out_gate"}}
    y_plain, hy_plain = plain.apply(p_plain, qv, hid, hyper, st, obs)
    assert float(np.abs(np.asarray(y_plain)).max()) > 1.0
    np.testing.assert_array_equal(np.asarray(hy), np.asarray(hy_plain))

    g = jax.grad(lambda p: gated.apply(p, qv, hid, hyper, st,
                                       obs)[0].sum())(params)
    assert float(np.abs(np.asarray(
        g["params"]["out_gate"])).max()) > 0.0


def test_mixer_gate_qslice_matches_dense():
    """The qslice mixer forward must honor the gate param (opened off its
    0-init so the equality is non-trivial)."""
    from t2omca_tpu.models.mixer import TransformerMixer
    from t2omca_tpu.ops.query_slice import mixer_forward_qslice

    emb, a, n_ent, feat = 16, 3, 3, 8
    qv, hid, hyper, st, obs = _mixer_inputs(emb, a, n_ent, feat)
    mixer = TransformerMixer(n_agents=a, n_entities=n_ent, feat_dim=feat,
                             emb=emb, heads=2, depth=2,
                             state_entity_mode=True, zero_init_gate=True)
    params = mixer.init(jax.random.PRNGKey(7), qv, hid, hyper, st, obs)
    params["params"]["out_gate"] = jnp.full((1,), 0.7)

    y_ref, hy_ref = mixer.apply(params, qv, hid, hyper, st, obs)
    y_qs, hy_qs = mixer_forward_qslice(
        params, qv, hid, hyper, st, obs,
        n_agents=a, n_entities=n_ent, feat_dim=feat, emb=emb,
        heads=2, depth=2, pos_func="abs", pos_func_beta=1.0,
        state_entity_mode=True)
    np.testing.assert_allclose(np.asarray(y_qs), np.asarray(y_ref),
                               rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(np.asarray(hy_qs), np.asarray(hy_ref),
                               rtol=5e-4, atol=5e-5)


def test_train_step_with_gate_opens_gate(setup):
    """e2e: a learner built with mixer_zero_init trains and moves the
    gate off zero — the recipe's full flag set in one step."""
    cfg, learner, ls, sample, w = setup
    from t2omca_tpu.controllers import BasicMAC
    from t2omca_tpu.envs.registry import make_env

    cfg2 = cfg.replace(td_loss="huber", huber_delta=10.0,
                       reward_unit=100.0,
                       model=dataclasses.replace(cfg.model,
                                                 mixer_zero_init=True))
    env = make_env(cfg2.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg2, info)
    lrn = QMixLearner.build(cfg2, mac, info)
    ls2 = lrn.init_state(jax.random.PRNGKey(0))
    assert np.asarray(
        ls2.params["mixer"]["params"]["out_gate"]).item() == 0.0
    ls3, info3 = jax.jit(lrn.train)(ls2, sample, w, jnp.asarray(0),
                                    jnp.asarray(2))
    assert np.isfinite(float(info3["loss"]))
    assert np.abs(np.asarray(
        ls3.params["mixer"]["params"]["out_gate"])).item() > 0.0


def test_sanity_check_validates_lever_flags():
    with pytest.raises(ValueError, match="td_loss"):
        sanity_check(TrainConfig(td_loss="l1"))
    with pytest.raises(ValueError, match="huber_delta"):
        sanity_check(TrainConfig(td_loss="huber", huber_delta=0.0))
    with pytest.raises(ValueError, match="reward_unit"):
        sanity_check(TrainConfig(reward_unit=-1.0))
    with pytest.raises(ValueError, match="double-scale"):
        sanity_check(TrainConfig(
            reward_unit=100.0,
            env_args=EnvConfig(reward_scaling=True)))
