"""Host-RAM replay (buffer_cpu_only mode): the device-side stratified
PER sample, pinned bit-parity against the sum-tree formulation it
replaced (PR 13), plus the retained Py/Native sum-tree reference."""

import numpy as np
import pytest

from t2omca_tpu.components.host_replay import (HostReplayBuffer, PySumTree)


def _native_or_skip(cap):
    from t2omca_tpu.components.host_replay import NativeSumTree
    try:
        return NativeSumTree(cap)
    except Exception as e:   # no g++ in env
        pytest.skip(f"native sumtree unavailable: {e}")


# ------------------------------------------------------------------ sum-tree

def test_native_sumtree_set_get_total():
    t = _native_or_skip(10)          # rounds up to 16 leaves
    t.set_batch(np.array([0, 3, 7]), np.array([1.0, 2.0, 5.0]))
    assert t.total() == pytest.approx(8.0)
    assert t.get(np.array([3]))[0] == pytest.approx(2.0)
    t.set_batch(np.array([3]), np.array([0.5]))
    assert t.total() == pytest.approx(6.5)


def test_native_sumtree_sampling_proportional():
    t = _native_or_skip(8)
    pri = np.array([1.0, 0.0, 0.0, 9.0])    # idx 3 has 90% of the mass
    t.set_batch(np.arange(4), pri)
    rng = np.random.default_rng(0)
    idx, p = t.sample(rng.random(1000))
    frac3 = float(np.mean(idx == 3))
    assert 0.85 < frac3 < 0.95
    assert set(np.unique(idx)) <= {0, 3}    # zero-priority never sampled
    np.testing.assert_allclose(p[idx == 3], 9.0)


def test_py_sumtree_matches_native():
    nat = _native_or_skip(8)
    py = PySumTree(8)
    pri = np.array([0.5, 2.0, 0.0, 1.5, 3.0, 0.0, 0.0, 1.0])
    nat.set_batch(np.arange(8), pri)
    py.set_batch(np.arange(8), pri)
    us = np.random.default_rng(1).random(64)
    i_n, p_n = nat.sample(us)
    i_p, p_p = py.sample(us)
    np.testing.assert_array_equal(i_n, i_p)
    np.testing.assert_allclose(p_n, p_p)


def test_native_sumtree_get_batch_matches_py():
    """``NativeSumTree.get`` goes through ONE ctypes crossing
    (``sumtree_get_batch``) instead of a per-element Python loop — exact
    parity with ``PySumTree.get`` on every input shape the buffer uses
    (scalar, array, duplicated + unordered indices)."""
    nat = _native_or_skip(8)
    py = PySumTree(8)
    pri = np.array([0.5, 2.0, 0.0, 1.5, 3.0, 0.25, 7.0, 1.0])
    nat.set_batch(np.arange(8), pri)
    py.set_batch(np.arange(8), pri)
    for idx in (3,                              # scalar
                np.arange(8),                   # full sweep
                np.array([7, 0, 3, 3, 6, 0])):  # unordered + dupes
        got = nat.get(idx)
        want = np.atleast_1d(py.get(np.atleast_1d(idx)))
        np.testing.assert_array_equal(got, want)
        assert got.dtype == np.float64


# ------------------------------------------------------------------ buffer

def _mk_batch(b, t=3, a=2, n_act=3, obs=4, state=5, seed=0):
    import jax.numpy as jnp
    from t2omca_tpu.components.episode_buffer import EpisodeBatch
    rng = np.random.default_rng(seed)
    return EpisodeBatch(
        obs=jnp.asarray(rng.normal(size=(b, t + 1, a, obs)), jnp.float32),
        state=jnp.asarray(rng.normal(size=(b, t + 1, state)), jnp.float32),
        avail_actions=jnp.ones((b, t + 1, a, n_act), jnp.int32),
        actions=jnp.asarray(rng.integers(0, n_act, (b, t, a)), jnp.int32),
        reward=jnp.asarray(rng.normal(size=(b, t)), jnp.float32),
        terminated=jnp.zeros((b, t), bool),
        filled=jnp.ones((b, t), bool),
    )


def _buf(**kw):
    kw.setdefault("capacity", 8)
    kw.setdefault("episode_limit", 3)
    kw.setdefault("n_agents", 2)
    kw.setdefault("n_actions", 3)
    kw.setdefault("obs_dim", 4)
    kw.setdefault("state_dim", 5)
    kw.setdefault("t_max", 100)
    return HostReplayBuffer(**kw)


def test_host_buffer_roundtrip_and_weights():
    buf = _buf()
    assert not buf.can_sample(2)
    buf.insert_episode_batch(_mk_batch(4, seed=1))
    assert buf.can_sample(4)
    batch, idx, w = buf.sample(3, t_env=0)
    assert batch.obs.shape == (3, 4, 2, 4)
    assert (np.asarray(idx) < 4).all()
    assert float(np.max(np.asarray(w))) == pytest.approx(1.0)
    buf.update_priorities(idx, np.array([5.0, 1.0, 0.1])[: len(idx)])
    # high-priority episode dominates subsequent samples
    counts = np.zeros(8)
    for _ in range(30):
        _, i2, _ = buf.sample(4, t_env=50)
        for j in np.asarray(i2):
            counts[j] += 1
    assert counts[np.asarray(idx)[0]] == counts.max()


def test_host_buffer_drop_pending_update():
    """A deferred priority update abandoned by a checkpoint restore must
    never reach the priority mirrors — the refs belong to the rolled-back
    train step (``run._restore_checkpoint`` calls ``drop_pending_update``);
    flushing them would stamp the abandoned computation's |TD| onto the
    restored buffer's priorities."""
    import jax.numpy as jnp
    buf = _buf()
    buf.insert_episode_batch(_mk_batch(4, seed=5))
    _, idx, _ = buf.sample(3, t_env=0)
    pri_before = buf._pri.copy()
    buf.defer_priority_update(np.asarray(idx),
                              jnp.full((len(np.asarray(idx)),), 1e6),
                              jnp.asarray(True))
    buf.drop_pending_update()
    assert buf._pending_update is None
    buf.flush_priority_updates()            # must be a no-op now
    np.testing.assert_array_equal(buf._pri, pri_before)
    np.testing.assert_array_equal(np.asarray(buf._pri_dev), pri_before)


def test_host_buffer_ring_wraparound():
    buf = _buf(capacity=4)
    buf.insert_episode_batch(_mk_batch(3, seed=2))
    buf.insert_episode_batch(_mk_batch(3, seed=3))
    assert buf._count == 4 and buf._pos == 2
    ref = np.asarray(_mk_batch(3, seed=3).reward)
    np.testing.assert_allclose(buf._storage.reward[0], ref[1])


# ------------------------------------ device-side PER sample (PR 13)

def _trees(cap, pri32):
    """Both sum-tree formulations loaded with the f32 stored priorities
    (f64 promotion is exact), native skipped without a toolchain."""
    out = []
    py = PySumTree(cap)
    py.set_batch(np.arange(len(pri32)), pri32.astype(np.float64))
    out.append(("py", py))
    try:
        from t2omca_tpu.components.host_replay import NativeSumTree
        nat = NativeSumTree(cap)
        nat.set_batch(np.arange(len(pri32)), pri32.astype(np.float64))
        out.append(("native", nat))
    except Exception:
        pass
    return out


def test_device_sample_bit_parity_vs_sumtree_formulation():
    """The PR 13 acceptance pin: the device stratified-sample program's
    INDICES are bit-equal to the genuine sum-tree formulations (the
    ctypes ``NativeSumTree`` descent where the toolchain exists, and
    ``PySumTree``'s f64 inverse-CDF) at the same stratum uniforms, and
    its importance WEIGHTS are bit-equal to the shared stored-precision
    weight formulation evaluated at the tree's own sampled indices —
    plus value-equal (float tolerance) to the legacy f64 sum-tree
    weight computation the old host path returned. Sweeps partial
    fill, full buffers, and batch sizes; uniforms are drawn f64 and
    cast f32 ONCE so both sides consume identical values."""
    import jax.numpy as jnp
    from t2omca_tpu.components.host_replay import (_importance_weights,
                                                   _stratified_sample)
    rng = np.random.default_rng(123)
    for trial in range(25):
        cap = int(rng.integers(4, 400))
        n = cap if trial % 3 == 0 else int(rng.integers(1, cap + 1))
        bs = int(rng.integers(1, min(n, 48) + 1))
        pri = np.zeros(cap, np.float32)
        pri[:n] = (rng.random(n) * 3 + 1e-6).astype(np.float32)
        us = rng.random(bs).astype(np.float32)
        beta = np.float32(rng.random())
        idx_d, w_d = _stratified_sample(
            jnp.asarray(pri), jnp.asarray(us), jnp.asarray(n, jnp.int32),
            jnp.asarray(beta))
        idx_d, w_d = np.asarray(idx_d), np.asarray(w_d)
        assert (idx_d < n).all()
        for label, tree in _trees(cap, pri):
            ti, tp = tree.sample(us.astype(np.float64))
            ti = np.minimum(ti, n - 1)     # the device clamp's semantics
            np.testing.assert_array_equal(idx_d, ti, err_msg=label)
            # weights: bit-equal through the ONE stored-precision
            # formulation, evaluated at the TREE's indices
            w_ref = np.asarray(_importance_weights(
                jnp.asarray(pri), jnp.asarray(ti),
                jnp.asarray(n, jnp.int32), jnp.asarray(beta)))
            np.testing.assert_array_equal(w_d, w_ref, err_msg=label)
            # ... and value-equal to the legacy f64 computation
            probs = tp / max(tree.total(), 1e-12)
            w64 = (n * np.maximum(probs, 1e-12)) ** (-float(beta))
            w64 = (w64 / max(w64.max(), 1e-12)).astype(np.float32)
            np.testing.assert_allclose(w_d, w64, rtol=3e-6, atol=3e-7,
                                       err_msg=label)


def test_device_sample_ignores_poisoned_tail():
    """Unfilled slots beyond the fill line carry arbitrary garbage on
    the device mirror's tail (NaN/huge/negative) without perturbing
    indices or weights — the valid mask zeroes their mass before the
    cdf, matching the PR 9 device-buffer partial-fill contract."""
    import jax.numpy as jnp
    from t2omca_tpu.components.host_replay import _stratified_sample
    rng = np.random.default_rng(7)
    cap, n, bs = 64, 40, 16
    pri = np.zeros(cap, np.float32)
    pri[:n] = (rng.random(n) + 1e-6).astype(np.float32)
    us = rng.random(bs).astype(np.float32)
    args = (jnp.asarray(us), jnp.asarray(n, jnp.int32),
            jnp.asarray(np.float32(0.7)))
    idx_clean, w_clean = _stratified_sample(jnp.asarray(pri), *args)
    poisoned = pri.copy()
    poisoned[n:] = np.resize([np.nan, 1e30, -7.0], cap - n)
    idx_p, w_p = _stratified_sample(jnp.asarray(poisoned), *args)
    np.testing.assert_array_equal(np.asarray(idx_clean),
                                  np.asarray(idx_p))
    np.testing.assert_array_equal(np.asarray(w_clean), np.asarray(w_p))
    assert (np.asarray(idx_p) < n).all()


def test_steady_state_sample_runs_zero_sumtree_calls(monkeypatch):
    """The acceptance criterion, enforced mechanically: with the native
    loader AND both tree classes booby-trapped, the whole
    insert → sample → deferred-feedback → sample cycle still runs —
    nothing on the live path may construct or call a sum-tree."""
    import jax.numpy as jnp
    import t2omca_tpu.components.host_replay as hr
    import t2omca_tpu.native as native

    def boom(*a, **kw):
        raise AssertionError("sum-tree touched on the live path")

    monkeypatch.setattr(native, "load_sumtree", boom)
    monkeypatch.setattr(hr.PySumTree, "__init__", boom)
    monkeypatch.setattr(hr.NativeSumTree, "__init__", boom)
    buf = _buf()
    buf.insert_episode_batch(_mk_batch(4, seed=9))
    batch, idx, w = buf.sample(3, t_env=10)
    buf.defer_priority_update(idx, jnp.asarray([0.5, 2.0, 0.1]),
                              jnp.asarray(True))
    _, idx2, w2 = buf.sample(3, t_env=20)     # flush + resample
    assert batch.obs.shape == (3, 4, 2, 4)
    assert (np.asarray(idx2) < 4).all()
    assert float(np.max(np.asarray(w2))) == pytest.approx(1.0)


def test_priority_mirrors_stay_identical():
    """Host and device priority mirrors are byte-twins through inserts,
    wraparound evictions, and |TD| feedback — and the buffer-level
    sample agrees bit-for-bit with the sum-tree formulation over the
    mirrored vector."""
    import jax.numpy as jnp
    buf = _buf(capacity=4)
    buf.insert_episode_batch(_mk_batch(3, seed=2))
    buf.update_priorities(np.array([0, 2]), np.array([3.0, 0.25]))
    buf.insert_episode_batch(_mk_batch(3, seed=3))     # wraps, evicts
    np.testing.assert_array_equal(buf._pri, np.asarray(buf._pri_dev))
    assert buf._pri[: buf._count].min() > 0.0
    # buffer-level sample vs the tree formulation over the same vector
    rng_probe = np.random.default_rng(0)   # buffer's own seed/stream
    us = rng_probe.random(3).astype(np.float32)
    batch, idx, w = buf.sample(3, t_env=50)
    for label, tree in _trees(buf.capacity, buf._pri):
        ti, _ = tree.sample(us.astype(np.float64))
        np.testing.assert_array_equal(
            idx, np.minimum(ti, buf._count - 1), err_msg=label)


def test_host_buffer_bf16_storage():
    buf = _buf(store_dtype="bfloat16")
    buf.insert_episode_batch(_mk_batch(2, seed=4))
    batch, _, _ = buf.sample(2, t_env=0)
    import jax.numpy as jnp
    assert batch.obs.dtype == jnp.bfloat16


@pytest.mark.slow   # full train compile (~21 s); the driver host-buffer e2e stays in-gate (test_driver)
def test_host_buffer_end_to_end_training():
    """Full driver loop with buffer_cpu_only=True (native sum-tree path)."""
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   TrainConfig, sanity_check)
    from t2omca_tpu.run import Experiment
    import jax
    import jax.numpy as jnp

    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, buffer_cpu_only=True),
    ))
    exp = Experiment.build(cfg)
    assert exp.host_buffer
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    for _ in range(2):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        insert(None, batch)
        ts = ts.replace(runner=rs, episode=ts.episode + cfg.batch_size_run)
    assert exp.buffer.can_sample(cfg.batch_size)
    ts2, info = train_iter(ts, jax.random.PRNGKey(0), jnp.asarray(8))
    assert np.isfinite(float(info["loss"]))
    assert int(ts2.learner.train_steps) == 1
