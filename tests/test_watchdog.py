"""Dispatch watchdog + retry/backoff + degradation ladder
(``t2omca_tpu/utils/watchdog.py``, docs/RESILIENCE.md §5): unit tests at
millisecond timeouts for the heartbeat monitor, the transient-error
classification/backoff, and the ladder policy — then driver integration
on the CPU backend: an injected hang at ``dispatch.superstep`` must fire
the watchdog within the configured timeout, produce a VALID emergency
checkpoint, and let a fresh driver resume to the original t_env target
(the PR acceptance criterion); injected transient failures must be
retried with backoff; exhausted retries must walk the ladder
(superstep K→1 → restore → abort-with-diagnosis).
"""

import glob
import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               ResilienceConfig, TrainConfig, load_config,
                               sanity_check)
from t2omca_tpu.run import Experiment, run
from t2omca_tpu.utils import resilience, watchdog
from t2omca_tpu.utils.checkpoint import find_checkpoint, verify_checkpoint
from t2omca_tpu.utils.logging import Logger


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


# ---------------------------------------------------------------------------
# Watchdog unit tests (millisecond timeouts; no jax programs)
# ---------------------------------------------------------------------------

def _wait_for(pred, timeout=2.0, step=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _warm(wd, *phases):
    """Complete each phase once: the strict timeout only applies to warm
    phases (first occurrence = compile, exempt)."""
    for p in phases:
        wd.stamp(p)
        wd.clear()


def test_watchdog_fires_on_stall_with_diagnosis():
    stalls, seen_states = [], []

    def _cb(diag):
        seen_states.append(diag.state)     # state visible TO the callback
        stalls.append(diag)

    wd = watchdog.Watchdog(0.05, on_stall=_cb, poll_s=0.01)
    with wd:
        _warm(wd, "dispatch.superstep")
        wd.stamp("dispatch.superstep", t_env=24, state="the-state")
        assert _wait_for(lambda: wd.stall_count == 1)
        diag = wd.take_diagnosis()
    assert diag is not None
    assert diag.phase == "dispatch.superstep"
    assert diag.t_env == 24
    assert diag.elapsed_s >= 0.05
    assert diag.timeout_s == 0.05
    assert diag.backend == jax.default_backend()
    # the emergency-save callback saw the stamped state; once it
    # completed, the retained diagnosis dropped the reference (keeping
    # it would pin the pre-stall TrainState — device ring included —
    # through the recovery and exit paths)
    assert seen_states == ["the-state"]
    assert _wait_for(lambda: diag.state is None)
    # the callback saw the same diagnosis; take_diagnosis consumed it
    assert stalls and stalls[0].phase == "dispatch.superstep"
    assert wd.take_diagnosis() is None
    # serializable diagnosis: state stays out of the JSON payload
    assert "state" not in diag.to_dict()
    assert json.dumps(diag.to_dict())


def test_watchdog_fires_once_per_stamp():
    wd = watchdog.Watchdog(0.03, poll_s=0.01)
    with wd:
        _warm(wd, "p")
        wd.stamp("p", t_env=1)
        assert _wait_for(lambda: wd.stall_count == 1)
        time.sleep(0.15)                       # stall persists, no re-fire
        assert wd.stall_count == 1
        wd.stamp("p", t_env=2)                 # NEW stamp can fire again
        assert _wait_for(lambda: wd.stall_count == 2)


def test_watchdog_wedged_on_stall_does_not_blind_monitor():
    """on_stall runs on its own thread: a callback wedged inside the
    stalled backend (the emergency save blocking on a dead tunnel) must
    not stop the monitor from firing for LATER stalls — otherwise the
    first wedge permanently disables the hang detection the watchdog
    exists to provide."""
    fired = []
    release = threading.Event()

    def _wedging_cb(diag):
        fired.append(diag.phase)
        if len(fired) == 1:
            release.wait(5.0)              # first callback wedges

    wd = watchdog.Watchdog(0.03, on_stall=_wedging_cb, poll_s=0.01)
    try:
        with wd:
            _warm(wd, "a", "b")
            wd.stamp("a", t_env=1)
            assert _wait_for(lambda: len(fired) == 1)
            wd.clear()                     # the call returned late...
            wd.stamp("b", t_env=2)         # ...and the next one stalls
            assert _wait_for(lambda: len(fired) == 2), \
                "monitor went blind behind the wedged callback"
            wd.clear()
    finally:
        release.set()
    assert fired == ["a", "b"]


def test_watchdog_cleared_and_idle_never_fires():
    # generous timeout vs the stamp→clear gap: a loaded CI box can
    # deschedule this thread for tens of ms and must not cause a fire
    wd = watchdog.Watchdog(1.0, poll_s=0.01)
    with wd:
        for i in range(4):                     # fast calls: stamp → clear
            wd.stamp("fast", t_env=i)
            time.sleep(0.01)
            wd.clear()
        time.sleep(0.2)                        # idle (no armed stamp)
        assert wd.stall_count == 0
        assert wd.take_diagnosis() is None


def test_watchdog_watch_context_manager_and_exception_path():
    wd = watchdog.Watchdog(0.05, poll_s=0.01)
    with wd:
        with wd.watch("ok", t_env=1):
            pass
        with pytest.raises(ValueError):
            with wd.watch("boom", t_env=2):
                raise ValueError("dispatch failed")
        time.sleep(0.15)                       # both cleared → no fire
        assert wd.stall_count == 0


def test_watchdog_hard_exit_fires_after_grace():
    exits = []
    wd = watchdog.Watchdog(0.03, poll_s=0.01, grace_s=0.05,
                           exit_code=17, _exit=exits.append)
    with wd:
        _warm(wd, "wedged")
        wd.stamp("wedged", t_env=5)            # never cleared
        assert _wait_for(lambda: bool(exits))
    assert exits == [17]


def test_watchdog_hard_exit_canceled_when_main_progresses():
    exits = []
    # grace generous vs the detect→clear gap so CI load can't turn the
    # cancellation race into a spurious hard exit
    wd = watchdog.Watchdog(0.03, poll_s=0.01, grace_s=2.0,
                           _exit=exits.append)
    with wd:
        _warm(wd, "slow")
        wd.stamp("slow", t_env=5)
        assert _wait_for(lambda: wd.stall_count == 1)
        wd.clear()                             # the call returned late
        time.sleep(0.3)
    assert exits == []


def test_watchdog_hard_exit_canceled_by_stop():
    exits = []
    wd = watchdog.Watchdog(0.03, poll_s=0.01, grace_s=10.0,
                           _exit=exits.append)
    wd.start()
    _warm(wd, "wedged")
    wd.stamp("wedged", t_env=5)
    assert _wait_for(lambda: wd.stall_count == 1)
    wd.stop()                                  # orderly exit path
    time.sleep(0.05)
    assert exits == []


def test_watchdog_on_stall_runs_off_main_thread_and_survives_errors():
    seen = []

    def _cb(diag):
        seen.append(threading.current_thread())
        raise RuntimeError("callback bug must not kill the monitor")

    wd = watchdog.Watchdog(0.03, on_stall=_cb, poll_s=0.01)
    with wd:
        _warm(wd, "a", "b")
        wd.stamp("a", t_env=1)
        assert _wait_for(lambda: len(seen) == 1)
        assert seen[0] is not threading.main_thread()
        wd.stamp("b", t_env=2)                 # monitor still alive
        assert _wait_for(lambda: len(seen) == 2)


def test_watchdog_first_occurrence_is_compile_exempt():
    """The first occurrence of a phase includes the XLA compile — the
    strict timeout must NOT apply to it (default: unbounded), and an
    exception does not count as the warming completion (attempt 2 may
    still be the one that compiles)."""
    wd = watchdog.Watchdog(0.03, poll_s=0.01)
    with wd:
        wd.stamp("cold", t_env=0)              # first occurrence: compiling
        time.sleep(0.15)
        assert wd.stall_count == 0
        # an exception-terminated watch leaves the phase cold
        with pytest.raises(RuntimeError):
            with wd.watch("cold2", t_env=0):
                raise RuntimeError("injected failure on attempt 1")
        wd.stamp("cold2", t_env=0)             # retry: may compile now
        time.sleep(0.15)
        assert wd.stall_count == 0
        wd.clear()                             # completes → warm
        wd.stamp("cold2", t_env=1)
        assert _wait_for(lambda: wd.stall_count == 1)


def test_watchdog_first_timeout_bounds_cold_phases():
    """resilience.first_dispatch_timeout: an explicit bound on the cold
    occurrence (the wedged-tunnel-at-startup shape) — the diagnosis must
    carry the limit that actually fired."""
    wd = watchdog.Watchdog(10.0, poll_s=0.01, first_timeout_s=0.05)
    with wd:
        wd.stamp("cold", t_env=0)
        assert _wait_for(lambda: wd.stall_count == 1)
        diag = wd.take_diagnosis()
    assert diag.timeout_s == 0.05


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError, match="timeout_s"):
        watchdog.Watchdog(0.0)


def test_exit_deadline_fires_when_region_overruns():
    """The preemption-exit save runs after wd.stop() — ExitDeadline is
    the only bound left over it. A region that outlives the bound must
    be hard-exited with the stall exit code."""
    exits = []
    with watchdog.ExitDeadline(0.05, 17, label="test save",
                               _exit=exits.append):
        assert _wait_for(lambda: bool(exits))
    assert exits == [17]


def test_exit_deadline_canceled_on_completion_and_exception():
    exits = []
    with watchdog.ExitDeadline(0.05, 17, _exit=exits.append):
        pass                                   # completes within bound
    with pytest.raises(RuntimeError):
        with watchdog.ExitDeadline(0.05, 17, _exit=exits.append):
            raise RuntimeError("save failed fast — deadline must still "
                               "be canceled")
    time.sleep(0.15)
    assert exits == []


# ---------------------------------------------------------------------------
# retry/backoff + classification
# ---------------------------------------------------------------------------

def test_is_transient_classification():
    assert watchdog.is_transient(RuntimeError(
        "EnforceNotMet: preamble size mismatch (gloo)"))
    assert watchdog.is_transient(ConnectionResetError(104, "reset"))
    assert watchdog.is_transient(TimeoutError())
    assert watchdog.is_transient(RuntimeError("DEADLINE_EXCEEDED: dcn"))
    assert watchdog.is_transient(OSError("Connection refused"))
    assert not watchdog.is_transient(ValueError("bad shape (4, 3)"))
    assert not watchdog.is_transient(KeyError("missing"))
    assert not watchdog.is_transient(SystemExit(1))


def test_backoff_delay_exponential_with_bounded_jitter():
    flat = [watchdog.backoff_delay(a, 0.5, jitter=0.0) for a in (1, 2, 3)]
    assert flat == [0.5, 1.0, 2.0]
    assert watchdog.backoff_delay(10, 0.5, max_s=3.0, jitter=0.0) == 3.0
    d = watchdog.backoff_delay(1, 1.0, jitter=0.25)
    assert 1.0 <= d <= 1.25


def test_retry_call_retries_transient_then_succeeds():
    sleeps, calls = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("connection reset by peer")
        return "ok"

    assert watchdog.retry_call(flaky, attempts=4, backoff_s=0.5,
                               jitter=0.0, sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.5, 1.0]                # exponential between attempts


def test_retry_call_nonretriable_raises_first_attempt():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError, match="deterministic"):
        watchdog.retry_call(broken, attempts=5, sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_call_exhaustion_reraises_last_error():
    calls = []

    def always():
        calls.append(1)
        raise TimeoutError(f"try {len(calls)}")

    with pytest.raises(TimeoutError, match="try 3"):
        watchdog.retry_call(always, attempts=3, sleep=lambda s: None)
    assert len(calls) == 3


# ---------------------------------------------------------------------------
# degradation ladder policy
# ---------------------------------------------------------------------------

def test_ladder_rung_order_degrade_restore_abort():
    ladder = watchdog.DegradationLadder(max_restores=2)
    assert ladder.next_action(can_degrade=True) == "degrade"
    assert ladder.degraded
    # degrade only happens once, even if the caller could still degrade
    assert ladder.next_action(can_degrade=True) == "restore"
    assert ladder.next_action(can_degrade=True) == "restore"
    assert ladder.next_action(can_degrade=True) == "abort"
    assert ladder.failures == 4
    assert ladder.restores == 2


def test_ladder_skips_degrade_when_not_applicable():
    ladder = watchdog.DegradationLadder(max_restores=1)
    assert ladder.next_action(can_degrade=False) == "restore"
    assert ladder.next_action(can_degrade=False) == "abort"
    assert watchdog.DegradationLadder(0).next_action(False) == "abort"


def test_dispatch_failed_carries_phase_and_cause():
    cause = RuntimeError("socket closed")
    df = watchdog.DispatchFailed("dispatch.superstep", 3, cause)
    assert df.phase == "dispatch.superstep"
    assert df.attempts == 3
    assert df.cause is cause
    assert "dispatch.superstep" in str(df) and "socket closed" in str(df)


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_resilience_watchdog_config_sanity_and_overrides():
    for bad in (dict(dispatch_timeout=-1.0), dict(stall_grace_s=-1.0),
                dict(stall_exit_code=0), dict(stall_exit_code=300),
                dict(dispatch_retries=-1), dict(retry_backoff_s=-0.5),
                dict(first_dispatch_timeout=-1.0),
                # silently-dead knob: first_dispatch_timeout only matters
                # once dispatch_timeout > 0 constructs the watchdog
                dict(first_dispatch_timeout=120.0, dispatch_timeout=0.0)):
        with pytest.raises(ValueError):
            sanity_check(TrainConfig(resilience=ResilienceConfig(**bad)))
    cfg = load_config(overrides=("resilience.dispatch_timeout=2.5",
                                 "dispatch_retries=4",
                                 "resilience.degrade_superstep=false"))
    assert cfg.resilience.dispatch_timeout == 2.5
    assert cfg.resilience.dispatch_retries == 4
    assert cfg.resilience.degrade_superstep is False
    # defaults: watchdog fully disabled
    assert TrainConfig().resilience.dispatch_timeout == 0.0


# ---------------------------------------------------------------------------
# driver integration (tiny CPU configs; millisecond watchdog timeouts)
# ---------------------------------------------------------------------------

def tiny_cfg(tmp_path, **kw):
    replay_kw = kw.pop("replay_kw", {})
    res_kw = kw.pop("res_kw", {})
    defaults = dict(
        t_max=60, batch_size_run=2, batch_size=4, test_interval=1_000_000,
        test_nepisode=2, log_interval=12, runner_log_interval=12,
        save_model=True, save_model_interval=12,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8, **replay_kw),
        resilience=ResilienceConfig(stall_grace_s=0.0, **res_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _metric_rows(tmp_path):
    rows = []
    for p in glob.glob(os.path.join(tmp_path, "*", "metrics.jsonl")):
        with open(p) as f:
            rows.extend(json.loads(line) for line in f)
    return rows


@pytest.mark.faultinject
@pytest.mark.slow   # two full run() legs (~60 s); the same hang scenario
                    # runs in the chaos battery (scripts/chaos.sh) and the
                    # watchdog fire/diagnosis mechanics are pinned by the
                    # millisecond unit tests above
def test_injected_hang_fires_watchdog_then_fresh_driver_resumes(tmp_path):
    """The acceptance chaos criterion end-to-end: a hang injected at
    ``dispatch.superstep`` → the watchdog fires within the configured
    timeout (diagnosis proves it fired DURING the hang), writes a VALID
    emergency checkpoint, the run exits cleanly — and a fresh driver
    resumes from it and reaches the original t_max (losing at most K
    iterations)."""
    # timeout chosen with wide headroom over a warm tiny-config dispatch
    # (~tens of ms) so a loaded CI box cannot trip it spuriously, while
    # the injected hang still dwarfs it
    cfg = tiny_cfg(tmp_path, superstep=2,
                   res_kw=dict(dispatch_timeout=0.75))
    hang_s = 2.5
    hung = []

    def _hang(t_env, **kw):
        if t_env >= 24 and not hung:
            hung.append(t_env)
            time.sleep(hang_s)

    resilience.register_fault("dispatch.superstep", _hang)
    ts = run(cfg, Logger())
    assert hung == [24], "the hang must have been injected exactly once"
    stopped_at = int(jax.device_get(ts.runner.t_env))
    assert stopped_at < cfg.t_max, "watchdog must have stopped the run"

    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    # diagnosis persisted, and it fired within the timeout — i.e. while
    # the call was still hung, well before the hang resolved on its own
    with open(os.path.join(model_dir, "stall_diagnosis.json")) as f:
        diag = json.load(f)
    assert diag["phase"] == "dispatch.superstep"
    assert diag["t_env"] == 24
    assert cfg.resilience.dispatch_timeout <= diag["elapsed_s"] < hang_s
    # a valid (verify_checkpoint-passing) checkpoint covering the stall
    found = find_checkpoint(model_dir)
    assert found is not None
    dirname, step = found
    assert verify_checkpoint(dirname)
    assert step >= 24, "emergency checkpoint must cover the stall point"

    # fresh driver, no faults: resumes from the emergency checkpoint and
    # reaches the original target
    resilience.clear_faults()
    cfg2 = cfg.replace(checkpoint_path=model_dir)
    ts2 = run(cfg2, Logger())
    assert int(jax.device_get(ts2.runner.t_env)) > cfg.t_max


@pytest.mark.faultinject
@pytest.mark.slow   # full run() (~45 s); retry mechanics pinned fast by
                    # the retry_call unit tests + the in-gate abort tests
def test_transient_dispatch_and_gather_failures_retried(tmp_path):
    """One transient failure at the fused dispatch and one at the
    checkpoint gather: both retried with backoff, the run completes, and
    the fault counter lands in the metric stream."""
    cfg = tiny_cfg(tmp_path, superstep=2,
                   res_kw=dict(dispatch_retries=2, retry_backoff_s=0.01))
    seen, gather_seen = [], []

    def _flaky_dispatch(t_env, attempt, **kw):
        seen.append((t_env, attempt))
        if t_env == 24 and attempt == 1:
            raise RuntimeError("injected: connection reset by peer")

    def _flaky_gather(t_env, **kw):
        gather_seen.append(t_env)
        if len(gather_seen) == 1:
            raise RuntimeError("injected: collective timed out")

    resilience.register_fault("dispatch.superstep", _flaky_dispatch)
    resilience.register_fault("collective.gather", _flaky_gather)
    ts = run(cfg, Logger())
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    # the failed dispatch was re-attempted at the same t_env
    assert (24, 1) in seen and (24, 2) in seen
    # the first save survived its injected gather failure via retry
    assert len(gather_seen) >= 2
    model_dir = glob.glob(os.path.join(tmp_path, "models", "*"))[0]
    assert find_checkpoint(model_dir) is not None
    rows = _metric_rows(tmp_path)
    faults = [r for r in rows if r["key"] == "dispatch_faults"]
    assert faults and faults[-1]["value"] >= 1


@pytest.mark.faultinject
@pytest.mark.slow   # full run() on the host-buffer path (~40 s)
def test_host_buffer_transient_dispatch_not_retried_in_place(tmp_path):
    """buffer_cpu_only dispatches carry non-idempotent HOST side effects
    inside the dispatched fn (``buffer.sample()`` advances the host RNG,
    the ring insert mutates host RAM) that commit-after-success cannot
    cover — so a transient failure must go straight to the ladder
    (restore) instead of replaying the dispatch in place, which would
    train on a different batch or double-insert episodes."""
    cfg = tiny_cfg(tmp_path, replay_kw=dict(buffer_cpu_only=True),
                   res_kw=dict(dispatch_retries=2, retry_backoff_s=0.01))
    train_attempts, fired = [], []

    def _flaky_train(t_env, attempt, **kw):
        train_attempts.append((t_env, attempt))
        if not fired:
            fired.append(t_env)
            raise RuntimeError("injected: connection reset by peer")

    resilience.register_fault("dispatch.train", _flaky_train)
    ts = run(cfg, Logger())
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    # the transient failure was seen exactly once and NEVER re-attempted
    # in place: despite dispatch_retries=2, every hook call is attempt 1
    assert fired and all(a == 1 for _, a in train_attempts)
    # it routed to the ladder (restore rung) instead
    rows = _metric_rows(tmp_path)
    failures = [r for r in rows if r["key"] == "dispatch_failures"]
    assert failures and failures[-1]["value"] >= 1


@pytest.mark.faultinject
@pytest.mark.slow   # Experiment.build (~8 s); ladder policy pinned fast
                    # by the DegradationLadder unit tests above
def test_exhausted_retries_without_checkpoint_abort_with_diagnosis(tmp_path):
    """K=1, persistent transient failure at the rollout dispatch,
    save_model off: the ladder has no degrade rung and no checkpoint to
    restore — the run must abort with the captured diagnosis naming the
    phase. Fast: the injector raises before the program would compile."""
    cfg = tiny_cfg(tmp_path, save_model=False,
                   res_kw=dict(dispatch_retries=1, retry_backoff_s=0.001))

    def _always(t_env, **kw):
        raise RuntimeError("injected: backend unavailable")

    resilience.register_fault("dispatch.rollout", _always)
    with pytest.raises(RuntimeError,
                       match="degradation ladder") as excinfo:
        run(cfg, Logger())
    msg = str(excinfo.value)
    assert "dispatch.rollout" in msg
    assert "no checkpoints exist" in msg
    assert isinstance(excinfo.value.__cause__, watchdog.DispatchFailed)


@pytest.mark.faultinject
@pytest.mark.slow   # Experiment.build (~8 s); classification pinned fast
                    # by test_is_transient + retry_call unit tests
def test_nontransient_dispatch_error_propagates_unretried(tmp_path):
    """A deterministic error in the dispatch path must NOT be retried or
    laddered — it surfaces immediately with its own type."""
    cfg = tiny_cfg(tmp_path, save_model=False,
                   res_kw=dict(dispatch_retries=3))
    calls = []

    def _bug(t_env, attempt, **kw):
        calls.append(attempt)
        raise ValueError("deterministic shape bug")

    resilience.register_fault("dispatch.rollout", _bug)
    with pytest.raises(ValueError, match="shape bug"):
        run(cfg, Logger())
    assert calls == [1]


@pytest.mark.faultinject
@pytest.mark.slow   # compiles both loop shapes (~35 s); policy pinned fast above
def test_ladder_degrades_superstep_to_classic_loop(tmp_path):
    """Persistent failure of the FUSED dispatch only: the ladder drops
    K→1 and the run completes on the classic three-program path (the
    smaller blast radius rung), recording the escalation in stats."""
    cfg = tiny_cfg(tmp_path, superstep=2, save_model=False,
                   res_kw=dict(dispatch_retries=1, retry_backoff_s=0.001))
    fused = []

    def _kill_fused(t_env, attempt, **kw):
        fused.append((t_env, attempt))
        raise RuntimeError("injected: fused dispatch socket closed")

    resilience.register_fault("dispatch.superstep", _kill_fused)
    ts = run(cfg, Logger())
    # both attempts of the fused dispatch failed, then the classic loop
    # carried the run to completion
    assert fused == [(0, 1), (0, 2)]
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    assert int(jax.device_get(ts.learner.train_steps)) > 0
    rows = _metric_rows(tmp_path)
    assert any(r["key"] == "dispatch_failures" for r in rows)
    assert any(r["key"] == "superstep_k" and r["value"] == 1 for r in rows)


@pytest.mark.faultinject
@pytest.mark.slow   # full run + mid-run restore (~30 s)
def test_ladder_restores_last_good_checkpoint_and_continues(tmp_path):
    """K=1 with checkpoints on: a burst of transient train-dispatch
    failures exhausts in-place retries, the ladder restores the newest
    checkpoint (t_env rewinds, host mirrors re-sync), the fault clears,
    and the run still reaches t_max."""
    cfg = tiny_cfg(tmp_path,
                   res_kw=dict(dispatch_retries=0, retry_backoff_s=0.001,
                               max_restores=2))
    failures = []

    def _burst(t_env, **kw):
        if t_env >= 36 and len(failures) < 1:
            failures.append(t_env)
            raise RuntimeError("injected: train dispatch timed out")

    resilience.register_fault("dispatch.train", _burst)
    ts = run(cfg, Logger())
    assert failures == [36]
    assert int(jax.device_get(ts.runner.t_env)) > cfg.t_max
    rows = _metric_rows(tmp_path)
    assert any(r["key"] == "dispatch_failures" for r in rows)
    # training continued past the restore
    assert int(jax.device_get(ts.learner.train_steps)) > 0
