"""Integration tests: MAC + rollout runner + QMIX learner on tiny shapes
(SURVEY.md §4(4): one jitted train step decreases loss)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.components import PrioritizedReplayBuffer
from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.learners import QMixLearner
from t2omca_tpu.runners import ParallelRunner


@pytest.fixture(scope="module")
def setup():
    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=3, target_update_interval=4,
        # lr pinned at the pre-round-4 1e-3: the overfit-rate thresholds
        # below were calibrated to it (the production default moved to
        # 5e-4 for stability, runs/config1_stable/SUMMARY.md)
        lr=0.001,
        # fast_norm=False: this module pins the DENSE rollout/learner
        # contract (flat obs tensors); the compact-storage equivalents
        # live in tests/test_entity_tables.py
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=10),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    return cfg, env, info, mac, learner, runner, ls, rs, run


def test_rollout_shapes_and_cursor(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs2, batch, stats = run(ls.params["agent"], rs, test_mode=False)
    b, t = cfg.batch_size_run, cfg.env_args.episode_limit
    a, n = info["n_agents"], info["n_actions"]
    assert batch.obs.shape == (b, t + 1, a, info["obs_shape"])
    assert batch.state.shape == (b, t + 1, info["state_shape"])
    assert batch.actions.shape == (b, t, a)
    assert batch.avail_actions.shape == (b, t + 1, a, n)
    # t_env advances by B per step in train mode (reference counts env steps
    # across all workers)
    assert int(rs2.t_env) == b * t
    # Q7: the only terminal is the time limit, recorded as NON-terminal
    assert not bool(np.asarray(batch.terminated).any())
    assert bool(np.asarray(batch.filled).all())


def test_rollout_test_mode_freezes_cursor_but_updates_norm(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs2, _, _ = run(ls.params["agent"], rs, test_mode=True)
    assert int(rs2.t_env) == int(rs.t_env)       # no env-step accounting
    # Q4: Welford stats still advanced during evaluation
    assert int(rs2.env_states.norm.n[0]) > int(rs.env_states.norm.n[0])


def test_rollout_actions_always_legal(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs2, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    avail = np.asarray(batch.avail_actions[:, :-1])     # (B, T, A, n)
    actions = np.asarray(batch.actions)                 # (B, T, A)
    taken = np.take_along_axis(avail, actions[..., None], axis=-1)
    assert (taken == 1).all()


def test_norm_state_persists_across_episodes(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs2, _, _ = run(ls.params["agent"], rs, test_mode=False)
    rs3, _, _ = run(ls.params["agent"], rs2, test_mode=False)
    # per-env Welford counters grow by the same amount each episode
    # (subprocess-lifetime semantics: stats carry across resets)
    d1 = int(rs2.env_states.norm.n[0]) - int(rs.env_states.norm.n[0])
    d2 = int(rs3.env_states.norm.n[0]) - int(rs2.env_states.norm.n[0])
    assert d1 == d2 > 0


def test_train_step_updates_and_priorities(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    buf = PrioritizedReplayBuffer(
        capacity=10, episode_limit=cfg.env_args.episode_limit,
        n_agents=info["n_agents"], n_actions=info["n_actions"],
        obs_dim=info["obs_shape"], state_dim=info["state_shape"],
        alpha=0.6, beta0=0.4, t_max=1000)
    bs = buf.insert_episode_batch(buf.init(), batch)
    rs, batch2, _ = run(ls.params["agent"], rs, test_mode=False)
    bs = buf.insert_episode_batch(bs, batch2)

    sample, idx, w = buf.sample(bs, jax.random.PRNGKey(2), cfg.batch_size, 0)
    ls2, tinfo = jax.jit(learner.train)(ls, sample, w, jnp.asarray(0),
                                        jnp.asarray(2))
    assert np.isfinite(float(tinfo["loss"]))
    assert tinfo["td_errors_abs"].shape == (cfg.batch_size,)
    assert (np.asarray(tinfo["td_errors_abs"]) >= 0).all()
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b),
                           ls.params, ls2.params)
    assert any(jax.tree.leaves(changed))


def test_repeated_training_decreases_loss(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    w = jnp.ones((cfg.batch_size_run,))
    train = jax.jit(learner.train)
    losses = []
    for i in range(50):
        ls, tinfo = train(ls, batch, w, jnp.asarray(i), jnp.asarray(0))
        losses.append(float(tinfo["loss"]))
    # overfitting one fixed batch must drive the TD loss down substantially
    # (grad-norm clip at 10 keeps steps small, so the drop is steady, not
    # instant). 50 iterations: the env-seed fold_in (Q8 wiring) changed the
    # fixture's rollout data and the old 30-step/0.3x pair became borderline
    # on the new batch (0.36x) — same threshold, longer overfit.
    assert losses[-1] < 0.3 * losses[0], losses[::10]
    assert losses[-1] < losses[25] < losses[0]


def test_target_network_hard_sync_at_interval(setup):
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    w = jnp.ones((cfg.batch_size_run,))
    train = jax.jit(learner.train)
    # episode below interval: targets stay at init
    ls1, _ = train(ls, batch, w, jnp.asarray(0), jnp.asarray(1))
    same = jax.tree.map(np.allclose, ls1.target_params, ls.target_params)
    assert all(jax.tree.leaves(same))
    # episode ≥ interval: hard sync to the just-updated online params
    ls2, _ = train(ls1, batch, w, jnp.asarray(0),
                   jnp.asarray(cfg.target_update_interval))
    synced = jax.tree.map(np.allclose, ls2.target_params, ls2.params)
    assert all(jax.tree.leaves(synced))
    assert int(ls2.last_target_update) == cfg.target_update_interval


def test_target_mixer_unrolls_from_episode_start(setup):
    """The target mixer's hyper-token recurrence must start at t=0 like the
    online mixer's (``/root/reference/n_transf_mixer.py:55,91``): targets are
    the [1:] outputs of a full T+1-step unroll, NOT a fresh recurrence started
    at t=1 (which would give the target one step less history at every t)."""
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    w = jnp.ones((cfg.batch_size_run,))
    _, linfo = learner._loss(ls.params, ls.target_params, batch, w)

    # oracle: replicate the target computation with an explicit full-length
    # unroll from t=0 and compare the resulting masked target mean
    obs = jnp.swapaxes(batch.obs, 0, 1).astype(jnp.float32)
    state = jnp.swapaxes(batch.state, 0, 1).astype(jnp.float32)
    avail = jnp.swapaxes(batch.avail_actions, 0, 1)
    reward = jnp.swapaxes(batch.reward, 0, 1)
    term = jnp.swapaxes(batch.terminated, 0, 1).astype(jnp.float32)
    mask = jnp.swapaxes(batch.filled, 0, 1).astype(jnp.float32)

    qs, hs = learner._unroll_agent(ls.params["agent"], obs)
    tqs, ths = learner._unroll_agent(ls.target_params["agent"], obs)
    best = jnp.argmax(jnp.where(avail > 0, qs, -jnp.inf), axis=-1)
    tmax = jnp.take_along_axis(tqs, best[..., None], axis=-1)[..., 0]
    # full unroll t=0..T with the target params, bootstrap values = [1:]
    t_qtot = learner._unroll_mixer(ls.target_params["mixer"], tmax, ths,
                                   state, obs)[1:]
    targets = reward + cfg.gamma * (1.0 - term) * t_qtot
    denom = jnp.maximum(mask.sum(), 1.0)
    expect = float((targets * mask).sum() / denom)
    assert np.isclose(float(linfo["target_mean"]), expect, rtol=1e-5)

    # full-loss oracle: SEPARATE unrolls here must reproduce the learner's
    # fused/stacked online+target scan bit-for-bit (pure batching claim)
    actions = jnp.swapaxes(batch.actions, 0, 1)
    chosen = jnp.take_along_axis(qs[:-1], actions[..., None],
                                 axis=-1)[..., 0]
    q_tot = learner._unroll_mixer(ls.params["mixer"], chosen, hs[:-1],
                                  state[:-1], obs[:-1])
    td = (q_tot - targets) * mask
    loss_expect = float((w[None, :] * td ** 2).sum() / denom)
    assert np.isclose(float(linfo["loss"]), loss_expect, rtol=1e-5)


@pytest.fixture(scope="module")
def noisy_setup():
    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=2, action_selector="noisy-new",
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=5),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1, dropout=0.1),
        replay=ReplayConfig(buffer_size=8),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")
    rs, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    return cfg, learner, ls, batch


def test_noisy_sigma_params_receive_gradient(noisy_setup):
    """NoisyNet semantics (``/root/reference/transf_agent.py:37-48``): the
    sigma parameters must be trained, i.e. noise is sampled during the loss
    unroll and grads flow into ``w_sigma``/``b_sigma``."""
    cfg, learner, ls, batch = noisy_setup
    assert learner.needs_rngs
    w = jnp.ones((cfg.batch_size_run,))
    grads, _ = jax.grad(learner._loss, has_aux=True)(
        ls.params, ls.target_params, batch, w, jax.random.PRNGKey(7))
    q_grads = grads["agent"]["params"]["q_basic"]
    for name in ("w_sigma", "b_sigma"):
        g = np.asarray(q_grads[name])
        assert np.abs(g).max() > 0, f"{name} gradient is zero"


@pytest.mark.slow   # full build for an error-path assertion
def test_noisy_train_requires_key(noisy_setup):
    cfg, learner, ls, batch = noisy_setup
    w = jnp.ones((cfg.batch_size_run,))
    with pytest.raises(ValueError, match="PRNG key"):
        learner.train(ls, batch, w, jnp.asarray(0), jnp.asarray(0))
    ls2, tinfo = jax.jit(learner.train)(ls, batch, w, jnp.asarray(0),
                                        jnp.asarray(0),
                                        jax.random.PRNGKey(3))
    assert np.isfinite(float(tinfo["loss"]))
    # sigma params actually move under the optimizer
    before = ls.params["agent"]["params"]["q_basic"]["w_sigma"]
    after = ls2.params["agent"]["params"]["q_basic"]["w_sigma"]
    assert not np.allclose(before, after)


def test_mixer_monotonic_in_agent_qs(setup):
    """QMIX monotonicity: dq_tot/dq_a ≥ 0 through pos_func (SURVEY.md §4(2))."""
    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    b, a = 2, info["n_agents"]
    key = jax.random.PRNGKey(3)
    qvals = jax.random.normal(key, (b, 1, a))
    hid = jax.random.normal(key, (b, a, cfg.model.emb))
    hyper = learner.mixer.initial_hyper(b)
    state = jax.random.normal(key, (b, info["state_shape"]))
    obs = jax.random.normal(key, (b, a, info["obs_shape"]))

    def qtot(qv):
        y, _ = learner.mixer.apply(ls.params["mixer"], qv, hid, hyper,
                                   state, obs)
        return y.sum()

    g = jax.grad(qtot)(qvals)
    assert (np.asarray(g) >= 0).all()


@pytest.mark.slow   # remat'd + plain backward compiles (~12 s)
def test_remat_is_exact(setup):
    """model.remat recomputes forwards in the backward pass — a
    memory/compute trade, not an approximation: the loss is identical and
    gradients agree to f32 recompute-reassociation noise (XLA may fuse
    the recomputed forward differently)."""
    import dataclasses

    cfg, env, info, mac, learner, runner, ls, rs, run = setup
    _, batch, _ = run(ls.params["agent"], rs, test_mode=False)
    w = jnp.ones((cfg.batch_size_run,))

    cfg_r = cfg.replace(model=dataclasses.replace(cfg.model, remat=True))
    learner_r = QMixLearner.build(cfg_r, mac, info)

    (l0, i0), g0 = jax.value_and_grad(learner._loss, has_aux=True)(
        ls.params, ls.target_params, batch, w)
    (l1, i1), g1 = jax.value_and_grad(learner_r._loss, has_aux=True)(
        ls.params, ls.target_params, batch, w)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4), g0, g1)


@pytest.mark.slow   # noisy remat backward compile
def test_remat_noisy_path_gradients_flow(noisy_setup):
    """remat wraps the rng-driven scan bodies too (noisy/dropout unrolls
    carry per-step keys): gradients must stay finite and sigma params
    still receive signal."""
    import dataclasses

    cfg, learner, ls, batch = noisy_setup
    cfg_r = cfg.replace(model=dataclasses.replace(cfg.model, remat=True))
    learner_r = QMixLearner.build(cfg_r, learner.mac, {
        "n_agents": learner.mixer.n_agents,
        "n_entities": learner.mixer.n_entities,
        "state_entity_feats": learner.mixer.feat_dim,
        "obs_entity_feats": learner.mixer.feat_dim,
        "obs_shape": learner.obs_dim, "state_shape": learner.state_dim,
    })
    w = jnp.ones((cfg.batch_size_run,))
    grads, _ = jax.grad(learner_r._loss, has_aux=True)(
        ls.params, ls.target_params, batch, w, jax.random.PRNGKey(9))
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    q_grads = grads["agent"]["params"]["q_basic"]
    assert np.abs(np.asarray(q_grads["w_sigma"])).max() > 0


# ---------------------------------------------------------------- reward scaling

def _rscale_cfg():
    return sanity_check(TrainConfig(
        batch_size_run=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, reward_scaling=True),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
    ))


def test_reward_scaling_matches_per_lane_oracle():
    """env_args.reward_scaling: recorded rewards are raw/(std(G)+1e-8)
    per lane (C2 RewardScaling semantics, reference normalization.py:38-52
    — imported by the env, never instantiated in the released slice);
    stats/returns stay RAW; the discounted return resets per episode while
    the running std persists."""
    cfg = _rscale_cfg()
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    run = jax.jit(runner.run, static_argnames="test_mode")

    import dataclasses
    raw_cfg = cfg.replace(env_args=dataclasses.replace(
        cfg.env_args, reward_scaling=False))
    raw_runner = ParallelRunner(env, mac, raw_cfg)
    rs_raw = raw_runner.init_state(jax.random.PRNGKey(1))
    raw_run = jax.jit(raw_runner.run, static_argnames="test_mode")

    rs2, batch, stats = run(ls.params["agent"], rs, test_mode=False)
    _, batch_raw, stats_raw = raw_run(ls.params["agent"], rs_raw,
                                      test_mode=False)
    raw = np.asarray(batch_raw.reward, np.float64)       # (B, T)
    scaled = np.asarray(batch.reward, np.float64)

    # oracle: sequential per-lane Welford over the discounted return
    gamma = cfg.gamma
    B, T = raw.shape
    expect = np.zeros_like(raw)
    for lane in range(B):
        g, n, mean, s, std = 0.0, 0, 0.0, 0.0, 0.0
        for t in range(T):
            g = gamma * g + raw[lane, t]
            n += 1
            if n == 1:
                mean, std = g, g          # Q5 first-sample quirk
            else:
                old = mean
                mean += (g - old) / n
                s += (g - old) * (g - mean)
                std = np.sqrt(s / n)
            expect[lane, t] = raw[lane, t] / (std + 1e-8)
    np.testing.assert_allclose(scaled, expect, rtol=2e-4)

    # metrics stay raw: identical trajectories => identical raw returns
    np.testing.assert_allclose(np.asarray(stats.episode_return),
                               np.asarray(stats_raw.episode_return),
                               rtol=1e-6)

    # cross-episode: std persists, discounted return resets
    rs3, batch2, _ = run(ls.params["agent"], rs2, test_mode=False)
    assert int(np.asarray(rs3.rscale.norm.n)) == 2 * T
    # test mode leaves the scale state untouched
    rs4, _, _ = run(ls.params["agent"], rs3, test_mode=True)
    assert int(np.asarray(rs4.rscale.norm.n)) == 2 * T


def test_reward_scaling_welford_matches_reference_quirk():
    """First scaled sample divides by std = G_0 itself — SIGNED (Q5,
    reference normalization.py:16-18)."""
    from t2omca_tpu.envs.normalization import (RewardScaleState,
                                               scale_reward)
    st = RewardScaleState.create(gamma=0.9, dim=2)
    x = jnp.asarray([2.0, -3.0])
    st, y = scale_reward(st, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x) / (np.asarray(x) + 1e-8), rtol=1e-6)
