"""Golden-value parity: flax modules vs the independent PyTorch oracle.

Identical weights are loaded into both implementations; outputs must agree to
fp32 tolerance. This pins quirks Q1 (full-emb heads, e**1/4 scaling), Q2
(post-LN / query residual), the layer-0 key threading, C6's hidden-token
recurrence, and C7's positional hypernet reads + monotonicity funcs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from t2omca_tpu.models import Transformer, TransformerAgent, TransformerMixer

import oracle_torch as oracle


def to_torch_params(flax_params):
    """Flatten a flax param tree into the oracle's flat dict naming."""
    flat = {}

    def rec(prefix, tree):
        keys = set(tree.keys())
        for k, v in tree.items():
            if isinstance(v, dict):
                rec(prefix + [k], v)
            else:
                name = "/".join(prefix)
                arr = torch.tensor(np.asarray(v))
                if k == "kernel":
                    flat[name] = arr
                elif k == "scale":
                    flat[name + "/scale"] = arr
                elif k == "bias" and "scale" in keys:
                    flat[name + "/bias"] = arr
                elif k == "bias":
                    flat[name + "_b"] = arr
                else:
                    raise KeyError(k)

    rec([], jax.tree.map(lambda x: x, flax_params))
    return flat


def assert_close(jx, tx, atol=2e-5):
    np.testing.assert_allclose(np.asarray(jx), tx.detach().numpy(),
                               atol=atol, rtol=1e-4)


@pytest.mark.parametrize("heads,depth", [(1, 1), (3, 2)])
def test_transformer_core_parity(heads, depth):
    emb, t, b = 8, 5, 4
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (b, t, emb))
    model = Transformer(emb=emb, heads=heads, depth=depth)
    params = model.init(jax.random.PRNGKey(1), x, x)["params"]
    out_j = model.apply({"params": params}, x, x)

    # oracle.transformer prefixes keys with "{prefix}/"; alias under "x/"
    tp2 = {("x/" + k): v for k, v in to_torch_params(params).items()}
    xt = torch.tensor(np.asarray(x))
    out_t = oracle.transformer(tp2, "x", xt, xt, heads, depth)
    assert_close(out_j, out_t)


def test_depth2_keys_are_layer0_input():
    """The second block must attend against the ORIGINAL input keys
    (reference transformer.py:126,140 tuple threading), not block-1 output."""
    emb, t, b, heads = 8, 4, 2, 2
    x = jax.random.normal(jax.random.PRNGKey(2), (b, t, emb))
    model = Transformer(emb=emb, heads=heads, depth=2)
    params = model.init(jax.random.PRNGKey(3), x, x)["params"]
    out = model.apply({"params": params}, x, x)

    # manual: block0(x, x) then block1(y, x)  — NOT block1(y, y)
    from t2omca_tpu.models.transformer import TransformerBlock
    blk = TransformerBlock(emb=emb, heads=heads)
    y = blk.apply({"params": params["block_0"]}, x, x)
    z_correct = blk.apply({"params": params["block_1"]}, y, x)
    z_wrong = blk.apply({"params": params["block_1"]}, y, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(z_correct), atol=1e-6)
    assert not np.allclose(np.asarray(out), np.asarray(z_wrong), atol=1e-4)


def test_agent_parity():
    b, a, n_entities, feat, emb, heads, depth, n_actions = 3, 4, 4, 9, 8, 2, 2, 5
    model = TransformerAgent(n_agents=a, n_entities=n_entities, feat_dim=feat,
                             emb=emb, heads=heads, depth=depth,
                             n_actions=n_actions)
    obs = jax.random.normal(jax.random.PRNGKey(4), (b, a, n_entities * feat))
    hid = jax.random.normal(jax.random.PRNGKey(5), (b, a, emb))
    params = model.init(jax.random.PRNGKey(6), obs, hid)["params"]
    q_j, h_j = model.apply({"params": params}, obs, hid)
    assert q_j.shape == (b, a, n_actions) and h_j.shape == (b, a, emb)

    tp = to_torch_params(params)
    q_t, h_t = oracle.agent_forward(
        tp, torch.tensor(np.asarray(obs)), torch.tensor(np.asarray(hid)),
        n_entities=n_entities, feat_dim=feat, emb=emb, heads=heads, depth=depth)
    assert_close(q_j, q_t)
    assert_close(h_j, h_t)


@pytest.mark.parametrize("pos,pos_beta", [("abs", 1.0), ("quadratic", 1.0),
                                          ("none", 1.0), ("softplus", 1.0),
                                          ("softplus", 2.5)])
def test_mixer_parity(pos, pos_beta):
    b, a, n_entities, feat, emb, heads, depth = 3, 4, 4, 8, 8, 2, 1
    model = TransformerMixer(n_agents=a, n_entities=n_entities, feat_dim=feat,
                             emb=emb, heads=heads, depth=depth,
                             qmix_pos_func=pos, qmix_pos_func_beta=pos_beta)
    qvals = jax.random.normal(jax.random.PRNGKey(7), (b, 1, a))
    hidden = jax.random.normal(jax.random.PRNGKey(8), (b, a, emb))
    hyper = jax.random.normal(jax.random.PRNGKey(9), (b, 3, emb))
    states = jax.random.normal(jax.random.PRNGKey(10), (b, n_entities * feat))
    obs = jnp.zeros((b, a, n_entities * feat))
    params = model.init(jax.random.PRNGKey(11), qvals, hidden, hyper,
                        states, obs)["params"]
    y_j, hw_j = model.apply({"params": params}, qvals, hidden, hyper, states, obs)
    assert y_j.shape == (b, 1, 1) and hw_j.shape == (b, 3, emb)

    tp = to_torch_params(params)
    y_t, hw_t = oracle.mixer_forward(
        tp, torch.tensor(np.asarray(qvals)), torch.tensor(np.asarray(hidden)),
        torch.tensor(np.asarray(hyper)), torch.tensor(np.asarray(states)),
        torch.tensor(np.asarray(obs)), n_agents=a, n_entities=n_entities,
        feat_dim=feat, emb=emb, heads=heads, depth=depth, pos=pos,
        pos_beta=pos_beta)
    # fp32 softplus formulations (softplus(bx)/b vs torch's beta kernel)
    # differ by up to ~4e-5 elementwise; loosen for that case only
    atol = 2e-4 if pos == "softplus" else 2e-5
    assert_close(y_j, y_t, atol=atol)
    assert_close(hw_j, hw_t, atol=atol)


def test_mixer_monotone_in_qvals():
    """q_tot must be monotonically non-decreasing in every agent's Q (QMIX
    constraint via pos_func on w1/w2, n_transf_mixer.py:84-89)."""
    b, a, n_entities, feat, emb = 2, 3, 3, 8, 8
    model = TransformerMixer(n_agents=a, n_entities=n_entities, feat_dim=feat,
                             emb=emb, heads=2, depth=1)
    qvals = jax.random.normal(jax.random.PRNGKey(12), (b, 1, a))
    hidden = jax.random.normal(jax.random.PRNGKey(13), (b, a, emb))
    hyper = jax.random.normal(jax.random.PRNGKey(14), (b, 3, emb))
    states = jax.random.normal(jax.random.PRNGKey(15), (b, n_entities * feat))
    obs = jnp.zeros((b, a, n_entities * feat))
    params = model.init(jax.random.PRNGKey(16), qvals, hidden, hyper,
                        states, obs)["params"]

    def qtot(qv):
        y, _ = model.apply({"params": params}, qv, hidden, hyper, states, obs)
        return y.sum()

    grad = jax.grad(qtot)(qvals)
    assert np.all(np.asarray(grad) >= 0), "mixer not monotone in agent Qs"


def test_agent_noisy_mode():
    b, a, n_entities, feat, emb = 2, 3, 3, 9, 8
    model = TransformerAgent(n_agents=a, n_entities=n_entities, feat_dim=feat,
                             emb=emb, heads=2, depth=1, n_actions=4, noisy=True)
    obs = jax.random.normal(jax.random.PRNGKey(17), (b, a, n_entities * feat))
    hid = jnp.zeros((b, a, emb))
    params = model.init(jax.random.PRNGKey(18), obs, hid)["params"]
    q_det, _ = model.apply({"params": params}, obs, hid, True)
    q_n1, _ = model.apply({"params": params}, obs, hid, False,
                          rngs={"noise": jax.random.PRNGKey(1)})
    q_n2, _ = model.apply({"params": params}, obs, hid, False,
                          rngs={"noise": jax.random.PRNGKey(2)})
    assert not np.allclose(q_n1, q_n2), "noise should vary with rng"
    assert not np.allclose(q_det, q_n1)
