"""graftmorph — topology-elastic checkpoint restore (docs/RESILIENCE.md
§6, ``utils/elastic.py`` + the elastic half of ``utils/checkpoint.py``).

Pins the elastic matrix: the meta.json topology stamp round-trips and
routes resumes (same shape → the rigid bit-exact paths, population
mismatch → ``restore_elastic``), per-host shard saves assemble back
into one complete state and are valid ONLY when every shard landed
(``find_checkpoint`` skips an incomplete set — the all-shards-or-skip
gate), dp N↔M restores are bit-identical through the leaf-streamed
path, population P grows (fold_in-salted runner keys, so no two members
share a trajectory stream) and shrinks (best-ranked members kept when
an EMA ranking exists, prefix otherwise), the checked-in v3 fixture
drives the full v3→v4→v5 migration chain from real frozen bytes, and
the classic↔sebulba loop flip resumes across shapes. The coordinated-
preemption negotiation's single-host and injected-failure legs are here
too; the multi-host SIGKILL leg lives in tests/test_multihost.py and
the driver-level chaos scenarios in tests/test_chaos.py."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from t2omca_tpu import population as graftpop
from t2omca_tpu.config import (EnvConfig, ModelConfig, PopulationConfig,
                               ReplayConfig, ResilienceConfig,
                               SebulbaConfig, TrainConfig, sanity_check)
from t2omca_tpu.parallel import distributed as dist
from t2omca_tpu.parallel import make_mesh
from t2omca_tpu.run import Experiment, run_sequential
from t2omca_tpu.utils import elastic, resilience
from t2omca_tpu.utils.checkpoint import (CheckpointIntegrityError,
                                         find_checkpoint, load_checkpoint,
                                         load_checkpoint_sharded,
                                         restore_elastic,
                                         restore_host_state,
                                         save_checkpoint,
                                         save_checkpoint_shards,
                                         verify_checkpoint, write_shard)
from t2omca_tpu.utils.logging import Logger

from tests.fixture_ckpt_v3 import FIXTURE_DIR, FIXTURE_STEP, fixture_cfg

pytestmark = pytest.mark.elastic


@pytest.fixture(autouse=True)
def _no_fault_leaks():
    resilience.clear_faults()
    yield
    resilience.clear_faults()


# ------------------------------------------------------- tiny structures

@struct.dataclass
class _Runner:
    key: jnp.ndarray
    t_env: jnp.ndarray


@struct.dataclass
class _TS:
    runner: _Runner
    w: jnp.ndarray


def _bare(seed=0, n=8):
    """A minimal checkpointable state with the two leaves the elastic
    machinery treats specially (``runner.key`` for re-salting, a bulk
    ``w`` for data movement)."""
    return _TS(runner=_Runner(key=jax.random.PRNGKey(seed),
                              t_env=jnp.asarray(seed, jnp.int32)),
               w=jnp.arange(seed, seed + 2 * n, dtype=jnp.float32
                            ).reshape(n, 2))


def _pop(p, n=8):
    """A P-member PopState over ``_TS`` (leading (P,) axis on every
    leaf), members distinguishable by content."""
    ts = jax.tree.map(lambda *xs: jnp.stack(xs),
                      *[_bare(seed=m, n=n) for m in range(p)])
    spec = graftpop.PopulationSpec(
        lr_scale=jnp.arange(p, dtype=jnp.float32) + 1.0,
        eps_scale=jnp.ones((p,), jnp.float32),
        per_alpha=jnp.full((p,), 0.6, jnp.float32),
        member=jnp.arange(p, dtype=jnp.int32))
    return graftpop.PopState(ts=ts, spec=spec)


def _eq(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for la, lb in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ------------------------------------------------------- topology stamp

def test_topology_stamp_written_and_compared(tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, 10, _bare(), topology={"loop": "classic"})
    with open(os.path.join(root, "10", "meta.json")) as f:
        meta = json.load(f)
    stamp = meta["topology"]
    assert stamp["device_count"] == jax.device_count()
    assert stamp["process_count"] == jax.process_count()
    assert stamp["population"] is None
    assert stamp["loop"] == "classic"
    # same shape → no mismatch, no elastic routing
    cur = elastic.current_topology(_bare(), loop="classic")
    assert elastic.topology_mismatch(stamp, cur) == []
    assert not elastic._needs_elastic(stamp, cur)
    # a population resize IS a mismatch and needs the elastic path
    cur_p = elastic.current_topology(_pop(2), loop="classic")
    diffs = elastic.topology_mismatch(stamp, cur_p)
    assert any("population" in d for d in diffs)
    assert elastic._needs_elastic(stamp, cur_p)
    # a stampless (pre-graftmorph) checkpoint is unknown, NOT mismatched
    assert elastic.topology_mismatch(None, cur_p) == []
    assert not elastic._needs_elastic(None, cur_p)
    # population size is read from the spec leaves
    assert elastic.current_topology(_pop(3))["population"] == 3


# ----------------------------------------------------------- shard saves

def test_shard_save_roundtrip_and_assembly(tmp_path):
    root = str(tmp_path / "ck")
    state = _pop(2)
    d = save_checkpoint_shards(root, 16, state,
                               topology={"loop": "classic"})
    assert os.path.basename(d) == "16"
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    assert meta["partial"] is True and meta["shards"] == 1
    # a 1-host shard set is already complete: verify passes and the
    # assembled state round-trips bit-exactly
    assert verify_checkpoint(d)
    _, raw = restore_host_state(d)
    restored = restore_elastic(d, state)
    _eq(restored, state)
    assert isinstance(raw, dict)


def test_find_checkpoint_all_shards_or_skip(tmp_path):
    """Satellite regression: an INCOMPLETE shard set (host died before
    every peer flushed) must fail verification and be skipped in favor
    of the newest complete checkpoint — never half-restored."""
    root = str(tmp_path / "ck")
    state = _bare()
    save_checkpoint(root, 10, state)           # complete, older
    # hand-write shard 0 of a claimed 2-shard set at a NEWER step
    host = jax.device_get(state)
    write_shard(root, 20, 0, 2, host)
    incomplete = os.path.join(root, "20")
    assert os.path.isdir(incomplete)
    assert not verify_checkpoint(incomplete)
    found = find_checkpoint(root)
    assert found is not None and found[1] == 10
    with pytest.raises(CheckpointIntegrityError):
        restore_host_state(incomplete)
    # the moment the second shard lands the set is complete: newest wins
    write_shard(root, 20, 1, 2, host, sharded_paths=["['w']"])
    assert verify_checkpoint(incomplete)
    assert find_checkpoint(root)[1] == 20
    # assembly: sharded leaves concatenate on axis 0, others take shard 0
    _, raw = restore_host_state(incomplete)
    np.testing.assert_array_equal(
        raw["w"], np.concatenate([host.w, host.w], axis=0))
    np.testing.assert_array_equal(raw["runner"]["key"],
                                  np.asarray(host.runner.key))


# --------------------------------------------------- population reshapes

def test_population_shrink_prefix_and_ranked(tmp_path):
    root = str(tmp_path / "ck")
    state = _pop(4)
    save_checkpoint(root, 8, state)
    d = os.path.join(root, "8")
    # prefix shrink: members 0..1 survive verbatim
    out = restore_elastic(d, _pop(2))
    _eq(out.ts, jax.tree.map(lambda a: a[:2], state.ts))
    _eq(out.spec, jax.tree.map(lambda a: a[:2], state.spec))
    # ranked shrink: the ranking's best two members land in slots 0, 1
    out = restore_elastic(d, _pop(2), member_ranking=[3, 1, 0, 2])
    _eq(out.ts, jax.tree.map(lambda a: a[np.array([3, 1])], state.ts))
    # a ranking that is not a permutation is rejected loudly
    with pytest.raises(ValueError):
        restore_elastic(d, _pop(2), member_ranking=[3, 3, 0, 2])


def test_population_grow_salts_new_member_keys(tmp_path):
    root = str(tmp_path / "ck")
    state = _pop(2)
    save_checkpoint(root, 8, state)
    out = restore_elastic(os.path.join(root, "8"), _pop(4))
    # members 0..1 are the restored run, verbatim
    _eq(jax.tree.map(lambda a: a[:2], out.ts), state.ts)
    # members 2..3 replicate 0..1 EXCEPT the runner key, which is
    # fold_in-salted — four distinct trajectory streams
    np.testing.assert_array_equal(np.asarray(out.ts.w[2]),
                                  np.asarray(state.ts.w[0]))
    keys = np.asarray(out.ts.runner.key)
    assert len({k.tobytes() for k in keys}) == 4, \
        "grown members must not share a rollout key stream"


def test_population_to_bare_extraction(tmp_path):
    root = str(tmp_path / "ck")
    state = _pop(3)
    save_checkpoint(root, 8, state)
    d = os.path.join(root, "8")
    # default: member 0 is the run that continues
    out = restore_elastic(d, _bare())
    _eq(out, jax.tree.map(lambda a: a[0], state.ts))
    # with a ranking: the BEST member is the one extracted
    out = restore_elastic(d, _bare(), member_ranking=[2, 0, 1])
    _eq(out, jax.tree.map(lambda a: a[2], state.ts))


def test_member_ranking_defaults_from_saved_stamp(tmp_path):
    """A shrink with no explicit ranking uses the one the SAVE stamped
    (the driver's EMA ordering at save time)."""
    root = str(tmp_path / "ck")
    state = _pop(4)
    save_checkpoint(root, 8, state,
                    topology={"member_ranking": [2, 3, 1, 0]})
    out = restore_elastic(os.path.join(root, "8"), _pop(2))
    _eq(out.ts, jax.tree.map(lambda a: a[np.array([2, 3])], state.ts))


# -------------------------------------------------------- resume routing

def test_resume_state_rigid_same_shape(tmp_path):
    root = str(tmp_path / "ck")
    state = _bare()
    save_checkpoint(root, 10, state, topology={"loop": "classic"})
    out, used = elastic.resume_state(os.path.join(root, "10"), _bare(),
                                     topology={"loop": "classic"})
    assert used is False
    _eq(out, state)


def test_resume_state_routes_population_mismatch(tmp_path):
    root = str(tmp_path / "ck")
    save_checkpoint(root, 10, _pop(4), topology={"loop": "classic"})
    fired = []
    resilience.register_fault("checkpoint.elastic",
                              lambda **kw: fired.append(kw))
    out, used = elastic.resume_state(os.path.join(root, "10"), _pop(2),
                                     topology={"loop": "classic"})
    assert used is True and fired
    assert jax.tree_util.tree_leaves(out.spec)[0].shape[0] == 2


def test_resume_state_stampless_falls_back_once(tmp_path):
    """A pre-graftmorph checkpoint (no stamp) that fails the rigid path
    STRUCTURALLY gets one elastic retry — old population saves restore
    into a resized run without anyone re-stamping them."""
    root = str(tmp_path / "ck")
    state = _pop(4)
    save_checkpoint(root, 10, state)
    meta_path = os.path.join(root, "10", "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["topology"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    out, used = elastic.resume_state(os.path.join(root, "10"), _pop(2))
    assert used is True
    _eq(out.ts, jax.tree.map(lambda a: a[:2], state.ts))


# ------------------------------------------------- dp N <-> M placement

def test_dp2_to_1_restore_bit_identity(tmp_path):
    """A dp=2 checkpoint restores on ONE device bit-exactly: the save
    gathered global content, the restore is placement-only."""
    root = str(tmp_path / "ck")
    mesh = make_mesh(2)
    state = _bare(n=8)
    sharded = _TS(
        runner=jax.device_put(state.runner,
                              NamedSharding(mesh, P())),
        w=jax.device_put(state.w, NamedSharding(mesh, P("data"))))
    save_checkpoint(root, 12, sharded, topology={"mesh_shape": [2]})
    template = jax.eval_shape(lambda: state)
    out, used = elastic.resume_state(os.path.join(root, "12"), template)
    assert used is False       # placement-only: rigid path, logged
    _eq(out, state)


def test_dp1_to_2_restore_streams_onto_mesh(tmp_path):
    """The reverse flip: a single-device save restores straight onto a
    dp=2 mesh (leaf-streamed, born-sharded placement) bit-exactly."""
    root = str(tmp_path / "ck")
    state = _bare(n=8)
    save_checkpoint(root, 12, state)
    mesh = make_mesh(2)
    template = jax.eval_shape(lambda: state)
    shardings = _TS(
        runner=jax.tree.map(lambda _: NamedSharding(mesh, P()),
                            template.runner),
        w=NamedSharding(mesh, P("data")))
    out, used = elastic.resume_state(os.path.join(root, "12"), template,
                                     shardings)
    assert used is False
    assert out.w.sharding.is_equivalent_to(
        NamedSharding(mesh, P("data")), out.w.ndim)
    _eq(jax.device_get(out), state)


# ------------------------------------------------- preemption negotiation

def test_negotiate_stop_step_single_host():
    target, ok = dist.negotiate_stop_step(42)
    assert (target, ok) == (42, True)


def test_negotiate_stop_step_degrades_on_barrier_fault():
    def boom(**kw):
        raise RuntimeError("peer died mid-negotiation")
    resilience.register_fault("preempt.barrier", boom)
    target, ok = dist.negotiate_stop_step(42)
    assert (target, ok) == (42, False)


def test_announce_and_peer_poll_are_noops_single_host():
    dist.announce_shutdown(7)                   # must not raise
    assert dist.peer_shutdown_requested() is False


# --------------------------------------------------- v3 fixture, e2e

def test_v3_fixture_full_migration_chain(tmp_path):
    """The checked-in v3-era bytes restore through the WHOLE chain:
    v3→v4 injects ``runner.env_params`` from the template, v4→v5 lifts
    the single member into a population with re-salted rollout keys —
    against real frozen bytes, not a synthesized old tree."""
    d = os.path.join(FIXTURE_DIR, str(FIXTURE_STEP))
    with open(os.path.join(d, "meta.json")) as f:
        assert json.load(f)["format"] == 3
    assert verify_checkpoint(d)                # sha256 gate still holds
    cfg = fixture_cfg(tmp_path)
    exp = Experiment.build(cfg)
    ts_template = exp.init_train_state(cfg.seed)

    # v3 → v4: bare restore, env_params injected from the template
    ts = load_checkpoint(d, ts_template)
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(ts.runner.env_params)[0]),
        np.asarray(jax.tree_util.tree_leaves(
            ts_template.runner.env_params)[0]))
    # everything the v3 writer DID store restores verbatim
    np.testing.assert_array_equal(np.asarray(ts.runner.key),
                                  np.asarray(ts_template.runner.key))

    # v3 → v4 → v5: population restore lifts the single member to P=2
    cfg_p = sanity_check(cfg.replace(
        population=PopulationConfig(size=2)))
    exp_p = Experiment.build(cfg_p)
    shapes = jax.eval_shape(
        lambda: graftpop.init_population(exp_p, cfg_p))[0]
    template = graftpop.PopState(ts=shapes,
                                 spec=graftpop.build_spec(cfg_p))
    ps = restore_elastic(d, template)
    assert jax.tree_util.tree_leaves(ps.ts)[0].shape[0] == 2
    # member 0 IS the restored run; member 1's rollout key is re-salted
    np.testing.assert_array_equal(np.asarray(ps.ts.runner.key[0]),
                                  np.asarray(ts.runner.key))
    assert not np.array_equal(np.asarray(ps.ts.runner.key[1]),
                              np.asarray(ps.ts.runner.key[0]))


# ------------------------------------------------ driver-level (slow)

def _pop_cfg(p, tmp_path, **kw):
    defaults = dict(
        t_max=24, batch_size_run=2, batch_size=4,
        test_interval=1_000_000, test_nepisode=2, log_interval=12,
        runner_log_interval=12, save_model=True, save_model_interval=12,
        epsilon_anneal_time=50, local_results_path=str(tmp_path),
        use_tensorboard=False,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        resilience=ResilienceConfig(),
    )
    if p:
        defaults["population"] = PopulationConfig(size=p)
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


def _model_dir(tmp_path):
    dirs = glob.glob(os.path.join(str(tmp_path), "models", "*"))
    assert dirs
    return dirs[0]


@pytest.mark.slow
@pytest.mark.parametrize("p_from,p_to", [(4, 2), (2, 4)])
def test_population_resize_resumes_to_tmax(tmp_path, p_from, p_to):
    """The acceptance matrix's P legs: a P=p_from run's checkpoint
    resumes as P=p_to and trains to t_max with DISTINCT per-member
    rollout streams (prefix/replicate + fold_in re-salt)."""
    cfg = _pop_cfg(p_from, tmp_path / "a")
    run_sequential(Experiment.build(cfg), Logger(), str(tmp_path / "ra"))
    ckpt = _model_dir(tmp_path / "a")
    cfg2 = _pop_cfg(p_to, tmp_path / "b", t_max=48,
                    checkpoint_path=ckpt)
    ts = run_sequential(Experiment.build(cfg2), Logger(),
                        str(tmp_path / "rb"))
    t_final = np.asarray(jax.device_get(ts.runner.t_env))
    assert t_final.shape == (p_to,)
    assert int(t_final[0]) >= cfg2.t_max
    keys = np.asarray(jax.device_get(ts.runner.key))
    assert len({k.tobytes() for k in keys}) == p_to, \
        "every member must roll out from its own key stream"


@pytest.mark.slow
def test_classic_to_sebulba_resume_parity(tmp_path):
    """The loop-shape leg: one classic checkpoint, resumed by the
    classic loop AND by lockstep sebulba (queue_slots=1, staleness=0 —
    the bit-parity mode test_sebulba pins), reaches t_max with
    BIT-identical learner params: the flip is pure routing."""
    cfg = _pop_cfg(0, tmp_path / "a")
    run_sequential(Experiment.build(cfg), Logger(), str(tmp_path / "ra"))
    ckpt = _model_dir(tmp_path / "a")

    cfg_c = _pop_cfg(0, tmp_path / "b", t_max=48, checkpoint_path=ckpt,
                     save_model=False)
    ts_c = run_sequential(Experiment.build(cfg_c), Logger(),
                          str(tmp_path / "rb"))
    cfg_s = _pop_cfg(0, tmp_path / "c", t_max=48, checkpoint_path=ckpt,
                     save_model=False,
                     sebulba=SebulbaConfig(actor_devices=1,
                                           learner_devices=1,
                                           queue_slots=1, staleness=0))
    ts_s = run_sequential(Experiment.build(cfg_s), Logger(),
                          str(tmp_path / "rc"))
    assert int(jax.device_get(ts_s.runner.t_env)) >= cfg_s.t_max
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        ts_c.learner.params, ts_s.learner.params)


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.faultinject
def test_degraded_shard_save_resumes_elastic_single_host(tmp_path):
    """The chaos acceptance's single-host leg: a preemption whose peer
    barrier FAILS degrades to the per-host shard save; on one host that
    shard set is already complete, and ``resume_state`` resumes it to
    t_max — a degraded exit costs nothing when no peer actually died."""
    def barrier_dies(**kw):
        raise RuntimeError("injected: peer died mid-negotiation")

    def trip(t_env=0, guard=None, **kw):
        if guard is not None and t_env >= 12:
            guard.request("preempt-test")

    resilience.register_fault("preempt.barrier", barrier_dies)
    resilience.register_fault("driver.iteration", trip)
    cfg = _pop_cfg(0, tmp_path / "a", t_max=60,
                   resilience=ResilienceConfig(emergency_checkpoint=True))
    run_sequential(Experiment.build(cfg), Logger(), str(tmp_path / "ra"))
    resilience.clear_faults()

    ckpt = _model_dir(tmp_path / "a")
    found = find_checkpoint(ckpt)
    assert found is not None and found[1] >= 12
    # the emergency save took the DEGRADED path: shard files, partial
    # meta — and it still verifies because the 1-host set is complete
    assert glob.glob(os.path.join(found[0], "shard.*.msgpack")), \
        "the failed barrier must route the exit through the shard save"
    with open(os.path.join(found[0], "meta.json")) as f:
        assert json.load(f)["partial"] is True
    assert verify_checkpoint(found[0])

    cfg2 = _pop_cfg(0, tmp_path / "b", t_max=60, checkpoint_path=ckpt,
                    save_model=False)
    ts = run_sequential(Experiment.build(cfg2), Logger(),
                        str(tmp_path / "rb"))
    assert int(jax.device_get(ts.runner.t_env)) >= cfg2.t_max
