"""graftshard — the collective-traffic & sharding auditor
(t2omca_tpu/analysis, docs/ANALYSIS.md §GP4xx): HLO census parsing and
replica-group axis attribution, the comms/transfers ratchet semantics,
the programs.json comms round-trip, in-process GP403/404/405 detection
on toy mesh programs, the Sebulba params.sync d2d pin, the dp×mp
logical-axis-rules table, and the CLI exit-code contract on the four
seeded fixtures (tests/fixtures_graftshard.py)."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from t2omca_tpu.analysis import load_programs
from t2omca_tpu.analysis.baseline import save_comms
from t2omca_tpu.analysis.graftshard import (
    COMMS_TOLERANCE, GP4_RULES, CommsReport, TransferReport,
    audit_transfer, axis_label, census_bytes, compare_comms,
    finish_comms_program, is_mesh_program, lower_comms_program,
    parse_collectives, raw_findings)
from t2omca_tpu.analysis.registry import (AuditProgram, TransferAudit,
                                          collect_transfer_audits)
from t2omca_tpu.parallel.mesh import (LOGICAL_AXIS_RULES,
                                      logical_to_mesh_axes, make_mesh,
                                      transformer_block_logical_axes)

pytestmark = [pytest.mark.analysis, pytest.mark.comms]

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "fixtures_graftshard.py"
PROGRAMS_JSON = REPO / "t2omca_tpu" / "analysis" / "programs.json"


def _cli(*args, timeout=240, env=None):
    import os
    e = None
    if env is not None:
        e = dict(os.environ)
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env=e)


def _load_fixtures():
    spec = importlib.util.spec_from_file_location(
        "fixtures_graftshard", FIXTURES)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------- HLO census parsing

SYNTH_HLO = """\
  %p0 = f32[8,4]{1,0} parameter(0)
  %ar = f32[8,4]{1,0} all-reduce(f32[8,4]{1,0} %p0), replica_groups={{0,1},{2,3}}, to_apply=%sum
  %ag = (f32[16,4]{1,0}, f32[16,4]{1,0}) all-gather(f32[8,4]{1,0} %a, f32[8,4]{1,0} %b), replica_groups=[2,2]<=[4], dimensions={0}
  %ags = f32[8]{0} all-gather-start(f32[4]{0} %c), replica_groups=[2,2]<=[2,2]T(1,0), dimensions={0}
  %agd = f32[8]{0} all-gather-done(f32[8]{0} %ags)
  %cp = f32[4]{0} collective-permute(f32[4]{0} %x), source_target_pairs={{0,2},{1,3}}
  %add = f32[8,4]{1,0} add(f32[8,4]{1,0} %p0, f32[8,4]{1,0} %p0)
"""


def test_parse_collectives_synthetic_census():
    census = parse_collectives(SYNTH_HLO, (2, 2), ("data", "model"))
    # the -done half of the async pair is skipped, its -start counted
    assert census["all-gather"]["count"] == 2
    assert census["all-gather"]["bytes"] == 2 * 16 * 4 * 4 + 8 * 4
    assert census["all-gather"]["axes"] == ["data", "model"]
    # explicit {{0,1},{2,3}} groups on a 2x2 mesh = the minor axis
    assert census["all-reduce"] == {
        "count": 1, "bytes": 8 * 4 * 4, "axes": ["model"]}
    # permute pairs (0,2)/(1,3) differ along the major axis only
    assert census["collective-permute"] == {
        "count": 1, "bytes": 4 * 4, "axes": ["data"]}
    assert census_bytes(census) == (8 * 4 * 4) + (2 * 16 * 4 * 4 + 32) \
        + 4 * 4


def test_parse_collectives_dtype_sizes_and_full_mesh_label():
    text = ("  %r = bf16[8]{0} all-reduce(bf16[8]{0} %x), "
            "replica_groups={{0,1,2,3}}, to_apply=%s\n")
    census = parse_collectives(text, (2, 2), ("data", "model"))
    assert census["all-reduce"]["bytes"] == 8 * 2
    # one group spanning the whole mesh is attributed to both axes
    assert census["all-reduce"]["axes"] == ["data+model"]


def test_axis_label_attribution():
    assert axis_label([[0, 2], [1, 3]], (2, 2), ("data", "model")) == \
        "data"
    assert axis_label([[0, 1], [2, 3]], (2, 2), ("data", "model")) == \
        "model"
    assert axis_label([[0, 1, 2, 3]], (4,), ("data",)) == "data"
    # groups matching no single axis pattern are mixed, not misattributed
    assert axis_label([[0, 3], [1, 2]], (2, 2), ("data", "model")) == \
        "mixed"
    assert axis_label(None, (2, 2), ("data", "model")) == "?"


# ----------------------------------------------------- ratchet semantics

def _rep(name="prog", census=None, total=0, rules=None):
    return CommsReport(name=name, census=census or {},
                       total_bytes=total, mesh="2 (data)",
                       rule_details=rules or {})


def _base(census=None, nbytes=0, tol=0.1, rules=None, extra=None):
    comms = {"collectives": census or {}, "bytes": nbytes,
             "tolerance": tol, "justification": "test"}
    if rules:
        comms["rules"] = rules
    entry = {"comms": comms}
    if extra:
        entry.update(extra)
    return {"platform": "cpu", "programs": {"prog": entry},
            "transfers": {}}


def _rules_of(findings):
    return [f.rule for f in findings]


def test_gp401_no_comms_baseline_flags_every_kind():
    rep = _rep(census={"all-reduce": {"count": 1, "bytes": 4,
                                      "axes": ["data"]},
                       "all-gather": {"count": 2, "bytes": 8,
                                      "axes": ["data"]}},
               total=12, rules={"GP403": ["blowup"]})
    findings, _ = compare_comms(
        [rep], [], {"platform": "cpu", "programs": {}, "transfers": {}})
    assert sorted(_rules_of(findings)) == ["GP401", "GP401", "GP403"]


def test_gp401_count_ratchet_and_stale_shrink():
    base = _base(census={"all-reduce": {"count": 2, "bytes": 8,
                                        "axes": ["data"]}}, nbytes=8)
    grown = _rep(census={"all-reduce": {"count": 3, "bytes": 8,
                                        "axes": ["data"]}}, total=8)
    findings, stale = compare_comms([grown], [], base)
    assert _rules_of(findings) == ["GP401"]
    shrunk = _rep(census={"all-reduce": {"count": 1, "bytes": 8,
                                         "axes": ["data"]}}, total=8)
    findings, stale = compare_comms([shrunk], [], base)
    assert findings == [] and any("count dropped" in s for s in stale)
    gone = _rep(census={}, total=0)
    findings, stale = compare_comms([gone], [], base)
    assert findings == [] and any("no longer present" in s
                                  for s in stale)


def test_gp402_tolerance_boundaries():
    base = _base(census={"all-reduce": {"count": 1, "bytes": 100,
                                        "axes": ["data"]}},
                 nbytes=100, tol=0.1)
    c = {"all-reduce": {"count": 1, "bytes": 110, "axes": ["data"]}}
    ok, _ = compare_comms([_rep(census=c, total=110)], [], base)
    assert ok == []                      # exactly at +10%: inside
    over, _ = compare_comms([_rep(census=c, total=111)], [], base)
    assert _rules_of(over) == ["GP402"]
    _, stale = compare_comms([_rep(census=c, total=89)], [], base)
    assert any("bytes improved" in s for s in stale)


def test_gp402_kinds_baselined_without_byte_budget():
    base = _base(census={"all-reduce": {"count": 1, "bytes": 4,
                                        "axes": ["data"]}}, nbytes=0)
    findings, _ = compare_comms(
        [_rep(census={"all-reduce": {"count": 1, "bytes": 4,
                                     "axes": ["data"]}}, total=4)],
        [], base)
    assert _rules_of(findings) == ["GP402"]


def test_structural_rule_ratchet_counts():
    base = _base(rules={"GP403": {"count": 1, "justification": "t"}})
    at = _rep(rules={"GP403": ["one"]})
    findings, stale = compare_comms([at], [], base)
    assert findings == [] and stale == []
    # one extra occurrence: the excess detail plus the count summary
    over = _rep(rules={"GP403": ["one", "two"]})
    findings, _ = compare_comms([over], [], base)
    assert _rules_of(findings) == ["GP403", "GP403"]
    fixed = _rep()
    findings, stale = compare_comms([fixed], [], base)
    assert findings == [] and any("GP403 count dropped" in s
                                  for s in stale)


def test_vanished_entries_and_skips_go_stale_not_fail():
    base = _base()
    findings, stale = compare_comms([], [], base)
    assert findings == [] and any("no longer audited" in s
                                  for s in stale)
    skip = CommsReport(name="prog", skipped="needs 4 devices")
    findings, stale = compare_comms([skip], [], base)
    assert findings == [] and any("skipped" in s for s in stale)


def test_transfer_ratchet_semantics():
    empty = {"platform": "cpu", "programs": {}, "transfers": {}}
    rep = TransferReport(name="sync", leaves=2, bytes=64,
                         kind="d2d-copy")
    findings, _ = compare_comms([], [rep], empty)
    assert _rules_of(findings) == ["GP401"]      # unbaselined transfer
    base = {"platform": "cpu", "programs": {},
            "transfers": {"sync": {"leaves": 2, "bytes": 64,
                                   "kind": "d2d-copy",
                                   "tolerance": 0.1,
                                   "justification": "t"}}}
    findings, stale = compare_comms([], [rep], base)
    assert findings == [] and stale == []
    degraded = TransferReport(name="sync", leaves=2, bytes=64,
                              kind="reshard",
                              rule_details={"GP404": ["leaf moved"]})
    findings, _ = compare_comms([], [degraded], base)
    assert sorted(_rules_of(findings)) == ["GP401", "GP404", "GP404"]
    fat = TransferReport(name="sync", leaves=2, bytes=256,
                         kind="d2d-copy")
    findings, _ = compare_comms([], [fat], base)
    assert _rules_of(findings) == ["GP402"]
    _, stale = compare_comms([], [], base)
    assert any("no longer registered" in s for s in stale)


def test_raw_findings_structural_only():
    rep = _rep(census={"all-reduce": {"count": 9, "bytes": 999,
                                      "axes": ["data"]}},
               total=999, rules={"GP404": ["boundary"]})
    tr = TransferReport(name="sync", kind="reshard",
                        rule_details={"GP404": ["leaf"]})
    out = raw_findings([rep], [tr])
    # GP401/402 are ratchets: without a baseline only GP403/404/405
    assert sorted(_rules_of(out)) == ["GP404", "GP404"]


# ------------------------------------------------ programs.json comms IO

def test_save_comms_round_trip_preserves_justifications(tmp_path):
    path = tmp_path / "programs.json"
    rep = _rep(census={"all-reduce": {"count": 2, "bytes": 64,
                                      "axes": ["data"]}},
               total=64, rules={"GP403": ["blowup"]})
    tr = TransferReport(name="sync", leaves=3, bytes=12,
                        kind="d2d-copy")
    save_comms(path, [rep], [tr], platform="cpu", old={})
    base = load_programs(path)
    comms = base["programs"]["prog"]["comms"]
    assert comms["collectives"]["all-reduce"]["count"] == 2
    assert comms["tolerance"] == COMMS_TOLERANCE
    assert comms["justification"].startswith("TODO")
    assert comms["rules"]["GP403"]["count"] == 1
    assert base["transfers"]["sync"]["kind"] == "d2d-copy"

    data = json.loads(path.read_text())
    data["programs"]["prog"]["comms"]["justification"] = "accepted"
    data["programs"]["prog"]["comms"]["tolerance"] = 0.02
    data["programs"]["prog"]["comms"]["rules"]["GP403"][
        "justification"] = "known gather"
    data["transfers"]["sync"]["justification"] = "pure publish"
    path.write_text(json.dumps(data))
    save_comms(path, [rep], [tr], platform="cpu",
               old=load_programs(path))
    base = load_programs(path)
    comms = base["programs"]["prog"]["comms"]
    assert comms["justification"] == "accepted"
    assert comms["tolerance"] == 0.02
    assert comms["rules"]["GP403"]["justification"] == "known gather"
    assert base["transfers"]["sync"]["justification"] == "pure publish"


def test_save_comms_keeps_program_sections_and_skips(tmp_path):
    path = tmp_path / "programs.json"
    old = {"platform": "cpu", "transfers": {},
           "programs": {"prog": {"fingerprint": "abc123",
                                 "comms": {"collectives": {},
                                           "bytes": 7,
                                           "tolerance": 0.1,
                                           "justification": "old"}}}}
    # a skipped audit must leave the previous section untouched
    save_comms(path, [CommsReport(name="prog", skipped="no devices")],
               [], platform="cpu", old=old)
    base = load_programs(path)
    assert base["programs"]["prog"]["fingerprint"] == "abc123"
    assert base["programs"]["prog"]["comms"]["bytes"] == 7
    assert base["programs"]["prog"]["comms"]["justification"] == "old"


def test_checked_in_comms_baseline_is_justified():
    """The ISSUE acceptance gate: every comms/transfers entry in the
    checked-in baseline carries a real justification (no TODO), the
    population learner pins ZERO cross-member collectives, and the
    dp×mp twin carries its model-axis contraction all-reduce."""
    base = json.loads(PROGRAMS_JSON.read_text())
    comms = {n: e["comms"] for n, e in base["programs"].items()
             if "comms" in e}
    assert set(comms) >= {"dp_superstep", "actor_step", "learner_step",
                          "pop_dp_superstep", "pop_learner_step",
                          "dpmp_block"}
    for name, c in comms.items():
        assert c["justification"] and "TODO" not in c["justification"], \
            name
        assert 0.0 <= c["tolerance"] <= 0.5, name
        for rule, r in c.get("rules", {}).items():
            assert rule in GP4_RULES, (name, rule)
            assert "TODO" not in r["justification"], (name, rule)
    assert comms["pop_learner_step"]["collectives"] == {}
    assert "model" in \
        comms["dpmp_block"]["collectives"]["all-reduce"]["axes"]
    sync = base["transfers"]["params_sync"]
    assert sync["kind"] == "d2d-copy"
    assert "TODO" not in sync["justification"]


# ------------------------------------------- in-process rule detection

def _finish(name, prog):
    rep, lowered = lower_comms_program(name, prog)
    return finish_comms_program(rep, prog, lowered.compile())


def _sds(shape, mesh, spec):
    return jax.ShapeDtypeStruct(shape, jnp.float32,
                                sharding=NamedSharding(mesh, spec))


def test_is_mesh_program_selection():
    mesh = make_mesh(2)
    stamped = AuditProgram(jax.jit(lambda x: x),
                           (_sds((8,), mesh, P("data")),))
    plain = AuditProgram(jax.jit(lambda x: x),
                         (jax.ShapeDtypeStruct((8,), jnp.float32),))
    assert is_mesh_program(stamped) and not is_mesh_program(plain)
    assert is_mesh_program(AuditProgram.skipped("small host"))


def test_gp403_full_gather_detected_in_process():
    mesh = make_mesh(2)
    prog = AuditProgram(
        jax.jit(lambda v: v * 2.0,
                out_shardings=NamedSharding(mesh, P())),
        (_sds((8, 4), mesh, P("data")),))
    rep = _finish("regather", prog)
    assert rep.rule_count("GP403") == 1
    assert "all-gather materializes" in rep.rule_details["GP403"][0]


def test_gp404_unstamped_donated_leaf_detected_in_process():
    mesh = make_mesh(2)
    prog = AuditProgram(
        jax.jit(lambda w, v: w + v, donate_argnums=(0,)),
        (jax.ShapeDtypeStruct((8, 4), jnp.float32),
         _sds((8, 4), mesh, P("data"))),
        donate_argnums=(0,))
    rep = _finish("bump", prog)
    assert rep.rule_count("GP404") == 1
    assert "defeats donation" in rep.rule_details["GP404"][0]


def test_gp404_negative_stamped_donation_is_clean():
    mesh = make_mesh(2)
    prog = AuditProgram(
        jax.jit(lambda w, v: w + v, donate_argnums=(0,)),
        (_sds((8, 4), mesh, P("data")), _sds((8, 4), mesh, P("data"))),
        donate_argnums=(0,))
    assert _finish("bump", prog).rule_count("GP404") == 0


def test_gp405_declared_output_sharding_violation():
    mesh = make_mesh(2)
    prog = AuditProgram(
        jax.jit(lambda v: v * 1.0,
                out_shardings=NamedSharding(mesh, P())),
        (_sds((8, 4), mesh, P("data")),),
        expected_output_shardings=NamedSharding(mesh, P("data")))
    rep = _finish("declared", prog)
    assert rep.rule_count("GP405") == 1
    honored = AuditProgram(
        jax.jit(lambda v: v * 1.0),
        (_sds((8, 4), mesh, P("data")),),
        expected_output_shardings=NamedSharding(mesh, P("data")))
    assert _finish("declared", honored).rule_count("GP405") == 0


# ----------------------------------------------------- transfer audits

def test_audit_transfer_classifies_local_copy_reshard():
    devs = jax.devices()
    learner = Mesh(devs[:2], ("data",))
    actor = Mesh(devs[2:4], ("data",))

    def one(src_spec, dst_mesh, dst_spec):
        src = _sds((4, 4), learner, src_spec)
        return audit_transfer("t", TransferAudit(
            src=(src,), dst_shardings=(NamedSharding(dst_mesh,
                                                     dst_spec),),
            description="test"))

    same = one(P(), learner, P())
    assert same.kind == "local" and same.bytes == 0
    copied = one(P(), actor, P())
    assert copied.kind == "d2d-copy"
    assert copied.bytes == 2 * 4 * 4 * 4      # full leaf to 2 new devs
    assert copied.rule_details == {}
    degraded = one(P("data"), actor, P())
    assert degraded.kind == "reshard"
    assert degraded.rule_count("GP404") == 1
    skipped = audit_transfer("t", TransferAudit.skipped("small host"))
    assert skipped.skipped == "small host"


def test_params_sync_publish_is_pure_d2d_copy():
    """Satellite pin: the Sebulba 2+2 params.sync publish must audit as
    a pure device-to-device copy — replicated learner params land
    verbatim on the actor mesh, never via a gather/reshard."""
    audits = collect_transfer_audits()
    assert "params_sync" in audits
    rep = audit_transfer("params_sync", audits["params_sync"])
    assert rep.skipped is None
    assert rep.kind == "d2d-copy"
    assert rep.rule_details == {}
    assert rep.leaves > 0 and rep.bytes > 0


# ----------------------------------------------- logical axis rules

def test_logical_to_mesh_axes_mapping():
    assert logical_to_mesh_axes(("batch", None, "heads")) == \
        P("data", None, "model")
    assert logical_to_mesh_axes(("embed", "joined_kv")) == \
        P(None, "model")
    assert logical_to_mesh_axes(("mlp",)) == P("model")
    with pytest.raises(ValueError, match="no LOGICAL_AXIS_RULES entry"):
        logical_to_mesh_axes(("batch", "vocab"))
    # replicated-by-rule axes map to None, not to a silent drop
    assert tuple(dict(LOGICAL_AXIS_RULES)[n] for n in
                 ("embed", "tokens", "kv")) == (None, None, None)


def test_transformer_block_logical_axes_table():
    leaf = object()
    params = {"params": {
        "tokeys": {"kernel": leaf}, "toqueries": {"kernel": leaf},
        "tovalues": {"kernel": leaf},
        "unifyheads": {"kernel": leaf, "bias": leaf},
        "ff1": {"kernel": leaf, "bias": leaf},
        "ff2": {"kernel": leaf, "bias": leaf},
        "norm1": {"scale": leaf, "bias": leaf},
        "norm2": {"scale": leaf, "bias": leaf},
    }}
    axes = transformer_block_logical_axes(params)["params"]
    assert axes["tokeys"]["kernel"] == ("embed", "joined_kv")
    assert axes["unifyheads"]["kernel"] == ("joined_kv", "embed")
    assert axes["unifyheads"]["bias"] == ("embed",)
    assert axes["ff1"]["kernel"] == ("embed", "mlp")
    assert axes["ff1"]["bias"] == ("mlp",)
    assert axes["ff2"]["kernel"] == ("mlp", "embed")
    assert axes["norm1"]["scale"] == ("embed",)
    with pytest.raises(ValueError, match="no logical-axes mapping"):
        transformer_block_logical_axes(
            {"params": {"mystery": {"kernel": leaf}}})


def test_obs_report_comms_census_section():
    """The report's static interconnect section renders straight off
    the checked-in baseline — no jax, nothing compiled."""
    from t2omca_tpu.obs.report import render_comms_census
    base = json.loads(PROGRAMS_JSON.read_text())
    lines = render_comms_census(base)
    text = "\n".join(lines)
    assert "collective census" in text
    assert "dp_superstep" in text and "dpmp_block" in text
    assert "params_sync" in text and "d2d-copy" in text
    # a baseline with no comms sections keeps the report unchanged
    assert render_comms_census({"programs": {}, "transfers": {}}) == []


# ----------------------------------------------------------------- CLI

def test_cli_seeded_comms_regressions_flip_exit_1(tmp_path):
    """The ISSUE acceptance gate: each planted comms hazard flips the
    CLI to exit 1 with the matching GP4xx rule id — and ONLY that rule
    (one subprocess for all four; the crafted baseline accepts
    everything except each fixture's seeded hazard)."""
    fixtures = _load_fixtures()
    baseline = tmp_path / "programs.json"
    baseline.write_text(json.dumps(fixtures.crafted_baseline()))
    r = _cli("--comms", "--program-module", str(FIXTURES),
             "--programs-baseline", str(baseline),
             "--only", "seeded_gp401", "--only", "seeded_gp402",
             "--only", "seeded_gp403", "--only", "seeded_gp404")
    assert r.returncode == 1, r.stderr
    expected = [("seeded_gp401", "GP401"), ("seeded_gp402", "GP402"),
                ("seeded_gp403", "GP403"), ("seeded_gp404", "GP404")]
    for prog, rule in expected:
        assert f"{prog}: {rule}" in r.stdout, (rule, r.stdout)
        for other in GP4_RULES:
            if other != rule:
                assert f"{prog}: {other}" not in r.stdout, \
                    (prog, other, r.stdout)


def test_cli_write_programs_refuses_only():
    r = _cli("--comms", "--write-programs", "--only", "seeded_gp401",
             timeout=60)
    assert r.returncode == 2
    assert "cannot be combined with --only" in r.stderr


@pytest.mark.slow
def test_cli_write_programs_refuses_small_host(tmp_path):
    """Satellite pin: a baseline rewrite on a host exposing fewer
    devices than the largest registered audit mesh must refuse (exit 2)
    instead of silently carrying stale sections for the skipped
    4-device programs."""
    baseline = tmp_path / "programs.json"
    r = _cli("--comms", "--write-programs",
             "--programs-baseline", str(baseline),
             env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                  "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "4 host devices, have 2" in r.stderr
    assert not baseline.exists()
