"""Unit tests: action selectors, schedules, and replay buffers (L3/L4
components; SURVEY.md §4 recommends pure-function unit tests per branch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.components.action_selectors import (EpsilonGreedySelector,
                                                    masked_argmax,
                                                    random_avail)
from t2omca_tpu.components.episode_buffer import (EpisodeBatch,
                                                  PrioritizedReplayBuffer,
                                                  ReplayBuffer)
from t2omca_tpu.components.schedules import DecayThenFlatSchedule


# ---------------------------------------------------------------- schedules

def test_schedule_linear_decay_then_flat():
    s = DecayThenFlatSchedule(1.0, 0.05, 100)
    assert float(s.eval(0)) == pytest.approx(1.0)
    assert float(s.eval(50)) == pytest.approx(0.525)
    assert float(s.eval(100)) == pytest.approx(0.05)
    assert float(s.eval(10_000)) == pytest.approx(0.05)


# ---------------------------------------------------------------- selectors

def test_masked_argmax_respects_availability():
    q = jnp.array([[3.0, 2.0, 1.0]])
    avail = jnp.array([[0, 1, 1]])
    assert int(masked_argmax(q, avail)[0]) == 1


def test_random_avail_only_picks_available():
    avail = jnp.array([[1, 0, 1, 0]])
    picks = {int(random_avail(jax.random.PRNGKey(i), avail)[0])
             for i in range(50)}
    assert picks <= {0, 2} and len(picks) == 2


def test_epsilon_greedy_test_mode_is_greedy():
    sel = EpsilonGreedySelector(DecayThenFlatSchedule(1.0, 0.05, 100))
    q = jnp.array([[0.1, 5.0, 0.2]])
    avail = jnp.ones((1, 3), jnp.int32)
    for i in range(20):
        a, eps = sel.select(jax.random.PRNGKey(i), q, avail,
                            jnp.asarray(0), test_mode=True)
        assert int(a[0]) == 1
        assert float(eps) == 0.0


def test_epsilon_greedy_explores_at_full_epsilon():
    sel = EpsilonGreedySelector(DecayThenFlatSchedule(1.0, 1.0, 100))
    q = jnp.array([[0.1, 5.0, 0.2]])
    avail = jnp.ones((1, 3), jnp.int32)
    picks = {int(sel.select(jax.random.PRNGKey(i), q, avail,
                            jnp.asarray(0))[0][0]) for i in range(60)}
    assert picks == {0, 1, 2}   # uniform over available actions


# ---------------------------------------------------------------- buffers

def _make_batch(b, t=3, a=2, n_act=3, obs=4, state=5, seed=0):
    rng = np.random.default_rng(seed)
    return EpisodeBatch(
        obs=jnp.asarray(rng.normal(size=(b, t + 1, a, obs)), jnp.float32),
        state=jnp.asarray(rng.normal(size=(b, t + 1, state)), jnp.float32),
        avail_actions=jnp.ones((b, t + 1, a, n_act), jnp.int32),
        actions=jnp.asarray(rng.integers(0, n_act, (b, t, a)), jnp.int32),
        reward=jnp.asarray(rng.normal(size=(b, t)), jnp.float32),
        terminated=jnp.zeros((b, t), bool),
        filled=jnp.ones((b, t), bool),
    )


def _buf(cls=ReplayBuffer, cap=5, **kw):
    return cls(capacity=cap, episode_limit=3, n_agents=2, n_actions=3,
               obs_dim=4, state_dim=5, **kw)


def test_ring_insert_and_wraparound():
    buf = _buf()
    s = buf.init()
    s = buf.insert_episode_batch(s, _make_batch(3, seed=1))
    assert int(s.episodes_in_buffer) == 3 and int(s.insert_pos) == 3
    s = buf.insert_episode_batch(s, _make_batch(3, seed=2))
    assert int(s.episodes_in_buffer) == 5      # capped at capacity
    assert int(s.insert_pos) == 1              # wrapped
    # slot 0 now holds the last episode of the second batch
    np.testing.assert_allclose(
        np.asarray(s.storage.reward[0]), np.asarray(_make_batch(3, seed=2).reward[2]))


def test_can_sample_gate():
    buf = _buf()
    s = buf.init()
    assert not bool(buf.can_sample(s, 2))
    s = buf.insert_episode_batch(s, _make_batch(2))
    assert bool(buf.can_sample(s, 2))


def test_uniform_sample_returns_valid_indices_without_replacement():
    buf = _buf()
    s = buf.insert_episode_batch(buf.init(), _make_batch(4))
    batch, idx, w = buf.sample(s, jax.random.PRNGKey(0), 3)
    idx = np.asarray(idx)
    assert (idx >= 0).all() and (idx < 4).all()
    assert len(set(idx.tolist())) == 3          # without replacement
    np.testing.assert_allclose(np.asarray(w), 1.0)
    assert batch.obs.shape == (3, 4, 2, 4)


def test_per_prioritized_sampling_prefers_high_priority():
    buf = _buf(PrioritizedReplayBuffer, cap=8, alpha=1.0, beta0=0.4,
               t_max=100)
    s = buf.insert_episode_batch(buf.init(), _make_batch(8))
    # one episode dominates the priority mass
    s = buf.update_priorities(s, jnp.arange(8),
                              jnp.asarray([100.0] + [0.01] * 7))
    counts = np.zeros(8)
    for i in range(20):
        _, idx, _ = buf.sample(s, jax.random.PRNGKey(i), 4, t_env=0)
        for j in np.asarray(idx):
            counts[j] += 1
    assert counts[0] == counts.max() and counts[0] >= 0.8 * counts.sum()


def test_per_importance_weights_anneal_to_one():
    buf = _buf(PrioritizedReplayBuffer, cap=4, alpha=0.6, beta0=0.4,
               t_max=100)
    s = buf.insert_episode_batch(buf.init(), _make_batch(4))
    s = buf.update_priorities(s, jnp.arange(4),
                              jnp.asarray([1.0, 2.0, 3.0, 4.0]))
    _, i0, w0 = buf.sample(s, jax.random.PRNGKey(0), 4, t_env=0)
    _, i1, w1 = buf.sample(s, jax.random.PRNGKey(0), 4, t_env=100)
    # weights are max-normalized at both ends of the anneal
    assert float(np.max(w0)) == pytest.approx(1.0)
    assert float(np.max(w1)) == pytest.approx(1.0)
    # importance correction is anti-monotone in priority: the lower-priority
    # sampled episode carries the larger weight
    pri = np.asarray(s.priorities)
    w1, i1 = np.asarray(w1), np.asarray(i1)
    order = np.argsort(pri[i1])
    assert (np.diff(w1[order]) <= 1e-6).all()


def test_per_stratified_sample_partial_fill_stays_in_bounds():
    """PER stratified inverse-CDF on a PARTIALLY-filled ring: the cdf
    plateaus at its total past ``episodes_in_buffer``, and
    ``searchsorted(side='left')`` on a plateau must resolve to the LAST
    valid slot, never an empty tail slot — across many keys and both
    ends of the β anneal (episode_buffer.PrioritizedReplayBuffer
    .sample)."""
    buf = _buf(PrioritizedReplayBuffer, cap=16, alpha=0.6, beta0=0.4,
               t_max=100)
    s = buf.insert_episode_batch(buf.init(), _make_batch(5))
    s = buf.update_priorities(s, jnp.arange(5),
                              jnp.asarray([4.0, 0.5, 2.0, 1.0, 3.0]))
    n = int(s.episodes_in_buffer)
    assert n == 5
    for i in range(25):
        for t_env in (0, 100):
            _, idx, w = buf.sample(s, jax.random.PRNGKey(i), 8,
                                   t_env=t_env)
            idx, w = np.asarray(idx), np.asarray(w)
            assert (idx >= 0).all() and (idx < n).all(), idx
            assert np.isfinite(w).all() and (w > 0).all()
            assert float(w.max()) == pytest.approx(1.0)


def test_per_weights_ignore_zero_priority_tail_slots():
    """Garbage priorities in the UNFILLED tail (e.g. stale values left
    by a wraparound-adjacent bug) must not leak into the sampling
    distribution or the importance weights: _probs masks on
    episodes_in_buffer, not on the priorities array."""
    buf = _buf(PrioritizedReplayBuffer, cap=8, alpha=1.0, beta0=1.0,
               t_max=1)
    s = buf.insert_episode_batch(buf.init(), _make_batch(3))
    s = buf.update_priorities(s, jnp.arange(3),
                              jnp.asarray([1.0, 2.0, 1.0]))
    # poison the tail: enormous priorities in never-filled slots
    s = s.replace(priorities=s.priorities.at[3:].set(1e6))
    seen = set()
    for i in range(30):
        _, idx, w = buf.sample(s, jax.random.PRNGKey(i), 4, t_env=1)
        idx, w = np.asarray(idx), np.asarray(w)
        assert (idx < 3).all(), idx              # tail never sampled
        seen.update(idx.tolist())
        # β=1 exact correction over the VALID mass only: w ∝ 1/p with
        # p from the 3 real episodes (1+2+1), max-normalized — the
        # poisoned tail would have crushed these toward 0
        pri = np.asarray(s.priorities)[idx]
        expect = (1.0 / pri) / (1.0 / pri).max()
        np.testing.assert_allclose(w, expect, rtol=1e-5)
    assert seen == {0, 1, 2}


def test_per_preexponentiated_storage_bit_matches_sample_time_pow():
    """PR 10 satellite: priorities are stored PRE-EXPONENTIATED
    (``p^alpha`` computed once per write) instead of re-computing
    ``priorities ** alpha`` over the full capacity on every sample. The
    sampled indices AND importance weights must be BIT-identical to the
    old formulation (same op on the same raw inputs, just moved from
    the sample path to the write path) — pinned here by re-implementing
    the pre-change sample over the raw priorities."""
    alpha, beta0, t_max, cap = 0.6, 0.4, 100, 8
    buf = _buf(PrioritizedReplayBuffer, cap=cap, alpha=alpha, beta0=beta0,
               t_max=t_max)
    s = buf.insert_episode_batch(buf.init(), _make_batch(5))
    raw = np.zeros(cap, np.float32)
    raw[:5] = 1.0                       # fresh stamp = raw running max
    s = buf.update_priorities(s, jnp.arange(3),
                              jnp.asarray([3.0, 0.5, 2.0]))
    raw[:3] = [3.0, 0.5, 2.0]
    # storage convention: stored == raw ** alpha, exactly
    np.testing.assert_array_equal(
        np.asarray(s.priorities),
        np.where(np.arange(cap) < 5,
                 jnp.asarray(raw) ** jnp.float32(alpha), 0.0))

    def old_sample(key, batch_size, t_env):
        """The pre-change formulation, verbatim: exponentiate the RAW
        priorities inside the sample."""
        n = s.episodes_in_buffer
        valid = jnp.arange(cap) < n
        p = jnp.where(valid, jnp.asarray(raw), 0.0) ** alpha
        p = jnp.where(valid, p, 0.0)
        probs = p / jnp.maximum(p.sum(), 1e-12)
        cdf = jnp.cumsum(probs)
        u = (jnp.arange(batch_size)
             + jax.random.uniform(key, (batch_size,))) / batch_size
        idx = jnp.searchsorted(cdf, u * cdf[-1], side="left")
        idx = jnp.clip(idx, 0, cap - 1)
        beta = beta0 + (1.0 - beta0) * jnp.clip(
            jnp.asarray(t_env, jnp.float32) / t_max, 0.0, 1.0)
        nf = jnp.maximum(n, 1).astype(jnp.float32)
        w = (nf * jnp.maximum(probs[idx], 1e-12)) ** (-beta)
        return idx, w / jnp.maximum(w.max(), 1e-12)

    for i in range(8):
        for t_env in (0, 37, 100):
            key = jax.random.PRNGKey(i)
            _, idx, w = buf.sample(s, key, 4, t_env=t_env)
            idx_old, w_old = old_sample(key, 4, t_env)
            np.testing.assert_array_equal(np.asarray(idx),
                                          np.asarray(idx_old))
            np.testing.assert_array_equal(np.asarray(w),
                                          np.asarray(w_old))


def test_per_update_priorities_valid_guard_is_noop_in_value():
    """The non-finite guard moved into ``update_priorities(valid=)``:
    valid=False must leave stored priorities AND the raw running max
    bit-identical to not updating at all (the driver's old inline
    ``jnp.where`` fallback, now in stored space)."""
    buf = _buf(PrioritizedReplayBuffer, cap=4, alpha=0.6, beta0=0.4,
               t_max=100)
    s = buf.insert_episode_batch(buf.init(), _make_batch(4))
    s = buf.update_priorities(s, jnp.arange(4),
                              jnp.asarray([1.0, 2.0, 3.0, 4.0]))
    tripped = buf.update_priorities(
        s, jnp.asarray([0, 2]), jnp.asarray([np.nan, 99.0]),
        valid=jnp.asarray(False))
    np.testing.assert_array_equal(np.asarray(tripped.priorities),
                                  np.asarray(s.priorities))
    assert float(tripped.max_priority) == float(s.max_priority)
    # and valid=True is exactly the unguarded update
    ok = buf.update_priorities(s, jnp.asarray([0, 2]),
                               jnp.asarray([5.0, 9.0]),
                               valid=jnp.asarray(True))
    plain = buf.update_priorities(s, jnp.asarray([0, 2]),
                                  jnp.asarray([5.0, 9.0]))
    np.testing.assert_array_equal(np.asarray(ok.priorities),
                                  np.asarray(plain.priorities))
    assert float(ok.max_priority) == float(plain.max_priority) == 9.0


def test_per_new_episodes_get_max_priority():
    buf = _buf(PrioritizedReplayBuffer, cap=4, alpha=1.0, beta0=0.4,
               t_max=100)
    s = buf.insert_episode_batch(buf.init(), _make_batch(2))
    s = buf.update_priorities(s, jnp.arange(2), jnp.asarray([5.0, 1.0]))
    s = buf.insert_episode_batch(s, _make_batch(1))
    assert float(s.priorities[2]) == pytest.approx(5.0)   # running max


@pytest.mark.slow   # full rollout compile (~19 s) for a dtype assertion
def test_avail_actions_storage_is_bool():
    """avail is a predicate: bool ring storage makes arithmetic misuse a
    type error (consumers only ever compare > 0)."""
    import jax
    from t2omca_tpu.config import EnvConfig, ModelConfig, ReplayConfig, \
        TrainConfig, sanity_check
    from t2omca_tpu.run import Experiment
    cfg = sanity_check(TrainConfig(
        batch_size_run=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8)))
    exp = Experiment.build(cfg)
    ts = exp.init_train_state(0)
    assert ts.buffer.storage.avail_actions.dtype == jnp.bool_
    rollout, insert, _ = exp.jitted_programs()
    _, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                          test_mode=False)
    assert batch.avail_actions.dtype == jnp.bool_
    buf = insert(ts.buffer, batch)
    assert buf.storage.avail_actions.dtype == jnp.bool_
