"""graftrace enforcement (t2omca_tpu/analysis/graftrace.py,
docs/ANALYSIS.md GT catalog): per-rule positive/negative fixtures —
including replicas of the three historical bugs (Logger.stats race →
GT101, wedged-exit save_lock acquire → GT102, Sebulba shared watchdog
stamp → GT105) so the gate provably catches them — plus baseline
round-trip/ratchet/family-scoping, the zero-new-findings ratchet over
the real package, and the subprocess CLI exit-code contract."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from t2omca_tpu.analysis import (GT_RULES, diff_baseline, filter_family,
                                 load_baseline, save_baseline,
                                 trace_package, trace_source)
from t2omca_tpu.analysis.graftlint import lint_source

pytestmark = pytest.mark.graftrace

REPO = Path(__file__).resolve().parents[1]


def rules_of(src, path="fixture.py"):
    return [f.rule for f in trace_source(src, path)]


# ------------------------------------------------- GT101 (Logger race)

LOGGER_RACE = """
import threading

class Logger:
    def __init__(self):
        self.stats = {}
        self.flusher = threading.Thread(target=self._flush, daemon=True)
        self.flusher.start()

    def log(self, k, v):
        self.stats[k] = v            # main-thread write

    def _flush(self):
        for k in list(self.stats):   # flusher-thread read/pop
            self.stats.pop(k)
"""


def test_gt101_logger_race_replica():
    """The historical unsynchronized ``Logger.stats`` race: written from
    the caller thread, drained from the flusher, no lock anywhere."""
    fs = trace_source(LOGGER_RACE, "fixture.py")
    assert [f.rule for f in fs] == ["GT101", "GT101"]
    assert all("stats" in f.message for f in fs)


def test_gt101_negative_locked_everywhere():
    src = """
import threading

class Logger:
    def __init__(self):
        self.lock = threading.Lock()
        self.stats = {}
        threading.Thread(target=self._flush, daemon=True).start()

    def log(self, k, v):
        with self.lock:
            self.stats[k] = v

    def _flush(self):
        with self.lock:
            self.stats.clear()
"""
    assert rules_of(src) == []


def test_gt101_negative_init_writes_are_pre_thread():
    """``__init__`` writes happen-before the spawn; a single-role module
    (no spawns) shares nothing at all."""
    src = """
class Plain:
    def __init__(self):
        self.stats = {}

    def log(self, k, v):
        self.stats[k] = v
"""
    assert rules_of(src) == []


def test_gt101_closure_var_shared_with_spawned_worker():
    src = """
import threading

def run():
    total = 0
    def worker():
        nonlocal total
        total += 1
    t = threading.Thread(target=worker)
    t.start()
    total += 1            # after the spawn: races the worker
"""
    fs = trace_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GT101", "GT101"]
    assert all("total" in f.message for f in fs)


def test_gt101_closure_writes_before_spawn_exempt():
    src = """
import threading

def run():
    total = 0             # setup: happens-before the spawn
    def worker():
        print(total)      # read-only consumer
    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=5.0)
"""
    assert rules_of(src) == []


# --------------------------------------------- GT102 (save_lock wedge)

SAVE_LOCK_WEDGE = """
import threading

save_lock = threading.Lock()

def save(state):
    save_lock.acquire()
    try:
        state.flush()
    finally:
        save_lock.release()
"""


def test_gt102_unbounded_acquire_replica():
    """The historical exit wedge: a bare ``save_lock.acquire()`` blocks
    forever if the holder is stuck — PR 4's bounded-acquire policy,
    made checkable."""
    fs = trace_source(SAVE_LOCK_WEDGE, "fixture.py")
    assert [f.rule for f in fs] == ["GT102"]
    assert "acquire" in fs[0].code


def test_gt102_negative_bounded_or_nonblocking():
    src = """
import threading

save_lock = threading.Lock()

def save(state):
    if not save_lock.acquire(timeout=30.0):
        raise TimeoutError("save_lock wedged")
    try:
        state.flush()
    finally:
        save_lock.release()

def try_save(state):
    if save_lock.acquire(blocking=False):
        try:
            state.flush()
        finally:
            save_lock.release()
"""
    assert rules_of(src) == []


# ------------------------------------------------- GT103 (mixed locks)

def test_gt103_mixed_locked_and_unlocked_access():
    src = """
import threading

class Counter:
    def __init__(self):
        self.lock = threading.Lock()
        self.n = 0
        threading.Thread(target=self._tick, daemon=True).start()

    def _tick(self):
        with self.lock:
            self.n += 1

    def read(self):
        return self.n          # unlocked: the lock protects nothing
"""
    fs = trace_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GT103"]
    assert "self.n" in fs[0].message
    assert fs[0].code.startswith("return self.n")


def test_gt103_negative_uniform_discipline():
    src = """
import threading

class Counter:
    def __init__(self):
        self.lock = threading.Lock()
        self.n = 0
        threading.Thread(target=self._tick, daemon=True).start()

    def _tick(self):
        with self.lock:
            self.n += 1

    def read(self):
        with self.lock:
            return self.n
"""
    assert rules_of(src) == []


# ------------------------------------------------ GT104 (ABBA deadlock)

def test_gt104_lock_ordering_cycle():
    src = """
import threading

a = threading.Lock()
b = threading.Lock()

def fwd():
    with a:
        with b:
            pass

def rev():
    with b:
        with a:
            pass
"""
    fs = trace_source(src, "fixture.py")
    assert [f.rule for f in fs] == ["GT104", "GT104"]


def test_gt104_negative_consistent_order_and_reentry():
    src = """
import threading

a = threading.Lock()
b = threading.Lock()
r = threading.RLock()

def f1():
    with a:
        with b:
            pass

def f2():
    with a:
        with b:
            pass

def reenter():
    with r:
        with r:               # RLock re-entry is not a cycle
            pass
"""
    assert rules_of(src) == []


# ------------------------------------------- GT105 (shared wd stamp)

SHARED_STAMP = """
import threading
from t2omca_tpu.utils.watchdog import Watchdog

wd = Watchdog()

def actor():
    while True:
        wd.stamp("actor.step")

def learner():
    threading.Thread(target=actor, daemon=True).start()
    while True:
        wd.stamp("learner.step")
"""


def test_gt105_shared_watchdog_stamp_replica():
    """The Sebulba gotcha: actor and learner stamping ONE watchdog mask
    each other's stalls — each thread needs its own."""
    fs = trace_source(SHARED_STAMP, "fixture.py")
    assert "GT105" in [f.rule for f in fs]
    gt105 = [f for f in fs if f.rule == "GT105"]
    assert any("wd" in f.message for f in gt105)


def test_gt105_negative_per_thread_watchdogs():
    src = """
import threading
from t2omca_tpu.utils.watchdog import Watchdog

wd_actor = Watchdog()
wd_learner = Watchdog()

def actor():
    wd_actor.stamp("actor.step")

def learner():
    threading.Thread(target=actor, daemon=True).start()
    wd_learner.stamp("learner.step")
"""
    assert "GT105" not in rules_of(src)


# --------------------------------------- GT106 (blocking under a lock)

def test_gt106_device_sync_under_contended_lock():
    src = """
import threading
import jax

lock = threading.Lock()

def worker():
    with lock:
        jax.block_until_ready(0)   # every contender stalls behind it

def driver():
    threading.Thread(target=worker, daemon=True).start()
    with lock:
        pass
"""
    fs = trace_source(src, "fixture.py")
    assert "GT106" in [f.rule for f in fs]


def test_gt106_negative_uncontended_or_outside_lock():
    src = """
import threading
import jax

lock = threading.Lock()

def worker():
    x = jax.block_until_ready(0)   # not holding anything
    with lock:
        pass

def driver():
    threading.Thread(target=worker, daemon=True).start()
    with lock:
        pass
"""
    assert "GT106" not in rules_of(src)


def test_gt106_negative_condition_wait_releases_its_own_lock():
    src = """
import threading

cond = threading.Condition()

def worker():
    with cond:
        cond.wait(timeout=1.0)    # releases cond while waiting

def driver():
    threading.Thread(target=worker, daemon=True).start()
    with cond:
        cond.notify_all()
"""
    assert "GT106" not in rules_of(src)


# ---------------------------------------------------------- suppression

def test_inline_suppression_and_skip_file():
    suppressed = LOGGER_RACE.replace(
        "self.stats[k] = v            # main-thread write",
        "self.stats[k] = v  # graftrace: disable=GT101")
    fs = trace_source(suppressed, "fixture.py")
    assert [f.rule for f in fs] == ["GT101"]   # only the _flush site
    skip = "# graftrace: skip-file\n" + LOGGER_RACE
    assert trace_source(skip, "fixture.py") == []
    # the graftlint suppression tag does NOT silence graftrace
    other_tool = LOGGER_RACE.replace(
        "# main-thread write", "# graftlint: disable=GT101")
    assert [f.rule for f in trace_source(other_tool, "fixture.py")] \
        == ["GT101", "GT101"]


# ------------------------------------------------------------- baseline

def test_baseline_round_trip_ratchet_and_line_shift(tmp_path):
    findings = trace_source(LOGGER_RACE, "pkg/mod.py")
    assert len(findings) == 2
    bl_path = tmp_path / "baseline.json"
    save_baseline(bl_path, findings)
    baseline = load_baseline(bl_path)
    new, stale = diff_baseline(findings, baseline)
    assert new == [] and stale == []
    # identity survives a line shift (keys are code text, not line no.)
    shifted = "\n# header comment\n" + LOGGER_RACE
    new, stale = diff_baseline(trace_source(shifted, "pkg/mod.py"),
                               baseline)
    assert new == [] and stale == []
    # a fresh hazard (new code text) exceeds the baseline -> new
    grown = LOGGER_RACE + """
    def log2(self, k):
        self.stats[k] = 1
"""
    new, _ = diff_baseline(trace_source(grown, "pkg/mod.py"), baseline)
    assert len(new) == 1 and new[0].rule == "GT101"
    # fixing everything leaves stale entries, never a failure
    new, stale = diff_baseline([], baseline)
    assert new == [] and len(stale) == 2


def test_family_scoped_save_carries_the_other_family(tmp_path):
    """GL and GT share baseline.json: a --threads --write-baseline must
    carry the lint entries verbatim (and vice versa)."""
    bl_path = tmp_path / "baseline.json"
    gl_src = ("import jax\n@jax.jit\ndef f(x):\n    if x > 0:\n"
              "        return x\n    return -x\n")
    gl = lint_source(gl_src, "pkg/traced.py")
    assert [f.rule for f in gl] == ["GL101"]
    save_baseline(bl_path, gl)
    # hand-justify the GL entry, as review would
    data = json.loads(bl_path.read_text())
    data["findings"][0]["justification"] = "intentional fixture branch"
    bl_path.write_text(json.dumps(data))
    old = load_baseline(bl_path)
    # a GT-scoped rewrite keeps the GL entry + its justification
    gt = trace_source(LOGGER_RACE, "pkg/mod.py")
    save_baseline(bl_path, gt, old, family="GT")
    merged = load_baseline(bl_path)
    assert filter_family(merged, "GL") == old
    assert len(filter_family(merged, "GT")) == 2
    # and a GL-scoped rewrite keeps the GT entries
    save_baseline(bl_path, gl, merged, family="GL")
    again = load_baseline(bl_path)
    assert filter_family(again, "GT") == filter_family(merged, "GT")


# ------------------------------------------------- the real package gate

def test_real_package_zero_new_findings():
    """The ratchet over t2omca_tpu/ itself: every current GT finding is
    either fixed or baselined with a justification — new hazards fail
    here (and in the scripts/t1.sh prelude before the pytest batch)."""
    findings = trace_package(REPO)
    baseline = filter_family(load_baseline(), "GT")
    new, _stale = diff_baseline(findings, baseline)
    assert new == [], "new graftrace findings:\n" + "\n".join(
        f.format() for f in new)
    assert baseline, "the GT baseline should not be empty"
    for key, entry in baseline.items():
        assert entry["justification"] and \
            not entry["justification"].startswith("TODO"), key


def test_rule_catalog_documented():
    doc = (REPO / "docs" / "ANALYSIS.md").read_text()
    for rule in GT_RULES:
        assert rule in doc, f"{rule} missing from docs/ANALYSIS.md"


# ------------------------------------------------------------------ CLI

def test_cli_exit_codes(tmp_path):
    env_probe = (
        "import sys, runpy\n"
        "sys.argv = ['t2omca_tpu.analysis', '--threads']\n"
        "try:\n"
        "    runpy.run_module('t2omca_tpu.analysis', "
        "run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert 'jax' not in sys.modules, 'CLI imported jax'\n"
        "    sys.exit(e.code)\n")
    r = subprocess.run([sys.executable, "-c", env_probe],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "graftrace:" in r.stdout
    # seeded hazard in a scratch tree -> exit 1, finding printed
    pkg = tmp_path / "t2omca_tpu"
    pkg.mkdir()
    (pkg / "seeded.py").write_text(SAVE_LOCK_WEDGE)
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--threads",
         "--root", str(tmp_path), "--no-baseline", str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "GT102" in r.stdout and "t2omca_tpu/seeded.py" in r.stdout
    # a corrupt baseline is an internal error (2), never "new findings"
    bad = tmp_path / "bad_baseline.json"
    bad.write_text('{"version": 99, "findings": []}')
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--threads",
         "--baseline", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2 and "baseline" in r.stderr


def test_cli_catches_all_three_historical_replicas(tmp_path):
    """The acceptance bar: Logger race, shared watchdog stamp, and the
    unbounded save_lock acquire are each caught in-gate."""
    pkg = tmp_path / "t2omca_tpu"
    pkg.mkdir()
    (pkg / "logger_race.py").write_text(LOGGER_RACE)
    (pkg / "save_wedge.py").write_text(SAVE_LOCK_WEDGE)
    (pkg / "shared_stamp.py").write_text(SHARED_STAMP)
    r = subprocess.run(
        [sys.executable, "-m", "t2omca_tpu.analysis", "--threads",
         "--root", str(tmp_path), "--no-baseline", str(pkg)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    for rule in ("GT101", "GT102", "GT105"):
        assert rule in r.stdout, f"{rule} not caught in-gate"
