"""The bench harness itself is a round artifact producer (the driver runs
``python bench.py`` on TPU and records its ONE JSON line in BENCH_r{N}.json)
— so its output contract is pinned here, on the CPU smoke path, where a
harness regression would otherwise only be discovered on the chip."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


def run_bench(*args):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # CPU run must not touch axon
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected ONE JSON line, got: {proc.stdout!r}"
    return json.loads(lines[0])


@pytest.mark.slow   # subprocess + fresh jit (~30 s); the round driver
                    # runs `bench.py --smoke` directly anyway
def test_default_line_schema():
    rec = run_bench()
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in rec, rec
    assert rec["metric"] == "env_steps_per_sec"
    assert rec["unit"] == "env-steps/s/chip"
    assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
    # smoke runs must not claim a BASELINE config id
    assert rec["config"] is None


@pytest.mark.slow   # subprocess + fresh jit; rides the same smoke run shape
def test_span_summary_embedded_in_record():
    """graftscope satellite (docs/OBSERVABILITY.md): every BENCH record
    embeds the per-phase span summary — build, compile (the first
    dispatch), warm, and the steady-state measure phase — so a
    BENCH_r*.json says where its wall-clock went."""
    rec = run_bench()
    spans = rec["spans"]
    for phase in ("bench.build", "bench.compile", "bench.warm",
                  "bench.measure"):
        assert phase in spans, (phase, sorted(spans))
        assert spans[phase]["n"] >= 1
        assert spans[phase]["total_ms"] > 0
    # the measure phase ran the timed iterations: first_ms isolates the
    # first timed run, steady_ms the warm median's neighborhood
    assert spans["bench.measure"]["n"] >= 3
    assert spans["bench.measure"]["steady_ms"] > 0
    # compile dominates warm on a fresh subprocess
    assert spans["bench.compile"]["first_ms"] > spans["bench.warm"]["first_ms"]


@pytest.mark.slow   # two subprocess benches; the acting flag plumbing is pure argparse
@pytest.mark.parametrize("acting", ["qslice", "dense"])
def test_acting_selector_reported(acting):
    rec = run_bench("--acting", acting)
    assert rec["acting"] == acting
    assert rec["value"] > 0


@pytest.mark.slow   # subprocess + two fresh dense-rollout jits (xla + pallas
                    # interpret) — the --kernels A/B contract (docs/PERF.md)
def test_kernels_ab_leg_records_per_mode():
    """``--kernels ab``: TWO records per kernel mode since PR 13 — the
    dense rollout (env_steps_per_sec) and the train-step leg
    (train_iters_per_sec, the flash-backward half of the A/B) — each
    carrying the mode and its own per-mode span legs, schema'd via
    ``_finalize``; the attributable A/B the roofline report joins
    against."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--kernels", "ab",
         "--envs", "4", "--steps", "4"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    assert [(r["kernels"], r["metric"]) for r in recs] == [
        ("xla", "env_steps_per_sec"), ("xla", "train_iters_per_sec"),
        ("pallas", "env_steps_per_sec"), ("pallas", "train_iters_per_sec")]
    for rec in recs:
        assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
        assert rec["schema"] == 1
        assert "bench.measure" in rec["spans"]
        if rec["metric"] == "env_steps_per_sec":
            assert rec["acting"] == "dense"
        else:
            assert rec["unit"] == "train-iters/s/chip"
            assert rec["train_batch_episodes"] > 0
            assert rec["leg"] == f"kernels-{rec['kernels']}-train"


@pytest.mark.slow   # subprocess + fresh jit; rbg impl pinned cheaply in test_driver
def test_prng_rbg_end_to_end():
    """--prng rbg routes every key through the XLA RngBitGenerator (the
    TPU-hardware path; subprocess keeps the process-global impl switch
    out of this pytest process). The record must carry the non-default
    impl so a chip measurement can't be misattributed to threefry."""
    rec = run_bench("--prng", "rbg")
    assert rec["value"] > 0
    assert rec["prng"] == "rbg"


@pytest.mark.slow   # subprocess + fresh jit; --pipeline plumbing only
def test_pipeline_flag_adds_steady_state_rate():
    rec = run_bench("--pipeline", "2")
    assert rec["pipelined_env_steps_per_sec"] > 0
    # the blocking median stays the headline value
    assert rec["metric"] == "env_steps_per_sec" and rec["value"] > 0


@pytest.mark.slow   # subprocess + train compile; pipeline flag covered by the rollout variant
def test_pipeline_train_steady_state():
    rec = run_bench("--train", "--pipeline", "2")
    assert rec["pipelined_train_steps_per_sec"] > 0
    assert rec["pipelined_interleaved_env_steps_per_sec"] > 0


def test_committed_config_presets_load():
    """The configs/ presets (BASELINE measurement points as config files —
    the reference's sacred-config workflow, M14) must stay loadable and
    sane as flags evolve."""
    from t2omca_tpu.config import load_config
    expect = {
        "config1_cpu_parity.yaml": dict(agv=4, envs=8, dp=0),
        "config3_tpu_northstar.yaml": dict(agv=64, envs=1024, dp=0),
        "config5_dp8.yaml": dict(agv=256, envs=8192, dp=8),
    }
    for name, e in expect.items():
        cfg = load_config(os.path.join(REPO, "configs", name))
        assert cfg.env_args.agv_num == e["agv"]
        assert cfg.batch_size_run == e["envs"]
        assert cfg.dp_devices == e["dp"]


def test_backend_probe_bound_emits_record():
    """A wedged TPU tunnel blocks backend init far past the caller's own
    timeout — the bounded SUBPROCESS probe must land a parseable,
    structured error record first (probe timeout <= 0 forces the
    timed-out branch deterministically; the retry must show in the
    message, and the record must attribute the failure to the TIMEOUT
    phase, not a backend error the child never got to raise)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["T2OMCA_BACKEND_PROBE_TIMEOUT"] = "0"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--envs", "8", "--steps", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["phase"] == "timeout"
    assert "probe bound" in rec["error"]
    assert "attempt 2/2" in rec["error"]            # one retry happened


def test_probe_timeout_kills_and_reaps_child():
    """The probe's timeout path must leave NO child behind: the wedged
    child is killed AND reaped (a zombie per probe would accumulate
    against the pid limit in a soak loop). In-process against
    probe_backend with a sleeping stand-in for the wedged init — fast,
    no jax import in the child."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    pids = []
    real_popen = bench.subprocess.Popen

    class RecordingPopen(real_popen):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            pids.append(self.pid)

    bench.subprocess.Popen = RecordingPopen
    try:
        failure = bench.probe_backend(
            2.0, _cmd=[sys.executable, "-c", "import time; time.sleep(300)"])
    finally:
        bench.subprocess.Popen = real_popen
    assert failure is not None
    assert failure["phase"] == "timeout"
    assert "attempt 2/2" in failure["error"]
    assert len(pids) == 2                           # both attempts spawned
    for pid in pids:
        # killed AND reaped: a reaped pid is gone (ProcessLookupError);
        # a zombie still accepts signal 0
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)


def test_probe_child_error_reports_backend_init_phase():
    """A child that starts but FAILS (real backend error) must be
    attributed to the backend_init phase with its stderr in the record —
    distinct from the timeout shape above."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    failure = bench.probe_backend(
        30.0, _cmd=[sys.executable, "-c",
                    "import sys; print('tunnel says no', file=sys.stderr); "
                    "sys.exit(3)"])
    assert failure is not None
    assert failure["phase"] == "backend_init"
    assert "tunnel says no" in failure["error"]


@pytest.mark.slow   # subprocess + fused-program jit (~33 s, the heaviest
                    # remaining in-gate bench test); the round driver runs
                    # `bench.py --smoke --superstep 1` directly anyway
def test_superstep_bench_reports_amortized_rate():
    """--superstep K: the fused-dispatch measurement. K=4 exercises the
    scan and the warm dispatch must have opened the train gate; the K=1
    leg (same code path, k-independent) rides the round driver's
    acceptance run of `bench.py --smoke --superstep 1`."""
    rec = run_bench("--superstep", "4")
    assert rec["metric"] == "env_steps_per_sec"
    assert isinstance(rec["value"], (int, float)) and rec["value"] > 0
    assert rec["superstep"] == 4
    assert rec["train_gate_open"] is True
    assert rec["config"] is None


def test_hbm_estimator_schema_and_no_device_work():
    """--hbm is pure shape arithmetic — it must work with the axon env
    var present (never touching a possibly-wedged backend) and report the
    compact-vs-dense storage difference."""
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "127.0.0.1"   # wedged-tunnel conditions
    env["T2OMCA_BACKEND_PROBE_TIMEOUT"] = "1"   # would fail if probed
    proc = subprocess.run(
        [sys.executable, "bench.py", "--hbm", "--config", "3"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "hbm_estimate_gib"
    assert rec["value"] > 0
    assert set(rec["breakdown_gib"]) == {
        "replay_ring", "rollout_episode_batch", "train_episode_batch",
        "learner_scan_residuals"}


@pytest.mark.slow   # DP=8 allocation + train compile (~2 min on the 2-core box)
def test_prod_hbm_allocates_ring_and_cross_checks_analytic():
    """--prod-hbm (VERDICT r4 item 4 producer): PRODUCTION-shaped ring
    (agv 256 / emb 256 / bf16 compact storage) actually allocated on the
    8-device virtual mesh, insert + train iteration run with it
    co-resident, and the --hbm analytic cross-checked against real
    allocated bytes. Reduced --ring/--envs/--steps keep the CI cost
    bounded; shapes per episode stay production."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_NUM_CPU_DEVICES"] = "8"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--prod-hbm", "--ring", "64",
         "--envs", "32", "--steps", "2"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "prod_ring_resident_gib"
    assert rec["value"] > 0
    assert rec["ring_episodes"] == 64
    # the analytic model must track the real allocation closely — this
    # is the bound that makes the --hbm budget trustworthy at config 5
    assert abs(rec["analytic_delta_pct"]) < 10, rec
    assert rec["train_loss"] is not None
    import math
    assert math.isfinite(rec["train_loss"])


@pytest.mark.slow   # 8-virtual-device mesh compile (~3 min on the 2-core box)
def test_dp_bench_path_on_virtual_mesh():
    """The --config 5 (DP=8) bench is the config-5 round-artifact
    producer: run it at reduced shapes on the 8-device virtual CPU mesh
    and check both metric halves appear (rollout env-steps/s headline +
    train-steps/s field)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_NUM_CPU_DEVICES"] = "8"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--config", "5", "--envs", "8",
         "--steps", "2", "--iters", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "env_steps_per_sec"
    assert rec["dp"] == 8
    assert rec["value"] > 0
    assert rec["train_steps_per_sec"] > 0
    # reduced shapes must not claim the BASELINE scale point
    assert rec["config"] is None
