"""CRITIC weighting (M2): standard-method invariants + NaN robustness."""

import jax.numpy as jnp
import numpy as np

from t2omca_tpu.envs import critic


def test_scores_shape_and_range():
    rng = np.random.default_rng(0)
    m = rng.uniform(0, 1, size=(6, 3)).astype(np.float32)
    s = np.asarray(critic(jnp.asarray(m)))
    assert s.shape == (6,)
    assert np.isfinite(s).all()
    assert (s >= 0).all() and (s <= 1 + 1e-6).all()


def test_dominant_row_scores_highest():
    m = jnp.asarray([[0.9, 0.9, 0.9],
                     [0.1, 0.2, 0.1],
                     [0.5, 0.4, 0.6]])
    s = np.asarray(critic(m))
    assert s.argmax() == 0 and s.argmin() == 1


def test_degenerate_column_no_nan():
    # constant column -> zero range & zero std; reference would print-guard,
    # we must stay finite (environment_multi_mec.py:102-104)
    m = jnp.asarray([[1.0, 0.3, 0.2],
                     [1.0, 0.7, 0.9],
                     [1.0, 0.1, 0.4]])
    s = np.asarray(critic(m))
    assert np.isfinite(s).all()


def test_matches_numpy_reference_implementation():
    """Cross-check against a straightforward NumPy CRITIC."""
    rng = np.random.default_rng(3)
    m = rng.uniform(0, 1, size=(8, 3))
    lo, hi = m.min(0), m.max(0)
    xn = (m - lo) / (hi - lo)
    std = xn.std(0)
    corr = np.corrcoef(xn.T)
    info = std * (1 - corr).sum(1)
    w = info / info.sum()
    expected = xn @ w
    got = np.asarray(critic(jnp.asarray(m.astype(np.float32))))
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)
