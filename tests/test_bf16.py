"""Coverage for the bfloat16 perf modes: compute dtype (model.dtype) and
episode/replay storage dtype (replay.store_dtype) — the paths bench.py uses
on TPU, exercised here on CPU at tiny scale."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.run import Experiment


@pytest.fixture(scope="module")
def bf16_exp():
    cfg = sanity_check(TrainConfig(
        batch_size_run=2, batch_size=2,
        # fast_norm=False: this fixture pins the DENSE bf16 storage path
        # (compact entity storage keeps its leaves f32 by design)
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=4, fast_norm=False),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1,
                          standard_heads=True, dtype="bfloat16"),
        replay=ReplayConfig(buffer_size=8, store_dtype="bfloat16"),
    ))
    return Experiment.build(cfg)


@pytest.mark.slow   # bf16 rollout compile (~21 s); the bf16 train-step e2e stays in-gate
def test_bf16_rollout_storage_and_boundaries(bf16_exp):
    exp = bf16_exp
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    rs, batch, stats = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
    # storage arrays are compact; reward/Q-side math stays f32
    assert batch.obs.dtype == jnp.bfloat16
    assert batch.state.dtype == jnp.bfloat16
    assert batch.reward.dtype == jnp.float32
    # params are f32 (bf16 is compute dtype, not param dtype)
    leaf = jax.tree.leaves(ts.learner.params)[0]
    assert leaf.dtype == jnp.float32
    assert np.isfinite(np.asarray(stats.episode_return)).all()


@pytest.mark.slow   # bf16 train compile (~16 s); the f32-boundary forward test stays in-gate
def test_bf16_end_to_end_train_step(bf16_exp):
    exp = bf16_exp
    cfg = exp.cfg
    ts = exp.init_train_state(0)
    rollout, insert, train_iter = exp.jitted_programs()
    for _ in range(2):
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=ts.episode + cfg.batch_size_run)
    assert bool(exp.buffer.can_sample(ts.buffer, cfg.batch_size))
    ts2, info = train_iter(ts, jax.random.PRNGKey(1), jnp.asarray(16))
    assert np.isfinite(float(info["loss"]))
    assert np.isfinite(float(info["grad_norm"]))
    changed = jax.tree.map(lambda a, b: not np.allclose(a, b),
                           ts.learner.params, ts2.learner.params)
    assert any(jax.tree.leaves(changed))


def test_bf16_forward_close_to_f32():
    """bf16 compute tracks the f32 forward within bf16 tolerance on the
    same parameters."""
    from t2omca_tpu.controllers import BasicMAC
    from t2omca_tpu.envs.registry import make_env

    def build(dtype):
        cfg = sanity_check(TrainConfig(
            env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                               episode_limit=4),
            model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                              mixer_heads=2, mixer_depth=1,
                              standard_heads=True, dtype=dtype)))
        env = make_env(cfg.env_args)
        return BasicMAC.build(cfg, env.get_env_info()), env.get_env_info()

    mac32, info = build("float32")
    mac16, _ = build("bfloat16")
    params = mac32.init_params(jax.random.PRNGKey(0), info["obs_shape"])
    obs = jax.random.normal(jax.random.PRNGKey(1),
                            (2, info["n_agents"], info["obs_shape"]))
    h = mac32.init_hidden(2)
    q32, _ = mac32.forward(params, obs, h)
    q16, _ = mac16.forward(params, obs, h)
    assert q16.dtype == jnp.float32          # boundary cast back to f32
    np.testing.assert_allclose(np.asarray(q32), np.asarray(q16),
                               atol=0.15, rtol=0.15)
