"""Fused Pallas transformer-block kernel vs the flax reference path.

Runs in the Pallas interpreter on CPU (SURVEY.md §4: no-cluster testing);
the same kernel compiles via Mosaic on TPU (exercised by bench.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import (EnvConfig, ModelConfig, TrainConfig,
                               sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.models.transformer import TransformerBlock
from t2omca_tpu.ops.transformer_block import fused_transformer_block


def _block_params(key, emb, heads, standard_heads=True):
    blk = TransformerBlock(emb=emb, heads=heads,
                           standard_heads=standard_heads)
    x = jnp.zeros((2, 5, emb))
    return blk, blk.init(key, x, x)["params"]


def _run_fused(params, xq, xk, heads, head_dim):
    at = params["attention"]
    return fused_transformer_block(
        xq, xk,
        at["toqueries"]["kernel"], at["tokeys"]["kernel"],
        at["tovalues"]["kernel"],
        at["unifyheads"]["kernel"], at["unifyheads"]["bias"],
        params["norm1"]["scale"], params["norm1"]["bias"],
        params["ff1"]["kernel"], params["ff1"]["bias"],
        params["ff2"]["kernel"], params["ff2"]["bias"],
        params["norm2"]["scale"], params["norm2"]["bias"],
        heads=heads, head_dim=head_dim, interpret=True)


@pytest.mark.parametrize("t", [5, 8, 16, 17])
def test_fused_block_matches_flax_f32(t):
    """Arbitrary (non-aligned) token counts: padding+masking must be exact."""
    emb, heads, s = 16, 2, 6
    blk, params = _block_params(jax.random.PRNGKey(0), emb, heads)
    xq = jax.random.normal(jax.random.PRNGKey(1), (s, t, emb))
    xk = jax.random.normal(jax.random.PRNGKey(2), (s, t, emb))
    ref = blk.apply({"params": params}, xq, xk)
    fused = _run_fused(params, xq, xk, heads, emb // heads)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_fused_block_full_emb_heads():
    """Quirk Q1 geometry (per-head dim = emb) through the kernel."""
    emb, heads, s, t = 8, 3, 4, 5
    blk, params = _block_params(jax.random.PRNGKey(3), emb, heads,
                                standard_heads=False)
    xq = jax.random.normal(jax.random.PRNGKey(4), (s, t, emb))
    xk = jax.random.normal(jax.random.PRNGKey(5), (s, t, emb))
    ref = blk.apply({"params": params}, xq, xk)
    fused = _run_fused(params, xq, xk, heads, emb)   # head_dim = emb
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("dtype,std,tol", [("float32", False, 1e-5),
                                           ("float32", True, 1e-5),
                                           ("bfloat16", True, 0.05)])
def test_fast_agent_matches_module(dtype, std, tol):
    """forward_fast (fused acting path) ≈ flax forward on the same params,
    including the depth-2 layer-0 key threading and hidden-token recurrence."""
    cfg = sanity_check(TrainConfig(
        env_args=EnvConfig(agv_num=4, mec_num=2, num_channels=2,
                           episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=2, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1,
                          standard_heads=std, dtype=dtype,
                          use_pallas=True)))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    params = mac.init_params(jax.random.PRNGKey(0), info["obs_shape"])
    obs = jax.random.normal(jax.random.PRNGKey(1),
                            (3, 4, info["obs_shape"]))
    h = jax.random.normal(jax.random.PRNGKey(2), (3, 4, 8))
    q_ref, h_ref = mac.forward(params, obs, h)
    q_fast, h_fast = mac.forward_fast(params, obs, h)
    np.testing.assert_allclose(np.asarray(q_fast), np.asarray(q_ref),
                               atol=tol, rtol=tol)
    np.testing.assert_allclose(np.asarray(h_fast), np.asarray(h_ref),
                               atol=tol, rtol=tol)


def test_pallas_rollout_matches_shapes_and_legality():
    """Full rollout with the fused acting path (interpret mode on CPU)."""
    from t2omca_tpu.runners import ParallelRunner
    from t2omca_tpu.learners import QMixLearner

    cfg = sanity_check(TrainConfig(
        batch_size_run=2,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=3),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1,
                          standard_heads=True, dtype="bfloat16",
                          use_pallas=True)))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    rs, batch, stats = jax.jit(runner.run, static_argnames="test_mode")(
        ls.params["agent"], rs, test_mode=False)
    avail = np.asarray(batch.avail_actions[:, :-1])
    actions = np.asarray(batch.actions)
    taken = np.take_along_axis(avail, actions[..., None], axis=-1)
    assert (taken == 1).all()
    assert np.isfinite(np.asarray(stats.episode_return)).all()


def test_use_pallas_rejects_noisy_and_dropout():
    cfg = TrainConfig(
        env_args=EnvConfig(agv_num=3, mec_num=2, episode_limit=4),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1, use_pallas=True,
                          dropout=0.1))
    with pytest.raises(ValueError, match="use_pallas"):
        sanity_check(cfg)
    # the MAC-level guard also fires for callers bypassing sanity_check
    env = make_env(cfg.env_args)
    with pytest.raises(ValueError, match="use_pallas"):
        BasicMAC.build(cfg, env.get_env_info())