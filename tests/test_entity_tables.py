"""Exactness of the entity-table acting path (ops/query_slice,
``agent_forward_qslice_entity``) against the obs-based query-slice forward.

The factored form must reproduce the full normalized entity observation's
embeddings (visible/masked tables + is-self diagonal) and hence identical
Q-values — on REAL env states (including the post-reset first-sample
statistics and mid-episode Welford states), not just synthetic inputs."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from t2omca_tpu.config import EnvConfig, ModelConfig, TrainConfig, sanity_check
from t2omca_tpu.controllers.basic_mac import BasicMAC
from t2omca_tpu.envs.mec_offload import MultiAgvOffloadingEnv
from t2omca_tpu.run import Experiment


def _cfg(**model_kw):
    return sanity_check(TrainConfig(
        batch_size_run=4,
        env_args=EnvConfig(agv_num=5, mec_num=2, num_channels=3,
                           episode_limit=6, fast_norm=True),
        model=ModelConfig(emb=16, heads=2, depth=2, mixer_emb=16,
                          mixer_heads=2, mixer_depth=2, **model_kw),
    ))


def _rolled_states(env, b, steps, key):
    """Env states after ``steps`` random steps (real queues + norm stats)."""
    states, obs, *_ = jax.vmap(env.reset)(jax.random.split(key, b))
    for t in range(steps):
        k = jax.random.fold_in(key, 100 + t)
        actions = jax.random.randint(k, (b, env.n_agents), 0, env.n_actions)
        actions = actions * states.job_valid[:, :, 0]
        states, _, _, _, obs, *_ = jax.vmap(env.step)(
            states, actions, jax.random.split(k, b))
    return states, obs


@pytest.mark.parametrize("steps", [0, 4])
@pytest.mark.parametrize("standard_heads", [False, True])
def test_entity_forward_matches_obs_forward(steps, standard_heads):
    cfg = _cfg(standard_heads=standard_heads)
    exp = Experiment.build(cfg)
    env, mac = exp.env, exp.mac
    assert mac.use_entity_tables

    b = cfg.batch_size_run
    key = jax.random.PRNGKey(steps)
    states, obs = _rolled_states(env, b, steps, key)
    compact = jax.vmap(env.compact_obs)(states)

    params = mac.init_params(key, env.obs_dim)
    hidden = jax.random.normal(jax.random.fold_in(key, 1),
                               (b, env.n_agents, cfg.model.emb))

    q_obs, h_obs = mac.forward_qslice(params, obs, hidden)
    q_ent, h_ent = mac.forward_entity(params, compact, hidden)
    np.testing.assert_allclose(q_ent, q_obs, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_ent, h_obs, rtol=2e-4, atol=2e-5)


def test_entity_forward_matches_dense_flax():
    """Transitively exact vs the dense module too."""
    cfg = _cfg()
    exp = Experiment.build(cfg)
    env, mac = exp.env, exp.mac
    b = cfg.batch_size_run
    key = jax.random.PRNGKey(7)
    states, obs = _rolled_states(env, b, 3, key)
    compact = jax.vmap(env.compact_obs)(states)
    params = mac.init_params(key, env.obs_dim)
    hidden = jnp.zeros((b, env.n_agents, cfg.model.emb))

    q_dense, h_dense = mac.forward(params, obs, hidden)
    q_ent, h_ent = mac.forward_entity(params, compact, hidden)
    np.testing.assert_allclose(q_ent, q_dense, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(h_ent, h_dense, rtol=5e-4, atol=5e-5)


def _assert_close_bf16_ulp(actual, desired, max_ulp=32):
    """Compare two bf16-computed tensors IN THE STORAGE DTYPE with a
    per-element tolerance of ``max_ulp`` bf16 ULPs. Both paths round
    intermediates at different points (docs/SPEC.md §7 header note), so
    the error scales with element MAGNITUDE — a flat f32 atol is too
    tight for large elements (the seed-known 1/320-element failure at
    atol=0.05) while saying nothing near zero. The per-element ULP is
    floored at the tensor's RMS scale: attention/LayerNorm reductions
    cancel, so absolute error lives at the scale of the SUMMANDS, and a
    near-zero result legitimately carries rounding noise from O(rms)
    terms. bf16 ULP at magnitude m = 2^(floor(log2 m) − 7) (8-bit
    mantissa). Observed worst over this path: ~22 ULPs (depth-2
    transformer, ≈20 differently-placed rounding steps)."""
    import ml_dtypes  # ships with jax

    a = np.asarray(np.asarray(actual, ml_dtypes.bfloat16), np.float32)
    d = np.asarray(np.asarray(desired, ml_dtypes.bfloat16), np.float32)
    scale = np.maximum(np.maximum(np.abs(a), np.abs(d)),
                       np.sqrt(np.mean(d ** 2)))
    ulp = 2.0 ** (np.floor(np.log2(scale)) - 7.0)
    err = np.abs(a - d) / ulp
    assert err.max() <= max_ulp, (
        f"{int((err > max_ulp).sum())}/{err.size} elements beyond "
        f"{max_ulp} bf16 ULPs (worst {err.max():.1f})")


def test_entity_forward_bf16_matches_obs_forward():
    """The production bench config (bfloat16 + standard heads + fast_norm)
    runs exactly this path — pin its numerics too. Both forwards compute
    in bf16, so equivalence is asserted in the storage dtype with a
    per-element ULP bound, not a flat f32 atol (see
    ``_assert_close_bf16_ulp``)."""
    cfg = _cfg(standard_heads=True, dtype="bfloat16")
    exp = Experiment.build(cfg)
    env, mac = exp.env, exp.mac
    assert mac.use_entity_tables
    b = cfg.batch_size_run
    key = jax.random.PRNGKey(3)
    states, obs = _rolled_states(env, b, 3, key)
    compact = jax.vmap(env.compact_obs)(states)
    params = mac.init_params(key, env.obs_dim)
    hidden = jnp.zeros((b, env.n_agents, cfg.model.emb))
    q_obs, h_obs = mac.forward_qslice(params, obs, hidden)
    q_ent, h_ent = mac.forward_entity(params, compact, hidden)
    _assert_close_bf16_ulp(q_ent, q_obs)
    _assert_close_bf16_ulp(h_ent, h_obs)


@pytest.mark.slow   # two rollout compiles (~16 s); numeric equivalence of the paths pinned above
def test_rollout_actions_match_obs_path():
    """Greedy episode through the runner: entity-table acting and obs-path
    acting pick identical actions and returns."""
    cfg = _cfg()
    exp_ent = Experiment.build(cfg)
    cfg_obs = cfg.replace(
        model=dataclasses.replace(cfg.model, use_entity_tables=False))
    exp_obs = Experiment.build(cfg_obs)
    assert exp_ent.mac.use_entity_tables
    assert not exp_obs.mac.use_entity_tables

    ts = exp_ent.init_train_state(0)
    run_ent = jax.jit(exp_ent.runner.run, static_argnames="test_mode")
    run_obs = jax.jit(exp_obs.runner.run, static_argnames="test_mode")
    p = ts.learner.params["agent"]
    _, b_ent, s_ent = run_ent(p, ts.runner, test_mode=True)
    _, b_obs, s_obs = run_obs(p, ts.runner, test_mode=True)
    np.testing.assert_array_equal(b_ent.actions, b_obs.actions)
    np.testing.assert_allclose(s_ent.episode_return, s_obs.episode_return,
                               rtol=1e-5)


def test_eligibility_gating():
    # sequential normalizer → tables ineligible (per-observer prefix stats)
    cfg = sanity_check(TrainConfig(
        env_args=EnvConfig(agv_num=4, mec_num=2, episode_limit=5,
                           fast_norm=False),
        model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                          mixer_heads=2)))
    assert not Experiment.build(cfg).mac.use_entity_tables

    # flat obs mode → ineligible
    cfg2 = sanity_check(TrainConfig(
        env_args=EnvConfig(agv_num=4, mec_num=2, episode_limit=5,
                           obs_entity_mode=False, fast_norm=True),
        model=ModelConfig(emb=16, heads=2, depth=1, mixer_emb=16,
                          mixer_heads=2)))
    assert not Experiment.build(cfg2).mac.use_entity_tables

    # eligible default
    cfg3 = _cfg()
    mac3 = Experiment.build(cfg3).mac
    assert mac3.use_entity_tables and mac3.use_qslice


@pytest.mark.slow   # two full train-step compiles (~40 s)
def test_compact_store_train_matches_full_store():
    """Rollout → insert → PER sample → train with compact entity storage
    produces the same loss/priorities as full-obs storage (the stored
    representation is exact, so the whole training step must agree)."""
    import jax.numpy as jnp

    def build(compact):
        cfg = _cfg()
        cfg = cfg.replace(batch_size=4, replay=dataclasses.replace(
            cfg.replay, buffer_size=8, prioritized=True,
            compact_entity_store=compact))
        return Experiment.build(cfg)

    exp_c, exp_f = build(True), build(False)
    assert exp_c.buffer.compact_obs and not exp_f.buffer.compact_obs

    losses = {}
    for name, exp in (("compact", exp_c), ("full", exp_f)):
        ts = exp.init_train_state(0)
        rollout, insert, train_iter = exp.jitted_programs()
        rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                               test_mode=False)
        ts = ts.replace(runner=rs, buffer=insert(ts.buffer, batch),
                        episode=jnp.asarray(4, jnp.int32))
        _, info = train_iter(ts, jax.random.PRNGKey(5), jnp.asarray(100))
        losses[name] = (float(info["loss"]),
                        jax.device_get(info["td_errors_abs"]))
    np.testing.assert_allclose(losses["compact"][0], losses["full"][0],
                               rtol=1e-4)
    np.testing.assert_allclose(losses["compact"][1], losses["full"][1],
                               rtol=1e-3, atol=1e-4)


@pytest.mark.slow   # noisy unroll compiles (~20 s); sigma-grad flow also pinned in test_learner_runner
def test_noisy_entity_path_noise_and_sigma_gradients():
    """The 16-agent campaign's arm-B training branch: a noisy config with
    the default fast stack routes acting AND the compact-storage learner
    unroll through ``forward_entity`` with noise keys. Pin (a) the key
    actually reaches the q-head (q perturbs off the mu path; same key →
    same draw; hidden stream untouched) and (b) sigma params receive
    gradient through the full compact-storage loss."""
    cfg = _cfg()
    cfg = cfg.replace(
        action_selector="noisy-new", batch_size=4,
        replay=dataclasses.replace(cfg.replay, buffer_size=8,
                                   prioritized=True))
    cfg = sanity_check(cfg)
    exp = Experiment.build(cfg)
    env, mac = exp.env, exp.mac
    assert mac.use_entity_tables and mac.agent.noisy

    b = cfg.batch_size_run
    key = jax.random.PRNGKey(0)
    states, _obs = _rolled_states(env, b, 3, key)
    compact = jax.vmap(env.compact_obs)(states)
    params = mac.init_params(key, env.obs_dim)
    hidden = jnp.zeros((b, env.n_agents, cfg.model.emb))

    q_mu, h_mu = mac.forward_entity(params, compact, hidden)
    q_n, h_n = mac.forward_entity(params, compact, hidden,
                                  key=jax.random.PRNGKey(5),
                                  deterministic=False)
    q_n2, _ = mac.forward_entity(params, compact, hidden,
                                 key=jax.random.PRNGKey(5),
                                 deterministic=False)
    np.testing.assert_array_equal(np.asarray(h_n), np.asarray(h_mu))
    np.testing.assert_array_equal(np.asarray(q_n), np.asarray(q_n2))
    assert not np.allclose(np.asarray(q_n), np.asarray(q_mu))

    # (b) full loss through the CompactEntityObs unroll
    from t2omca_tpu.components.episode_buffer import CompactEntityObs
    ts = exp.init_train_state(0)
    rollout, insert, _ = exp.jitted_programs()
    rs, batch, _ = rollout(ts.learner.params["agent"], ts.runner,
                           test_mode=False)
    bstate = insert(ts.buffer, batch)
    sample, idx, w = exp.buffer.sample(bstate, jax.random.PRNGKey(2),
                                       cfg.batch_size, 0)
    assert isinstance(sample.obs, CompactEntityObs)
    grads, _ = jax.grad(exp.learner._loss, has_aux=True)(
        ts.learner.params, ts.learner.target_params, sample, w,
        jax.random.PRNGKey(7))
    qg = grads["agent"]["params"]["q_basic"]
    for name in ("w_sigma", "b_sigma"):
        assert np.abs(np.asarray(qg[name])).max() > 0, name


@pytest.mark.slow   # full run() + resume (~30 s)
def test_compact_store_driver_e2e(tmp_path):
    """Full run() through compact storage: trains, checkpoints (the buffer
    pytree now nests CompactEntityObs), resumes."""
    from t2omca_tpu.run import run as run_driver

    cfg = _cfg()
    cfg = cfg.replace(
        t_max=40, batch_size=2, test_interval=1000, log_interval=1000,
        save_model=True, save_model_interval=10,
        local_results_path=str(tmp_path),
        replay=dataclasses.replace(cfg.replay, buffer_size=8))
    from t2omca_tpu.ops.query_slice import entity_store_eligible
    assert entity_store_eligible(cfg)
    ts = run_driver(cfg)
    assert float(jax.tree.leaves(ts.learner.params)[0].sum()) == \
        float(jax.tree.leaves(ts.learner.params)[0].sum())  # finite/no nan

    import glob as g
    ckpts = g.glob(str(tmp_path) + "/models/*/*")
    assert ckpts, "driver saved no checkpoint under compact storage"
    cfg2 = cfg.replace(checkpoint_path=str(
        sorted(ckpts)[0].rsplit("/", 1)[0]))
    ts2 = run_driver(cfg2)   # resumes from the saved step and finishes
    assert int(ts2.runner.t_env) >= 40


def test_compact_obs_reconstructs_full_obs():
    """(rows, mask, stats) → the exact normalized obs the env returned."""
    cfg = _cfg()
    env = Experiment.build(cfg).env
    b = 3
    key = jax.random.PRNGKey(11)
    states, obs = _rolled_states(env, b, 5, key)
    rows, same_mec, mean, std = jax.vmap(env.compact_obs)(states)

    a, f = env.n_agents, env.obs_entity_feats
    rows9 = jnp.concatenate([rows, jnp.zeros((b, a, 1))], axis=-1)
    raw = jnp.where(same_mec[:, :, :, None], rows9[:, None, :, :], 0.0)
    raw = raw.at[:, jnp.arange(a), jnp.arange(a), f - 1].set(1.0)
    denom = std + 1e-8
    norm = (raw - mean[:, None]) / denom[:, None]
    np.testing.assert_allclose(norm.reshape(b, a, a * f), obs,
                               rtol=1e-5, atol=1e-6)


def test_default_config_resolves_to_full_fast_stack():
    """TrainConfig() defaults land on the documented production path
    (BASELINE.md / docs/ROUND3.md "default on"): entity-table acting +
    compact entity storage, with fast_norm gating satisfied (VERDICT r3
    Weak #3 — config, docs, and this pin must agree)."""
    from t2omca_tpu.config import TrainConfig, sanity_check
    from t2omca_tpu.ops.query_slice import (agent_qslice_eligible,
                                            entity_store_eligible,
                                            entity_tables_eligible)
    cfg = sanity_check(TrainConfig())
    assert cfg.env_args.fast_norm
    assert agent_qslice_eligible(cfg)
    assert entity_tables_eligible(cfg)
    assert entity_store_eligible(cfg)
    # and the built experiment actually wires those paths
    exp = Experiment.build(cfg.replace(
        env_args=dataclasses.replace(cfg.env_args, episode_limit=4),
        replay=dataclasses.replace(cfg.replay, buffer_size=8)))
    assert exp.mac.use_entity_tables
    assert exp.buffer.compact_obs


def test_compact_store_ineligible_past_int8_mec_range():
    """mec_index narrows to int8 in compact storage; ids are 0..mec_num-1,
    so mec_num=128 (max id 127) still fits and mec_num=129 would alias —
    the eligibility predicate must fall back to dense storage there."""
    from t2omca_tpu.ops.query_slice import entity_store_eligible
    base = sanity_check(TrainConfig())
    assert entity_store_eligible(base)
    at_edge = base.replace(env_args=dataclasses.replace(
        base.env_args, mec_num=128, agv_num=256))
    assert entity_store_eligible(at_edge)
    big = base.replace(env_args=dataclasses.replace(
        base.env_args, mec_num=129, agv_num=256))
    assert not entity_store_eligible(big)
