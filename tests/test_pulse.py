"""graftpulse live telemetry plane (``t2omca_tpu/obs/pulse.py``,
``memwatch.py``, ``timeline.py``; docs/OBSERVABILITY.md §pulse):
MetricsHub rendering/probes/health, the HTTP endpoint routes, the
on-demand trace trigger, HBM memwatch high-water attribution, the
torn-tail/degraded-input contracts of the post-mortem readers, the
timeline CLI over every historical BENCH shape, and — slow-marked —
the acceptance paths: a live CPU run scraped mid-flight (env-steps/s +
watchdog heartbeat-age gauges, /healthz flipping to degraded on a
chaos-injected hang) and ``bench.py --daemon`` surviving an injected
init-wedge on the backoff ladder."""

import glob
import json
import os
import socket
import stat
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from t2omca_tpu.config import ObsConfig, TrainConfig, sanity_check
from t2omca_tpu.obs.memwatch import (MemWatch, NULL_MEMWATCH,
                                     make_memwatch)
from t2omca_tpu.obs.pulse import (MetricsHub, PulseServer,
                                  TraceController, make_pulse)
from t2omca_tpu.obs.spans import KNOWN_PHASES, SpanRecorder
from t2omca_tpu.utils.ioutil import read_jsonl_tolerant

pytestmark = pytest.mark.pulse

REPO = os.path.join(os.path.dirname(__file__), "..")


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# MetricsHub
# ---------------------------------------------------------------------------

def test_hub_gauges_counters_and_quantiles():
    hub = MetricsHub(window=64)
    hub.set("env_steps_per_sec", 123.5)
    hub.set("hbm_bytes_in_use", 10, device="0")
    hub.inc("serve_requests_total")
    hub.inc("serve_rows_total", 5, bucket=8)
    for v in (1.0, 2.0, 3.0, 100.0):
        hub.observe("serve_select_ms", v)
    out = hub.render_prometheus()
    assert "t2omca_env_steps_per_sec 123.5" in out
    assert 't2omca_hbm_bytes_in_use{device="0"} 10' in out
    assert "# TYPE t2omca_serve_requests_total counter" in out
    assert 't2omca_serve_rows_total{bucket="8"} 5' in out
    assert "t2omca_serve_select_ms_p50 3" in out
    assert "t2omca_serve_select_ms_p99 100" in out
    assert "t2omca_serve_select_ms_count 4" in out
    assert "t2omca_beat_age_seconds" in out
    # window is bounded: old samples evict
    for v in range(200):
        hub.observe("serve_select_ms", 50.0)
    assert "serve_select_ms_p99 50" in hub.render_prometheus()


def test_hub_probes_and_health():
    hub = MetricsHub()
    hub.probe(lambda: [("watchdog_armed_seconds",
                        {"phase": "dispatch.train"}, 2.5)])
    hub.probe(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    out = hub.render_prometheus()   # a raising probe never kills scrape
    assert ('t2omca_watchdog_armed_seconds{phase="dispatch.train"} 2.5'
            in out)
    hub.health("good", lambda: (True, "fine"))
    ok, payload = hub.healthz()
    assert ok and payload["status"] == "ok"
    hub.health("bad", lambda: (False, "stalled"))
    ok, payload = hub.healthz()
    assert not ok and payload["status"] == "degraded"
    assert payload["checks"]["bad"] == {"ok": False, "detail": "stalled"}
    # a RAISING health check reads as degraded, never as green
    hub2 = MetricsHub()
    hub2.health("dead", lambda: (_ for _ in ()).throw(ValueError("x")))
    ok2, payload2 = hub2.healthz()
    assert not ok2 and "check failed" in payload2["checks"]["dead"]["detail"]


def test_hub_one_type_line_per_family():
    """Prometheus text format: a second ``# TYPE`` line for the same
    metric name fails the WHOLE scrape — a multi-label family (two
    devices, actor+learner sides, two buckets) must render one TYPE
    line followed by all its samples."""
    hub = MetricsHub()
    hub.set("hbm_bytes_in_use", 10, device="0")
    hub.set("hbm_bytes_in_use", 20, device="1")
    hub.inc("serve_dispatches_total", bucket=2)
    hub.inc("serve_dispatches_total", bucket=4)
    hub.probe(lambda: [("watchdog_armed", {"side": "actor"}, 1.0),
                       ("watchdog_armed", {"side": "learner"}, 0.0)])
    out = hub.render_prometheus()
    for fam in ("t2omca_hbm_bytes_in_use",
                "t2omca_serve_dispatches_total", "t2omca_watchdog_armed"):
        type_lines = [l for l in out.splitlines()
                      if l.startswith(f"# TYPE {fam} ")]
        samples = [l for l in out.splitlines()
                   if l.startswith(fam + "{")]
        assert len(type_lines) == 1, (fam, type_lines)
        assert len(samples) == 2, (fam, samples)
    # samples immediately follow their family's TYPE line
    lines = out.splitlines()
    i = lines.index("# TYPE t2omca_hbm_bytes_in_use gauge")
    assert lines[i + 1].startswith("t2omca_hbm_bytes_in_use{")
    assert lines[i + 2].startswith("t2omca_hbm_bytes_in_use{")


def test_pulse_server_binds_loopback_by_default():
    """/trace is unauthenticated and state-changing: the default bind
    must be loopback; off-host exposure is an explicit pulse_host."""
    srv = PulseServer(MetricsHub(), 0)
    try:
        assert srv._srv.server_address[0] == "127.0.0.1"
    finally:
        srv.close()
    assert ObsConfig().pulse_host == "127.0.0.1"


def test_hub_trace_request_consumed_once():
    hub = MetricsHub()
    assert not hub.take_trace_request()
    hub.request_trace()
    assert hub.take_trace_request()
    assert not hub.take_trace_request()


# ---------------------------------------------------------------------------
# PulseServer routes
# ---------------------------------------------------------------------------

def test_pulse_server_routes(tmp_path):
    rec = SpanRecorder(ring_size=32,
                       jsonl_path=str(tmp_path / "spans.jsonl"),
                       flush_every=1)
    hub = MetricsHub()
    hub.set("t_env", 42)
    hub.health("always", lambda: (True, "fine"))
    srv = PulseServer(hub, 0, rec=rec).start()   # 0 = ephemeral (tests)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _get(base + "/metrics")
        assert status == 200 and "t2omca_t_env 42" in body
        status, body = _get(base + "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"
        status, body = _get(base + "/trace")
        assert status == 200 and json.loads(body)["armed"] is True
        assert hub.take_trace_request()
        hub.health("bad", lambda: (False, "watchdog fired"))
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/healthz")
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["status"] == "degraded"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()
    # scrape spans stay OUT of the flight ring (a scrape cadence must
    # not evict the pre-stall phase history) but land in the JSONL
    # sink + phase aggregate; the rare trace-arm span IS ringed
    tail_phases = {e.get("phase") for e in rec.tail()}
    assert "pulse.scrape" not in tail_phases
    assert "trace.trigger" in tail_phases
    assert "pulse.scrape" in rec.summary()
    rec.close()
    events = [json.loads(l) for l in open(tmp_path / "spans.jsonl")]
    phases = {e.get("phase") for e in events}
    # scrapes and the endpoint trace-arm are spanned + registered
    assert "pulse.scrape" in phases and "trace.trigger" in phases
    assert not any("_ring" in e for e in events)    # internal flag only
    assert {"pulse.scrape", "trace.trigger"} <= KNOWN_PHASES


def test_pulse_server_trace_unsupported_says_so():
    """An endpoint with no TraceController behind it (the jax-free
    bench daemon) must refuse /trace instead of acking an arm nothing
    will ever consume."""
    hub = MetricsHub()
    srv = PulseServer(hub, 0, trace_supported=False).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/trace")
        assert ei.value.code == 501
        assert "no trace consumer" in ei.value.read().decode()
        assert not hub.take_trace_request()     # nothing latched
    finally:
        srv.close()


def test_memwatch_keeps_verdict_over_transient_device_failure():
    """A transient device-list failure after successful snapshots must
    not flip the report to 'unsupported' over its own populated rows."""
    devs = {"fn": lambda: [_FakeDev(0, 100)]}
    mw = MemWatch(_devices=lambda: devs["fn"]())
    mw.snapshot("startup")
    assert mw.supported is True

    def _boom():
        raise RuntimeError("backend teardown race")
    devs["fn"] = _boom
    assert mw.snapshot("shutdown") is None
    rep = mw.report()
    assert rep["supported"] is True and rep["devices"]


def test_make_pulse_off_state_and_bind_failure():
    assert make_pulse(ObsConfig()) is None          # default: no plane
    assert make_pulse(ObsConfig(pulse_port=0)) is None
    # a taken port degrades to None + warning, never a crash
    blocker = socket.socket()
    blocker.bind(("0.0.0.0", 0))
    port = blocker.getsockname()[1]

    class _Log:
        def __init__(self):
            self.warned = []

        def warning(self, msg):
            self.warned.append(msg)

        info = warning
    log = _Log()
    assert make_pulse(ObsConfig(pulse_port=port), log=log) is None
    assert any("could not bind" in w for w in log.warned)
    blocker.close()


def test_pulse_config_sanity():
    sanity_check(TrainConfig(obs=ObsConfig(pulse_port=8080)))
    with pytest.raises(ValueError):
        sanity_check(TrainConfig(obs=ObsConfig(pulse_port=70000)))
    with pytest.raises(ValueError):
        sanity_check(TrainConfig(obs=ObsConfig(pulse_port=-1)))
    with pytest.raises(ValueError):
        sanity_check(TrainConfig(obs=ObsConfig(pulse_window=4)))
    # memwatch without the master switch is a dead knob (program_trace
    # policy); with it, valid
    with pytest.raises(ValueError):
        sanity_check(TrainConfig(obs=ObsConfig(memwatch=True)))
    sanity_check(TrainConfig(obs=ObsConfig(enabled=True, memwatch=True)))


# ---------------------------------------------------------------------------
# TraceController (stubbed window — no profiler needed)
# ---------------------------------------------------------------------------

class _StubWindow:
    def __init__(self, trace_dir, out_dir=None, n_iterations=3):
        self.trace_dir = trace_dir
        self.n_iterations = n_iterations
        self._active = None
        self._done = False
        self.ticks = 0

    def maybe_start(self, t_env):
        self._active = self.n_iterations

    def tick(self, logger=None, t_env=0):
        if self._active is None:
            return
        self.ticks += 1
        self._active -= 1
        if self._active <= 0:
            self._active = None
            self._done = True


def test_trace_controller_file_trigger(tmp_path):
    rec = SpanRecorder(ring_size=32)
    made = []

    def factory(trace_dir, out_dir=None, n_iterations=3):
        w = _StubWindow(trace_dir, out_dir, n_iterations)
        made.append(w)
        return w

    trc = TraceController(str(tmp_path), rec=rec, n_iterations=2,
                          window_factory=factory)
    trc.poll(0)
    assert not made                         # no trigger, no window
    trigger = tmp_path / "PULSE_TRACE"
    trigger.touch()
    trc.poll(12)
    assert len(made) == 1                   # armed at the boundary
    assert not trigger.exists()             # trigger consumed
    assert "pulse_trace_01_t12" in made[0].trace_dir
    trc.poll(12)                            # active: no re-arm
    assert len(made) == 1
    trc.tick(None, 12)
    trc.tick(None, 24)                      # bounded: closes after 2
    assert made[0]._done
    # a NEW trigger after close arms a fresh window
    trigger.touch()
    trc.poll(36)
    assert len(made) == 2 and trc.captures == 2
    # the arming is spanned with the registered phase
    tail = rec.tail()
    assert any(e.get("phase") == "trace.trigger" and e.get("source") ==
               "file" for e in tail)


def test_trace_controller_endpoint_trigger(tmp_path):
    hub = MetricsHub()
    made = []
    trc = TraceController(
        str(tmp_path), hub=hub, n_iterations=1,
        window_factory=lambda d, out_dir=None, n_iterations=3:
            made.append(_StubWindow(d, out_dir, n_iterations)) or made[-1])
    hub.request_trace()
    trc.poll(48)
    assert len(made) == 1
    assert not hub.take_trace_request()     # consumed by the controller


# ---------------------------------------------------------------------------
# memwatch
# ---------------------------------------------------------------------------

class _FakeDev:
    def __init__(self, i, bytes_in_use, peak=None, broken=False):
        self.id = i
        self._b = bytes_in_use
        self._p = peak if peak is not None else bytes_in_use
        self._broken = broken

    def memory_stats(self):
        if self._broken:
            raise RuntimeError("allocator says no")
        return {"bytes_in_use": self._b, "peak_bytes_in_use": self._p}


def test_memwatch_high_water_phase_attribution():
    rec = SpanRecorder(ring_size=32)
    devs = [[_FakeDev(0, 100, peak=120), _FakeDev(1, 50)]]
    mw = MemWatch(rec=rec, budgets={"superstep": 247866.0},
                  _devices=lambda: devs[0])
    snap = mw.snapshot("startup", t_env=0)
    assert snap["0"]["bytes_in_use"] == 100
    devs[0] = [_FakeDev(0, 900, peak=950), _FakeDev(1, 40)]
    mw.snapshot("dispatch.train", t_env=48)
    rep = mw.report()
    assert rep["supported"] is True and rep["snapshots"] == 2
    d0 = rep["devices"]["0"]
    assert d0["high_water_bytes"] == 950
    assert d0["high_water_phase"] == "dispatch.train"
    assert d0["high_water_t_env"] == 48
    # device 1 peaked at startup — attribution is per-device
    assert rep["devices"]["1"]["high_water_phase"] == "startup"
    assert rep["budgets_audit_peak_bytes"]["superstep"] == 247866.0
    # snapshots are spanned with the registered phase
    assert any(e.get("phase") == "memwatch.snapshot"
               for e in rec.tail())
    assert "memwatch.snapshot" in KNOWN_PHASES


def test_memwatch_degrades_without_allocator_stats():
    # the CPU-client shape: memory_stats raises (or returns None) on
    # every device — report states unsupported, nothing crashes
    mw = MemWatch(_devices=lambda: [_FakeDev(0, 0, broken=True)])
    assert mw.snapshot("startup") is None
    rep = mw.report()
    assert rep["supported"] is False and rep["devices"] == {}
    # a device-list failure degrades the same way
    def _boom():
        raise RuntimeError("no backend")
    mw2 = MemWatch(_devices=_boom)
    assert mw2.snapshot("startup") is None
    assert mw2.supported is False


def test_make_memwatch_gating():
    assert make_memwatch(ObsConfig()) is NULL_MEMWATCH
    assert make_memwatch(ObsConfig(enabled=True)) is NULL_MEMWATCH
    assert make_memwatch(ObsConfig(memwatch=True)) is NULL_MEMWATCH
    mw = make_memwatch(ObsConfig(enabled=True, memwatch=True))
    assert mw.enabled and isinstance(mw, MemWatch)
    # the GP303 budgets rode along from programs.json (jax-free read)
    assert mw._budgets.get("superstep")
    assert NULL_MEMWATCH.snapshot("x") is None
    assert NULL_MEMWATCH.report() == {}


def test_watchdog_heartbeat_snapshot():
    from t2omca_tpu.utils.watchdog import Watchdog
    wd = Watchdog(timeout_s=60.0)
    hb = wd.heartbeat()
    assert hb["armed_phase"] is None and hb["stall_count"] == 0
    wd.stamp("dispatch.train", t_env=48)
    time.sleep(0.02)
    hb = wd.heartbeat()
    assert hb["armed_phase"] == "dispatch.train"
    assert hb["armed_s"] >= 0.02
    assert hb["beat_age_s"] >= 0.02
    wd.clear()
    hb = wd.heartbeat()
    assert hb["armed_phase"] is None and hb["beat_age_s"] < 0.02


# ---------------------------------------------------------------------------
# torn-tail tolerance + report degraded inputs (satellites)
# ---------------------------------------------------------------------------

def test_read_jsonl_tolerant_torn_tail(tmp_path):
    p = tmp_path / "spans.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"event": "mark", "kind": "run"}) + "\n")
        f.write(json.dumps({"event": "span", "phase": "x"}) + "\n")
        f.write('{"event": "span", "phase": "dispatch.trai')  # torn tail
    bad = []
    out = read_jsonl_tolerant(str(p),
                              on_bad=lambda ln, last: bad.append((ln,
                                                                  last)))
    assert len(out) == 2
    assert bad == [(3, True)]               # final line, flagged as such
    # mid-file corruption is flagged distinctly
    with open(p, "w") as f:
        f.write("{broken\n")
        f.write(json.dumps({"ok": 1}) + "\n")
    bad.clear()
    assert read_jsonl_tolerant(str(p), on_bad=lambda ln, last:
                               bad.append((ln, last))) == [{"ok": 1}]
    assert bad == [(1, False)]


def _seed_spans(run_dir, torn=False):
    run_dir.mkdir(parents=True, exist_ok=True)
    events = [
        {"event": "mark", "kind": "run", "seq": 1, "t0": 0.0,
         "backend": "cpu", "batch_size_run": 2, "episode_limit": 6,
         "batch_size": 4, "superstep": 1},
        {"event": "span", "seq": 2, "t0": 0.0, "phase":
         "dispatch.rollout", "t_env": 0, "depth": 0, "wall_ms": 5000.0,
         "outcome": "ok", "first": True},
        {"event": "span", "seq": 3, "t0": 0.0, "phase":
         "dispatch.rollout", "t_env": 12, "depth": 0, "wall_ms": 80.0,
         "outcome": "ok"},
    ]
    with open(run_dir / "spans.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        if torn:
            f.write('{"event": "span", "phase": "dispatch.tr')
    return events


def test_report_skips_torn_tail_with_warning(tmp_path, capsys):
    """Satellite: the exact artifact a killed run leaves — a truncated
    final spans.jsonl line — must be skipped with a warning, and the
    report must still render the intact prefix."""
    from t2omca_tpu.obs.__main__ import main
    run_dir = tmp_path / "run"
    _seed_spans(run_dir, torn=True)
    rc = main(["report", str(run_dir)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "dispatch.rollout" in cap.out    # intact prefix rendered
    assert "torn final line" in cap.err     # warned, not raised


def test_report_flight_recorder_only_run_dir(tmp_path, capsys):
    """Degraded input: a run dir with ONLY a flight_recorder.json (the
    crash artifact) still reports — from the bounded tail, stated."""
    from t2omca_tpu.obs.__main__ import main
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    events = _seed_spans(tmp_path / "donor")     # same event schema
    with open(run_dir / "flight_recorder.json", "w") as f:
        json.dump({"version": 1, "events": events,
                   "memwatch": {"supported": False}}, f)
    rc = main(["report", str(run_dir)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "dispatch.rollout" in cap.out
    assert "flight-recorder tail" in cap.err
    # an empty dir (neither artifact) is still the usage error
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["report", str(empty)]) == 2


def test_report_empty_metrics_and_missing_device_times(tmp_path,
                                                       capsys):
    """Degraded inputs: device_times.json absent (fine, wall source)
    and an EMPTY metrics.jsonl — the per-slice table must state 'no
    data', not crash (PR 11's table reads this file)."""
    from t2omca_tpu.obs.__main__ import main
    run_dir = tmp_path / "run"
    _seed_spans(run_dir)
    (run_dir / "metrics.jsonl").write_text("")
    rc = main(["report", str(run_dir)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "scenario slices: no data" in out
    # a metrics.jsonl with ONLY a torn line: tolerated the same way
    (run_dir / "metrics.jsonl").write_text('{"key": "slice0_retu')
    assert main(["report", str(run_dir)]) == 0


# ---------------------------------------------------------------------------
# timeline CLI (satellite: BENCH schema heterogeneity)
# ---------------------------------------------------------------------------

def test_timeline_over_checked_in_bench_records(capsys):
    """Acceptance: the full BENCH_r01–r07 trajectory renders, with
    measured numbers distinguished from wedged partials."""
    from t2omca_tpu.obs.__main__ import main
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r0*.json")))
    assert len(paths) >= 7
    rc = main(["timeline", *paths])
    out = capsys.readouterr().out
    assert rc == 0
    assert "BENCH_r01" in out and "BENCH_r07" in out
    assert "4,838.2" in out                 # r01's real number
    assert "measured" in out and "wedged" in out
    # r03–r07 all render as wedged rows
    for line in out.splitlines():
        for r in ("BENCH_r03", "BENCH_r04", "BENCH_r05", "BENCH_r06",
                  "BENCH_r07"):
            if line.startswith(r):
                assert "wedged" in line, line


def test_timeline_row_classification(tmp_path, capsys):
    from t2omca_tpu.obs.__main__ import main
    # bare (r01-style inner record, no wrapper)
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(
        {"metric": "env_steps_per_sec", "value": 9000.5,
         "unit": "env-steps/s/chip", "vs_baseline": 0.18,
         "schema": 1, "platform": "tpu", "superstep": 4}))
    # wrapper with parsed=null but a parseable tail line
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(
        {"n": 9, "rc": 0, "parsed": None,
         "tail": 'noise\n{"metric": "env_steps_per_sec", "value": 8.0, '
                 '"unit": "u", "vs_baseline": null}\n'}))
    # wrapper with nothing parseable
    (tmp_path / "BENCH_r10.json").write_text(json.dumps(
        {"n": 10, "rc": 1, "tail": "Traceback (most recent call last)"}))
    # unreadable file
    (tmp_path / "BENCH_r11.json").write_text("{not json")
    rc = main(["timeline", *sorted(str(p) for p in
                                   tmp_path.glob("BENCH_r*.json")),
               "--json"])
    assert rc == 0
    rows = {r["name"]: r for r in
            json.loads(capsys.readouterr().out)["rows"]}
    assert rows["BENCH_r08"]["status"] == "measured"
    assert rows["BENCH_r08"]["platform"] == "tpu"
    assert "superstep=4" in rows["BENCH_r08"]["note"]
    assert rows["BENCH_r09"]["status"] == "measured"    # tail rescue
    assert rows["BENCH_r09"]["value"] == 8.0
    assert rows["BENCH_r10"]["status"] == "no-record"
    assert rows["BENCH_r11"]["status"] == "unreadable"


def test_timeline_parses_kernels_train_leg_record(tmp_path, capsys):
    """PR 13 satellite: the new ``--kernels`` TRAIN-step record
    (``train_iters_per_sec``, one per kernel mode, emitted via
    ``_finalize``) renders as a measured timeline row with the kernel
    mode in the note — and a torn copy of the same record (the tail a
    killed daemon leg leaves) degrades to a no-record row instead of
    raising, keeping the t1 timeline prelude green."""
    from t2omca_tpu.obs.__main__ import main
    rec = {"metric": "train_iters_per_sec", "value": 26.42,
           "unit": "train-iters/s/chip", "vs_baseline": None,
           "kernels": "pallas", "leg": "kernels-pallas-train",
           "train_batch_episodes": 32, "config": 3,
           "schema": 1, "platform": "tpu", "host": "h"}
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(rec))
    # wrapper-with-tail shape (the daemon relay), torn mid-record
    torn = json.dumps(rec)[: len(json.dumps(rec)) // 2]
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(
        {"n": 9, "rc": 1, "parsed": None, "tail": "noise\n" + torn}))
    rc = main(["timeline", *sorted(str(p) for p in
                                   tmp_path.glob("BENCH_r*.json")),
               "--json"])
    assert rc == 0
    rows = {r["name"]: r for r in
            json.loads(capsys.readouterr().out)["rows"]}
    row = rows["BENCH_r08"]
    assert row["status"] == "measured"
    assert row["metric"] == "train_iters_per_sec"
    assert row["value"] == 26.42
    assert "kernels=pallas" in row["note"]
    assert "leg=kernels-pallas-train" in row["note"]
    assert rows["BENCH_r09"]["status"] == "no-record"   # torn, not raised


def test_timeline_run_rows_and_torn_metrics(tmp_path, capsys,
                                            monkeypatch):
    from t2omca_tpu.obs.__main__ import main
    run_dir = tmp_path / "run1"
    run_dir.mkdir()
    with open(run_dir / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"key": "env_steps_per_sec", "value": 100.0,
                            "t": 12}) + "\n")
        f.write(json.dumps({"key": "env_steps_per_sec", "value": 250.0,
                            "t": 24}) + "\n")
        f.write("null\n")       # corrupt line parsing to a bare scalar
        f.write('{"key": "env_steps_per_s')        # torn tail
    rc = main(["timeline", "--runs", str(run_dir), "--json"])
    cap = capsys.readouterr()
    assert rc == 0
    rows = json.loads(cap.out)["rows"]
    assert rows[0]["status"] == "run" and rows[0]["value"] == 250.0
    assert "torn tail" in cap.err               # warned, not raised
    # a run dir without metrics.jsonl is a stated row, not a crash
    empty = tmp_path / "run2"
    empty.mkdir()
    assert main(["timeline", "--runs", str(empty)]) == 0
    # nothing at all is the usage error
    monkeypatch.chdir(tmp_path / "run2")
    assert main(["timeline"]) == 2


@pytest.mark.slow   # subprocess import check (~2 s interpreter startup)
def test_timeline_cli_is_jax_free():
    """The trajectory question gets asked from hosts that cannot
    initialize a backend — the timeline CLI must not import jax."""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import t2omca_tpu.obs.timeline, t2omca_tpu.obs.__main__, sys; "
         "assert 'jax' not in sys.modules, 'timeline imports jax'"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]


# ---------------------------------------------------------------------------
# serve front-end hub wiring (host logic only — stubbed programs)
# ---------------------------------------------------------------------------

def test_serve_frontend_hub_metrics():
    import numpy as np
    from t2omca_tpu.obs.spans import NULL_RECORDER
    from t2omca_tpu.serve.frontend import ServeFrontend, SessionStore

    hub = MetricsHub()
    meta = {"buckets": [2, 4], "n_agents": 3, "obs_dim": 5,
            "n_actions": 4, "emb": 8}
    fe = ServeFrontend("/nonexistent", meta, mac=None, params=None,
                       dtype="float32", use_exported=False,
                       rec=NULL_RECORDER, hub=hub)

    def fake_program(params, obs, avail, hidden):
        n = obs.shape[0]
        return (np.zeros((n, 3), np.int32),
                np.zeros((n, 3, 8), np.float32))

    fe._steps = {2: fake_program, 4: fake_program}
    obs = np.zeros((3, 3, 5), np.float32)
    avail = np.ones((3, 3, 4), bool)
    fe.select(obs, avail)                   # one chunk, bucket 4
    out = hub.render_prometheus()
    assert 't2omca_serve_dispatches_total{bucket="4"} 1' in out
    assert 't2omca_serve_rows_total{bucket="4"} 3' in out
    assert "t2omca_serve_requests_total 1" in out
    assert "t2omca_serve_select_ms_p50" in out
    # SessionStore LRU fill gauge
    store = SessionStore(fe, max_sessions=4)
    store.select(["a", "b"], obs[:2], avail[:2])
    out = hub.render_prometheus()
    assert "t2omca_serve_sessions 2" in out
    assert "t2omca_serve_session_lru_fill 0.5" in out


# ---------------------------------------------------------------------------
# bench schema meta (satellite) — unit level, no subprocess
# ---------------------------------------------------------------------------

def _load_bench_module():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_finalize_uniform_schema_meta():
    bench = _load_bench_module()
    rec = bench._finalize({"metric": "env_steps_per_sec", "value": 1.0,
                           "unit": "u", "vs_baseline": None})
    assert rec["schema"] == bench.BENCH_SCHEMA == 1
    assert rec["host"] == socket.gethostname()
    assert "platform" in rec and "spans" in rec
    # an existing platform (fallback tag / live backend) is never
    # clobbered by the env-pin default
    rec2 = bench._finalize({"metric": "m", "platform": "tpu"})
    assert rec2["platform"] == "tpu"


def test_daemon_legs_matrix():
    bench = _load_bench_module()

    class A:
        smoke = True
        iters = 1
        artifact = None
        legs = None
    legs = dict(bench._daemon_legs(A()))
    assert set(legs) == {"superstep", "kernels", "sebulba", "population",
                         "lattice"}
    assert "--smoke" in legs["superstep"]
    assert legs["kernels"][:2] == ["--kernels", "ab"]
    assert legs["population"][:2] == ["--population", "4"]
    assert legs["lattice"][0] == "--lattice"
    A.artifact = "/art"
    assert "serve" in dict(bench._daemon_legs(A()))
    A.legs = "superstep,sebulba"
    assert set(dict(bench._daemon_legs(A()))) == {"superstep", "sebulba"}
    A.legs = "bogus"
    with pytest.raises(SystemExit):
        bench._daemon_legs(A())
    A.legs, A.artifact = "serve", None
    with pytest.raises(SystemExit):
        bench._daemon_legs(A())


# ---------------------------------------------------------------------------
# driver integration (slow: full run() legs on tiny CPU configs)
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tiny_cfg(tmp_path, port, **kw):
    from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                                   ResilienceConfig)
    res_kw = kw.pop("res_kw", {})
    obs_kw = kw.pop("obs_kw", {})
    defaults = dict(
        t_max=120, batch_size_run=2, batch_size=4,
        test_interval=1_000_000, test_nepisode=2, log_interval=12,
        runner_log_interval=12, save_model=False,
        local_results_path=str(tmp_path), use_tensorboard=False,
        epsilon_anneal_time=50,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=6),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=8),
        resilience=ResilienceConfig(stall_grace_s=0.0, **res_kw),
        obs=ObsConfig(enabled=True, flush_every=1, pulse_port=port,
                      memwatch=True, **obs_kw),
    )
    defaults.update(kw)
    return sanity_check(TrainConfig(**defaults))


class _Poller(threading.Thread):
    """Scrapes /metrics + /healthz concurrently with a live run and
    keeps what it saw — the run's exit tears the server down, so the
    assertions read the poller's captures."""

    def __init__(self, port):
        super().__init__(daemon=True)
        self.base = f"http://127.0.0.1:{port}"
        self.metrics = []
        self.health = []
        self.stop = threading.Event()

    def run(self):
        while not self.stop.is_set():
            try:
                self.metrics.append(_get(self.base + "/metrics",
                                         timeout=1)[1])
            except Exception:
                pass
            try:
                self.health.append(_get(self.base + "/healthz",
                                        timeout=1))
            except urllib.error.HTTPError as e:
                self.health.append((e.code, e.read().decode()))
            except Exception:
                pass
            time.sleep(0.05)


@pytest.mark.slow
def test_pulse_live_scrape_during_run(tmp_path):
    """Acceptance: during a CPU smoke run with obs.pulse_port set,
    /metrics returns env-steps/s + watchdog heartbeat-age gauges and
    /healthz reports ok."""
    from t2omca_tpu.run import run
    from t2omca_tpu.utils.logging import Logger

    port = _free_port()
    cfg = _tiny_cfg(tmp_path, port,
                    res_kw=dict(dispatch_timeout=30.0))
    poller = _Poller(port)
    poller.start()
    try:
        run(cfg, Logger())
    finally:
        poller.stop.set()
        poller.join(timeout=5)
    assert poller.metrics, "endpoint never answered during the run"
    joined = "\n".join(poller.metrics)
    assert "t2omca_env_steps_per_sec" in joined
    assert "t2omca_watchdog_heartbeat_age_seconds" in joined
    assert "t2omca_t_env" in joined
    assert any(code == 200 and json.loads(body)["status"] == "ok"
               for code, body in poller.health)
    # the scrape spans landed in the run's own span stream
    run_dir = [d for d in glob.glob(os.path.join(str(tmp_path), "*"))
               if os.path.isdir(d)
               and os.path.basename(d) != "models"][0]
    events = [json.loads(l)
              for l in open(os.path.join(run_dir, "spans.jsonl"))
              if l.strip()]
    phases = {e.get("phase") for e in events if e["event"] == "span"}
    assert "pulse.scrape" in phases
    assert "memwatch.snapshot" in phases
    assert phases <= KNOWN_PHASES, phases - KNOWN_PHASES


@pytest.mark.slow
@pytest.mark.faultinject
def test_healthz_degrades_on_injected_hang(tmp_path):
    """Acceptance: a chaos-injected hang trips the watchdog and the
    LIVE /healthz flips to degraded while the run is still wedged."""
    from t2omca_tpu.run import run
    from t2omca_tpu.utils import resilience
    from t2omca_tpu.utils.logging import Logger

    resilience.clear_faults()
    port = _free_port()
    cfg = _tiny_cfg(tmp_path, port,
                    res_kw=dict(dispatch_timeout=0.75))
    hung = []

    def _hang(t_env, **kw):
        if t_env >= 24 and not hung:
            hung.append(t_env)
            time.sleep(2.5)

    resilience.register_fault("dispatch.rollout", _hang)
    poller = _Poller(port)
    poller.start()
    try:
        run(cfg, Logger())
    finally:
        poller.stop.set()
        poller.join(timeout=5)
        resilience.clear_faults()
    assert hung == [24]
    degraded = [(c, b) for c, b in poller.health if c == 503]
    assert degraded, "healthz never flipped to degraded during the hang"
    payload = json.loads(degraded[-1][1])
    assert payload["status"] == "degraded"
    # the watchdog check is the one that flipped it
    assert any(not chk["ok"] and "stalls=" in chk["detail"]
               for name, chk in payload["checks"].items()
               if name.startswith("watchdog"))


@pytest.mark.slow
def test_trace_trigger_on_live_run(tmp_path):
    """On-demand capture: touching <run_dir>/PULSE_TRACE mid-run arms a
    bounded ProgramTraceWindow without a restart; the capture directory
    and refreshed device_times.json land in the run dir."""
    from t2omca_tpu.run import run
    from t2omca_tpu.utils import resilience
    from t2omca_tpu.utils.logging import Logger

    resilience.clear_faults()
    armed = []

    def _touch(t_env, **kw):
        if t_env >= 24 and not armed:
            dirs = [d for d in glob.glob(os.path.join(str(tmp_path),
                                                      "*"))
                    if os.path.isdir(d)
                    and os.path.basename(d) != "models"]
            if dirs:
                open(os.path.join(dirs[0], "PULSE_TRACE"), "w").close()
                armed.append(t_env)

    resilience.register_fault("driver.iteration", _touch)
    cfg = _tiny_cfg(tmp_path, 0)        # plane off: file trigger alone
    try:
        run(cfg, Logger())
    finally:
        resilience.clear_faults()
    assert armed, "trigger never planted"
    run_dir = [d for d in glob.glob(os.path.join(str(tmp_path), "*"))
               if os.path.isdir(d)
               and os.path.basename(d) != "models"][0]
    captures = glob.glob(os.path.join(run_dir, "pulse_trace_*"))
    assert captures, "no pulse trace capture directory"
    assert not os.path.exists(os.path.join(run_dir, "PULSE_TRACE"))
    events = [json.loads(l)
              for l in open(os.path.join(run_dir, "spans.jsonl"))
              if l.strip()]
    assert any(e.get("phase") == "trace.trigger" for e in events)


# ---------------------------------------------------------------------------
# bench daemon (slow: subprocess legs)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_daemon_single_session_record_per_leg(tmp_path):
    """Acceptance: ``bench.py --daemon`` on CPU emits one complete
    record per matrix leg in a single session, schema'd + leg-tagged,
    plus the daemon summary."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               T2OMCA_BACKEND_PROBE_TIMEOUT="120")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--daemon", "--smoke",
         "--legs", "superstep,sebulba", "--iters", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(l) for l in proc.stdout.splitlines()
               if l.strip()]
    by_leg = {}
    for r in records[:-1]:
        by_leg.setdefault(r["leg"], []).append(r)
    assert set(by_leg) == {"superstep", "sebulba"}
    for leg, recs in by_leg.items():
        assert any(isinstance(r["value"], (int, float)) for r in recs)
        for r in recs:
            assert r["schema"] == 1
            assert r["platform"] == "cpu"
            assert r["host"]
    summary = records[-1]
    assert summary["metric"] == "bench_daemon_legs"
    assert summary["value"] == 2
    assert summary["legs"]["superstep"]["measured"] is True
    assert "bench.daemon.probe" in summary["spans"]
    assert "bench.daemon.leg" in summary["spans"]


@pytest.mark.slow
@pytest.mark.faultinject
def test_bench_daemon_retries_injected_init_wedge(tmp_path):
    """Acceptance: an injected init-wedge (probe command failing twice)
    is retried on the backoff ladder; the daemon then runs the matrix
    and the summary records the attempt count."""
    counter = tmp_path / "count"
    script = tmp_path / "wedge.sh"
    script.write_text(
        "#!/bin/sh\n"
        f"n=$(cat {counter} 2>/dev/null || echo 0)\n"
        f"echo $((n+1)) > {counter}\n"
        "[ $n -ge 2 ] && exit 0 || exit 1\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               T2OMCA_BENCH_DAEMON_PROBE_CMD=str(script),
               T2OMCA_BENCH_DAEMON_BACKOFF="0.05",
               T2OMCA_BACKEND_PROBE_TIMEOUT="30")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--daemon", "--smoke",
         "--legs", "superstep", "--iters", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(l) for l in proc.stdout.splitlines()
               if l.strip()]
    summary = records[-1]
    assert summary["probe_attempts"] == 3       # 2 wedged + 1 success
    assert summary["value"] == 1
    assert "backoff ladder retries" in proc.stderr


@pytest.mark.slow
@pytest.mark.faultinject
def test_bench_daemon_budget_exhaustion_partial_record(tmp_path):
    """A tunnel that never opens: the daemon's budget runs out and ONE
    parseable partial record lands on stdout (the r03+ contract)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               T2OMCA_BENCH_DAEMON_PROBE_CMD="false",
               T2OMCA_BENCH_DAEMON_BUDGET="2",
               T2OMCA_BENCH_DAEMON_BACKOFF="0.2",
               T2OMCA_BACKEND_PROBE_TIMEOUT="1")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--daemon", "--smoke",
         "--legs", "superstep"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    records = [json.loads(l) for l in proc.stdout.splitlines()
               if l.strip()]
    assert len(records) == 1
    assert records[0]["value"] is None
    assert records[0]["schema"] == 1
    assert records[0]["probe_attempts"] >= 1
