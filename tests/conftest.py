"""Test configuration: force the JAX CPU backend with 8 virtual devices.

SURVEY.md §4: multi-chip paths are tested without a cluster, on a faked
8-device CPU mesh. Environment traps: the axon sitecustomize registers a TPU
backend at interpreter start, and ``import pytest`` itself imports jax
(plugin entry points), so env-var mutation here is too late. The jax config
API works post-import because backends initialize lazily:
``jax_platforms='cpu'`` overrides the axon selection and
``jax_num_cpu_devices=8`` replaces ``xla_force_host_platform_device_count``.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_default_matmul_precision", "highest")
