"""Test configuration: force the JAX CPU backend with 8 virtual devices.

SURVEY.md §4: multi-chip paths are tested without a cluster via
``xla_force_host_platform_device_count``. The axon sitecustomize registers a
TPU backend whenever ``PALLAS_AXON_POOL_IPS`` is set, so it is cleared before
anything imports jax.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
