"""Test configuration: force the JAX CPU backend with 8 virtual devices.

SURVEY.md §4: multi-chip paths are tested without a cluster, on a faked
8-device CPU mesh. Environment traps: the axon sitecustomize registers a TPU
backend at interpreter start, and ``import pytest`` itself imports jax
(plugin entry points), so env-var mutation here is *almost* too late. The
jax config API works post-import because backends initialize lazily:
``jax_platforms='cpu'`` overrides the axon selection. The device-count knob
is version-dependent: ``jax_num_cpu_devices`` only exists on newer JAX; on
older builds (0.4.x) the only lever is ``XLA_FLAGS=
--xla_force_host_platform_device_count=8`` — which is read at CPU-backend
init, and backends are lazy, so mutating ``os.environ`` here (before any
device query has run) still takes effect.
"""

import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older JAX: the flag must land before the (lazy) CPU backend
    # initializes; appending preserves any operator-set XLA flags
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
jax.config.update("jax_default_matmul_precision", "highest")
