"""Metric-contract tests (SURVEY.md §5.5): terminal-info aggregation
semantics of ``/root/reference/parallel_runner.py:168-170,202-231``."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from t2omca_tpu.config import (EnvConfig, ModelConfig, ReplayConfig,
                               TrainConfig, sanity_check)
from t2omca_tpu.controllers import BasicMAC
from t2omca_tpu.envs.registry import make_env
from t2omca_tpu.learners import QMixLearner
from t2omca_tpu.runners import ParallelRunner
from t2omca_tpu.utils.stats import TERMINAL_INFO_KEYS, StatsAccumulator


class RecordingLogger:
    def __init__(self):
        self.logged = []

    def log_stat(self, key, value, t):
        self.logged.append((key, value, t))

    def last(self, key):
        vals = [v for k, v, _ in self.logged if k == key]
        return vals[-1] if vals else None


@dataclasses.dataclass
class FakeStats:
    episode_return: np.ndarray
    epsilon: np.ndarray
    reward: np.ndarray = None
    delay_reward: np.ndarray = None
    overtime_penalty: np.ndarray = None
    channel_utilization_rate: np.ndarray = None
    conflict_ratio: np.ndarray = None
    episode_limit: np.ndarray = None
    task_completion_rate: np.ndarray = None
    task_completion_delay: np.ndarray = None
    deadline_miss_rate: np.ndarray = None
    scenario: np.ndarray = None         # graftworld family tags (optional)

    def __post_init__(self):
        for k in TERMINAL_INFO_KEYS:
            if getattr(self, k) is None:
                setattr(self, k, np.zeros_like(self.episode_return))


def test_accumulator_sums_terminal_infos_across_rollouts():
    """<k>_mean = Σ(terminal infos over envs AND rollouts) / n_episodes."""
    acc = StatsAccumulator()
    s1 = FakeStats(episode_return=np.array([1.0, 3.0]),
                   epsilon=np.array(0.5),
                   reward=np.array([2.0, 4.0]),
                   task_completion_rate=np.array([0.5, 0.7]))
    s2 = FakeStats(episode_return=np.array([5.0, 7.0]),
                   epsilon=np.array(0.4),
                   reward=np.array([6.0, 8.0]),
                   task_completion_rate=np.array([0.9, 0.9]))
    acc.push(s1)
    acc.push(s2)
    assert acc.n_episodes == 4
    log = RecordingLogger()
    acc.flush(log, t_env=100)
    assert log.last("return_mean") == np.mean([1, 3, 5, 7])
    assert log.last("reward_mean") == (2 + 4 + 6 + 8) / 4
    assert log.last("task_completion_rate_mean") == (0.5 + 0.7 + 0.9 + 0.9) / 4
    # flush clears: a second flush logs nothing new for return_mean
    n_before = len(log.logged)
    acc.flush(log, t_env=200)
    assert all(k != "return_mean" for k, _, t in log.logged[n_before:])
    assert acc.n_episodes == 0


def test_accumulator_folds_pending_at_cap():
    """_pending must stay bounded when runner_log_interval spans many
    rollouts (ADVICE r4): past FOLD_EVERY pushes the refs are folded to
    host sums, with flush semantics unchanged across fold boundaries."""
    acc = StatsAccumulator()
    n = StatsAccumulator.FOLD_EVERY + 5
    for i in range(n):
        acc.push(FakeStats(episode_return=np.array([float(i)]),
                           epsilon=np.array(i / n),
                           reward=np.array([2.0 * i])))
        assert len(acc._pending) < StatsAccumulator.FOLD_EVERY
    assert acc.n_episodes == n
    log = RecordingLogger()
    acc.flush(log, t_env=100)
    assert log.last("return_mean") == np.mean(np.arange(n, dtype=float))
    assert log.last("reward_mean") == 2.0 * np.mean(np.arange(n))
    assert acc.epsilon == (n - 1) / n
    assert acc.n_episodes == 0 and not acc._pending


def test_accumulator_epsilon_tracks_last_push():
    acc = StatsAccumulator()
    acc.push(FakeStats(episode_return=np.array([0.0]),
                       epsilon=np.array(0.25)))
    assert acc.epsilon == 0.25


def test_rollout_stats_carry_terminal_step_values():
    """RolloutStats info fields must be the TERMINAL step's info values,
    not per-step sums (reference ``final_env_infos`` semantics)."""
    cfg = sanity_check(TrainConfig(
        batch_size_run=3,
        env_args=EnvConfig(agv_num=3, mec_num=2, num_channels=2,
                           episode_limit=5),
        model=ModelConfig(emb=8, heads=2, depth=1, mixer_emb=8,
                          mixer_heads=2, mixer_depth=1),
        replay=ReplayConfig(buffer_size=4),
    ))
    env = make_env(cfg.env_args)
    info = env.get_env_info()
    mac = BasicMAC.build(cfg, info)
    learner = QMixLearner.build(cfg, mac, info)
    runner = ParallelRunner(env, mac, cfg)
    ls = learner.init_state(jax.random.PRNGKey(0))
    rs = runner.init_state(jax.random.PRNGKey(1))
    rs, batch, stats = jax.jit(runner.run, static_argnames="test_mode")(
        ls.params["agent"], rs, test_mode=False)

    reward = np.asarray(batch.reward)                     # (B, T)
    np.testing.assert_allclose(np.asarray(stats.episode_return),
                               reward.sum(axis=1), rtol=1e-6)
    # terminal-step semantics: stats.reward is the LAST slot's reward
    np.testing.assert_allclose(np.asarray(stats.reward), reward[:, -1],
                               rtol=1e-6)
    # the env terminates only via the time limit -> episode_limit info = 1
    np.testing.assert_allclose(np.asarray(stats.episode_limit), 1.0)
    assert stats.task_completion_rate.shape == (3,)
    assert float(np.asarray(stats.task_completion_rate).min()) >= 0.0
