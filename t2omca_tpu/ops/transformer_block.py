"""Fused transformer block as a Pallas TPU kernel.

Why: profiling (BASELINE.md, round-1 measurements) shows the acting-path
transformer is **HBM-bandwidth bound**, not MXU bound — the XLA path
materializes QKV, per-head transposes, attention logits, the 4×emb FFN
hidden, and every residual/LN intermediate to HBM, ~40+ passes over ~1 GB
activations per forward at the north-star scale. This kernel computes the
ENTIRE block (QKV → per-head attention → output proj → post-LN → FFN →
post-LN) for a tile of sequences without leaving VMEM: HBM traffic drops to
one read of the query/key blocks + one write of the output block + the
(tiny, reused) weights.

Semantics: bit-compatible layout with ``models.transformer.TransformerBlock``
(same param tree; quirks Q1/Q2 and the layer-0 key threading are honored by
the caller passing ``x_k`` = the layer-0 key embeddings to every depth).
Attention softmax and LN statistics are computed in f32; matmuls accumulate
in f32 with bf16 operands (MXU-native).

Scope: forward only (no custom VJP) — used on the acting/rollout path and
target-network unrolls where no gradient flows. The learner's differentiable
unroll uses the XLA path with identical parameters.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LN_EPS = 1e-6   # must match models.transformer._layer_norm


def _pick_tile(s: int, target: int = 16) -> int:
    """Largest divisor of ``s`` that is ≤ target (grid must tile exactly)."""
    for g in range(min(target, s), 0, -1):
        if s % g == 0:
            return g
    return 1


def _ln(x32: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray
        ) -> jnp.ndarray:
    """f32 fast-variance LayerNorm over the last axis (flax-compatible)."""
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + LN_EPS)
    return (x32 - mean) * inv * scale + bias


def _block_kernel(xq_ref, xk_ref, wq_ref, wk_ref, wv_ref, wo_ref, wob_ref,
                  n1s_ref, n1b_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                  n2s_ref, n2b_ref, out_ref, *, heads: int, head_dim: int,
                  t_real: int):
    g, t, e = xq_ref.shape   # t is padded to a sublane multiple
    d = head_dim
    cdt = xq_ref.dtype   # compute dtype of the activations (bf16 or f32)

    xq = xq_ref[:].reshape(g * t, e)
    xk = xk_ref[:].reshape(g * t, e)

    # padded key positions (j >= t_real) are masked out of every softmax
    key_pad = (jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
               >= t_real)[None]              # (1, t, t), broadcasts over g

    # Per-head attention with weights pre-split (H, E, D) so the kernel only
    # ever indexes leading dims — Mosaic supports neither multi-batch-dim
    # matmuls nor lane-splitting reshapes. The head loop is unrolled
    # (heads is static and small); attention FLOPs are a minor term.
    scale = d ** -0.25
    attended = wob_ref[:].astype(jnp.float32)        # (1, E), broadcasts
    for hi in range(heads):
        q = jnp.dot(xq, wq_ref[hi], preferred_element_type=jnp.float32)
        k = jnp.dot(xk, wk_ref[hi], preferred_element_type=jnp.float32)
        v = jnp.dot(xk, wv_ref[hi], preferred_element_type=jnp.float32)
        # Q1 scaling: queries AND keys divided by head_dim ** 1/4
        q = (q * scale).astype(cdt).reshape(g, t, d)
        k = (k * scale).astype(cdt).reshape(g, t, d)
        v = v.astype(cdt).reshape(g, t, d)
        logits = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)      # (g, t, t)
        logits = jnp.where(key_pad, -1e30, logits)
        attn = jax.nn.softmax(logits, axis=-1).astype(cdt)
        ctx = jax.lax.dot_general(
            attn, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)      # (g, t, d)
        ctx = ctx.astype(cdt).reshape(g * t, d)
        attended = attended + jnp.dot(
            ctx, wo_ref[hi], preferred_element_type=jnp.float32)

    # Q2: post-LN over (attended + query input), f32 statistics
    x1 = _ln(attended + xq.astype(jnp.float32), n1s_ref[:], n1b_ref[:])

    # FFN fused: the (g*t, 4e) hidden never leaves VMEM
    hcast = x1.astype(cdt)
    hid = jnp.dot(hcast, w1_ref[:], preferred_element_type=jnp.float32)
    hid = jnp.maximum(hid + b1_ref[:].astype(jnp.float32), 0.0).astype(cdt)
    y = jnp.dot(hid, w2_ref[:], preferred_element_type=jnp.float32)
    y = y + b2_ref[:].astype(jnp.float32)

    x2 = _ln(y + x1, n2s_ref[:], n2b_ref[:])
    out_ref[:] = x2.astype(cdt).reshape(g, t, e)


def fused_transformer_block(
        x_q: jnp.ndarray, x_k: jnp.ndarray,
        wq: jnp.ndarray, wk: jnp.ndarray, wv: jnp.ndarray,
        wo: jnp.ndarray, wo_b: jnp.ndarray,
        n1_scale: jnp.ndarray, n1_bias: jnp.ndarray,
        w1: jnp.ndarray, b1: jnp.ndarray,
        w2: jnp.ndarray, b2: jnp.ndarray,
        n2_scale: jnp.ndarray, n2_bias: jnp.ndarray,
        heads: int, head_dim: int,
        interpret: bool = False, t_real: int | None = None,
        tile: int = 16) -> jnp.ndarray:
    """One transformer block over ``(S, T, E)`` sequences, fully fused.

    ``x_q``/``x_k`` are the query tokens and the (layer-0) key tokens.
    Weight layouts match the flax modules: ``wq/wk/wv (E, H·D)``,
    ``wo (H·D, E)``, ``w1 (E, ff·E)``, ``w2 (ff·E, E)``.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    ``t_real``: pass the true token count when the input is already padded
    to a sublane multiple (multi-layer callers pad once); the output then
    stays padded. ``tile``: target sequences per grid step (more rows per
    kernel invocation = wider matmuls + better pipelining, bounded by VMEM).
    """
    s, t, e = x_q.shape
    pre_padded = t_real is not None
    if t_real is None:
        t_real = t
    g = _pick_tile(s, tile)
    cdt = x_q.dtype
    # pad the token axis to a sublane multiple: in-kernel (g, t, e) →
    # (g·t, e) reshapes are layout-trivial only when t is tile-aligned
    # (Mosaic rejects merges of padded sublane dims as 'unsupported shape
    # cast'); padded keys are softmax-masked inside the kernel
    sublane = 16 if cdt == jnp.bfloat16 else 8
    tp = -(-t // sublane) * sublane
    if tp != t:
        pad = [(0, 0), (0, tp - t), (0, 0)]
        x_q = jnp.pad(x_q, pad)
        x_k = jnp.pad(x_k, pad)
    wcast = lambda w: w.astype(cdt)
    # 1-D params become (1, n): TPU VMEM wants ≥2-D operands
    row = lambda v, dt=jnp.float32: v.astype(dt).reshape(1, -1)
    # pre-split heads OUTSIDE the kernel (XLA handles the relayout once):
    # (E, H·D) → (H, E, D) for q/k/v, (H·D, E) → (H, D, E) for the out proj
    split_in = lambda w: (w.reshape(e, heads, head_dim)
                          .transpose(1, 0, 2).astype(cdt))
    wq, wk, wv = split_in(wq), split_in(wk), split_in(wv)
    wo = wo.reshape(heads, head_dim, e).astype(cdt)

    kernel = functools.partial(_block_kernel, heads=heads,
                               head_dim=head_dim, t_real=t_real)
    seq_spec = pl.BlockSpec((g, tp, e), lambda i: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    full = lambda shape: pl.BlockSpec(
        shape, lambda i: (0,) * len(shape), memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        kernel,
        grid=(s // g,),
        in_specs=[
            seq_spec, seq_spec,
            full(wq.shape), full(wk.shape), full(wv.shape),
            full(wo.shape), full((1, wo_b.shape[-1])),
            full((1, n1_scale.shape[-1])), full((1, n1_bias.shape[-1])),
            full(w1.shape), full((1, b1.shape[-1])),
            full(w2.shape), full((1, b2.shape[-1])),
            full((1, n2_scale.shape[-1])), full((1, n2_bias.shape[-1])),
        ],
        out_specs=seq_spec,
        out_shape=jax.ShapeDtypeStruct((s, tp, e), cdt),
        interpret=interpret,
    )(x_q, x_k, wq, wk, wv, wo, row(wo_b),
      row(n1_scale), row(n1_bias),
      wcast(w1), row(b1), wcast(w2), row(b2),
      row(n2_scale), row(n2_bias))
    return out if pre_padded else (out[:, :t, :] if tp != t else out)
