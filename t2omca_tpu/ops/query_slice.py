"""Query-slice agent forward: compute ONLY the hidden token's row.

An exact algebraic reduction of ``TransformerAgent.__call__``, exploiting two
structural facts of the reference architecture (both pinned by parity tests):

1. **Keys are layer-0-pinned.** Every block attends its evolving queries
   against the ORIGINAL embedded tokens — blocks return ``k`` unchanged
   (``/root/reference/transformer.py:126,140``; ``models/transformer.py``
   "Key threading"). So token ``i``'s output at depth ``L`` depends only on
   token ``i``'s own query path and the shared layer-0 keys: information
   never flows token→token→token across layers.
2. **Only token 0 is consumed.** The agent reads ``out[:, 0]`` as the new
   hidden state and Q-head input (``/root/reference/transf_agent.py:71``);
   the other ``n_entities`` output rows are dead.

Therefore the attention-output / unify / LayerNorm / FFN work for every
entity token is dead computation — ~``(T-1)/T`` ≈ 98% of block FLOPs at the
64-agent scale. This path carries a single query row (token 0) through the
stack and contracts the key/value projections away entirely:

* ``logits_h = (q_h·s)·(k0 Wk_h·s)^T = x0 (Wq_h Wk_h^T s^2) k0^T`` — fold
  ``Wqk_h = Wq_h Wk_h^T s^2`` (E×E per head, computed once from the weights,
  O(params) not O(tokens)), so keys are never materialized.
* ``attended = Σ_h softmax(logits_h) (k0 Wv_h) Wu_h = Σ_h (attn_h k0) Wvu_h``
  with ``Wvu_h = Wv_h Wu_h`` — values are never materialized either.

Per sequence the block cost drops from O(T·E²·ff) to O(E²·ff + H·T·E): at the
north-star scale (T=65, E=256) a ~50× FLOP reduction with bit-compatible
semantics (float reassociation only; equivalence pinned to the flax module in
``tests/test_qslice.py``, including gradients — the reduction is exact, so
the learner can unroll through it too).

All ops are fat ``(S, ·)×(·, ·)`` matmuls over the folded batch×agent axis
plus two bandwidth-bound batched contractions against ``k0`` — no Pallas
needed; XLA fuses the rest. Numerics conventions: f32 accumulation, f32
LayerNorm statistics, softmax in f32 for the f32 parity mode and bf16 for
the perf mode (mirroring ``models/transformer.py:101-105``).

Forward-compatible with gradient flow: everything here is plain jnp, so
``jax.grad`` through it yields the same gradients as the dense module (same
function, different association).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

LN_EPS = 1e-6   # flax nn.LayerNorm default


def _ln(x32: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray
        ) -> jnp.ndarray:
    """f32 fast-variance LayerNorm over the last axis (flax-compatible)."""
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + LN_EPS)
    return (x32 - mean) * inv * scale + bias


#: marker key of a pre-folded parameter tree (see ``fold_transformer``)
FOLDED = "__qslice_folded__"


def agent_qslice_eligible(cfg) -> bool:
    """Single source of truth for agent-side eligibility: the reduction
    needs a deterministic transformer STACK (no dropout mask inside the
    blocks). NoisyLinear is fine: the noise lives only in the q-head
    (``models/agent.py:64-66``), which applies AFTER the sliced stack —
    ``_q_head`` samples it from an explicit key (round 5; previously
    noisy configs were excluded wholesale, which forced the reference's
    own selector onto the dense path). Consumers: ``BasicMAC.build`` and
    ``QMixLearner`` (both acting and learner unrolls share it)."""
    return (cfg.model.use_qslice
            and cfg.agent == "transformer"
            and cfg.model.dropout == 0.0)


def entity_tables_eligible(cfg) -> bool:
    """Entity-table eligibility: needs the ``use_entity_tables`` kill
    switch on (it covers BOTH acting and the learner's compact-storage
    unroll), the qslice agent path, the entity observation mode (the
    factored structure IS the entity obs), the batched normalizer (the
    sequential one gives each observer different prefix statistics), and
    no entity-count override (tables are derived from the env's own
    agents)."""
    return (cfg.model.use_entity_tables
            and agent_qslice_eligible(cfg)
            and cfg.env_args.obs_entity_mode
            and cfg.env_args.fast_norm
            and cfg.model.n_entities_obs == 0)


def entity_store_eligible(cfg) -> bool:
    """Compact entity episode STORAGE eligibility: on top of the acting
    eligibility, the learner must be able to unroll through the entity
    forward (deterministic transformer — already implied) and the mixer
    must not consume stored obs (Q12 fallback needs the full tensor), and
    the host-RAM buffer keeps the plain layout (its escape-hatch use case
    predates the 20× shrink)."""
    return (cfg.replay.compact_entity_store
            and entity_tables_eligible(cfg)
            and cfg.env_args.state_entity_mode
            and not cfg.replay.buffer_cpu_only
            # the stored mec_index narrows to int8
            # (runners/parallel_runner.py obs_store); ids are 0..mec_num-1,
            # so any id past 127 would alias and corrupt reconstructed
            # same-MEC visibility
            and cfg.env_args.mec_num <= 128)


def mixer_qslice_eligible(cfg) -> bool:
    """Mixer-side eligibility: deterministic transformer mixer only (only
    the last ``n_agents+3`` output rows are consumed, models/mixer.py)."""
    return (cfg.model.use_qslice
            and cfg.mixer == "transformer"
            and cfg.model.dropout == 0.0)


def _fold_block(bp: dict, emb: int, heads: int, head_dim: int,
                dtype) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold the block's attention projections (f32, O(E²·H·D) — independent
    of the token/batch axes).

    Returns ``wqk (E, H·E)`` with the Q1 dual ``head_dim**-0.25`` scaling
    folded in, and ``wvu (H·E, E)``.
    """
    at = bp["attention"]
    wq = at["toqueries"]["kernel"].astype(jnp.float32)   # (E, H·D)
    wk = at["tokeys"]["kernel"].astype(jnp.float32)
    wv = at["tovalues"]["kernel"].astype(jnp.float32)
    wu = at["unifyheads"]["kernel"].astype(jnp.float32)  # (H·D, E)
    h, d, e = heads, head_dim, emb
    wq_h = wq.reshape(e, h, d)
    wk_h = wk.reshape(e, h, d)
    wv_h = wv.reshape(e, h, d)
    wu_h = wu.reshape(h, d, e)
    # Q1: queries AND keys are each scaled by d**-0.25 → d**-0.5 on logits
    wqk = jnp.einsum("ehd,fhd->ehf", wq_h, wk_h) * (d ** -0.5)   # (E, H, E)
    wvu = jnp.einsum("ehd,hdf->hef", wv_h, wu_h)                 # (H, E, E)
    return (wqk.reshape(e, h * e).astype(dtype),
            wvu.reshape(h * e, e).astype(dtype))


def fold_transformer(tf_params: dict, *, emb: int, heads: int,
                     head_dim: int, depth: int, dtype) -> dict:
    """Pre-fold every block's attention projections ONCE. The fold is
    differentiable (einsums of the raw kernels), so gradients flow back to
    the original parameters unchanged. Callers whose forward sits inside a
    ``lax.scan`` body (rollout step, learner unroll) should fold OUTSIDE the
    scan and pass the result through — relying on XLA's loop-invariant code
    motion to hoist the fold dots is not guaranteed."""
    blocks = []
    for i in range(depth):
        bp = tf_params[f"block_{i}"]
        wqk, wvu = _fold_block(bp, emb, heads, head_dim, dtype)
        blocks.append({"wqk": wqk, "wvu": wvu,
                       "u_bias": bp["attention"]["unifyheads"]["bias"],
                       "n1": bp["norm1"], "n2": bp["norm2"],
                       "ff1": bp["ff1"], "ff2": bp["ff2"]})
    return {FOLDED: True, "blocks": blocks}


def transformer_rows(tf_folded: dict, k0: jnp.ndarray, x0: jnp.ndarray, *,
                     emb: int, heads: int, depth: int,
                     dtype=jnp.float32, attn_impl: str = "xla"
                     ) -> jnp.ndarray:
    """Carry ``R`` query rows through ``depth`` pre-folded blocks against
    the pinned layer-0 keys ``k0 (S, T, E)``. ``x0 (S, R, E)`` must be the
    slice of ``k0`` rows whose outputs are consumed (agent: row 0; mixer:
    the last ``n_agents+3`` rows). Returns the final rows ``(S, R, E)`` in
    f32.

    ``attn_impl`` is the ``kernels.attention`` switch for THIS forward:
    ``"pallas"`` routes the ``R·H`` sliced query rows through the flash
    kernel (``kernels/attention.py``) as one head-free attention —
    batch ``S``, query axis ``R·H``, the shared ``k0`` as both keys and
    values — so neither the ``(S, R·H, T)`` logits tensor nor (under
    ``jax.grad``) its backward recompute ever reach HBM. The learner
    unrolls pass the config switch; acting/serving callers keep the
    default (the rollout's per-step attention is Q=H rows — too small
    for the tiling to pay — and the serving artifact's lowering must
    never depend on a training-run perf knob). Numerics: the kernel
    keeps f32 softmax statistics at every dtype, so the bf16 mode is
    *better*-conditioned than the einsum branch below (which softmaxes
    in bf16); f32 matches to reassociation (tests/test_kernels.py)."""
    s, r, _ = x0.shape
    for i in range(depth):
        bp = tf_folded["blocks"][i]
        wqk, wvu = bp["wqk"], bp["wvu"]
        # logits over all T keys for each head, keys never materialized
        qp = jnp.dot(x0.reshape(s * r, emb), wqk,
                     preferred_element_type=jnp.float32)
        qp = qp.astype(dtype).reshape(s, r * heads, emb)
        if attn_impl == "pallas":
            # fused flash kernel over the R·H sliced rows: the folded
            # wqk already carries the d**-0.5 logit scaling, k0 doubles
            # as keys AND values (the qslice identity: ctx = attn·k0,
            # wvu applies after), no mask/causal structure
            from ..kernels.attention import flash_attention
            ctx = flash_attention(qp[:, None], k0[:, None],
                                  k0[:, None])[:, 0]        # (S, R·H, E)
            ctx = ctx.astype(dtype).reshape(s * r, heads * emb)
        else:
            logits = jax.lax.dot_general(
                qp, k0, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)             # (S, R·H, T)
            # parity mode keeps f32 softmax; bf16 perf mode stays in bf16
            # (mirrors models/transformer.py:101-105)
            if dtype == jnp.float32:
                attn = jax.nn.softmax(logits, axis=-1)
            else:
                attn = jax.nn.softmax(logits.astype(dtype), axis=-1)
            attn = attn.astype(dtype)
            ctx = jax.lax.dot_general(
                attn, k0, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)             # (S, R·H, E)
            ctx = ctx.astype(dtype).reshape(s * r, heads * emb)
        attended = (jnp.dot(ctx, wvu, preferred_element_type=jnp.float32)
                    + bp["u_bias"].astype(jnp.float32))         # (S·R, E) f32
        x0 = _block_tail(bp, attended,
                         x0.reshape(s * r, emb), dtype).reshape(s, r, emb)

    return x0.astype(jnp.float32)


def _block_tail(bp: dict, attended: jnp.ndarray, x0_flat: jnp.ndarray,
                dtype) -> jnp.ndarray:
    """Post-attention block tail shared by both query-slice forwards:
    Q2 post-LN residuals + FFN, f32 statistics.
    ``attended (N, E)`` f32, ``x0_flat (N, E)`` in compute dtype."""
    x1 = _ln(attended + x0_flat.astype(jnp.float32),
             bp["n1"]["scale"].astype(jnp.float32),
             bp["n1"]["bias"].astype(jnp.float32))
    hid = jnp.dot(x1.astype(dtype), bp["ff1"]["kernel"].astype(dtype),
                  preferred_element_type=jnp.float32)
    hid = jnp.maximum(hid + bp["ff1"]["bias"].astype(jnp.float32), 0.0)
    y = jnp.dot(hid.astype(dtype), bp["ff2"]["kernel"].astype(dtype),
                preferred_element_type=jnp.float32)
    y = y + bp["ff2"]["bias"].astype(jnp.float32)
    x2 = _ln(y + x1,
             bp["n2"]["scale"].astype(jnp.float32),
             bp["n2"]["bias"].astype(jnp.float32))
    return x2.astype(dtype)


def _q_head(qb: dict, h_new: jnp.ndarray,
            noise_key: jnp.ndarray | None = None) -> jnp.ndarray:
    """Apply the Q head to ``(S, E)`` f32 hidden rows. ``qb`` is either
    the dense ``q_basic`` params ({kernel, bias}) or NoisyLinear params
    ({w_mu, w_sigma, b_mu, b_sigma} — ``models/noisy.py``).

    ``noise_key=None`` is the deterministic path (mu weights — exactly
    NoisyLinear's eval mode, so test-mode equivalence with the dense
    module is bit-for-reassociation). With a key, ONE factored-Gaussian
    draw perturbs the weights for the whole call — the dense module's
    one-draw-per-forward semantics (all agents share the draw; per-agent
    diversity comes through each agent's h). The raw key is split here
    (in/out factors) rather than run through flax's path-folded
    ``make_rng``, so the NOISE STREAM differs from the flax module's for
    the same key — identical distribution, different sample; documented
    in docs/SPEC.md §7 (use_qslice row)."""
    if "kernel" in qb:
        return (jnp.dot(h_new, qb["kernel"].astype(jnp.float32))
                + qb["bias"].astype(jnp.float32))
    w = qb["w_mu"].astype(jnp.float32)
    b = qb["b_mu"].astype(jnp.float32)
    if noise_key is not None:
        from ..models.noisy import noisy_weights
        w, b = noisy_weights(w, qb["w_sigma"].astype(jnp.float32),
                             b, qb["b_sigma"].astype(jnp.float32),
                             noise_key)
    return jnp.dot(h_new, w) + b


def fold_agent_params(variables: dict, *, emb: int, heads: int, depth: int,
                      standard_heads: bool = False, dtype=jnp.float32
                      ) -> dict:
    """Pre-fold an agent param tree for ``agent_forward_qslice``. Call once
    OUTSIDE any scan whose body runs the forward (rollout step fn, learner
    unroll); the result is an ordinary pytree."""
    if FOLDED in variables:
        return variables
    p = variables["params"]
    head_dim = emb // heads if standard_heads else emb
    return {FOLDED: True,
            "fe": p["feat_embedding"],
            "tf": fold_transformer(p["transformer"], emb=emb, heads=heads,
                                   head_dim=head_dim, depth=depth,
                                   dtype=dtype),
            "qb": p["q_basic"]}


def agent_forward_qslice(variables: dict, inputs: jnp.ndarray,
                         hidden_state: jnp.ndarray, *,
                         n_entities: int, feat_dim: int, emb: int,
                         heads: int, depth: int, n_actions: int,
                         standard_heads: bool = False,
                         dtype=jnp.float32,
                         noise_key: jnp.ndarray | None = None,
                         attn_impl: str = "xla"
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``TransformerAgent.apply`` (dropout=0; noisy heads
    supported via ``noise_key`` — see ``_q_head``):
    inputs ``(B, A, obs)``, hidden ``(B, A, emb)`` → (q, hidden').
    Accepts either the raw flax variables or a ``fold_agent_params`` tree.
    ``attn_impl`` selects the sliced-attention lowering (see
    ``transformer_rows``; the learner unroll passes the config's
    ``kernels.attention``)."""
    f = fold_agent_params(variables, emb=emb, heads=heads, depth=depth,
                          standard_heads=standard_heads, dtype=dtype)
    b, a, _ = inputs.shape
    s = b * a

    x = inputs.reshape(s, n_entities, feat_dim).astype(dtype)
    h0 = hidden_state.reshape(s, emb).astype(dtype)

    fe = f["fe"]
    embs = (jnp.dot(x, fe["kernel"].astype(dtype),
                    preferred_element_type=jnp.float32)
            + fe["bias"].astype(jnp.float32)).astype(dtype)     # (S, N, E)
    # layer-0 key tokens: hidden token prepended at position 0
    k0 = jnp.concatenate([h0[:, None, :], embs], axis=1)        # (S, T, E)

    out = transformer_rows(f["tf"], k0, h0[:, None, :],
                           emb=emb, heads=heads, depth=depth,
                           dtype=dtype, attn_impl=attn_impl)    # (S, 1, E)

    h_new = out[:, 0, :]                                        # (S, E) f32
    q = _q_head(f["qb"], h_new, noise_key)
    return (q.reshape(b, a, n_actions),
            h_new.reshape(b, a, emb))


def make_mixer_qslice(mixer):
    """(fold_fn, apply_fn) pair closing over a ``TransformerMixer``'s
    attributes, so callers (the learner unroll) don't re-plumb the module
    config. ``apply_fn`` matches ``mixer.apply``'s positional signature.
    The mixer's ``attn_impl`` (= the config's ``kernels.attention``)
    threads through: this pair is consumed ONLY by the learner unroll,
    so the kernel switch lands exactly on the train path."""
    fold = lambda variables: fold_mixer_params(
        variables, emb=mixer.emb, heads=mixer.heads, depth=mixer.depth,
        standard_heads=mixer.standard_heads, dtype=mixer.dtype)
    apply = lambda mp, qvals, h, hyper, s, o: mixer_forward_qslice(
        mp, qvals, h, hyper, s, o,
        n_agents=mixer.n_agents, n_entities=mixer.n_entities,
        feat_dim=mixer.feat_dim, emb=mixer.emb, heads=mixer.heads,
        attn_impl=mixer.attn_impl,
        depth=mixer.depth, pos_func=mixer.qmix_pos_func,
        pos_func_beta=mixer.qmix_pos_func_beta,
        state_entity_mode=mixer.state_entity_mode,
        standard_heads=mixer.standard_heads, dtype=mixer.dtype)
    return fold, apply


def agent_forward_qslice_entity(variables: dict, rows: jnp.ndarray,
                                same_mec: jnp.ndarray, mean: jnp.ndarray,
                                std: jnp.ndarray, hidden_state: jnp.ndarray,
                                *, emb: int, heads: int, depth: int,
                                n_actions: int, standard_heads: bool = False,
                                dtype=jnp.float32,
                                noise_key: jnp.ndarray | None = None
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Entity-table acting forward: ``agent_forward_qslice`` without ever
    materializing per-agent token embeddings. ``noise_key`` as in
    ``_q_head`` (noisy heads supported).

    Exploits the structure of the entity observation
    (``envs/mec_offload.py:_raw_obs`` + the shared ``fast_norm`` affine):
    agent ``i``'s token ``j`` is ``(same_mec[i,j] ? rows[j] : 0, is_self)``
    normalized by per-position statistics that are identical for every
    observer — so each env has only TWO distinct embedded values per entity
    (visible / masked) plus a diagonal is-self correction. Attention logits
    and context therefore contract against per-env ``(A, E)`` tables instead
    of per-agent ``(A, A+1, E)`` key tensors: at the north-star scale this
    removes the 576→emb embedding matmul (~1.2 TFLOP/slot) AND the
    ``(B·A, A+1, E)`` key materialization (~GBs/slot of HBM traffic) from
    the acting path. Exact to float reassociation vs the obs-path forward
    (pinned in tests/test_entity_tables.py).

    Inputs per ``MultiAgvOffloadingEnv.compact_obs``: ``rows (B, A, 8)``,
    ``same_mec (B, A, A)`` bool, ``mean/std (B, A, 9)``; ``hidden_state
    (B, A, emb)``. Requires ``obs_entity_mode`` + ``fast_norm`` and no
    ``n_entities`` override (gated by ``entity_tables_eligible``)."""
    f = fold_agent_params(variables, emb=emb, heads=heads, depth=depth,
                          standard_heads=standard_heads, dtype=dtype)
    b, a, _ = rows.shape
    s = b * a

    # ---- per-env embedding tables (feat 8 = is_self; _raw_obs layout)
    denom = std.astype(jnp.float32) + 1e-8                    # (B, A, 9)
    rows9 = jnp.concatenate(
        [rows.astype(jnp.float32), jnp.zeros((b, a, 1))], axis=-1)
    nv = ((rows9 - mean) / denom).astype(dtype)               # visible row
    nh = ((-mean) / denom).astype(dtype)                      # masked row
    we = f["fe"]["kernel"].astype(dtype)                      # (9, E)
    be = f["fe"]["bias"].astype(jnp.float32)
    e_vis = (jnp.dot(nv, we, preferred_element_type=jnp.float32)
             + be).astype(dtype)                              # (B, A, E)
    e_hid = (jnp.dot(nh, we, preferred_element_type=jnp.float32)
             + be).astype(dtype)
    self_corr = (we[8][None, None, :].astype(jnp.float32)
                 / denom[..., 8:9]).astype(dtype)             # (B, A, E)

    h_tok = hidden_state.astype(dtype)                        # (B, A, E)
    vis = same_mec[:, :, None, :]                             # (B, A, 1, A)
    eye = jnp.eye(a, dtype=dtype)[None, :, None, :]           # (1, A, 1, A)
    idx_diag = jnp.arange(a)[None, :, None, None]

    x0 = h_tok
    for i in range(depth):
        bp = f["tf"]["blocks"][i]
        qp = jnp.dot(x0.reshape(s, emb), bp["wqk"],
                     preferred_element_type=jnp.float32)
        qp = qp.astype(dtype).reshape(b, a, heads, emb)
        # logits against key 0 (own hidden token) and the entity tables
        l0 = jnp.einsum("bahe,bae->bah", qp, h_tok,
                        preferred_element_type=jnp.float32)
        lv = jnp.einsum("bahe,bje->bahj", qp, e_vis,
                        preferred_element_type=jnp.float32)
        lh = jnp.einsum("bahe,bje->bahj", qp, e_hid,
                        preferred_element_type=jnp.float32)
        ls = jnp.einsum("bahe,bae->bah", qp, self_corr,
                        preferred_element_type=jnp.float32)
        lent = jnp.where(vis, lv, lh) + eye.astype(jnp.float32) \
            * ls[..., None]
        logits = jnp.concatenate([l0[..., None], lent], axis=-1)
        if dtype == jnp.float32:
            attn = jax.nn.softmax(logits, axis=-1)
        else:
            attn = jax.nn.softmax(logits.astype(dtype), axis=-1)
        attn = attn.astype(dtype)
        a0, ae = attn[..., 0], attn[..., 1:]                  # (B,A,H[,A])
        av = ae * vis.astype(dtype)
        ah = ae - av                                          # masked branch
        diag = jnp.take_along_axis(ae, idx_diag, axis=-1)[..., 0]
        ctx = (a0[..., None] * h_tok[:, :, None, :]
               + jnp.einsum("bahj,bje->bahe", av, e_vis,
                            preferred_element_type=jnp.float32).astype(dtype)
               + jnp.einsum("bahj,bje->bahe", ah, e_hid,
                            preferred_element_type=jnp.float32).astype(dtype)
               + diag[..., None] * self_corr[:, :, None, :])
        ctx = ctx.astype(dtype).reshape(s, heads * emb)
        attended = (jnp.dot(ctx, bp["wvu"],
                            preferred_element_type=jnp.float32)
                    + bp["u_bias"].astype(jnp.float32))
        x0 = _block_tail(bp, attended, x0.reshape(s, emb), dtype) \
            .reshape(b, a, emb)

    h_new = x0.astype(jnp.float32).reshape(s, emb)
    q = _q_head(f["qb"], h_new, noise_key)
    return (q.reshape(b, a, n_actions),
            h_new.reshape(b, a, emb))


def fold_mixer_params(variables: dict, *, emb: int, heads: int, depth: int,
                      standard_heads: bool = False, dtype=jnp.float32
                      ) -> dict:
    """Pre-fold a mixer param tree for ``mixer_forward_qslice`` (see
    ``fold_agent_params``)."""
    if FOLDED in variables:
        return variables
    p = variables["params"]
    head_dim = emb // heads if standard_heads else emb
    tree = {FOLDED: True,
            "fe": p["feat_embedding"],
            "tf": fold_transformer(p["transformer"], emb=emb, heads=heads,
                                   head_dim=head_dim, depth=depth,
                                   dtype=dtype),
            "hb": p["hyper_b2"]}
    if "out_gate" in p:        # zero_init_gate configs (models/mixer.py)
        tree["og"] = p["out_gate"]
    return tree


def mixer_forward_qslice(variables: dict, qvals: jnp.ndarray,
                         hidden_states: jnp.ndarray,
                         hyper_weights: jnp.ndarray, states: jnp.ndarray,
                         obs: jnp.ndarray, *,
                         n_agents: int, n_entities: int, feat_dim: int,
                         emb: int, heads: int, depth: int,
                         pos_func: str, pos_func_beta: float,
                         state_entity_mode: bool = True,
                         standard_heads: bool = False,
                         dtype=jnp.float32, attn_impl: str = "xla"
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``TransformerMixer.apply`` (dropout=0): only the last
    ``n_agents+3`` output rows are consumed (w1 per agent, b1, w2, the b2
    source, and the 3 recurrent hyper tokens are WITHIN those rows —
    positions [-3:] — so one row-slice covers readout + recurrence); the
    ``n_entities`` state-embedding rows are dead computation in the dense
    module. Returns ``(q_tot (b,1,1), hyper (b,3,emb))``. Accepts either
    the raw flax variables or a ``fold_mixer_params`` tree."""
    from ..models.mixer import qmix_pos_func

    f = fold_mixer_params(variables, emb=emb, heads=heads, depth=depth,
                          standard_heads=standard_heads, dtype=dtype)
    b = qvals.shape[0]

    if state_entity_mode:
        inputs = states.reshape(b, n_entities, feat_dim).astype(dtype)
    else:  # Q12: all agents' obs entities
        inputs = obs.reshape(b, n_agents * n_entities, feat_dim).astype(dtype)

    fe = f["fe"]
    embs = (jnp.dot(inputs, fe["kernel"].astype(dtype),
                    preferred_element_type=jnp.float32)
            + fe["bias"].astype(jnp.float32)).astype(dtype)

    k0 = jnp.concatenate(
        [embs, hidden_states.astype(dtype), hyper_weights.astype(dtype)],
        axis=1)                                                 # (b, T, E)

    r = n_agents + 3
    out = transformer_rows(f["tf"], k0, k0[:, -r:, :],
                           emb=emb, heads=heads, depth=depth,
                           dtype=dtype, attn_impl=attn_impl)    # (b, A+3, E)

    w1 = out[:, :n_agents, :]                                   # (b, A, emb)
    b1 = out[:, -3, :].reshape(b, 1, emb)
    w2 = out[:, -2, :].reshape(b, emb, 1)
    hb = f["hb"]
    b2 = jax.nn.relu(
        jnp.dot(out[:, -1, :], hb["kernel"].astype(jnp.float32))
        + hb["bias"].astype(jnp.float32)).reshape(b, 1, 1)

    w1 = qmix_pos_func(w1, pos_func, pos_func_beta)
    w2 = qmix_pos_func(w2, pos_func, pos_func_beta)

    hidden = jax.nn.elu(jnp.matmul(qvals.astype(jnp.float32), w1) + b1)
    y = jnp.matmul(hidden, w2) + b2                             # (b, 1, 1)
    if "og" in f:              # zero_init_gate configs (models/mixer.py)
        y = y * f["og"].astype(jnp.float32)
    return y, out[:, -3:, :]
