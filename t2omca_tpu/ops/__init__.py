from .transformer_block import fused_transformer_block

__all__ = ["fused_transformer_block"]
