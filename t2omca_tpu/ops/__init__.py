"""Hot-path op reductions (query-slice / entity tables).

The Pallas fused-block kernel that used to live here
(``transformer_block.py`` + ``fast_agent.py``) was deleted in round 5:
it computed the FULL dense forward for every token, which the
query-slice reduction (token-0-only, K/V contracted away) and the
entity-table acting path strictly dominate on FLOPs — see BASELINE.md
round-5 notes for the decision record.
"""

from .query_slice import (agent_forward_qslice, agent_forward_qslice_entity,
                          fold_agent_params, mixer_forward_qslice)

__all__ = ["agent_forward_qslice", "agent_forward_qslice_entity",
           "fold_agent_params", "mixer_forward_qslice"]
