"""Fast (fused-kernel) agent forward over the standard flax param tree.

The acting path — ``episode_limit`` sequential agent forwards inside the
rollout scan — is HBM-bandwidth bound under XLA (BASELINE.md). This module
re-implements ``TransformerAgent.__call__`` as a pure function that reads
the SAME parameter pytree the flax module owns and dispatches every
transformer block to ``fused_transformer_block`` (one VMEM-resident Pallas
kernel per block). No separate parameters, no checkpoint divergence: the
learner keeps differentiating the flax module; the rollout calls this.

Semantics mirror ``models/agent.py`` + ``models/transformer.py`` exactly:
entity embedding, hidden token prepended at position 0, layer-0 key
threading across depth (keys pinned to the embedded input tokens), token 0
out as (new hidden, Q-head input). Dropout must be 0 (it is in every
reference config; guarded at build time in the MAC).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .transformer_block import fused_transformer_block


def fast_transformer_apply(tf_params: dict, tokens: jnp.ndarray,
                           heads: int, depth: int, head_dim: int,
                           interpret: bool = False,
                           tile: int = 16) -> jnp.ndarray:
    """Apply ``depth`` fused blocks; ``tokens (S, T, E)``. Keys stay pinned
    to the layer-0 input (``transformer.py:126,140`` tuple threading).
    The token axis is padded to a sublane multiple ONCE here so every
    layer's kernel works on layout-trivial shapes; the caller slices."""
    t = tokens.shape[1]
    sublane = 16 if tokens.dtype == jnp.bfloat16 else 8
    tp = -(-t // sublane) * sublane
    if tp != t:
        tokens = jnp.pad(tokens, [(0, 0), (0, tp - t), (0, 0)])
    k0 = tokens
    x = tokens
    for i in range(depth):
        bp = tf_params[f"block_{i}"]
        at = bp["attention"]
        x = fused_transformer_block(
            x, k0,
            at["toqueries"]["kernel"], at["tokeys"]["kernel"],
            at["tovalues"]["kernel"],
            at["unifyheads"]["kernel"], at["unifyheads"]["bias"],
            bp["norm1"]["scale"], bp["norm1"]["bias"],
            bp["ff1"]["kernel"], bp["ff1"]["bias"],
            bp["ff2"]["kernel"], bp["ff2"]["bias"],
            bp["norm2"]["scale"], bp["norm2"]["bias"],
            heads=heads, head_dim=head_dim, interpret=interpret,
            t_real=t, tile=tile)
    return x[:, :t, :] if tp != t else x


def agent_forward_fast(variables: dict, inputs: jnp.ndarray,
                       hidden_state: jnp.ndarray, *,
                       n_entities: int, feat_dim: int, emb: int,
                       heads: int, depth: int, n_actions: int,
                       standard_heads: bool = False,
                       dtype=jnp.float32,
                       interpret: bool = False,
                       tile: int = 16
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``TransformerAgent.apply`` (non-noisy, dropout=0):
    inputs ``(B, A, obs)``, hidden ``(B, A, emb)`` → (q, hidden')."""
    p = variables["params"]
    b, a, _ = inputs.shape
    x = inputs.reshape(b * a, n_entities, feat_dim).astype(dtype)
    h = hidden_state.reshape(b * a, 1, emb).astype(dtype)

    fe = p["feat_embedding"]
    embs = (jnp.dot(x, fe["kernel"].astype(dtype),
                    preferred_element_type=jnp.float32)
            + fe["bias"].astype(jnp.float32)).astype(dtype)

    tokens = jnp.concatenate([h, embs], axis=1)
    head_dim = emb // heads if standard_heads else emb
    out = fast_transformer_apply(p["transformer"], tokens, heads, depth,
                                 head_dim, interpret=interpret, tile=tile)

    h_new = out[:, 0, :].astype(jnp.float32)
    qb = p["q_basic"]
    q = (jnp.dot(h_new, qb["kernel"].astype(jnp.float32),
                 preferred_element_type=jnp.float32)
         + qb["bias"].astype(jnp.float32))
    return (q.reshape(b, a, n_actions),
            h_new.reshape(b, a, emb))
