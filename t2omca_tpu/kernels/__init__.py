"""Rollout hot-path kernel layer (docs/PERF.md).

Hand-written accelerator kernels for the programs the rollout scan
spends its time in, each behind a config switch with the XLA lowering
as the default/fallback and CPU-gate parity tests pinning equivalence
(``tests/test_kernels.py``). graftlint treats this package as hot-path
(GL105: no host syncs), and graftprog fingerprints/ratchets both kernel
modes of every program registered here (``analysis/registry.py``).
"""

from .attention import flash_attention

__all__ = ["flash_attention"]
