"""Pallas flash-style fused entity-attention (ROADMAP item 1).

The XLA einsum path (``models/transformer.MultiHeadAttention``)
materializes the full ``(B·A, H, Q, K)`` logits tensor in HBM every env
step — at the north-star scale (64 agents, 65 tokens, 1024 envs) that is
the single largest write of the rollout slot, and Podracer/EnvPool
(PAPERS.md) both identify exactly this class of per-step tensor traffic
as what keeps a fused rollout memory-bandwidth-bound. This kernel runs
the classic flash pattern instead: tiled ``QK^T`` → masked **online
softmax** → ``PV`` accumulation, all inside one ``pallas_call`` whose
logits tile lives only in VMEM — the ``(Q, K)`` tensor never exists in
HBM.

Numerics contract (pinned by ``tests/test_kernels.py``):

* **f32 accumulators always** — the running max/denominator and the PV
  accumulator are f32 regardless of the input dtype, so the bf16 path
  here is *better*-conditioned than the einsum bf16 path (which
  softmaxes in bf16). f32 inputs match the einsum path to float
  reassociation (online vs max-subtracted softmax — same math,
  different association; ULP-bounded in tests).
* **Mask semantics mirror the module**: padding-mask positions are
  *replaced* with ``NEG_MASK_VALUE`` (−1e9), not biased — so a
  fully-masked row degrades to the same uniform distribution the
  einsum path produces (an additive bias would silently cancel in the
  softmax). Causal positions use the same finite value; ``exp``
  underflows those contributions to exactly 0.0 in both paths.
* **Differentiable everywhere**: the backward pass recomputes the
  reference einsum attention and takes its VJP (a custom VJP — Pallas
  primitives have no transpose rule), so the learner's dense unroll can
  train straight through the kernel with gradients identical to the
  einsum path evaluated at the same inputs.

``interpret=None`` (the default) auto-selects interpreter mode off-TPU,
which is what makes the kernel testable in the CPU tier-1 gate and
auditable by graftprog (the registered ``attn_pallas`` program lowers
the interpret form on the gate's pinned CPU platform).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - import surface depends on the jaxlib build
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# the ONE reference masked_fill value — imported, not redefined, so the
# kernel's replacement bias can never drift from the einsum path's
# (models/transformer.py only imports this module lazily inside
# __call__, so there is no import cycle)
from ..models.transformer import NEG_MASK_VALUE  # noqa: E402
#: key-tail padding fill: strictly below every representable masked
#: logit, so padded columns get exp(pad − m) = 0 even in the
#: all-masked-row case where m == NEG_MASK_VALUE (the einsum path's
#: uniform-over-real-keys degenerate behavior is preserved)
_PAD_VALUE = -1e30

#: default VMEM tile sizes (clamped to the padded token counts); 128
#: matches the MXU/VPU lane width
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
#: sublane quantum that serves both f32 (8) and bf16 (16) tilings
_SUBLANE = 16
#: MXU/VPU lane width — the last dim of every VMEM tile pads to this
#: on real TPU lowerings (interpret mode skips the pad)
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _flash_attention_kernel(q_ref, k_ref, v_ref, *rest, causal: bool,
                            has_bias: bool, t_k: int, t_k_pad: int,
                            block_q: int, block_k: int):
    """One (batch, head, q-block) grid cell: online-softmax attention of
    a ``(block_q, d)`` query tile against all keys, k-tiled by
    ``block_k``. The ``(block_q, block_k)`` logits tile is the only
    score buffer that ever exists."""
    if has_bias:
        bias_ref, o_ref = rest
    else:
        (o_ref,) = rest
    q = q_ref[0, 0].astype(jnp.float32)                    # (bq, d)
    d = q.shape[-1]
    q_row0 = pl.program_id(2) * block_q

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                                   # (bk, d)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        col = (j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1))
        if has_bias:
            # REPLACEMENT semantics (bias is 0 or NEG_MASK_VALUE): a
            # nonzero bias overwrites the logit, exactly like the
            # module's `where(mask == 0, NEG_MASK_VALUE, logits)` — an
            # additive bias would cancel in softmax on all-masked rows
            bb = bias_ref[0, 0, :, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = jnp.where(bb != 0.0, bb, s)
        if causal:
            # reference mask_: upper triangle excluding the diagonal
            row = q_row0 + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(col > row, NEG_MASK_VALUE, s)
        # key-tail padding sits strictly below every masked logit
        s = jnp.where(col < t_k, s, _PAD_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                             # f32 always
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l * alpha + jnp.sum(p, axis=1, keepdims=True), acc

    m0 = jnp.full((block_q, 1), _PAD_VALUE, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, t_k_pad // block_k, body,
                                  (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         bias: Optional[jnp.ndarray],
                         causal: bool) -> jnp.ndarray:
    """The einsum path on ``(B, H, T, D)`` layout — the semantics the
    kernel must match, and the function whose VJP serves as the
    kernel's backward pass (evaluated at the same inputs)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    if bias is not None:
        s = jnp.where(bias != 0.0, bias.astype(jnp.float32), s)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        tri = jnp.triu(jnp.ones((t_q, t_k), dtype=bool), k=1)
        s = jnp.where(tri[None, None], NEG_MASK_VALUE, s)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.lru_cache(maxsize=None)
def _build(causal: bool, block_q: int, block_k: int, interpret: bool,
           has_bias: bool):
    """One differentiable pallas program per static configuration
    (cached: ``jax.custom_vjp`` objects must be stable across traces so
    jit caches hit)."""

    def forward(q, k, v, bias):
        b, h, t_q, d = q.shape
        t_k = k.shape[2]
        # clamp tiles to the (sublane-rounded) token counts, then pad
        # tokens to tile multiples; off-TPU interpret mode skips the
        # lane pad (no hardware tiling to satisfy)
        bq = min(block_q, _round_up(t_q, _SUBLANE))
        bk = min(block_k, _round_up(t_k, _SUBLANE))
        t_q_pad = _round_up(t_q, bq)
        t_k_pad = _round_up(t_k, bk)
        d_pad = d if interpret else _round_up(d, _LANE)

        pad = lambda x, t: jnp.pad(
            x, ((0, 0), (0, 0), (0, t - x.shape[2]),
                (0, d_pad - x.shape[3])))
        qp, kp, vp = pad(q, t_q_pad), pad(k, t_k_pad), pad(v, t_k_pad)

        in_specs = [
            pl.BlockSpec((1, 1, bq, d_pad), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, t_k_pad, d_pad),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, t_k_pad, d_pad),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
        ]
        args = [qp, kp, vp]
        if has_bias:
            h_b = bias.shape[1]             # 1 (broadcast) or H
            bp = jnp.pad(bias, ((0, 0), (0, 0),
                                (0, t_q_pad - bias.shape[2]),
                                (0, t_k_pad - bias.shape[3])))
            in_specs.append(pl.BlockSpec(
                (1, 1, bq, t_k_pad),
                lambda b_, h_, i, hb=h_b: (b_, h_ if hb > 1 else 0, i, 0)))
            args.append(bp)

        kernel = functools.partial(
            _flash_attention_kernel, causal=causal, has_bias=has_bias,
            t_k=t_k, t_k_pad=t_k_pad, block_q=bq, block_k=bk)
        out = pl.pallas_call(
            kernel,
            grid=(b, h, t_q_pad // bq),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, bq, d_pad),
                                   lambda b_, h_, i: (b_, h_, i, 0)),
            out_shape=jax.ShapeDtypeStruct((b, h, t_q_pad, d_pad),
                                           q.dtype),
            interpret=interpret,
        )(*args)
        return out[:, :, :t_q, :d]

    @jax.custom_vjp
    def attn(q, k, v, bias):
        return forward(q, k, v, bias)

    def attn_fwd(q, k, v, bias):
        return forward(q, k, v, bias), (q, k, v, bias)

    def attn_bwd(res, g):
        q, k, v, bias = res
        # recompute-in-backward against the reference einsum math: exact
        # gradients of the same function (up to float reassociation),
        # no residual logits tensor kept from the forward
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _reference_attention(q_, k_, v_, bias,
                                                    causal), q, k, v)
        dq, dk, dv = vjp(g)
        db = jnp.zeros_like(bias) if bias is not None else None
        return dq, dk, dv, db

    attn.defvjp(attn_fwd, attn_bwd)
    return attn


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask: Optional[jnp.ndarray] = None,
                    causal: bool = False, *,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused attention ``softmax(QK^T [masked]) V`` on ``(B, H, T, D)``
    layout. Any Q1 query/key scaling is the caller's job (the module
    scales both by ``head_dim**-0.25`` before calling, exactly as on
    the einsum path).

    ``mask``: optional ``(B, 1|H, T_q, T_k)``; zero entries are
    suppressed (module semantics). ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (CPU tier-1 gate); pass an explicit bool
    to force either mode."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bias = None
    if mask is not None:
        if mask.ndim != 4:
            raise ValueError(f"mask must be (B, 1|H, T_q, T_k), got "
                             f"shape {mask.shape}")
        # encode the module's replacement semantics as a float plane:
        # 0 = keep the logit, NEG_MASK_VALUE = overwrite it
        bias = jnp.where(mask == 0, jnp.float32(NEG_MASK_VALUE),
                         jnp.float32(0.0))
    fn = _build(bool(causal), int(block_q), int(block_k), bool(interpret),
                bias is not None)
    return fn(q, k, v, bias)


def register_audit_programs(ctx):
    """graftprog registry hook (``analysis/registry.py``): lower BOTH
    kernel modes of ``MultiHeadAttention`` on the frozen audit config's
    model shapes so each stays ratcheted and fingerprinted
    (``analysis/programs.json``) — a silent jaxpr change in either the
    einsum path or the pallas lowering fails the gate like every other
    hot program. The pallas variant lowers the interpret form (the gate
    is pinned to CPU); on-TPU it lowers to a Mosaic custom call with
    the same kernel body."""
    from ..analysis.registry import AuditProgram
    from ..models.transformer import MultiHeadAttention

    m = ctx.cfg.model
    dt = jnp.dtype(m.dtype)
    b, t = 4, 8                         # tiny token grid, audit-scale

    def make(impl, fn_name):
        mha = MultiHeadAttention(emb=m.emb, heads=m.heads,
                                 standard_heads=m.standard_heads,
                                 dtype=dt, attn_impl=impl)
        q0 = jnp.zeros((b, t, m.emb), dt)
        k0 = jnp.zeros((b, t, m.emb), dt)
        params = jax.eval_shape(lambda: mha.init(
            jax.random.PRNGKey(0), q0, k0))
        aval = jax.ShapeDtypeStruct((b, t, m.emb), dt)

        def apply(p, q, kk):
            return mha.apply(p, q, kk)
        apply.__name__ = apply.__qualname__ = fn_name
        return AuditProgram(
            jax.jit(apply), (params, aval, aval),
            description=f"MultiHeadAttention ({impl} kernel mode) at "
                        f"audit model shapes — both rollout-path "
                        f"attention lowerings stay fingerprinted")

    return {
        "attn_xla": make("xla", "_attn_xla"),
        "attn_pallas": make("pallas", "_attn_pallas"),
    }
