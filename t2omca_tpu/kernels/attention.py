"""Pallas flash-style fused entity-attention (ROADMAP item 1).

The XLA einsum path (``models/transformer.MultiHeadAttention``)
materializes the full ``(B·A, H, Q, K)`` logits tensor in HBM every env
step — at the north-star scale (64 agents, 65 tokens, 1024 envs) that is
the single largest write of the rollout slot, and Podracer/EnvPool
(PAPERS.md) both identify exactly this class of per-step tensor traffic
as what keeps a fused rollout memory-bandwidth-bound. This kernel runs
the classic flash pattern instead: tiled ``QK^T`` → masked **online
softmax** → ``PV`` accumulation, all inside one ``pallas_call`` whose
logits tile lives only in VMEM — the ``(Q, K)`` tensor never exists in
HBM.

Numerics contract (pinned by ``tests/test_kernels.py``):

* **f32 accumulators always** — the running max/denominator and the PV
  accumulator are f32 regardless of the input dtype, so the bf16 path
  here is *better*-conditioned than the einsum bf16 path (which
  softmaxes in bf16). f32 inputs match the einsum path to float
  reassociation (online vs max-subtracted softmax — same math,
  different association; ULP-bounded in tests).
* **Mask semantics mirror the module**: padding-mask positions are
  *replaced* with ``NEG_MASK_VALUE`` (−1e9), not biased — so a
  fully-masked row degrades to the same uniform distribution the
  einsum path produces (an additive bias would silently cancel in the
  softmax). Causal positions use the same finite value; ``exp``
  underflows those contributions to exactly 0.0 in both paths.
* **Differentiable everywhere, flash both ways**: the forward pass
  under ``jax.grad`` additionally emits per-row softmax residuals —
  the running max ``m`` and denominator ``l``, kept SEPARATE rather
  than fused into one logsumexp so the all-masked-row degenerate case
  survives f32 (``m = −1e9`` swallows ``log l`` at f32 resolution;
  ``exp(s − m) / l`` does not) — and the backward pass recomputes the
  probability tiles in VMEM from ``(q, k, residuals)`` to produce
  ``dq/dk/dv`` without ever materializing the logits or P in HBM.
  The pre-PR-13 VJP instead re-ran the reference einsum attention in
  the backward, paying the exact ``(B·A, H, Q, K)`` HBM round-trip the
  forward kernel exists to kill — on the learner unrolls (where the
  agent/mixer transformers burn most FLOPs per dispatch) that write
  dominated train-step memory traffic. Gradients equal the einsum
  VJP's at the same inputs up to float reassociation (pinned at f32
  ~1e-5; replacement-mask/causal/all-masked-row semantics identical).
  Residual cost: O(B·H·Q) f32 per forward — two rows of statistics vs
  the O(B·H·Q·K) P tensor the einsum VJP keeps alive.

``interpret=None`` (the default) auto-selects interpreter mode off-TPU,
which is what makes the kernel testable in the CPU tier-1 gate and
auditable by graftprog (the registered ``attn_pallas``/
``attn_pallas_bwd`` programs lower the interpret form on the gate's
pinned CPU platform). Interpret mode also skips the TPU sublane/lane
tile quanta (token counts pad only to the clamped block sizes, head dim
not at all) — the kernel *body* is the one that lowers to Mosaic, but
off-TPU there is no hardware tiling to satisfy and the padding would
only inflate the audit's cost model with work the chip never does.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - import surface depends on the jaxlib build
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# the ONE reference masked_fill value — imported, not redefined, so the
# kernel's replacement bias can never drift from the einsum path's
# (models/transformer.py only imports this module lazily inside
# __call__, so there is no import cycle)
from ..models.transformer import NEG_MASK_VALUE  # noqa: E402
#: key-tail padding fill: strictly below every representable masked
#: logit, so padded columns get exp(pad − m) = 0 even in the
#: all-masked-row case where m == NEG_MASK_VALUE (the einsum path's
#: uniform-over-real-keys degenerate behavior is preserved)
_PAD_VALUE = -1e30

#: default VMEM tile sizes (clamped to the padded token counts); 128
#: matches the MXU/VPU lane width
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
#: sublane quantum that serves both f32 (8) and bf16 (16) tilings.
#: Applied only on real TPU lowerings — interpret mode (CPU gate) pads
#: tokens to the clamped block size alone, so tiny audit shapes are not
#: charged for pad rows Mosaic would process but the interpreter won't.
_SUBLANE = 16
#: MXU/VPU lane width — the last dim of every VMEM tile pads to this
#: on real TPU lowerings (interpret mode skips the pad)
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _tile_geometry(t_q: int, t_k: int, d: int, block_q: int, block_k: int,
                   interpret: bool):
    """One source for the (clamped block, padded token, padded head)
    geometry — the backward kernels must reuse the forward's exact
    padding so the saved per-row residuals line up with the recomputed
    tiles."""
    quantum = 1 if interpret else _SUBLANE
    bq = min(block_q, _round_up(t_q, quantum))
    bk = min(block_k, _round_up(t_k, quantum))
    t_q_pad = _round_up(t_q, bq)
    t_k_pad = _round_up(t_k, bk)
    d_pad = d if interpret else _round_up(d, _LANE)
    return bq, bk, t_q_pad, t_k_pad, d_pad


def _flash_attention_kernel(q_ref, k_ref, v_ref, *rest, causal: bool,
                            has_bias: bool, save_res: bool, t_k: int,
                            t_k_pad: int, block_q: int, block_k: int):
    """One (batch, head, q-block) grid cell: online-softmax attention of
    a ``(block_q, d)`` query tile against all keys, k-tiled by
    ``block_k``. The ``(block_q, block_k)`` logits tile is the only
    score buffer that ever exists. With ``save_res`` the final running
    max and denominator are emitted per row — the residuals the flash
    backward recomputes P from."""
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    o_ref = rest.pop(0)
    if save_res:
        m_ref, l_ref = rest
    q = q_ref[0, 0].astype(jnp.float32)                    # (bq, d)
    d = q.shape[-1]
    q_row0 = pl.program_id(2) * block_q

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)                                   # (bk, d)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        col = (j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1))
        if has_bias:
            # REPLACEMENT semantics (bias is 0 or NEG_MASK_VALUE): a
            # nonzero bias overwrites the logit, exactly like the
            # module's `where(mask == 0, NEG_MASK_VALUE, logits)` — an
            # additive bias would cancel in softmax on all-masked rows
            bb = bias_ref[0, 0, :, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = jnp.where(bb != 0.0, bb, s)
        if causal:
            # reference mask_: upper triangle excluding the diagonal
            row = q_row0 + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(col > row, NEG_MASK_VALUE, s)
        # key-tail padding sits strictly below every masked logit
        s = jnp.where(col < t_k, s, _PAD_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)                             # f32 always
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l * alpha + jnp.sum(p, axis=1, keepdims=True), acc

    m0 = jnp.full((block_q, 1), _PAD_VALUE, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, t_k_pad // block_k, body,
                                  (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    if save_res:
        # m/l stay SEPARATE (not m + log l): in the all-masked-row case
        # m is −1e9 and f32 addition swallows log l entirely, which
        # would turn the backward's recomputed P into exp(0) = 1
        # instead of the uniform 1/t_k the forward produced
        m_ref[0, 0] = m[:, 0]
        l_ref[0, 0] = l[:, 0]


def _recompute_p(q, kb, bias_blk, m, l, row0, col0, causal: bool,
                 t_k: int, block_q: int, block_k: int):
    """Shared backward-tile recompute: the (block_q, block_k)
    probability tile ``P = exp(S − m) / l`` with the forward's exact
    replacement-mask/causal/pad semantics, plus the ``replaced`` plane
    (positions whose logit the forward OVERWROTE — their softmax
    cotangent is zero, exactly like the einsum path's
    ``where(mask == 0, NEG_MASK_VALUE, logits)`` VJP)."""
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    col = col0 + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    replaced = col >= t_k
    if bias_blk is not None:
        bmask = bias_blk != 0.0
        s = jnp.where(bmask, bias_blk, s)
        replaced = replaced | bmask
    if causal:
        row = row0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cmask = col > row
        s = jnp.where(cmask, NEG_MASK_VALUE, s)
        replaced = replaced | cmask
    s = jnp.where(col >= t_k, _PAD_VALUE, s)
    p = jnp.exp(s - m) / l
    return p, replaced


def _flash_attention_bwd_dq_kernel(q_ref, k_ref, v_ref, *rest,
                                   causal: bool, has_bias: bool, t_k: int,
                                   t_k_pad: int, block_q: int,
                                   block_k: int):
    """dQ for one (batch, head, q-block) grid cell: loop the key blocks,
    recompute each P tile in VMEM from the saved residuals, accumulate
    ``dQ = Σ_k dS · K`` with ``dS = P ∘ (dP − Δ)`` zeroed at replaced
    positions. Neither the logits nor P ever reach HBM."""
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    g_ref, m_ref, l_ref, delta_ref, dq_ref = rest
    q = q_ref[0, 0].astype(jnp.float32)                    # (bq, d)
    g = g_ref[0, 0].astype(jnp.float32)                    # (bq, d)
    m = m_ref[0, 0][:, None]                               # (bq, 1) f32
    l = l_ref[0, 0][:, None]
    delta = delta_ref[0, 0][:, None]
    row0 = pl.program_id(2) * block_q

    def body(j, acc):
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32)
        bias_blk = None
        if has_bias:
            bias_blk = bias_ref[0, 0, :, pl.ds(j * block_k,
                                               block_k)].astype(
                jnp.float32)
        p, replaced = _recompute_p(q, kb, bias_blk, m, l, row0,
                                   j * block_k, causal, t_k, block_q,
                                   block_k)
        dp = jax.lax.dot_general(g, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(replaced, 0.0, p * (dp - delta))
        return acc + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(0, t_k_pad // block_k, body, acc0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _flash_attention_bwd_dkv_kernel(q_ref, k_ref, v_ref, *rest,
                                    causal: bool, has_bias: bool,
                                    t_k: int, t_q_pad: int, block_q: int,
                                    block_k: int):
    """dK/dV for one (batch, head, k-block) grid cell: loop the query
    blocks, recompute each P tile, accumulate ``dV = Σ_q Pᵀ · dO`` (the
    FULL P — an all-masked row's uniform weights really do route
    cotangent into V, matching the einsum VJP) and
    ``dK = Σ_q dSᵀ · Q``."""
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    g_ref, m_ref, l_ref, delta_ref, dk_ref, dv_ref = rest
    kb = k_ref[0, 0].astype(jnp.float32)                   # (bk, d)
    vb = v_ref[0, 0].astype(jnp.float32)
    col0 = pl.program_id(2) * block_k
    d = kb.shape[-1]

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        gb = g_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        mb = m_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        lb = l_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        db = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        bias_blk = None
        if has_bias:
            bias_blk = bias_ref[0, 0, pl.ds(i * block_q, block_q),
                                :].astype(jnp.float32)
        p, replaced = _recompute_p(qb, kb, bias_blk, mb, lb, i * block_q,
                                   col0, causal, t_k, block_q, block_k)
        dv = dv + jax.lax.dot_general(
            p, gb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, d)
        dp = jax.lax.dot_general(gb, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = jnp.where(replaced, 0.0, p * (dp - db))
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, d)
        return dk, dv

    z = jnp.zeros((kb.shape[0], d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, t_q_pad // block_q, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         bias: Optional[jnp.ndarray],
                         causal: bool) -> jnp.ndarray:
    """The einsum path on ``(B, H, T, D)`` layout — the semantics the
    kernel (forward AND backward) must match; the parity tests compare
    both the primal outputs and ``jax.grad`` through this function
    against the kernels."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    if bias is not None:
        s = jnp.where(bias != 0.0, bias.astype(jnp.float32), s)
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        tri = jnp.triu(jnp.ones((t_q, t_k), dtype=bool), k=1)
        s = jnp.where(tri[None, None], NEG_MASK_VALUE, s)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.lru_cache(maxsize=None)
def _build(causal: bool, block_q: int, block_k: int, interpret: bool,
           has_bias: bool):
    """One differentiable pallas program per static configuration
    (cached: ``jax.custom_vjp`` objects must be stable across traces so
    jit caches hit)."""

    def _pad_args(q, k, v, bias, bq, bk, t_q_pad, t_k_pad, d_pad):
        # no-op pads are SKIPPED, not emitted: unoptimized HLO charges a
        # zero-width lax.pad as a full read+write of the tensor, which
        # would bill the audit's cost ratchets for copies the optimizer
        # deletes (interpret mode at exact tile sizes pads nothing)
        def pad(x, t):
            if t == x.shape[2] and d_pad == x.shape[3]:
                return x
            return jnp.pad(x, ((0, 0), (0, 0), (0, t - x.shape[2]),
                               (0, d_pad - x.shape[3])))
        qp, kp, vp = pad(q, t_q_pad), pad(k, t_k_pad), pad(v, t_k_pad)
        in_specs = [
            pl.BlockSpec((1, 1, bq, d_pad), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, t_k_pad, d_pad),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, t_k_pad, d_pad),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
        ]
        args = [qp, kp, vp]
        if has_bias:
            h_b = bias.shape[1]             # 1 (broadcast) or H
            bp = bias
            if (t_q_pad, t_k_pad) != bias.shape[2:]:
                bp = jnp.pad(bias, ((0, 0), (0, 0),
                                    (0, t_q_pad - bias.shape[2]),
                                    (0, t_k_pad - bias.shape[3])))
            in_specs.append(pl.BlockSpec(
                (1, 1, bq, t_k_pad),
                lambda b_, h_, i, hb=h_b: (b_, h_ if hb > 1 else 0, i, 0)))
            args.append(bp)
        return args, in_specs

    def forward(q, k, v, bias, save_res: bool):
        b, h, t_q, d = q.shape
        t_k = k.shape[2]
        bq, bk, t_q_pad, t_k_pad, d_pad = _tile_geometry(
            t_q, t_k, d, block_q, block_k, interpret)
        args, in_specs = _pad_args(q, k, v, bias, bq, bk, t_q_pad,
                                   t_k_pad, d_pad)

        kernel = functools.partial(
            _flash_attention_kernel, causal=causal, has_bias=has_bias,
            save_res=save_res, t_k=t_k, t_k_pad=t_k_pad, block_q=bq,
            block_k=bk)
        out_shape = jax.ShapeDtypeStruct((b, h, t_q_pad, d_pad), q.dtype)
        out_specs = pl.BlockSpec((1, 1, bq, d_pad),
                                 lambda b_, h_, i: (b_, h_, i, 0))
        # slice only if the output actually carries pad (cost-model
        # cleanliness, like _pad_args)
        unpad = (lambda o: o if (t_q_pad, d_pad) == (t_q, d)
                 else o[:, :, :t_q, :d])
        if save_res:
            res_spec = pl.BlockSpec((1, 1, bq),
                                    lambda b_, h_, i: (b_, h_, i))
            res_shape = jax.ShapeDtypeStruct((b, h, t_q_pad), jnp.float32)
            out, m, l = pl.pallas_call(
                kernel,
                grid=(b, h, t_q_pad // bq),
                in_specs=in_specs,
                out_specs=(out_specs, res_spec, res_spec),
                out_shape=(out_shape, res_shape, res_shape),
                interpret=interpret,
            )(*args)
            return unpad(out), m, l
        out = pl.pallas_call(
            kernel,
            grid=(b, h, t_q_pad // bq),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*args)
        return unpad(out)

    def backward(q, k, v, bias, o, m, l, g):
        """Flash backward: ``Δ = rowsum(dO ∘ O)`` (one elementwise pass,
        no score-shaped tensor), then two pallas programs — dQ gridded
        over q-blocks, dK/dV over k-blocks — each recomputing P tiles in
        VMEM from (q, k, residuals)."""
        b, h, t_q, d = q.shape
        t_k = k.shape[2]
        bq, bk, t_q_pad, t_k_pad, d_pad = _tile_geometry(
            t_q, t_k, d, block_q, block_k, interpret)
        args, in_specs = _pad_args(q, k, v, bias, bq, bk, t_q_pad,
                                   t_k_pad, d_pad)
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                           # (b, h, t_q)
        gp = g
        if (t_q_pad, d_pad) != (t_q, d):
            gp = jnp.pad(g, ((0, 0), (0, 0), (0, t_q_pad - t_q),
                             (0, d_pad - d)))
        dp_ = delta
        if t_q_pad != t_q:
            dp_ = jnp.pad(delta, ((0, 0), (0, 0), (0, t_q_pad - t_q)))
        # m/l come back from the forward already t_q_pad-long
        qd_spec = pl.BlockSpec((1, 1, bq, d_pad),
                               lambda b_, h_, i: (b_, h_, i, 0))
        qrow_spec = pl.BlockSpec((1, 1, bq), lambda b_, h_, i: (b_, h_, i))
        qfull_spec = pl.BlockSpec((1, 1, t_q_pad, d_pad),
                                  lambda b_, h_, j: (b_, h_, 0, 0))
        qfullrow_spec = pl.BlockSpec((1, 1, t_q_pad),
                                     lambda b_, h_, j: (b_, h_, 0))
        kd_spec = pl.BlockSpec((1, 1, bk, d_pad),
                               lambda b_, h_, j: (b_, h_, j, 0))

        dq_kernel = functools.partial(
            _flash_attention_bwd_dq_kernel, causal=causal,
            has_bias=has_bias, t_k=t_k, t_k_pad=t_k_pad, block_q=bq,
            block_k=bk)
        dq = pl.pallas_call(
            dq_kernel,
            grid=(b, h, t_q_pad // bq),
            in_specs=in_specs + [qd_spec, qrow_spec, qrow_spec,
                                 qrow_spec],
            out_specs=qd_spec,
            out_shape=jax.ShapeDtypeStruct((b, h, t_q_pad, d_pad),
                                           q.dtype),
            interpret=interpret,
        )(*args, gp, m, l, dp_)

        # dK/dV grid over key blocks: Q/dO/residuals arrive whole, the
        # key/value/bias specs re-map onto the k-block axis
        in_specs_kv = [
            qfull_spec,                                     # q (full)
            kd_spec,                                        # k block
            kd_spec,                                        # v block
        ]
        if has_bias:
            h_b = bias.shape[1]
            in_specs_kv.append(pl.BlockSpec(
                (1, 1, t_q_pad, bk),
                lambda b_, h_, j, hb=h_b: (b_, h_ if hb > 1 else 0, 0, j)))
        dkv_kernel = functools.partial(
            _flash_attention_bwd_dkv_kernel, causal=causal,
            has_bias=has_bias, t_k=t_k, t_q_pad=t_q_pad, block_q=bq,
            block_k=bk)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(b, h, t_k_pad // bk),
            in_specs=in_specs_kv + [qfull_spec, qfullrow_spec,
                                    qfullrow_spec, qfullrow_spec],
            out_specs=(kd_spec, kd_spec),
            out_shape=(jax.ShapeDtypeStruct((b, h, t_k_pad, d_pad),
                                            k.dtype),
                       jax.ShapeDtypeStruct((b, h, t_k_pad, d_pad),
                                            v.dtype)),
            interpret=interpret,
        )(*args, gp, m, l, dp_)
        unpad_q = (lambda x: x if (t_q_pad, d_pad) == (t_q, d)
                   else x[:, :, :t_q, :d])
        unpad_k = (lambda x: x if (t_k_pad, d_pad) == (t_k, d)
                   else x[:, :, :t_k, :d])
        return unpad_q(dq), unpad_k(dk), unpad_k(dv)

    @jax.custom_vjp
    def attn(q, k, v, bias):
        return forward(q, k, v, bias, save_res=False)

    def attn_fwd(q, k, v, bias):
        o, m, l = forward(q, k, v, bias, save_res=True)
        return o, (q, k, v, bias, o, m, l)

    def attn_bwd(res, g):
        q, k, v, bias, o, m, l = res
        dq, dk, dv = backward(q, k, v, bias, o, m, l, g)
        # the bias plane encodes the (non-differentiable) mask; its
        # cotangent is structurally zero, as on the einsum path where
        # the mask feeds only `where` predicates
        db = jnp.zeros_like(bias) if bias is not None else None
        return dq, dk, dv, db

    attn.defvjp(attn_fwd, attn_bwd)
    return attn


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    mask: Optional[jnp.ndarray] = None,
                    causal: bool = False, *,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused attention ``softmax(QK^T [masked]) V`` on ``(B, H, T, D)``
    layout. Any Q1 query/key scaling is the caller's job (the module
    scales both by ``head_dim**-0.25`` before calling, exactly as on
    the einsum path).

    ``mask``: optional ``(B, 1|H, T_q, T_k)``; zero entries are
    suppressed (module semantics). ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (CPU tier-1 gate); pass an explicit bool
    to force either mode. Differentiating through the call runs the
    flash backward kernels (P recomputed in VMEM from per-row
    residuals — no logits/P tensor in HBM either direction)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bias = None
    if mask is not None:
        if mask.ndim != 4:
            raise ValueError(f"mask must be (B, 1|H, T_q, T_k), got "
                             f"shape {mask.shape}")
        # encode the module's replacement semantics as a float plane:
        # 0 = keep the logit, NEG_MASK_VALUE = overwrite it
        bias = jnp.where(mask == 0, jnp.float32(NEG_MASK_VALUE),
                         jnp.float32(0.0))
    fn = _build(bool(causal), int(block_q), int(block_k), bool(interpret),
                bias is not None)
    return fn(q, k, v, bias)


def register_audit_programs(ctx):
    """graftprog registry hook (``analysis/registry.py``): lower BOTH
    kernel modes of ``MultiHeadAttention`` on the frozen audit config's
    model shapes so each stays ratcheted and fingerprinted
    (``analysis/programs.json``) — a silent jaxpr change in either the
    einsum path or the pallas lowering fails the gate like every other
    hot program. ``attn_pallas_bwd`` additionally lowers the GRADIENT
    of the pallas module (value_and_grad over q/k inputs), pinning the
    flash backward kernels — the train-path lowering PR 13 added — the
    same way. The pallas variants lower the interpret form (the gate is
    pinned to CPU); on-TPU they lower to Mosaic custom calls with the
    same kernel bodies."""
    from ..analysis.registry import AuditProgram
    from ..models.transformer import MultiHeadAttention

    m = ctx.cfg.model
    dt = jnp.dtype(m.dtype)
    b, t = 4, 8                         # tiny token grid, audit-scale

    def parts(impl):
        mha = MultiHeadAttention(emb=m.emb, heads=m.heads,
                                 standard_heads=m.standard_heads,
                                 dtype=dt, attn_impl=impl)
        q0 = jnp.zeros((b, t, m.emb), dt)
        k0 = jnp.zeros((b, t, m.emb), dt)
        params = jax.eval_shape(lambda: mha.init(
            jax.random.PRNGKey(0), q0, k0))
        aval = jax.ShapeDtypeStruct((b, t, m.emb), dt)
        return mha, params, aval

    def make(impl, fn_name):
        mha, params, aval = parts(impl)

        def apply(p, q, kk):
            return mha.apply(p, q, kk)
        apply.__name__ = apply.__qualname__ = fn_name
        return AuditProgram(
            jax.jit(apply), (params, aval, aval),
            description=f"MultiHeadAttention ({impl} kernel mode) at "
                        f"audit model shapes — both rollout-path "
                        f"attention lowerings stay fingerprinted")

    def make_bwd():
        mha, params, aval = parts("pallas")

        def loss(p, q, kk):
            return (mha.apply(p, q, kk).astype(jnp.float32) ** 2).sum()

        grad = jax.value_and_grad(loss, argnums=(1, 2))
        grad.__name__ = grad.__qualname__ = "_attn_pallas_bwd"
        return AuditProgram(
            jax.jit(grad), (params, aval, aval),
            description="value_and_grad through the pallas "
                        "MultiHeadAttention — the flash backward "
                        "kernels (dq + dkv pallas programs, P "
                        "recomputed in VMEM) stay fingerprinted and "
                        "ratcheted alongside the forward")

    return {
        "attn_xla": make("xla", "_attn_xla"),
        "attn_pallas": make("pallas", "_attn_pallas"),
        "attn_pallas_bwd": make_bwd(),
    }
