"""graftpop: a vmapped population axis over the whole learner (ROADMAP
item 5, docs/POPULATION.md).

Podracer's Anakin (PAPERS.md) trains *populations* of agents per chip by
vmapping the entire agent–learner stack. Our fused superstep is already
Anakin-shaped — one pure function from ``TrainState`` to ``TrainState`` —
so the population axis is exactly ``jax.vmap`` over a leading ``(P,)``
stack of the full train state (params, opt_state, replay ring + PER
priorities, runner state incl. per-lane EnvParams, RNG keys) plus a
:class:`PopulationSpec` of per-member hyperparameter scalars. ONE donated
dispatch then advances P seed/hyperparameter variants
(``run.Experiment.population_superstep_program``), multiplying experiment
throughput per chip by P without touching dispatch count.

Per-member knobs enter the math as **vmapped-over scalar leaves**, each a
neutral operation at its default so the P=1 population is BIT-identical
to the classic loop (tests/test_population.py):

* ``lr_scale`` — multiplies the optimizer's update tree after
  ``opt.update`` (learning rate enters optax's adam/rmsprop linearly
  after the moment statistics, so scaling updates == scaling lr exactly;
  1.0 multiplies bitwise-identically);
* ``eps_scale`` — multiplies the epsilon-greedy schedule's epsilon
  (components/action_selectors.py; 1.0 is bitwise-neutral);
* ``per_alpha`` — the PER exponent as a traced scalar
  (components/episode_buffer.py stores ``p^alpha`` at write time; the
  same ``pow`` on the same values, so the config-default value is
  value-identical to the static path);
* ``member`` — the member index, used for per-member scenario
  decorrelation (``population.scenario_salt`` folds it into the
  graftworld sampler key, envs/graftworld.py) and per-member logging.

Seed replication is the degenerate case: an empty grid leaves every
scale at its neutral value and members differ only through their seeds
(member ``i`` inits from ``seed + i·seed_stride``), so member 0 is
bit-exactly the solo run at ``cfg.seed``.

Optional PBT (``population.pbt.*``, off by default): host-side
select-and-perturb on the population axis at checkpoint-save boundaries
ONLY — the bottom ``frac`` members copy the full train state of the top
``frac`` (one device gather, zero extra steady-state dispatches) and
multiplicatively perturb their spec leaves. PBT (and any non-neutral
grid) deliberately breaks member-0/solo parity — that is its job.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class PopulationSpec:
    """Per-member hyperparameters: ``(P,)``-stacked scalar leaves the
    population superstep vmaps over (module docstring for semantics)."""

    lr_scale: jnp.ndarray      # (P,) f32 — optimizer update multiplier
    eps_scale: jnp.ndarray     # (P,) f32 — epsilon-schedule multiplier
    per_alpha: jnp.ndarray     # (P,) f32 — PER priority exponent
    member: jnp.ndarray        # (P,) int32 — member index (scenario salt)


@struct.dataclass
class PopState:
    """The checkpointable population state: the ``(P,)``-stacked
    TrainState plus the (PBT-mutable, therefore checkpointed) spec.
    ``utils/checkpoint.py`` FORMAT_VERSION 5 lifts a single-member v4
    checkpoint into this layout (``_migrate_raw``)."""

    ts: object                 # run.TrainState, every leaf (P,)-stacked
    spec: PopulationSpec


def population_size(cfg) -> int:
    """P when the population axis is on, else 0 (``population.size``;
    the ``superstep_eligible`` predicate pattern). ``sanity_check`` has
    already rejected the incompatible combinations (host-RAM replay,
    dp_devices, sebulba, evaluate/animation)."""
    return int(cfg.population.size)


def member_seeds(cfg) -> List[int]:
    """Member ``i`` inits and threads keys from ``seed + i·seed_stride``
    — stride 1 (default) = seed replication with member 0 bit-exactly
    the solo run; stride 0 = identical seeds (grid-over-knobs mode,
    usually together with ``scenario_salt``)."""
    pc = cfg.population
    return [cfg.seed + i * pc.seed_stride for i in range(pc.size)]


def build_spec(cfg) -> PopulationSpec:
    """The config's per-member grids as a stacked spec. Empty grids
    replicate the base config's value — ``lr_scale``/``eps_scale`` at
    exactly 1.0 and ``per_alpha`` at ``replay.per_alpha``, the neutral
    leaves the P=1 bit-parity contract stands on."""
    pc = cfg.population
    p = pc.size
    lr = pc.lr or (cfg.lr,) * p
    eps = pc.eps_scale or (1.0,) * p
    alpha = pc.per_alpha or (cfg.replay.per_alpha,) * p
    return PopulationSpec(
        lr_scale=jnp.asarray([v / cfg.lr for v in lr], jnp.float32),
        eps_scale=jnp.asarray(eps, jnp.float32),
        per_alpha=jnp.asarray(alpha, jnp.float32),
        member=jnp.arange(p, dtype=jnp.int32),
    )


def init_population(exp, cfg) -> Tuple[object, PopulationSpec]:
    """→ (stacked TrainState, spec): P explicit solo inits stacked
    along the new leading axis — member ``i``'s leaves are BIT-identical
    to a solo ``init_train_state(seed_i)`` by construction. Deliberately
    not ``vmap(init)``: batched random/normal lowering drifts a ULP on
    some leaves (the same f32-reassociation effect the P=1 superstep
    squeeze path documents), and init runs once — correctness of the
    seed-replication contract over one-time elegance."""
    states = [exp.init_train_state(s) for s in member_seeds(cfg)]
    ts = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    return ts, build_spec(cfg)


def member_keys(cfg) -> List[jax.Array]:
    """The P host-side driver key streams (classic loop convention:
    ``PRNGKey(seed + 1)`` per member) — the driver mirrors the train
    gate once (the counters evolve identically across members) and
    splits EACH member's stream exactly like the classic loop, so
    member 0's consumed key stream is the solo run's."""
    return [jax.random.PRNGKey(s + 1) for s in member_seeds(cfg)]


# --------------------------------------------------------------------------
# PBT: host-side select-and-perturb at checkpoint-save boundaries
# --------------------------------------------------------------------------


def pbt_step(cfg, ts, spec: PopulationSpec,
             member_perf: Optional[List[Optional[float]]], t_env: int
             ) -> Tuple[object, PopulationSpec, Optional[dict]]:
    """One exploit/explore pass (``population.pbt.*``): rank members by
    ``member_perf`` (the stats accumulator's per-member return EMA),
    copy the bottom ``frac`` members' FULL train state from the top
    ``frac`` (one device gather — the only extra device work PBT ever
    does), and multiplicatively perturb the copied members' spec leaves
    by ``perturb``/``1/perturb``. Returns ``(ts, spec, info|None)``;
    ``None`` info = no-op (insufficient perf history, or P too small).

    The perturbation RNG derives from ``(seed, t_env)``: two runs
    reaching the same boundary with the same ranking make identical
    decisions. The ranking itself (``member_perf`` — the accumulator's
    return EMA) is HOST state that is deliberately not checkpointed: a
    restore rebuilds it from fresh flushes, so a restored run may
    no-op a boundary the original timeline exploited at. That is safe
    by construction — checkpoints hold the PRE-PBT population, so the
    restored trajectory is self-consistent; it just re-warms its
    ranking before exploiting again (docs/POPULATION.md §PBT). Losers
    keep their OWN driver key streams, and their copied ROLLOUT key
    (the ``runner.key`` leaf, gathered with the donor's device state)
    is re-salted with a per-(member, t_env) ``fold_in`` — without the
    salt an exploited member would replay its donor's exact
    trajectories (identical scenario draws and exploration) until the
    differently-sampled train batches pulled the params apart, halving
    the diversity the exploit step exists to create."""
    pc = cfg.population.pbt
    p = cfg.population.size
    if (member_perf is None or len(member_perf) != p
            or any(v is None for v in member_perf)):
        return ts, spec, None
    n = max(1, int(round(p * pc.frac)))
    if 2 * n > p:
        n = p // 2
    if n < 1:
        return ts, spec, None
    order = np.argsort(np.asarray(member_perf, np.float64), kind="stable")
    losers, winners = order[:n], order[-n:]
    src = np.arange(p)
    src[losers] = winners
    ts = jax.tree.map(lambda x: x[jnp.asarray(src)], ts)
    runner = getattr(ts, "runner", None)
    if runner is not None and hasattr(runner, "key"):
        # re-salt exploited members' rollout key (docstring): the
        # gather above copied the donor's stream verbatim
        rkey = runner.key
        for m in losers:
            rkey = rkey.at[int(m)].set(jax.random.fold_in(
                rkey[int(m)], int(t_env) + int(m) + 1))
        ts = ts.replace(runner=runner.replace(key=rkey))
    rng = np.random.default_rng((int(cfg.seed) << 17) ^ (int(t_env) + 1))
    lr = np.asarray(jax.device_get(spec.lr_scale), np.float32).copy()
    eps = np.asarray(jax.device_get(spec.eps_scale), np.float32).copy()
    alpha = np.asarray(jax.device_get(spec.per_alpha), np.float32).copy()
    alpha_pre = alpha.copy()          # donors' pre-perturb exponents

    def _perturb(v):
        return v * (pc.perturb if rng.random() < 0.5 else 1.0 / pc.perturb)

    for m in losers:
        lr[m] = _perturb(lr[src[m]])
        eps[m] = _perturb(eps[src[m]])
        alpha[m] = float(np.clip(_perturb(alpha[src[m]]), 1e-3, 1.0))
    buf = getattr(ts, "buffer", None)
    if buf is not None and hasattr(buf, "priorities"):
        # the gathered ring stores the DONOR's pre-exponentiated
        # priorities (p^alpha_donor); the loser's future writes use its
        # perturbed exponent — rescale the copied entries to
        # p^alpha_new = (p^alpha_donor)^(alpha_new/alpha_donor) so the
        # stored-space sampler and IS weights keep one consistent
        # exponent per member (zeros in the unfilled tail stay zero)
        pri = buf.priorities
        for m in losers:
            a_old = float(alpha_pre[src[m]])
            a_new = float(alpha[m])
            if a_old != a_new and a_old > 0:
                pri = pri.at[int(m)].set(pri[int(m)] ** (a_new / a_old))
        if pri is not buf.priorities:
            ts = ts.replace(buffer=buf.replace(priorities=pri))
    spec = PopulationSpec(
        lr_scale=jnp.asarray(lr), eps_scale=jnp.asarray(eps),
        per_alpha=jnp.asarray(alpha), member=spec.member)
    return ts, spec, {
        "copied": {int(m): int(src[m]) for m in losers},
        "perf": [float(v) for v in member_perf],
    }
